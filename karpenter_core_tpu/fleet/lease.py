"""Replica liveness over the existing lease CAS plane.

The service already exposes a compare-and-swap lease store
(/LeaseGet, /LeaseApply — service/snapshot_channel.py) for operator leader
election.  The fleet reuses the SAME wire protocol for replica liveness:

  LeasePlane       the router-side authority — an in-memory CAS map with the
                   exact handler semantics of the service's lease plane,
                   JSON-persisted so a router restart keeps the directory
  ReplicaPulse     the replica-side heartbeat: a ``RemoteLeaseStore`` CAS
                   renew of ``fleet-replica-<id>`` every ``heartbeat_s``;
                   SIGTERM drain flips ``leaseDurationSeconds`` to 0 so the
                   router remaps the replica's arc BEFORE the process exits
  LeaseDirectory   the router's read view: alive / draining replica sets by
                   renew-time freshness against the injected clock

A replica with NO lease yet counts alive (bootstrap: routing must not wait
for the first heartbeat); a replica whose lease went stale counts dead and
its tenants remap (warm, via the fleet checkpoints).  SIGKILL needs no
cooperation — the lease simply stops renewing.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Iterable, Optional, Set, Tuple

import msgpack

from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

LEASE_NAMESPACE = "kc-fleet"
LEASE_PREFIX = "fleet-replica-"


def lease_name(replica_id: str) -> str:
    return f"{LEASE_PREFIX}{replica_id}"


class LeasePlane:
    """The router-hosted lease store: same CAS semantics, same wire shapes
    as ``SnapshotSolverService._lease_get/_lease_apply`` — ``RemoteLeaseStore``
    clients cannot tell the difference (that is the point)."""

    def __init__(self, path: str = "") -> None:
        self._leases: Dict[Tuple[str, str], Dict] = {}
        self._lock = threading.Lock()
        self._path = path
        self._load()

    def _load(self) -> None:
        if not self._path:
            return
        import json

        try:
            with open(self._path) as f:
                for entry in json.load(f):
                    self._leases[(entry.get("namespace", ""), entry["name"])] = entry
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 - durability is best-effort
            log.warning("fleet lease state load failed (%s), starting empty", e)

    def _persist_locked(self) -> None:
        if not self._path:
            return
        import json
        import os

        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = f"{self._path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(list(self._leases.values()), f)
            os.replace(tmp, self._path)
        except Exception as e:  # noqa: BLE001 - durability is best-effort
            log.debug("fleet lease state persist failed: %s", e)

    def get_wire(self, request: bytes) -> bytes:
        req = msgpack.unpackb(request)
        with self._lock:
            stored = self._leases.get((req.get("namespace", ""), req["name"]))
            return msgpack.packb({"lease": dict(stored) if stored else None})

    def apply_wire(self, request: bytes) -> bytes:
        req = msgpack.unpackb(request)
        lease = dict(req["lease"])
        key = (lease.get("namespace", ""), lease["name"])
        expected = req.get("expectedVersion")
        with self._lock:
            stored = self._leases.get(key)
            if expected is None:
                if stored is not None:
                    return msgpack.packb(
                        {"ok": False, "conflict": True, "lease": dict(stored)}
                    )
                lease["resourceVersion"] = 1
            else:
                if stored is None or stored["resourceVersion"] != expected:
                    return msgpack.packb({
                        "ok": False, "conflict": True,
                        "lease": dict(stored) if stored else None,
                    })
                lease["resourceVersion"] = stored["resourceVersion"] + 1
            self._leases[key] = lease
            self._persist_locked()
            return msgpack.packb(
                {"ok": True, "conflict": False, "lease": dict(lease)}
            )

    def snapshot(self) -> Dict[str, Dict]:
        """replica_id -> lease wire dict, for the LeaseDirectory."""
        with self._lock:
            out = {}
            for (ns, name), lease in self._leases.items():
                if ns == LEASE_NAMESPACE and name.startswith(LEASE_PREFIX):
                    out[name[len(LEASE_PREFIX):]] = dict(lease)
            return out


class LeaseDirectory:
    """The router's liveness read: which fleet-map replicas are alive or
    draining right now, by lease freshness."""

    def __init__(self, plane: LeasePlane, *, clock: Optional[Clock] = None,
                 ttl_s: float = 10.0) -> None:
        self.plane = plane
        self.clock = clock or Clock()
        self.ttl_s = float(ttl_s)

    def view(self, replica_ids: Iterable[str]) -> Tuple[Set[str], Set[str]]:
        """(alive, draining) subsets of ``replica_ids``.  No lease yet =
        alive (bootstrap); duration 0 = draining; stale renew = dead."""
        leases = self.plane.snapshot()
        now = self.clock.now()
        alive: Set[str] = set()
        draining: Set[str] = set()
        for rid in replica_ids:
            lease = leases.get(rid)
            if lease is None:
                alive.add(rid)
                continue
            if int(lease.get("leaseDurationSeconds", 0) or 0) == 0:
                draining.add(rid)
                continue
            if now - float(lease.get("renewTime", 0.0) or 0.0) <= self.ttl_s:
                alive.add(rid)
        return alive, draining


class ReplicaPulse:
    """The replica's heartbeat thread: CAS-renew this replica's lease at the
    router every ``heartbeat_s``.  Failures log and retry on the next beat —
    a router restart or partition must not take the replica down with it."""

    def __init__(self, store, replica_id: str, *,
                 clock: Optional[Clock] = None, heartbeat_s: float = 2.0,
                 ttl_s: float = 10.0) -> None:
        self.store = store  # RemoteLeaseStore-shaped (get/create/update_with_version)
        self.replica_id = replica_id
        self.clock = clock or Clock()
        self.heartbeat_s = max(float(heartbeat_s), 0.05)
        self.ttl_s = float(ttl_s)
        self._stop = threading.Event()
        self._draining = False
        self._thread: Optional[threading.Thread] = None

    def _lease(self, duration_s: int):
        from karpenter_core_tpu.apis.objects import Lease, LeaseSpec, ObjectMeta

        now = self.clock.now()
        return Lease(
            metadata=ObjectMeta(
                name=lease_name(self.replica_id), namespace=LEASE_NAMESPACE
            ),
            spec=LeaseSpec(
                holder_identity=self.replica_id,
                lease_duration_seconds=duration_s,
                acquire_time=now,
                renew_time=now,
            ),
        )

    def beat(self) -> bool:
        """One heartbeat: create the lease, or CAS-renew whatever version is
        stored.  Returns True when the renewal landed."""
        from karpenter_core_tpu.operator.kubeclient import ConflictError

        duration = 0 if self._draining else max(int(round(self.ttl_s)), 1)
        try:
            stored = self.store.get(None, lease_name(self.replica_id),
                                    LEASE_NAMESPACE)
            lease = self._lease(duration)
            if stored is None:
                self.store.create(lease)
            else:
                self.store.update_with_version(
                    lease, stored.metadata.resource_version
                )
            return True
        except ConflictError:
            # a concurrent create/renew won the CAS — next beat re-reads
            return False
        except Exception as e:  # noqa: BLE001 - liveness is best-effort
            log.debug("fleet heartbeat failed for %s: %s", self.replica_id, e)
            return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-pulse-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.heartbeat_s)

    def mark_draining(self) -> None:
        """SIGTERM path: advertise drain NOW (duration 0) so the router
        remaps this replica's arc before the process exits."""
        self._draining = True
        self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
