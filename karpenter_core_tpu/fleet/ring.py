"""Consistent-hash tenant→replica placement: the fleet map and the ring.

The router (fleet/router.py) places every tenant on exactly one replica so
its server-side solve lineage (session, warm carry, journal chain) has one
home.  Placement must be:

  deterministic   two routers with the same ``FleetMap`` place identically —
                  the ring hashes replica ids and tenant ids through sha256
                  (PYTHONHASHSEED-free), never ``hash()``.

  stable          adding/removing one replica moves only the tenants on the
                  affected arcs (classic consistent hashing with ``vnodes``
                  virtual points per replica).

  bounded-load    the "consistent hashing with bounded loads" variant: a
                  replica already carrying more than ``load_factor`` times
                  its fair share is skipped and the tenant walks to the next
                  arc, so one hot arc cannot melt a single replica while its
                  peers idle (docs/FLEET.md "Placement").

``FleetMap`` is the static replica roster (``KC_FLEET_MAP``:
``r1=host:port,r2=host:port``); LIVENESS is dynamic and comes from the lease
directory (fleet/lease.py) — the ring only ever places on replicas the
caller says are alive.
"""

from __future__ import annotations

import bisect
import hashlib
import math
import os
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple


def _point(key: str) -> int:
    """Deterministic 64-bit ring coordinate (sha256, PYTHONHASHSEED-free)."""
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")


@dataclass(frozen=True)
class FleetMap:
    """The ordered replica roster: ((replica_id, address), ...)."""

    replicas: Tuple[Tuple[str, str], ...] = ()

    @classmethod
    def parse(cls, spec: str) -> "FleetMap":
        """``r1=host:port,r2=host:port`` — unparseable parts are skipped (a
        typo must not take routing down), duplicate ids keep the first."""
        seen = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            rid, _, address = part.partition("=")
            rid, address = rid.strip(), address.strip()
            if rid and address and rid not in seen:
                seen[rid] = address
        return cls(replicas=tuple(seen.items()))

    @classmethod
    def from_env(cls) -> "FleetMap":
        return cls.parse(os.environ.get("KC_FLEET_MAP", ""))

    @property
    def size(self) -> int:
        return len(self.replicas)

    def ids(self) -> Tuple[str, ...]:
        return tuple(rid for rid, _ in self.replicas)

    def addresses(self) -> Dict[str, str]:
        return dict(self.replicas)


class HashRing:
    """Deterministic consistent-hash ring with bounded-load placement."""

    def __init__(self, fleet_map: FleetMap, vnodes: int = 64,
                 load_factor: float = 1.25) -> None:
        self.fleet_map = fleet_map
        self.vnodes = max(int(vnodes), 1)
        self.load_factor = max(float(load_factor), 1.0)
        points = []
        for rid, _address in fleet_map.replicas:
            for v in range(self.vnodes):
                points.append((_point(f"{rid}#{v}"), rid))
        points.sort()
        self._points = points
        self._keys = [p for p, _ in points]

    def arc(self, tenant: str) -> Tuple[str, ...]:
        """The full preference walk for a tenant: every replica once, in ring
        successor order from the tenant's coordinate.  Placement, failover
        remap, and the chaos matrix all derive from this ONE ordering."""
        if not self._points:
            return ()
        start = bisect.bisect_right(self._keys, _point(tenant))
        seen = []
        have = set()
        n = len(self._points)
        for i in range(n):
            _, rid = self._points[(start + i) % n]
            if rid not in have:
                have.add(rid)
                seen.append(rid)
        return tuple(seen)

    def owner(self, tenant: str, alive: Optional[Iterable[str]] = None,
              assigned: Optional[Dict[str, int]] = None) -> Optional[str]:
        """The tenant's home replica: first ALIVE replica on its arc whose
        current assignment count is under the bounded-load cap
        (``ceil(load_factor * (total+1) / alive)``).  Every alive replica
        over the cap ⇒ the first alive one takes it anyway (the bound is a
        spreading pressure, not an availability cliff)."""
        walk = self.arc(tenant)
        if not walk:
            return None
        alive_set = set(walk if alive is None else alive)
        candidates = [rid for rid in walk if rid in alive_set]
        if not candidates:
            return None
        if not assigned:
            return candidates[0]
        total = sum(int(assigned.get(rid, 0)) for rid in candidates)
        cap = math.ceil(self.load_factor * (total + 1) / len(candidates))
        for rid in candidates:
            if int(assigned.get(rid, 0)) < cap:
                return rid
        return candidates[0]
