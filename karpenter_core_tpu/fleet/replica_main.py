"""Fleet replica subprocess entrypoint.

The multi-process soak (soak/tenants.py ``fleet-failover``) and the fleet
tests launch replicas as REAL processes — separate interpreters, separate
device runtimes, killable with SIGKILL — via::

    python -m karpenter_core_tpu.fleet.replica_main

Configuration arrives entirely through the KC_FLEET_* environment
(fleet/__init__.py FleetLocal.from_env): the shared fleet directory, this
replica's id, the fleet map, and the router address to heartbeat at.  The
process prints ``PORT <n>`` on stdout once the port is bound and serving —
the parent reads that line instead of racing a poll — then blocks until
terminated.  SIGTERM runs the graceful drain (final checkpoints, lease
flip); SIGKILL is the failover path under test and needs no cooperation.
"""

from __future__ import annotations

import logging
import os
import sys
import threading


def main() -> int:
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s replica[{os.environ.get('KC_FLEET_REPLICA', '?')}]"
               " %(levelname)s %(name)s: %(message)s",
    )
    from karpenter_core_tpu import fleet as fleet_mod
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_core_tpu.service.snapshot_channel import serve

    fleet = fleet_mod.FleetLocal.from_env()
    if fleet is None:
        print("KC_FLEET=1 and KC_FLEET_DIR are required", file=sys.stderr)
        return 2
    server, port = serve(
        FakeCloudProvider(),
        address=os.environ.get("KC_FLEET_BIND", "127.0.0.1:0"),
        fleet=fleet,
        drain_on_sigterm=True,
    )
    # the parent parses this exact line; flush so a pipe reader never stalls
    print(f"PORT {port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
