"""Columnar pod-batch ingestion: the steady-state fast path.

At production scale the per-pod work (signature derivation, requirements
construction) must happen once per pod *lifetime* — at watch-event time — not
once per reconcile.  Two front-ends feed the solver without per-pod work on
the solve path:

  - ``PodIngest``: the in-process incremental store.  ``add``/``remove``
    maintain exact signature→class-slot dedup as pods arrive from the
    informer; ``classes()`` assembles solver-ready PodClass lists in O(C).
    This is the analog of the reference maintaining cluster state across
    reconciles (state/cluster.go:152-196) rather than re-reading the world.
  - ``ColumnarPodBatch``: pods as columns (requests matrix + signature rows)
    for callers that arrive over a binary channel; classification reduces to
    grouping identical signature rows through the native runtime
    (models.native, C++) instead of per-object Python hashing.

The per-pod cost of both front-ends is bounded by ``_fast_sig_key``: a cheap
EXACT pre-key over the dominant pod shapes (single plain container, any mix
of labels/selectors/tolerations/spreads/affinity) that lets the full
``models.snapshot._class_signature`` tuple — and its eight ``sorted()``
calls — run once per distinct shape instead of once per pod.  Shapes the
fast key cannot capture exactly (multi-container, resource limits, host
ports, PVC claims) return ``None`` and pay the full derivation; there is no
collision risk anywhere — equal fast keys imply equal signatures by
construction (tests/test_encode_delta.py fuzzes the guarantee).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.models import native
from karpenter_core_tpu.utils import resources as resources_util

# fast-key caches are pruned when they outgrow the live shape population —
# label churn (e.g. pod-template-hash) mints fresh keys forever, and retired
# entries must not accumulate (same motive as PodIngest slot eviction)
_FAST_CACHE_FLOOR = 1024


def _drop_oldest_half(cache: Dict) -> None:
    """Evict the older half of an insertion-ordered cache IN PLACE (dict
    identity preserved — callers may hold bound methods).  First-sight order
    approximates recency for shape caches: fleets with >floor live shapes
    keep their warmer half instead of going fully cold on every overflow."""
    for key in list(cache)[: len(cache) // 2]:
        del cache[key]


def _fast_selector_key(selector):
    """Raw (unsorted) content of a LabelSelector — injective into
    models.snapshot._selector_sig: equal raw tuples sort equal."""
    if selector is None:
        return None
    exprs = selector.match_expressions
    return (
        tuple(selector.match_labels.items()),
        tuple([(e.key, e.operator, tuple(e.values)) for e in exprs])
        if exprs
        else (),
    )


def _fast_term_key(t):
    """Raw content of one pod-(anti-)affinity term (selector + namespace
    scope) — the fields ``_class_signature``'s term/ns_sig tuples sort."""
    ns = t.namespaces
    ns_sel = t.namespace_selector
    return (
        t.topology_key,
        _fast_selector_key(t.label_selector),
        tuple(ns) if ns else (),
        _fast_selector_key(ns_sel) if ns_sel is not None else None,
    )


def _fast_affinity_key(affinity):
    """Raw content of an Affinity block (node + pod + anti terms), covering
    every field ``_class_signature`` folds in, without the sorts."""
    parts = []
    na = affinity.node_affinity
    if na is not None:
        req = (
            tuple([
                tuple([
                    (e.key, e.operator, tuple(e.values))
                    for e in term.match_expressions
                ])
                for term in na.required.node_selector_terms
            ])
            if na.required is not None
            else ()
        )
        pref = tuple([
            (
                p.weight,
                tuple([
                    (e.key, e.operator, tuple(e.values))
                    for e in p.preference.match_expressions
                ]),
            )
            for p in na.preferred
        ])
        parts.append(("node", req, pref))
    pa = affinity.pod_affinity
    if pa is not None:
        parts.append((
            "aff",
            tuple([_fast_term_key(t) for t in pa.required]),
            tuple([(w.weight, _fast_term_key(w.pod_affinity_term)) for w in pa.preferred]),
        ))
    anti = affinity.pod_anti_affinity
    if anti is not None:
        parts.append((
            "anti",
            tuple([_fast_term_key(t) for t in anti.required]),
            tuple([(w.weight, _fast_term_key(w.pod_affinity_term)) for w in anti.preferred]),
        ))
    return tuple(parts)


def _fast_sig_key_py(pod: Pod):
    """A cheap pre-key that EXACTLY determines ``_class_signature``: two pods
    with equal fast keys always have equal signatures (the key carries the
    raw, unsorted content of every field the signature sorts; structural
    branch choices below — one constraint vs many, one affinity term vs a
    full block — are themselves content, so equal-content pods always take
    the same branch and build the same key shape).  Returns None for shapes
    the key cannot capture exactly — multi/init containers, resource limits,
    host ports, PVC claims — which then pay the full signature derivation.
    No sorting, no quantity parsing: the dominant simple shape costs a
    handful of attribute reads and small tuples."""
    spec = pod.spec
    containers = spec.containers
    if len(containers) != 1 or spec.init_containers:
        return None
    c0 = containers[0]
    resources = c0.resources
    if resources.limits:
        return None
    ports = c0.ports
    if ports:
        for p in ports:
            if p.host_port:
                return None
    volumes = spec.volumes
    if volumes:
        for v in volumes:
            if v.persistent_volume_claim is not None:
                return None
    metadata = pod.metadata
    labels = metadata.labels
    node_selector = spec.node_selector
    base = (
        metadata.namespace or "",
        tuple(labels.items()) if labels else (),
        tuple(node_selector.items()) if node_selector else (),
        tuple(resources.requests.items()),
    )
    affinity = spec.affinity
    spreads = spec.topology_spread_constraints
    tolerations = spec.tolerations
    if affinity is None and not spreads and not tolerations:
        return base
    if spreads:
        if len(spreads) == 1:
            # flat key for the dominant one-constraint shape (a 4-tuple, vs
            # the general branch's tuple-of-4-tuples — never equal across
            # branches, and the branch choice is content)
            c = spreads[0]
            sel = c.label_selector
            if sel is None:
                sel_key = None
            else:
                ml = sel.match_labels
                me = sel.match_expressions
                sel_key = (
                    tuple(ml.items()) if ml else (),
                    tuple([(e.key, e.operator, tuple(e.values)) for e in me])
                    if me
                    else (),
                )
            spread_key = (c.topology_key, c.max_skew, c.when_unsatisfiable, sel_key)
        else:
            spread_key = tuple([
                (
                    c.topology_key,
                    c.max_skew,
                    c.when_unsatisfiable,
                    _fast_selector_key(c.label_selector),
                )
                for c in spreads
            ])
    else:
        spread_key = ()
    if affinity is None:
        aff_key = None
    else:
        pa = affinity.pod_affinity
        if (
            pa is not None
            and affinity.node_affinity is None
            and affinity.pod_anti_affinity is None
            and not pa.preferred
            and len(pa.required) == 1
        ):
            # flat key for the dominant single-required-affinity shape (a
            # 5-tuple with a string marker, vs the general branch's
            # tuple-of-parts — never equal across branches)
            term = pa.required[0]
            sel = term.label_selector
            if sel is None:
                sel_key = None
            else:
                ml = sel.match_labels
                me = sel.match_expressions
                sel_key = (
                    tuple(ml.items()) if ml else (),
                    tuple([(e.key, e.operator, tuple(e.values)) for e in me])
                    if me
                    else (),
                )
            ns = term.namespaces
            ns_sel = term.namespace_selector
            aff_key = (
                "aff1",
                term.topology_key,
                sel_key,
                tuple(ns) if ns else (),
                _fast_selector_key(ns_sel) if ns_sel is not None else None,
            )
        else:
            aff_key = _fast_affinity_key(affinity)
    return base + (
        tuple([(t.key, t.operator, t.value, t.effect) for t in tolerations])
        if tolerations
        else (),
        spread_key,
        aff_key,
    )


_sig_key_cached = None


def _sig_key_impl():
    """The resolved fast-key callable: the kc_sig C extension fused with the
    Python twin (C covers the dominant shapes; ``NotImplemented`` routes the
    rest through the twin, whose keys are value-identical by construction —
    the parity fuzz in tests/test_encode_delta.py pins it).  Falls back to
    the pure-Python twin when the extension is unavailable or KC_NATIVE_SIG=0
    disables it.  Resolution (a possible one-time g++ build) happens on the
    first call, never at import."""
    global _sig_key_cached
    impl = _sig_key_cached
    if impl is not None:
        return impl
    from karpenter_core_tpu.models import nativesig

    mod = nativesig.load()
    if mod is None:
        impl = _fast_sig_key_py
    else:
        def impl(pod, _c=mod.fast_sig_key, _py=_fast_sig_key_py):
            key = _c(pod)
            return _py(pod) if key is NotImplemented else key
    _sig_key_cached = impl
    return impl


def _fast_sig_key(pod: Pod):
    """Dispatching front door of the fast key (the resolved C-or-Python
    implementation); see ``_fast_sig_key_py`` for the exactness contract."""
    return _sig_key_impl()(pod)


class SignatureInterner:
    """Shared fast-key → signature (and ladder prototype) cache for callers
    that classify pods across reconciles without a PodIngest — the
    provisioning controller's batch split keeps one alive so steady-state
    batches pay the signature/ladder derivation once per distinct shape, not
    once per pod per reconcile (trace events then cost membership deltas,
    not pod-list rebuilds)."""

    __slots__ = ("_sigs", "_ladders")

    def __init__(self) -> None:
        self._sigs: Dict[tuple, tuple] = {}  # fast key -> full signature
        # signature -> (proto or None, captured KernelUnsupported or None)
        self._ladders: Dict[tuple, tuple] = {}

    def sig_of(self, pod: Pod) -> tuple:
        """The exact ``_class_signature`` of ``pod``, interned."""
        from karpenter_core_tpu.models.snapshot import _class_signature

        fk = _fast_sig_key(pod)
        if fk is None:
            return _class_signature(pod)
        sig = self._sigs.get(fk)
        if sig is None:
            if len(self._sigs) > max(_FAST_CACHE_FLOOR, 4 * len(self._ladders)):
                _drop_oldest_half(self._sigs)  # label churn mints keys forever
            sig = self._sigs[fk] = _class_signature(pod)
        return sig

    def ladder_of(self, sig: tuple, pod: Pod):
        """(proto, error) for one shape: the ``build_pod_ladder`` prototype
        (pods list EMPTY — callers attach members via dataclasses.replace,
        never by mutating the shared proto), or the captured
        KernelUnsupported when the shape routes to the host path."""
        from karpenter_core_tpu.models.snapshot import (
            KernelUnsupported,
            build_pod_ladder,
        )

        hit = self._ladders.get(sig)
        if hit is None:
            proto, error = None, None
            try:
                proto = build_pod_ladder(pod)
            except KernelUnsupported as e:
                error = e
            if len(self._ladders) > 4 * _FAST_CACHE_FLOOR:
                _drop_oldest_half(self._ladders)
            hit = self._ladders[sig] = (proto, error)
        return hit


@dataclass
class ColumnarPodBatch:
    """Pods as columns.  ``signature`` carries one u64 row per pod: stable
    hashes of the pod's constraint content (requirements, tolerations,
    topology, labels) plus its quantized resource vector."""

    n_pods: int
    requests: np.ndarray  # f32[P, R]
    resource_names: List[str]
    signature: np.ndarray  # u64[P, W]
    pods: Optional[List[Pod]] = None  # object backing when converted

    @classmethod
    def from_pods(cls, pods: List[Pod], resource_names: Optional[List[str]] = None) -> "ColumnarPodBatch":
        from karpenter_core_tpu.models.snapshot import _class_signature

        # one signature-hash + resolved-request row per distinct shape via the
        # fast key; the per-pod loop is O(1) dict work, and the requests
        # matrix fills through one vectorized scatter instead of a Python
        # store per (pod, resource) cell
        shape_cache: Dict[tuple, tuple] = {}  # fast key -> (hash64, res items)
        per_pod: List[tuple] = []
        for pod in pods:
            fk = _fast_sig_key(pod)
            hit = shape_cache.get(fk) if fk is not None else None
            if hit is None:
                sig_hash = np.uint64(hash(_class_signature(pod)) & (2**64 - 1))
                res_items = tuple(resources_util.ceiling(pod).items())
                if fk is not None:
                    shape_cache[fk] = hit = (sig_hash, res_items)
                else:
                    hit = (sig_hash, res_items)
            per_pod.append(hit)

        if resource_names is None:
            seen: Dict[str, None] = {}
            for _, res_items in per_pod:
                for name, _ in res_items:
                    seen.setdefault(name)
            resource_names = sorted(seen)
        index = {name: r for r, name in enumerate(resource_names)}
        rows: List[int] = []
        cols: List[int] = []
        vals: List[float] = []
        signature = np.zeros((len(pods), 1), dtype=np.uint64)
        for p, (sig_hash, res_items) in enumerate(per_pod):
            signature[p, 0] = sig_hash
            for name, quantity in res_items:
                col = index.get(name)
                if col is not None:
                    rows.append(p)
                    cols.append(col)
                    vals.append(quantity)
        requests = np.zeros((len(pods), len(resource_names)), dtype=np.float32)
        if rows:
            requests[np.asarray(rows), np.asarray(cols)] = np.asarray(
                vals, dtype=np.float32
            )
        return cls(
            n_pods=len(pods),
            requests=requests,
            resource_names=resource_names,
            signature=signature,
            pods=pods,
        )


@dataclass
class ColumnarClasses:
    class_ids: np.ndarray  # i64[P]
    n_classes: int
    counts: np.ndarray  # i64[C]
    requests: np.ndarray  # f32[C, R] per-pod request vector of each class


class _ClassSlot:
    """One equivalence class tracked incrementally: the derived class state is
    built once (at first sight of the shape) and reused every reconcile."""

    __slots__ = ("sig", "proto", "error", "pods", "live")

    def __init__(self, sig, proto, error) -> None:
        self.sig = sig  # the full class signature this slot deduplicates on
        self.proto = proto  # PodClass with derived state, empty pods list
        self.error = error  # KernelUnsupported captured at build time, if any
        self.pods: Dict[str, Pod] = {}  # uid -> pod (insertion-ordered)
        # registration state in PodIngest._slots, maintained at every mutation
        # point so the bulk path never re-hashes the (large) signature tuple
        # just to check whether the slot is still registered
        self.live = False


class PodIngest:
    """Incremental pod store: per-pod work happens once at add() time.

    The informer feeds pod add/remove events as they happen; ``classes()``
    then assembles the solver's PodClass list in O(distinct shapes) — the
    steady-state reconcile never re-scans the pod set.  Dedup is exact (full
    signature tuples as dict keys; the fast-key layer is a pure interning
    accelerator — equal fast keys imply equal signatures), so unlike
    hash-row grouping there is no collision risk.

    A shape the kernel doesn't model doesn't fail ingestion — the captured
    KernelUnsupported is raised at classes() time, when the solve is routed,
    so callers keep their host-path fallback semantics.
    """

    def __init__(self) -> None:
        self._slots: Dict[tuple, _ClassSlot] = {}
        self._by_uid: Dict[str, _ClassSlot] = {}
        # fast key -> slot: the bulk-path accelerator.  Entries may outlive
        # their slot's _slots registration (an emptied shape re-minting) —
        # _add_one revalidates against the live registry on every hit.
        self._fast: Dict[tuple, _ClassSlot] = {}
        # monotonic mutation counter: every effective add/remove bumps it, so
        # the versioned snapshot store (models.store) can stamp each encode
        # with the exact ingest state it saw and cheap-compare "anything
        # changed?" without walking the pod set
        self._version = 0

    @property
    def version(self) -> int:
        """Monotonic count of effective mutations (adds + removes)."""
        return self._version

    def class_members(self) -> Dict[tuple, tuple]:
        """signature -> (uid, ...) per live class, in insertion order — the
        equivalence-class bookkeeping the snapshot store's diff rides (no
        signature re-derivation, no per-pod hashing on the solve path)."""
        return {
            sig: tuple(slot.pods) for sig, slot in self._slots.items() if slot.pods
        }

    def get(self, uid: str):
        """The live Pod for ``uid`` (None when not tracked)."""
        slot = self._by_uid.get(uid)
        if slot is None:
            return None
        return slot.pods.get(uid)

    def __len__(self) -> int:
        return len(self._by_uid)

    def _drop(self, uid: str) -> None:
        """Unlink one tracked uid (no version bump — callers account it)."""
        slot = self._by_uid.pop(uid)
        slot.pods.pop(uid, None)
        if not slot.pods:
            del self._slots[slot.sig]
            slot.live = False

    def _add_one(self, pod: Pod) -> None:
        """One add with the fast-key accelerator: the full signature (and the
        ladder build) runs once per distinct shape; every subsequent member
        of the shape costs a few dict operations."""
        uid = pod.metadata.uid
        if uid in self._by_uid:
            # re-add replaces: same bookkeeping (and version arithmetic) as
            # a remove followed by an add
            self._drop(uid)
            self._version += 1
        fk = _fast_sig_key(pod)
        slot = None
        if fk is not None:
            slot = self._fast.get(fk)
            if slot is not None and not slot.live:
                slot = self._revive(fk, slot)
        if slot is None:
            slot = self._slot_for(pod, fk)
        slot.pods[uid] = pod
        self._by_uid[uid] = slot
        self._version += 1

    def _revive(self, fk, slot: _ClassSlot) -> _ClassSlot:
        """A fast-key hit on a slot no longer registered: either the emptied
        shape is returning (re-register it) or the shape was re-minted
        through the full-signature path while this entry idled (converge on
        the live slot).  Rare — only here does the signature get re-hashed."""
        live = self._slots.get(slot.sig)
        if live is None:
            self._slots[slot.sig] = slot
            slot.live = True
            return slot
        self._fast[fk] = live
        return live

    def _slot_for(self, pod: Pod, fk) -> _ClassSlot:
        from karpenter_core_tpu.models.snapshot import (
            KernelUnsupported,
            _class_signature,
            build_pod_ladder,
        )

        sig = _class_signature(pod)
        slot = self._slots.get(sig)
        if slot is None:
            proto, error = None, None
            try:
                proto = build_pod_ladder(pod)
            except KernelUnsupported as e:
                error = e
            slot = _ClassSlot(sig, proto, error)
            self._slots[sig] = slot
            slot.live = True
        if fk is not None:
            if len(self._fast) > max(_FAST_CACHE_FLOOR, 4 * len(self._slots)):
                # retired shapes must not accumulate (label churn mints fresh
                # fast keys forever); keep only entries backing live pods.
                # Pruned IN PLACE: add_all holds a bound `self._fast.get`
                # across the batch, so the dict object must stay the same.
                live = {k: s for k, s in self._fast.items() if s.pods}
                self._fast.clear()
                self._fast.update(live)
            self._fast[fk] = slot
        return slot

    def add(self, pod: Pod) -> None:
        self._add_one(pod)

    def add_all(self, pods: List[Pod]) -> None:
        """Bulk add — the trace/watch-stream ingest path.  Same final state
        (slots, members, version) as ``add`` in a loop; one tracing span for
        the whole batch, one version settlement, and the per-pod body is
        inlined dict work (the hot loop the ``per-pod-loop`` hygiene rule
        keeps honest — everything O(pods) about it is O(1) per pod)."""
        from karpenter_core_tpu import tracing

        with tracing.span("ingest", pods=len(pods)) as sp:
            by_uid = self._by_uid
            slots = self._slots
            fast_get = self._fast.get
            fast_key = _sig_key_impl()
            mutations = 0
            for pod in pods:
                uid = pod.metadata.uid
                if uid in by_uid:
                    self._drop(uid)
                    mutations += 1
                fk = fast_key(pod)
                slot = fast_get(fk) if fk is not None else None
                if slot is None:
                    slot = self._slot_for(pod, fk)
                elif not slot.live:
                    slot = self._revive(fk, slot)
                slot.pods[uid] = pod
                by_uid[uid] = slot
                mutations += 1
            self._version += mutations
            sp.set(classes=len(slots))

    def remove(self, uid: str) -> bool:
        if uid not in self._by_uid:
            return False
        # _drop also evicts emptied shapes from the registry: label churn
        # (e.g. pod-template-hash) mints fresh signatures forever, so retired
        # slots must not accumulate
        self._drop(uid)
        self._version += 1
        return True

    def pods(self) -> List[Pod]:
        return [p for slot in self._slots.values() for p in slot.pods.values()]

    def classes(self):
        """Solver-ready PodClass list (fresh list each call; derived state
        shared with the slot prototypes).  Raises the first captured
        KernelUnsupported so callers route the batch to the host path."""
        from dataclasses import replace

        from karpenter_core_tpu.models.snapshot import finalize_classes

        classes = []
        for slot in self._slots.values():
            if not slot.pods:
                continue
            if slot.error is not None:
                raise slot.error
            classes.append(replace(
                slot.proto, pods=list(slot.pods.values()),
                # the slot's signature rides along so the encode's reuse key
                # never re-derives it (models.snapshot._class_plane_key)
                interned_sig=slot.sig,
            ))
        return finalize_classes(classes)


def classify_columnar(batch: ColumnarPodBatch) -> ColumnarClasses:
    """Group the batch into equivalence classes through the native runtime
    (numpy fallback is batch ops too — no per-pod Python on either path)."""
    class_ids, n_classes = native.group_rows(batch.signature)
    totals, counts = native.class_totals(batch.requests, class_ids, n_classes)
    # per-pod request vector = class total / count (identical pods by definition)
    requests = totals / np.maximum(counts[:, None], 1)
    return ColumnarClasses(
        class_ids=class_ids, n_classes=n_classes, counts=counts, requests=requests
    )
