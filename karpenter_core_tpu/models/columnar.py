"""Columnar pod-batch ingestion: the wire-format fast path.

At production scale the solver sidecar receives cluster snapshots over a
binary channel (SURVEY.md §5.8), not as Python objects — pods arrive columnar:
a requests matrix plus integer-coded constraint columns.  Classification then
reduces to grouping identical signature rows, which runs through the native
runtime (models.native, C++) instead of per-object Python hashing.

``from_pods`` converts an object batch for benchmarking/tests; a gRPC/IPC
front-end would construct ColumnarPodBatch directly from the wire.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.models import native
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class ColumnarPodBatch:
    """Pods as columns.  ``signature`` carries one u64 row per pod: stable
    hashes of the pod's constraint content (requirements, tolerations,
    topology, labels) plus its quantized resource vector."""

    n_pods: int
    requests: np.ndarray  # f32[P, R]
    resource_names: List[str]
    signature: np.ndarray  # u64[P, W]
    pods: Optional[List[Pod]] = None  # object backing when converted

    @classmethod
    def from_pods(cls, pods: List[Pod], resource_names: Optional[List[str]] = None) -> "ColumnarPodBatch":
        from karpenter_core_tpu.models.snapshot import _class_signature

        if resource_names is None:
            seen: Dict[str, None] = {}
            for pod in pods:
                for name in resources_util.ceiling(pod):
                    seen.setdefault(name)
            resource_names = sorted(seen)
        requests = np.zeros((len(pods), len(resource_names)), dtype=np.float32)
        index = {name: r for r, name in enumerate(resource_names)}
        signature = np.zeros((len(pods), 1), dtype=np.uint64)
        for p, pod in enumerate(pods):
            for name, quantity in resources_util.ceiling(pod).items():
                requests[p, index[name]] = quantity
            signature[p, 0] = np.uint64(hash(_class_signature(pod)) & (2**64 - 1))
        return cls(
            n_pods=len(pods),
            requests=requests,
            resource_names=resource_names,
            signature=signature,
            pods=pods,
        )


@dataclass
class ColumnarClasses:
    class_ids: np.ndarray  # i64[P]
    n_classes: int
    counts: np.ndarray  # i64[C]
    requests: np.ndarray  # f32[C, R] per-pod request vector of each class


def classify_columnar(batch: ColumnarPodBatch) -> ColumnarClasses:
    """Group the batch into equivalence classes through the native runtime."""
    class_ids, n_classes = native.group_rows(batch.signature)
    totals, counts = native.class_totals(batch.requests, class_ids, n_classes)
    # per-pod request vector = class total / count (identical pods by definition)
    requests = totals / np.maximum(counts[:, None], 1)
    return ColumnarClasses(
        class_ids=class_ids, n_classes=n_classes, counts=counts, requests=requests
    )
