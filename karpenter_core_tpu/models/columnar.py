"""Columnar pod-batch ingestion: the steady-state fast path.

At production scale the per-pod work (signature derivation, requirements
construction) must happen once per pod *lifetime* — at watch-event time — not
once per reconcile.  Two front-ends feed the solver without per-pod work on
the solve path:

  - ``PodIngest``: the in-process incremental store.  ``add``/``remove``
    maintain exact signature→class-slot dedup as pods arrive from the
    informer; ``classes()`` assembles solver-ready PodClass lists in O(C).
    This is the analog of the reference maintaining cluster state across
    reconciles (state/cluster.go:152-196) rather than re-reading the world.
  - ``ColumnarPodBatch``: pods as columns (requests matrix + signature rows)
    for callers that arrive over a binary channel; classification reduces to
    grouping identical signature rows through the native runtime
    (models.native, C++) instead of per-object Python hashing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.models import native
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class ColumnarPodBatch:
    """Pods as columns.  ``signature`` carries one u64 row per pod: stable
    hashes of the pod's constraint content (requirements, tolerations,
    topology, labels) plus its quantized resource vector."""

    n_pods: int
    requests: np.ndarray  # f32[P, R]
    resource_names: List[str]
    signature: np.ndarray  # u64[P, W]
    pods: Optional[List[Pod]] = None  # object backing when converted

    @classmethod
    def from_pods(cls, pods: List[Pod], resource_names: Optional[List[str]] = None) -> "ColumnarPodBatch":
        from karpenter_core_tpu.models.snapshot import _class_signature

        if resource_names is None:
            seen: Dict[str, None] = {}
            for pod in pods:
                for name in resources_util.ceiling(pod):
                    seen.setdefault(name)
            resource_names = sorted(seen)
        requests = np.zeros((len(pods), len(resource_names)), dtype=np.float32)
        index = {name: r for r, name in enumerate(resource_names)}
        signature = np.zeros((len(pods), 1), dtype=np.uint64)
        for p, pod in enumerate(pods):
            for name, quantity in resources_util.ceiling(pod).items():
                requests[p, index[name]] = quantity
            signature[p, 0] = np.uint64(hash(_class_signature(pod)) & (2**64 - 1))
        return cls(
            n_pods=len(pods),
            requests=requests,
            resource_names=resource_names,
            signature=signature,
            pods=pods,
        )


@dataclass
class ColumnarClasses:
    class_ids: np.ndarray  # i64[P]
    n_classes: int
    counts: np.ndarray  # i64[C]
    requests: np.ndarray  # f32[C, R] per-pod request vector of each class


class _ClassSlot:
    """One equivalence class tracked incrementally: the derived class state is
    built once (at first sight of the shape) and reused every reconcile."""

    __slots__ = ("proto", "error", "pods")

    def __init__(self, proto, error) -> None:
        self.proto = proto  # PodClass with derived state, empty pods list
        self.error = error  # KernelUnsupported captured at build time, if any
        self.pods: Dict[str, Pod] = {}  # uid -> pod (insertion-ordered)


class PodIngest:
    """Incremental pod store: per-pod work happens once at add() time.

    The informer feeds pod add/remove events as they happen; ``classes()``
    then assembles the solver's PodClass list in O(distinct shapes) — the
    steady-state reconcile never re-scans the pod set.  Dedup is exact (full
    signature tuples as dict keys), so unlike hash-row grouping there is no
    collision risk.

    A shape the kernel doesn't model doesn't fail ingestion — the captured
    KernelUnsupported is raised at classes() time, when the solve is routed,
    so callers keep their host-path fallback semantics.
    """

    def __init__(self) -> None:
        self._slots: Dict[tuple, _ClassSlot] = {}
        self._by_uid: Dict[str, tuple] = {}
        # monotonic mutation counter: every effective add/remove bumps it, so
        # the versioned snapshot store (models.store) can stamp each encode
        # with the exact ingest state it saw and cheap-compare "anything
        # changed?" without walking the pod set
        self._version = 0

    @property
    def version(self) -> int:
        """Monotonic count of effective mutations (adds + removes)."""
        return self._version

    def class_members(self) -> Dict[tuple, tuple]:
        """signature -> (uid, ...) per live class, in insertion order — the
        equivalence-class bookkeeping the snapshot store's diff rides (no
        signature re-derivation, no per-pod hashing on the solve path)."""
        return {
            sig: tuple(slot.pods) for sig, slot in self._slots.items() if slot.pods
        }

    def get(self, uid: str):
        """The live Pod for ``uid`` (None when not tracked)."""
        sig = self._by_uid.get(uid)
        if sig is None:
            return None
        return self._slots[sig].pods.get(uid)

    def __len__(self) -> int:
        return len(self._by_uid)

    def add(self, pod: Pod) -> None:
        from karpenter_core_tpu.models.snapshot import (
            KernelUnsupported,
            _class_signature,
            build_pod_ladder,
        )

        if pod.uid in self._by_uid:
            self.remove(pod.uid)
        sig = _class_signature(pod)
        slot = self._slots.get(sig)
        if slot is None:
            proto, error = None, None
            try:
                proto = build_pod_ladder(pod)
            except KernelUnsupported as e:
                error = e
            slot = _ClassSlot(proto, error)
            self._slots[sig] = slot
        slot.pods[pod.uid] = pod
        self._by_uid[pod.uid] = sig
        self._version += 1

    def add_all(self, pods: List[Pod]) -> None:
        from karpenter_core_tpu import tracing

        with tracing.span("ingest", pods=len(pods)) as sp:
            for pod in pods:
                self.add(pod)
            sp.set(classes=len(self._slots))

    def remove(self, uid: str) -> bool:
        sig = self._by_uid.pop(uid, None)
        if sig is None:
            return False
        slot = self._slots[sig]
        slot.pods.pop(uid, None)
        if not slot.pods:
            # evict emptied shapes: label churn (e.g. pod-template-hash) mints
            # fresh signatures forever, so retired slots must not accumulate
            del self._slots[sig]
        self._version += 1
        return True

    def pods(self) -> List[Pod]:
        return [p for slot in self._slots.values() for p in slot.pods.values()]

    def classes(self):
        """Solver-ready PodClass list (fresh list each call; derived state
        shared with the slot prototypes).  Raises the first captured
        KernelUnsupported so callers route the batch to the host path."""
        from dataclasses import replace

        from karpenter_core_tpu.models.snapshot import finalize_classes

        classes = []
        for slot in self._slots.values():
            if not slot.pods:
                continue
            if slot.error is not None:
                raise slot.error
            classes.append(replace(slot.proto, pods=list(slot.pods.values())))
        return finalize_classes(classes)


def classify_columnar(batch: ColumnarPodBatch) -> ColumnarClasses:
    """Group the batch into equivalence classes through the native runtime."""
    class_ids, n_classes = native.group_rows(batch.signature)
    totals, counts = native.class_totals(batch.requests, class_ids, n_classes)
    # per-pod request vector = class total / count (identical pods by definition)
    requests = totals / np.maximum(counts[:, None], 1)
    return ColumnarClasses(
        class_ids=class_ids, n_classes=n_classes, counts=counts, requests=requests
    )
