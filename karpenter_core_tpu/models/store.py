"""Versioned, diffable snapshot store — the substrate of the incremental
warm-start solve path (docs/INCREMENTAL.md).

Every reconcile used to re-encode and re-solve the full cluster snapshot from
scratch; at steady-state churn rates only a handful of pods/nodes change
between ticks, so the right amortization is to version each encode and solve
only the diff.  Three pieces:

  - ``VersionedSnapshot``: one ``encode_snapshot`` output stamped with a
    monotonic version, per-plane content digests (sha256 over the encoded
    tensor bytes — process-independent), and the per-class membership rows
    the diff operates on.  The rows ride ``models.columnar.PodIngest``'s
    existing equivalence-class bookkeeping (``class_members``) — the diff
    never re-derives a pod signature.
  - ``SnapshotDelta``: the structured difference between two versions — new
    and evicted pod rows per class, new/removed classes, which supply-side
    planes changed, the unchanged class-index extents, and the delta
    fraction the fallback policy thresholds on.  ``apply`` replays a delta
    onto the older version's membership summary; ``diff`` then ``apply``
    reproducing the newer summary is the store's core invariant
    (tests/test_incremental.py).
  - ``SnapshotStore``: holds the current version, mints the next
    (``commit``), and diffs (``diff_snapshots``).

Supply-side change detection (``supply_digest``/``catalog_digest``) hashes
the solve INPUTS — state nodes, bound pods, catalog, provisioner templates —
not the encoded planes, because the whole point of a delta reconcile is to
skip the encode when nothing supply-side moved.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.models.snapshot import EncodedSnapshot, _class_signature

# plane groups digested independently, so a delta can name WHICH side moved
# (a catalog refresh invalidates different reuse than a pod-row change)
PLANE_FIELDS: Dict[str, Tuple[str, ...]] = {
    "catalog": (
        "it_mask", "it_defined", "it_negative", "it_gt", "it_lt",
        "it_alloc", "it_avail", "it_price", "it_capacity",
    ),
    "templates": (
        "tmpl_mask", "tmpl_defined", "tmpl_negative", "tmpl_gt", "tmpl_lt",
        "tmpl_zone", "tmpl_ct", "tmpl_it", "tmpl_daemon", "tmpl_limits",
    ),
    "vocab": ("valid", "is_custom", "vocab_ints"),
    "classes": (
        "cls_mask", "cls_defined", "cls_negative", "cls_gt", "cls_lt",
        "cls_zone", "cls_ct", "cls_it", "cls_requests", "cls_count",
        "cls_relax_next", "cls_anti_soft", "cls_root", "cls_tol", "cls_ports",
    ),
    "groups": ("grp_skew", "grp_is_zone", "grp_is_anti", "grp_member", "cls_groups"),
    # the policy-objective planes (policy.planes): the price sheet versions
    # independently of the feasibility catalog so a spot-market move (or a
    # risk/throughput prior change) is its own named escalation reason
    "policy": ("pol_price", "pol_risk", "pol_throughput"),
}


def _digest_arrays(arrays) -> str:
    """sha256 over dtype + shape + raw bytes of each array, in order.  Pure
    content — no id()s, no hash() — so two processes encoding the same input
    produce the same digest (PYTHONHASHSEED-independent)."""
    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"<none>")
            continue
        arr = np.ascontiguousarray(np.asarray(a))
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def snapshot_digests(
    snapshot: EncodedSnapshot,
    prev_snapshot: Optional[EncodedSnapshot] = None,
    prev_digests: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Per-plane content digests of one encoded snapshot, plus an ``axes``
    digest covering the name spaces the planes index into.

    ``prev_snapshot``/``prev_digests`` enable the delta-consuming commit: a
    plane group whose every array is the SAME OBJECT as in the previously
    digested snapshot reuses the previous digest instead of re-hashing the
    bytes.  The delta-native encode (models.snapshot ``encode_reused``)
    shares unchanged planes by reference exactly so this identity test
    fires; content digests stay content digests — identity is only ever a
    proof that the content cannot have changed (planes are immutable
    post-encode)."""
    out = {}
    for name, fields in PLANE_FIELDS.items():
        prev = prev_digests.get(name) if prev_digests else None
        if (
            prev is not None
            and prev_snapshot is not None
            and all(
                getattr(snapshot, f, None) is getattr(prev_snapshot, f, None)
                for f in fields
            )
        ):
            out[name] = prev
            continue
        out[name] = _digest_arrays(getattr(snapshot, f, None) for f in fields)
    h = hashlib.sha256()
    for axis in (
        snapshot.resources, snapshot.zones, snapshot.capacity_types,
        snapshot.it_names, [repr(p) for p in (snapshot.ports or [])],
    ):
        h.update(("\x1f".join(axis) + "\x1e").encode())
    h.update(repr(tuple(snapshot.features or ())).encode())
    h.update(str(snapshot.scan_passes).encode())
    out["axes"] = h.hexdigest()
    return out


def stable_canonical(obj):
    """A cross-process canonical form of a (possibly nested) python value:
    sets/frozensets are sorted, dicts are sorted item tuples, everything else
    passes through (or falls back to repr).  The point is PYTHONHASHSEED
    independence — class keys hold frozensets whose iteration (and repr)
    order is hash-randomized, so a digest of a raw repr would differ between
    two processes encoding identical state.  The durable-session journal
    (service/journal.py) verifies restored lineages against digests the
    crashed process wrote, so its verification digests must canonicalize
    through here."""
    if isinstance(obj, (frozenset, set)):
        return ("set", tuple(sorted((stable_canonical(v) for v in obj), key=repr)))
    if isinstance(obj, dict):
        return (
            "map",
            tuple(sorted(
                ((stable_canonical(k), stable_canonical(v)) for k, v in obj.items()),
                key=repr,
            )),
        )
    if isinstance(obj, (list, tuple)):
        return tuple(stable_canonical(v) for v in obj)
    if isinstance(obj, (str, int, float, bool, bytes, type(None))):
        return obj
    return repr(obj)


def stable_digest(obj) -> str:
    """sha256 over the stable canonical form — equal values digest equally in
    any process, whatever PYTHONHASHSEED says."""
    return hashlib.sha256(repr(stable_canonical(obj)).encode()).hexdigest()


def content_digest(chunks) -> str:
    """sha256 hex over an iterable of byte chunks — the whole-file content
    digest the fleet session checkpoints (fleet/checkpoint.py) stamp in their
    trailer frame and re-derive on read (never-trust: a checkpoint whose body
    doesn't hash to its trailer is treated as missing, the restore ladder
    falls to journal replay)."""
    h = hashlib.sha256()
    for chunk in chunks:
        h.update(chunk)
    return h.hexdigest()


def class_key(cls) -> tuple:
    """Version-stable identity of one class row: the equivalence-class
    signature of its representative pod (ladder variants carry the relaxed
    representative, so each rung keys distinctly).  Producers that already
    hold the signature stamp it on the class (``PodClass.interned_sig``,
    contract: equals the derivation exactly) so commits skip the O(C)
    re-derivation."""
    sig = getattr(cls, "interned_sig", None)
    if sig is not None:
        return sig
    return _class_signature(cls.pods[0])


@dataclass(frozen=True)
class ClassRow:
    """One class's membership at one version (roots carry the pod uids;
    ladder variants place rolled-over counts and own no pods)."""

    key: tuple
    count: int
    uids: Tuple[str, ...] = ()


@dataclass
class VersionedSnapshot:
    """One encode output + the version metadata the diff operates on."""

    version: int
    snapshot: EncodedSnapshot
    digests: Dict[str, str]
    rows: Tuple[ClassRow, ...]
    supply: str = ""  # supply_digest at encode time ("" = not tracked)

    def index_of(self) -> Dict[tuple, int]:
        return {row.key: i for i, row in enumerate(self.rows)}

    def summary(self) -> Dict[tuple, Tuple[str, ...]]:
        """class key -> member uids (the diff/apply state space)."""
        return {row.key: row.uids for row in self.rows}


@dataclass
class SnapshotDelta:
    """Structured difference between two snapshot versions."""

    from_version: int
    to_version: int
    # pod-row changes, keyed by class identity
    added: Dict[tuple, Tuple[str, ...]] = field(default_factory=dict)
    evicted: Dict[tuple, Tuple[str, ...]] = field(default_factory=dict)
    new_classes: Tuple[tuple, ...] = ()
    removed_classes: Tuple[tuple, ...] = ()
    # supply-side planes whose digests differ (catalog/templates/vocab/
    # groups/axes — plus "supply" when the node-side input digest moved)
    changed_planes: Tuple[str, ...] = ()
    # class-index extents (start, end) of the NEWER version whose rows are
    # untouched — the regions a masked repair never has to look at
    unchanged_extents: Tuple[Tuple[int, int], ...] = ()
    touched_classes: Tuple[int, ...] = ()
    # requirement-mask words touched by class changes: word count of the
    # packed cls_mask rows of touched classes (0 when only counts moved)
    touched_mask_words: int = 0
    pods_before: int = 0
    pods_after: int = 0

    @property
    def added_count(self) -> int:
        return sum(len(u) for u in self.added.values())

    @property
    def evicted_count(self) -> int:
        return sum(len(u) for u in self.evicted.values())

    @property
    def delta_fraction(self) -> float:
        """(added + evicted) over the larger population — the fallback
        policy's primary threshold."""
        base = max(self.pods_before, self.pods_after, 1)
        return (self.added_count + self.evicted_count) / base

    @property
    def node_side_changed(self) -> bool:
        return bool(self.changed_planes)

    @property
    def class_shape_changed(self) -> bool:
        """True when the class AXIS itself moved (new/removed classes) —
        tensor reuse is impossible and the repair must re-encode."""
        return bool(self.new_classes or self.removed_classes)

    def apply(self, prev_summary: Dict[tuple, Tuple[str, ...]]) -> Dict[tuple, Tuple[str, ...]]:
        """Replay this delta onto the older version's membership summary.
        ``diff_snapshots(prev, cur)`` then ``apply(prev.summary())`` must
        reproduce ``cur.summary()`` exactly (diff ∘ apply == identity)."""
        out = {key: list(uids) for key, uids in prev_summary.items()}
        for key in self.removed_classes:
            out.pop(key, None)
        for key in self.new_classes:
            out.setdefault(key, [])
        for key, uids in self.evicted.items():
            if key in out:
                gone = set(uids)
                out[key] = [u for u in out[key] if u not in gone]
        for key, uids in self.added.items():
            out.setdefault(key, []).extend(uids)
        return {
            key: tuple(uids) for key, uids in out.items()
            if uids or key in self.new_classes or key not in self.evicted
        }


def diff_members(
    prev_members: Dict[tuple, Tuple[str, ...]],
    cur_members: Dict[tuple, Tuple[str, ...]],
    from_version: int = 0,
    to_version: int = 0,
    supply_changed: Tuple[str, ...] = (),
) -> SnapshotDelta:
    """A SnapshotDelta from two membership maps alone — the NO-ENCODE diff a
    delta reconcile uses (class key -> member uids, straight off
    PodIngest.class_members or a prebuilt class list).  Plane-level fields
    (extents, mask words) stay empty: nothing was encoded to measure them;
    ``supply_changed`` carries the input-digest verdict instead."""
    added: Dict[tuple, Tuple[str, ...]] = {}
    evicted: Dict[tuple, Tuple[str, ...]] = {}
    new_classes = tuple(k for k in cur_members if k not in prev_members)
    removed_classes = tuple(k for k in prev_members if k not in cur_members)
    for key, uids in cur_members.items():
        before = set(prev_members.get(key, ()))
        now = set(uids)
        plus = tuple(u for u in uids if u not in before)
        minus = tuple(u for u in prev_members.get(key, ()) if u not in now)
        if plus:
            added[key] = plus
        if minus:
            evicted[key] = minus
    for key in removed_classes:
        if prev_members[key]:
            evicted[key] = prev_members[key]
    return SnapshotDelta(
        from_version=from_version,
        to_version=to_version or from_version + 1,
        added=added,
        evicted=evicted,
        new_classes=new_classes,
        removed_classes=removed_classes,
        changed_planes=tuple(supply_changed),
        pods_before=sum(len(u) for u in prev_members.values()),
        pods_after=sum(len(u) for u in cur_members.values()),
    )


def rows_from_snapshot(snapshot: EncodedSnapshot) -> Tuple[ClassRow, ...]:
    """Membership rows in class order.  Root classes carry their pod uids;
    ladder variants own no pods (counts roll into them in-kernel)."""
    rows: List[ClassRow] = []
    for cls in snapshot.classes:
        uids = () if cls.is_ladder_variant else tuple(p.uid for p in cls.pods)
        rows.append(ClassRow(key=class_key(cls), count=len(uids), uids=uids))
    return tuple(rows)


def diff_snapshots(prev: VersionedSnapshot, cur: VersionedSnapshot) -> SnapshotDelta:
    """The structured delta between two committed versions: the membership
    arithmetic delegated to ``diff_members`` (one implementation of the
    added/evicted/new/removed edge cases), plus the plane-level fields only
    committed versions can measure (digest verdicts, extents, mask words)."""
    delta = diff_members(
        prev.summary(), cur.summary(),
        from_version=prev.version, to_version=cur.version,
    )

    changed = tuple(
        name
        for name in ("catalog", "templates", "vocab", "groups", "axes", "policy")
        if prev.digests.get(name) != cur.digests.get(name)
    )
    if prev.supply != cur.supply:
        changed = changed + ("supply",)

    new_set = set(delta.new_classes)
    touched = tuple(
        i for i, row in enumerate(cur.rows)
        if row.key in delta.added or row.key in new_set or row.key in delta.evicted
    )
    touched_set = set(touched)
    extents: List[Tuple[int, int]] = []
    start: Optional[int] = None
    for i in range(len(cur.rows) + 1):
        clean = i < len(cur.rows) and i not in touched_set
        if clean and start is None:
            start = i
        elif not clean and start is not None:
            extents.append((start, i))
            start = None
    mask_words = 0
    cls_mask = getattr(cur.snapshot, "cls_mask", None)
    if cls_mask is not None and len(touched):
        row_words = int(np.prod(cls_mask.shape[1:])) if cls_mask.ndim > 1 else 0
        # bool planes pack 32 semantic slots per uint32 word in-kernel
        mask_words = len(touched) * -(-row_words // 32)
    delta.changed_planes = changed
    delta.unchanged_extents = tuple(extents)
    delta.touched_classes = touched
    delta.touched_mask_words = mask_words
    return delta


class SnapshotStore:
    """Holds the current snapshot version and mints successors.

    The store is deliberately small: versioning + diffing only.  The warm
    carry, padded tensors, and placement bookkeeping live in
    ``solver.incremental`` — they are solve-path state, not snapshot state.
    """

    def __init__(self) -> None:
        self._version = 0
        self.current: Optional[VersionedSnapshot] = None

    def seed_version(self, version: int) -> None:
        """Pre-position the version counter so the NEXT commit mints
        ``version + 1``.  Journal replay (service/journal.py) uses this to
        restore a recovered lineage at the exact version the crashed process
        last echoed to its client — without it, a replayed anchor would mint
        version 1 and every client claiming the true version would be forced
        into a spurious ``session-lost`` re-anchor.  Only valid on a store
        that has never committed (replay always starts from a fresh
        session)."""
        if self.current is not None:
            raise RuntimeError("seed_version is only valid before the first commit")
        self._version = max(int(version), 0)

    def commit(self, snapshot: EncodedSnapshot, supply: str = "") -> VersionedSnapshot:
        """Stamp one encode output as the next version and make it current.

        Consumes the delta-native encode's reuse: plane groups the encode
        shared by reference from the previous committed snapshot keep their
        digests without re-hashing a byte — on a steady-state churn tick only
        the ``classes`` group (whose cls_count moved) and the recomputed
        ``policy`` planes touch the hasher, so the commit cost scales with
        what changed, not with the fleet."""
        self._version += 1
        prev = self.current
        versioned = VersionedSnapshot(
            version=self._version,
            snapshot=snapshot,
            digests=snapshot_digests(
                snapshot,
                prev_snapshot=prev.snapshot if prev is not None else None,
                prev_digests=prev.digests if prev is not None else None,
            ),
            rows=rows_from_snapshot(snapshot),
            supply=supply,
        )
        self.current = versioned
        return versioned

    def diff(self, cur: VersionedSnapshot) -> Optional[SnapshotDelta]:
        """Delta from the current version to ``cur`` (None when no current)."""
        if self.current is None or cur is self.current:
            return None
        return diff_snapshots(self.current, cur)


def supply_digest(state_nodes, bound_pods) -> str:
    """Content digest of the solve's supply side INPUTS: state nodes (labels,
    available capacity, taints, volume limits/usage) and the bound pods whose
    membership seeds topology counts.  Computed without encoding anything —
    the delta path's whole point is skipping the encode when this is stable.
    O(nodes + bound pods) python, small constants."""
    h = hashlib.sha256()
    for sn in state_nodes or []:
        node = sn.node
        h.update(node.name.encode())
        h.update(repr(sorted(node.metadata.labels.items())).encode())
        h.update(repr(sorted(sn.available().items())).encode())
        h.update(repr(sorted(
            (t.key, t.value, t.effect) for t in sn.taints()
        )).encode())
        h.update(b"1" if sn.initialized() else b"0")
        h.update(repr(sorted(sn.volume_limits().items())).encode())
        h.update(repr(sorted(
            (d, tuple(sorted(ids))) for d, ids in sn.volume_usage().volumes.items()
        )).encode())
        h.update(b"\x1e")
    for pod in bound_pods or []:
        h.update((pod.uid or "").encode())
        h.update((pod.spec.node_name or "").encode())
        h.update((pod.namespace or "").encode())
        h.update(repr(sorted(pod.metadata.labels.items())).encode())
        h.update(b"\x1e")
    return h.hexdigest()


def catalog_digest(provisioners, instance_types) -> str:
    """Content digest of the provisioner/catalog inputs (the template plane's
    upstream).  Provisioner specs are covered via resourceVersion/generation
    plus the weight order; the catalog via names, capacity, and offerings."""
    h = hashlib.sha256()
    for p in provisioners or []:
        h.update(p.name.encode())
        h.update(str(p.metadata.resource_version or "").encode())
        h.update(str(getattr(p.metadata, "generation", "") or "").encode())
        h.update(str(getattr(p.spec, "weight", 0) or 0).encode())
        h.update(b"\x1e")
    for name in sorted(instance_types or {}):
        h.update(name.encode())
        for it in instance_types[name]:
            h.update(it.name.encode())
            h.update(repr(sorted(it.capacity.items())).encode())
            h.update(repr(sorted(
                (o.zone, o.capacity_type, o.available, o.price)
                for o in it.offerings
            )).encode())
        h.update(b"\x1e")
    return h.hexdigest()
