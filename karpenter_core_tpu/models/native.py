"""ctypes bridge to the native runtime kernels (native/kc_runtime.cc).

Builds the shared library on first use (g++ via the checked-in Makefile) and
caches it; falls back to numpy when no toolchain is available.  Used by the
columnar ingestion path (models.columnar) for pod-class grouping at 50k-pod
scale.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libkc_runtime.so")
_lock = threading.Lock()
_lib: "Optional[ctypes.CDLL]" = None
_build_failed = False
# set while one thread runs the (up to 120 s) g++ build outside the lock;
# latecomers wait on it instead of serializing behind a held mutex
# (kcanalyze lock-order: blocking-under-lock)
_in_flight: "Optional[threading.Event]" = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed, _in_flight
    while True:
        with _lock:
            if _lib is not None or _build_failed:
                return _lib
            building = _in_flight
            if building is None:
                building = _in_flight = threading.Event()
                break  # this thread builds
        building.wait(timeout=180.0)
    lib = None
    try:
        lib = _build_and_load()
    finally:
        with _lock:
            if lib is None:
                _build_failed = True
            else:
                _lib = lib
            _in_flight = None
        building.set()
    return lib


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and dlopen the library.  Runs with NO lock held —
    the subprocess can take up to 120 s and must not stall other threads;
    the caller holds the in-flight slot, so the build is still run once."""
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception as e:  # noqa: BLE001 - fall back to numpy
            log.warning("native runtime build failed, using numpy fallback: %s", e)
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        log.warning("native runtime load failed, using numpy fallback: %s", e)
        return None
    lib.kc_group_rows.restype = ctypes.c_int64
    lib.kc_group_rows.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.kc_class_totals.restype = ctypes.c_int64
    lib.kc_class_totals.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64),
    ]
    return lib


def available() -> bool:
    return _load() is not None


def group_rows(matrix: np.ndarray) -> Tuple[np.ndarray, int]:
    """(class_ids i64[n], n_classes): group identical rows of a u64 matrix,
    classes numbered in first-seen order."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint64)
    n, w = matrix.shape
    lib = _load()
    if lib is not None:
        out = np.empty(n, dtype=np.int64)
        n_classes = lib.kc_group_rows(
            matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n,
            w,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if n_classes >= 0:
            return out, int(n_classes)
        log.warning("kc_group_rows returned %d, using numpy fallback", n_classes)
    # numpy fallback: unique rows, remapped to first-seen order
    _, first_idx, inverse = np.unique(
        matrix, axis=0, return_index=True, return_inverse=True
    )
    order = np.argsort(np.argsort(first_idx))
    return order[inverse].astype(np.int64), len(first_idx)


def class_totals(
    matrix: np.ndarray, class_ids: np.ndarray, n_classes: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(totals f32[n_classes, w], counts i64[n_classes]): per-class row sums."""
    matrix = np.ascontiguousarray(matrix, dtype=np.float32)
    class_ids = np.ascontiguousarray(class_ids, dtype=np.int64)
    n, w = matrix.shape
    lib = _load()
    if lib is not None:
        out = np.zeros((n_classes, w), dtype=np.float32)
        counts = np.zeros(n_classes, dtype=np.int64)
        rc = lib.kc_class_totals(
            matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            class_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n,
            w,
            n_classes,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc == 0:
            return out, counts
        log.warning("kc_class_totals returned %d, using numpy fallback", rc)
    out = np.zeros((n_classes, w), dtype=np.float32)
    np.add.at(out, class_ids, matrix)
    counts = np.bincount(class_ids, minlength=n_classes).astype(np.int64)
    return out, counts
