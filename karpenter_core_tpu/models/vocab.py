"""Value vocabulary: the finite universe behind the mask encoding.

At snapshot-encode time the universe of values per label key is finite — it is
the union of values carried by instance types, provisioners, existing nodes, and
the pod batch.  Each key gets a dense value index; every Requirements set then
encodes as boolean masks over [K, V+1], the final slot meaning "any value not in
the vocabulary" (see karpenter_core_tpu.ops.masks).

Structural keys (hostname, instance-type, zone, capacity-type) are excluded
from the general mask axes by the snapshot encoder — they are handled
structurally (node identity, viability vectors, zone/capacity axes), which
keeps mask state small at 50k-node scale.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.scheduling import Requirement, Requirements

STRUCTURAL_KEYS = (
    labels_api.LABEL_HOSTNAME,
    labels_api.LABEL_INSTANCE_TYPE_STABLE,
    labels_api.LABEL_TOPOLOGY_ZONE,
    labels_api.LABEL_CAPACITY_TYPE,
)


@dataclass
class Vocabulary:
    keys: List[str]
    values: Dict[str, List[str]]
    key_index: Dict[str, int] = field(default_factory=dict)
    value_index: Dict[str, Dict[str, int]] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.key_index = {k: i for i, k in enumerate(self.keys)}
        self.value_index = {
            k: {v: i for i, v in enumerate(vals)} for k, vals in self.values.items()
        }

    @property
    def n_keys(self) -> int:
        return len(self.keys)

    @property
    def vmax(self) -> int:
        return max((len(v) for v in self.values.values()), default=0)

    @property
    def width(self) -> int:
        """V+1: mask width including the 'other' slot."""
        return self.vmax + 1

    def valid_mask(self) -> np.ndarray:
        """bool[K, V+1]: which slots are real values per key (other slot on)."""
        out = np.zeros((self.n_keys, self.width), dtype=bool)
        for k, key in enumerate(self.keys):
            out[k, : len(self.values[key])] = True
            out[k, -1] = True
        return out

    def is_custom(self) -> np.ndarray:
        """bool[K]: keys subject to the denied-if-undefined rule
        (requirements.go:125)."""
        return np.array(
            [k not in labels_api.WELL_KNOWN_LABELS for k in self.keys], dtype=bool
        )

    @classmethod
    def build(
        cls,
        requirement_sets: Iterable[Requirements],
        exclude_keys: Tuple[str, ...] = STRUCTURAL_KEYS,
        supply_sets: Iterable[Requirements] = (),
    ) -> "Vocabulary":
        """``requirement_sets`` are demand-side (pods/classes, provisioner
        templates): they define which keys exist.  ``supply_sets`` are
        supply-side (instance-type requirements, existing-node labels): they
        only widen the value lists of keys the demand side already references.

        A key no demand-side set defines can never deny compatibility — the
        reference's denial paths (empty intersection, or the custom-key
        denied-if-undefined rule, requirements.go:115-131) both require the
        pod/template side to carry the key — so admitting supply-only keys
        would spend mask width (and kernel compute, which is quadratic in the
        widest key) on planes whose checks are vacuously true.  The fake
        catalog's per-instance ``integer`` label is the canonical offender:
        1000 instance types otherwise cost a [*, K, 1001] mask encoding."""

        def widen(bucket: Dict[str, None], r: Requirement) -> None:
            for v in r.values:
                bucket.setdefault(v, None)
            # materialize small finite Gt/Lt ranges so bounded-integer
            # requirements stay exact under the mask encoding
            if r.greater_than is not None and r.less_than is not None:
                lo, hi = r.greater_than + 1, r.less_than
                if 0 < hi - lo <= 64:
                    for i in range(lo, hi):
                        bucket.setdefault(str(i), None)

        values: Dict[str, Dict[str, None]] = {}
        for reqs in requirement_sets:
            for key in reqs.keys():
                if key in exclude_keys:
                    continue
                widen(values.setdefault(key, {}), reqs.get(key))
        for reqs in supply_sets:
            for key in reqs.keys():
                if key in values:
                    widen(values[key], reqs.get(key))
        keys = sorted(values)
        return cls(keys=keys, values={k: list(v) for k, v in values.items()})

    # -- encoding -------------------------------------------------------------

    def ints_table(self) -> np.ndarray:
        """f32[K, Vmax]: vocabulary values as numbers, +inf where non-numeric
        or padding — the kernel counts these inside Gt/Lt ranges when deciding
        unseen-value overlap (ops.masks._unseen_overlap)."""
        out = np.full((self.n_keys, self.vmax), np.inf, dtype=np.float32)
        for k, key in enumerate(self.keys):
            for i, v in enumerate(self.values[key]):
                try:
                    out[k, i] = float(int(v))
                except ValueError:
                    pass
        return out

    def _key_numbers(self, key: str) -> np.ndarray:
        """f32[V_k]: numeric values per key (+inf non-numeric), cached."""
        cache = getattr(self, "_num_cache", None)
        if cache is None:
            cache = self._num_cache = {}
        out = cache.get(key)
        if out is None:
            values = self.values[key]
            out = np.full(len(values), np.inf, dtype=np.float64)
            for i, v in enumerate(values):
                try:
                    out[i] = float(int(v))
                except ValueError:
                    pass
            cache[key] = out
        return out

    def encode_requirement(self, r: Requirement) -> Tuple[np.ndarray, bool, float, float]:
        """(mask row bool[V+1], negative, gt, lt) for one requirement of a
        known key.  The other-slot is the complement bit; Gt/Lt bounds are
        returned separately (±inf when absent) for exact range math in-kernel.

        O(|values|) for the common In/NotIn case instead of O(V) — vocabulary
        value counts reach the instance-catalog size (e.g. 1k integer labels).
        """
        key = r.key
        index = self.value_index[key]
        n_values = len(index)
        row = np.zeros(self.width, dtype=bool)
        gt = float(r.greater_than) if r.greater_than is not None else -np.inf
        lt = float(r.less_than) if r.less_than is not None else np.inf
        if r.complement:
            row[:n_values] = True
            for v in r.values:
                idx = index.get(v)
                if idx is not None:
                    row[idx] = False
            row[-1] = True
        else:
            for v in r.values:
                idx = index.get(v)
                if idx is not None:
                    row[idx] = True
        if r.greater_than is not None or r.less_than is not None:
            nums = self._key_numbers(key)
            row[:n_values] &= (nums > gt) & (nums < lt)
        negative = r.operator() in ("NotIn", "DoesNotExist")
        return row, negative, gt, lt

    def encode_requirements(
        self, reqs: Requirements
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """(mask[K, V+1], defined[K], negative[K], gt[K], lt[K]) — undefined
        keys read as Exists (all slots allowed), per requirements.go:114-120."""
        mask = self.valid_mask().copy()
        defined = np.zeros(self.n_keys, dtype=bool)
        negative = np.zeros(self.n_keys, dtype=bool)
        gt = np.full(self.n_keys, -np.inf, dtype=np.float32)
        lt = np.full(self.n_keys, np.inf, dtype=np.float32)
        for k, key in enumerate(self.keys):
            if not reqs.has(key):
                continue
            row, neg, g, l = self.encode_requirement(reqs.get(key))
            mask[k] = row
            defined[k] = True
            negative[k] = neg
            gt[k] = g
            lt[k] = l
        return mask, defined, negative, gt, lt


def encode_value_set(requirement: Optional[Requirement], universe: List[str]) -> np.ndarray:
    """bool[len(universe)]: which universe values a requirement allows (None =
    all).  Used for the structural zone/capacity-type/instance-type axes."""
    if requirement is None:
        return np.ones(len(universe), dtype=bool)
    return np.array([requirement.has(v) for v in universe], dtype=bool)


def encode_value_sets(
    requirements: List[Optional[Requirement]], universe: List[str]
) -> np.ndarray:
    """bool[N, len(universe)]: ``encode_value_set`` batched over a requirement
    list through ONE interned universe index.  Plain In requirements (no
    complement, no numeric bounds — the overwhelmingly common case) fill by
    value-index lookup in O(|values|) instead of a ``has`` call per universe
    value, which matters when the universe is the instance-type catalog
    (thousands of names per class row).  Bit-identical to the scalar path
    (tests/test_encode_delta.py fuzzes the equivalence)."""
    n_universe = len(universe)
    out = np.ones((len(requirements), n_universe), dtype=bool)
    index: Optional[Dict[str, int]] = None
    for i, req in enumerate(requirements):
        if req is None:
            continue
        if (
            not req.complement
            and req.greater_than is None
            and req.less_than is None
        ):
            if index is None:
                index = {v: j for j, v in enumerate(universe)}
            row = np.zeros(n_universe, dtype=bool)
            for v in req.values:
                j = index.get(v)
                if j is not None:
                    row[j] = True
            out[i] = row
        else:
            # complement/bounded operators keep the exact scalar semantics
            out[i] = encode_value_set(req, universe)
    return out
