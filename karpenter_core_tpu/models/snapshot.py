"""Dense cluster-snapshot encoding for the TPU solve kernel.

Encodes the solver's inputs (SURVEY.md §7 step 2) into numpy tensors:

  - instance types: general-key requirement masks, allocatable vectors, and
    offering availability/price over the zone × capacity-type axes
  - machine templates (per provisioner, weight-ordered): requirement masks,
    structural-axis masks, daemonset overhead, taints (pre-evaluated against
    pod classes)
  - pod *classes*: pods deduplicated by (requirements, requests, tolerations,
    topology spec) — the kernel's scan runs over classes, not pods, which is
    what makes 50k-pod solves tractable: cost scales with distinct pod shapes

Structural keys (hostname / instance-type / zone / capacity-type) are encoded
as dedicated axes rather than general masks (models.vocab.STRUCTURAL_KEYS).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.cloudprovider import InstanceType
from karpenter_core_tpu.models.vocab import Vocabulary, encode_value_set
from karpenter_core_tpu.scheduling import Requirements, Taints
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import resources as resources_util

UNLIMITED = np.int32(1 << 30)


@dataclass
class PodClass:
    """One equivalence class of identical pods."""

    pods: List[Pod]
    requirements: Requirements
    requests: resources_util.ResourceList
    # topology spec (self-selecting groups only; cross-class groups take the
    # host path — see encode_pods)
    zone_spread_skew: Optional[int] = None
    host_spread_skew: Optional[int] = None
    zone_anti_affinity: bool = False
    host_anti_affinity: bool = False
    zone_affinity: bool = False  # self-affinity: colocate the class in one zone
    host_affinity: bool = False  # self-affinity: colocate the class on one node

    @property
    def count(self) -> int:
        return len(self.pods)


@dataclass
class EncodedSnapshot:
    vocab: Vocabulary
    resources: List[str]  # R axis
    zones: List[str]  # Z axis
    capacity_types: List[str]  # CT axis
    it_names: List[str]  # I axis
    classes: List[PodClass]  # C axis (solve order: FFD cpu/mem descending)

    # instance types [I, ...]
    it_mask: np.ndarray = None
    it_defined: np.ndarray = None
    it_negative: np.ndarray = None
    it_gt: np.ndarray = None
    it_lt: np.ndarray = None
    it_alloc: np.ndarray = None  # f32[I, R]
    it_avail: np.ndarray = None  # bool[I, Z, CT] offering available
    it_price: np.ndarray = None  # f32[I, Z, CT] (+inf unavailable)

    # templates [T, ...] (weight-ordered)
    tmpl_mask: np.ndarray = None
    tmpl_defined: np.ndarray = None
    tmpl_negative: np.ndarray = None
    tmpl_gt: np.ndarray = None
    tmpl_lt: np.ndarray = None
    tmpl_zone: np.ndarray = None  # bool[T, Z]
    tmpl_ct: np.ndarray = None  # bool[T, CT]
    tmpl_it: np.ndarray = None  # bool[T, I] catalog membership ∧ it-name reqs
    tmpl_daemon: np.ndarray = None  # f32[T, R]

    # pod classes [C, ...]
    cls_mask: np.ndarray = None
    cls_defined: np.ndarray = None
    cls_negative: np.ndarray = None
    cls_gt: np.ndarray = None
    cls_lt: np.ndarray = None
    cls_zone: np.ndarray = None  # bool[C, Z]
    cls_ct: np.ndarray = None  # bool[C, CT]
    cls_it: np.ndarray = None  # bool[C, I]
    cls_requests: np.ndarray = None  # f32[C, R]
    cls_count: np.ndarray = None  # i32[C]
    cls_tol: np.ndarray = None  # bool[C, T] tolerates template taints
    cls_zone_cap: np.ndarray = None  # i32[C] max added pods per zone (anti-aff=1)
    cls_zone_skew: np.ndarray = None  # i32[C] spread skew (UNLIMITED = none)
    cls_host_cap: np.ndarray = None  # i32[C] max pods per node
    cls_zone_count0: np.ndarray = None  # i32[C, Z] pre-existing group counts
    cls_zone_aff: np.ndarray = None  # bool[C] self-affinity on zone
    cls_host_aff: np.ndarray = None  # bool[C] self-affinity on hostname

    # vocabulary statics
    valid: np.ndarray = None  # bool[K, V+1]
    is_custom: np.ndarray = None  # bool[K]
    vocab_ints: np.ndarray = None  # f32[K, V]


def _class_signature(pod: Pod) -> tuple:
    """Equivalence key computed from the raw spec — cheap enough to run per pod
    at 50k scale; Requirements construction happens once per class."""
    selector_sig = tuple(sorted(pod.spec.node_selector.items()))
    affinity_req_sig = ()
    if pod.spec.affinity is not None and pod.spec.affinity.node_affinity is not None:
        na = pod.spec.affinity.node_affinity
        req_terms = (
            tuple(
                tuple(
                    (e.key, e.operator, tuple(e.values))
                    for e in term.match_expressions
                )
                for term in na.required.node_selector_terms
            )
            if na.required is not None
            else ()
        )
        pref_terms = tuple(
            (
                p.weight,
                tuple((e.key, e.operator, tuple(e.values)) for e in p.preference.match_expressions),
            )
            for p in na.preferred
        )
        affinity_req_sig = (req_terms, pref_terms)
    req_sig = (selector_sig, affinity_req_sig)
    # fast path for the dominant shape: one plain container, no limits/init
    spec = pod.spec
    if len(spec.containers) == 1 and not spec.init_containers and not spec.containers[0].resources.limits:
        req_vec = tuple(sorted(spec.containers[0].resources.requests.items()))
    else:
        requests = resources_util.ceiling(pod)
        req_vec = tuple(sorted((k, round(v, 9)) for k, v in requests.items()))
    tol_sig = tuple(
        sorted((t.key, t.operator, t.value, t.effect) for t in pod.spec.tolerations)
    )
    spread_sig = tuple(
        sorted(
            (
                c.topology_key,
                c.max_skew,
                c.when_unsatisfiable,
                _selector_sig(c.label_selector),
            )
            for c in pod.spec.topology_spread_constraints
        )
    )
    affinity_sig = ()
    if pod.spec.affinity is not None:
        aff = pod.spec.affinity
        terms = []
        if aff.pod_affinity is not None:
            for t in aff.pod_affinity.required:
                terms.append(("aff", t.topology_key, _selector_sig(t.label_selector)))
        if aff.pod_anti_affinity is not None:
            for t in aff.pod_anti_affinity.required:
                terms.append(("anti", t.topology_key, _selector_sig(t.label_selector)))
        affinity_sig = tuple(sorted(terms))
    labels_sig = tuple(sorted(pod.metadata.labels.items()))
    ports_sig = tuple(
        sorted(
            (p.host_port, p.protocol, p.host_ip)
            for c in pod.spec.containers
            for p in c.ports
            if p.host_port
        )
    )
    return (req_sig, req_vec, tol_sig, spread_sig, affinity_sig, labels_sig, ports_sig)


def _selector_sig(selector) -> tuple:
    if selector is None:
        return ()
    return (
        tuple(sorted(selector.match_labels.items())),
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in selector.match_expressions
            )
        ),
    )


def _self_selecting(pod: Pod, selector) -> bool:
    return selector is not None and selector.matches(pod.metadata.labels)


class KernelUnsupported(Exception):
    """The batch uses a feature the tensor kernel does not cover; callers fall
    back to the host solver (solver.scheduler.Scheduler)."""


def classify_pods(pods: List[Pod]) -> List[PodClass]:
    """Group pods into equivalence classes and derive each class's topology
    spec.  Raises KernelUnsupported for shapes the kernel doesn't model:
    cross-class selectors, non-self-selecting affinity, host ports, region/
    custom-key spreads."""
    groups: Dict[tuple, PodClass] = {}
    order: List[tuple] = []
    for pod in pods:
        sig = _class_signature(pod)
        cls = groups.get(sig)
        if cls is None:
            cls = PodClass(
                pods=[],
                requirements=Requirements.from_pod(pod),
                requests=resources_util.ceiling(pod),
            )
            _derive_topology_spec(pod, cls)
            groups[sig] = cls
            order.append(sig)
        cls.pods.append(pod)

    classes = [groups[sig] for sig in order]

    # the kernel counts topology per class (group == class); a selector that
    # also matches ANOTHER class's pods couples the groups and needs the host
    # path's shared-group counting
    for cls in classes:
        selectors = _constraint_selectors(cls.pods[0])
        if not selectors:
            continue
        for other in classes:
            if other is cls:
                continue
            other_labels = other.pods[0].metadata.labels
            if any(s.matches(other_labels) for s in selectors):
                raise KernelUnsupported(
                    "topology selector spans multiple pod classes"
                )

    # FFD: cpu desc, then memory desc (queue.go:74-110)
    classes.sort(
        key=lambda c: (
            -c.requests.get(resources_util.CPU, 0.0),
            -c.requests.get(resources_util.MEMORY, 0.0),
        )
    )
    return classes


def _constraint_selectors(pod: Pod) -> List[LabelSelector]:
    selectors = []
    for constraint in pod.spec.topology_spread_constraints:
        if constraint.when_unsatisfiable == "DoNotSchedule" and constraint.label_selector:
            selectors.append(constraint.label_selector)
    if pod.spec.affinity is not None:
        for group in (pod.spec.affinity.pod_affinity, pod.spec.affinity.pod_anti_affinity):
            if group is not None:
                for term in group.required:
                    if term.label_selector is not None:
                        selectors.append(term.label_selector)
    return selectors


def _derive_topology_spec(pod: Pod, cls: PodClass) -> None:
    for constraint in pod.spec.topology_spread_constraints:
        if constraint.when_unsatisfiable != "DoNotSchedule":
            continue  # ScheduleAnyway spreads relax away on failure
        if not _self_selecting(pod, constraint.label_selector):
            raise KernelUnsupported("spread selector not self-selecting")
        if constraint.topology_key == labels_api.LABEL_TOPOLOGY_ZONE:
            cls.zone_spread_skew = constraint.max_skew
        elif constraint.topology_key == labels_api.LABEL_HOSTNAME:
            cls.host_spread_skew = constraint.max_skew
        else:
            raise KernelUnsupported(
                f"spread on {constraint.topology_key} not kernel-supported"
            )
    affinity = pod.spec.affinity
    if affinity is not None:
        if affinity.pod_affinity is not None:
            for term in affinity.pod_affinity.required:
                # only *self*-affinity is kernel-supported: the group colocates
                # with itself (the dominant benchmark shape); affinity to other
                # groups needs the host path's cross-group resolution
                if not _self_selecting(pod, term.label_selector):
                    raise KernelUnsupported("pod affinity selector not self-selecting")
                if term.topology_key == labels_api.LABEL_TOPOLOGY_ZONE:
                    cls.zone_affinity = True
                elif term.topology_key == labels_api.LABEL_HOSTNAME:
                    cls.host_affinity = True
                else:
                    raise KernelUnsupported(
                        f"pod affinity on {term.topology_key} not kernel-supported"
                    )
        if affinity.pod_anti_affinity is not None:
            for term in affinity.pod_anti_affinity.required:
                if not _self_selecting(pod, term.label_selector):
                    raise KernelUnsupported("anti-affinity selector not self-selecting")
                if term.topology_key == labels_api.LABEL_HOSTNAME:
                    cls.host_anti_affinity = True
                elif term.topology_key == labels_api.LABEL_TOPOLOGY_ZONE:
                    cls.zone_anti_affinity = True
                else:
                    raise KernelUnsupported(
                        f"anti-affinity on {term.topology_key} not kernel-supported"
                    )
    for container in pod.spec.containers:
        if any(p.host_port for p in container.ports):
            raise KernelUnsupported("host ports not kernel-supported")
    if cls.zone_affinity and cls.zone_spread_skew is not None:
        raise KernelUnsupported("combined zone spread + zone affinity not kernel-supported")
    if cls.zone_affinity and cls.zone_anti_affinity:
        raise KernelUnsupported("combined zone affinity + anti-affinity not kernel-supported")
    if cls.host_affinity and (cls.host_spread_skew is not None or cls.host_anti_affinity):
        raise KernelUnsupported("combined hostname affinity + spread/anti not kernel-supported")


def encode_snapshot(
    pods: List[Pod],
    provisioners: List[Provisioner],
    templates: List[MachineTemplate],
    instance_types: Dict[str, List[InstanceType]],
    extra_requirement_sets: Optional[List[Requirements]] = None,
) -> EncodedSnapshot:
    """Encode a solve input.  ``templates`` must be weight-ordered (the order
    is the kernel's template preference order, scheduler.go:174-219).
    ``extra_requirement_sets`` widen the vocabulary (e.g. existing-node label
    values, which must be representable for NotIn semantics to stay exact)."""
    classes = classify_pods(pods)

    # -- axes -----------------------------------------------------------------
    all_its: List[InstanceType] = []
    it_index: Dict[str, int] = {}
    for tmpl in templates:
        for it in instance_types.get(tmpl.provisioner_name, []):
            if it.name not in it_index:
                it_index[it.name] = len(all_its)
                all_its.append(it)
    it_names = [it.name for it in all_its]

    zones: List[str] = []
    capacity_types: List[str] = []
    for it in all_its:
        for off in it.offerings:
            if off.zone not in zones:
                zones.append(off.zone)
            if off.capacity_type not in capacity_types:
                capacity_types.append(off.capacity_type)
    zones = sorted(zones)
    capacity_types = sorted(capacity_types)

    resources: List[str] = [resources_util.CPU, resources_util.MEMORY, resources_util.PODS]
    for cls in classes:
        for name in cls.requests:
            if name not in resources:
                resources.append(name)
    for it in all_its:
        for name in it.capacity:
            if name not in resources:
                resources.append(name)

    # -- vocabulary -----------------------------------------------------------
    req_sets = [cls.requirements for cls in classes]
    req_sets += [it.requirements for it in all_its]
    req_sets += [tmpl.requirements for tmpl in templates]
    req_sets += list(extra_requirement_sets or [])
    vocab = Vocabulary.build(req_sets)

    snap = EncodedSnapshot(
        vocab=vocab,
        resources=resources,
        zones=zones,
        capacity_types=capacity_types,
        it_names=it_names,
        classes=classes,
    )
    snap.valid = vocab.valid_mask()
    snap.is_custom = vocab.is_custom()
    snap.vocab_ints = vocab.ints_table()

    # -- instance types -------------------------------------------------------
    I, Z, CT, R = len(all_its), len(zones), len(capacity_types), len(resources)
    snap.it_alloc = np.zeros((I, R), dtype=np.float32)
    snap.it_avail = np.zeros((I, Z, CT), dtype=bool)
    snap.it_price = np.full((I, Z, CT), np.inf, dtype=np.float32)
    it_planes = [vocab.encode_requirements(it.requirements) for it in all_its]
    snap.it_mask, snap.it_defined, snap.it_negative, snap.it_gt, snap.it_lt = (
        np.stack([p[j] for p in it_planes]) for j in range(5)
    )
    zone_idx = {z: i for i, z in enumerate(zones)}
    ct_idx = {c: i for i, c in enumerate(capacity_types)}
    for i, it in enumerate(all_its):
        alloc = it.allocatable()
        for r, name in enumerate(resources):
            snap.it_alloc[i, r] = alloc.get(name, 0.0)
        for off in it.offerings:
            if off.available:
                snap.it_avail[i, zone_idx[off.zone], ct_idx[off.capacity_type]] = True
                snap.it_price[i, zone_idx[off.zone], ct_idx[off.capacity_type]] = off.price

    # -- templates ------------------------------------------------------------
    T = len(templates)
    tmpl_planes = [vocab.encode_requirements(t.requirements) for t in templates]
    snap.tmpl_mask, snap.tmpl_defined, snap.tmpl_negative, snap.tmpl_gt, snap.tmpl_lt = (
        np.stack([p[j] for p in tmpl_planes]) for j in range(5)
    )
    snap.tmpl_zone = np.zeros((T, Z), dtype=bool)
    snap.tmpl_ct = np.zeros((T, CT), dtype=bool)
    snap.tmpl_it = np.zeros((T, I), dtype=bool)
    snap.tmpl_daemon = np.zeros((T, R), dtype=np.float32)
    for t, tmpl in enumerate(templates):
        reqs = tmpl.requirements
        snap.tmpl_zone[t] = encode_value_set(
            reqs.get(labels_api.LABEL_TOPOLOGY_ZONE) if reqs.has(labels_api.LABEL_TOPOLOGY_ZONE) else None,
            zones,
        )
        snap.tmpl_ct[t] = encode_value_set(
            reqs.get(labels_api.LABEL_CAPACITY_TYPE) if reqs.has(labels_api.LABEL_CAPACITY_TYPE) else None,
            capacity_types,
        )
        name_req = (
            reqs.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
            if reqs.has(labels_api.LABEL_INSTANCE_TYPE_STABLE)
            else None
        )
        catalog = {it.name for it in instance_types.get(tmpl.provisioner_name, [])}
        snap.tmpl_it[t] = np.array(
            [
                name in catalog and (name_req is None or name_req.has(name))
                for name in it_names
            ],
            dtype=bool,
        )
        for r, name in enumerate(resources):
            snap.tmpl_daemon[t, r] = tmpl.requests.get(name, 0.0) if tmpl.requests else 0.0

    # -- pod classes ----------------------------------------------------------
    C = len(classes)
    if C == 0:
        K, W = vocab.n_keys, vocab.width
        snap.cls_mask = np.zeros((0, K, W), dtype=bool)
        snap.cls_defined = np.zeros((0, K), dtype=bool)
        snap.cls_negative = np.zeros((0, K), dtype=bool)
        snap.cls_gt = np.zeros((0, K), dtype=np.float32)
        snap.cls_lt = np.zeros((0, K), dtype=np.float32)
    else:
        cls_planes = [vocab.encode_requirements(c.requirements) for c in classes]
        snap.cls_mask, snap.cls_defined, snap.cls_negative, snap.cls_gt, snap.cls_lt = (
            np.stack([p[j] for p in cls_planes]) for j in range(5)
        )
    snap.cls_zone = np.zeros((C, Z), dtype=bool)
    snap.cls_ct = np.zeros((C, CT), dtype=bool)
    snap.cls_it = np.zeros((C, I), dtype=bool)
    snap.cls_requests = np.zeros((C, R), dtype=np.float32)
    snap.cls_count = np.zeros(C, dtype=np.int32)
    snap.cls_tol = np.zeros((C, T), dtype=bool)
    snap.cls_zone_cap = np.full(C, UNLIMITED, dtype=np.int32)
    snap.cls_zone_skew = np.full(C, UNLIMITED, dtype=np.int32)
    snap.cls_host_cap = np.full(C, UNLIMITED, dtype=np.int32)
    snap.cls_zone_count0 = np.zeros((C, Z), dtype=np.int32)
    snap.cls_zone_aff = np.zeros(C, dtype=bool)
    snap.cls_host_aff = np.zeros(C, dtype=bool)
    for c, cls in enumerate(classes):
        reqs = cls.requirements
        snap.cls_zone[c] = encode_value_set(
            reqs.get(labels_api.LABEL_TOPOLOGY_ZONE) if reqs.has(labels_api.LABEL_TOPOLOGY_ZONE) else None,
            zones,
        )
        snap.cls_ct[c] = encode_value_set(
            reqs.get(labels_api.LABEL_CAPACITY_TYPE) if reqs.has(labels_api.LABEL_CAPACITY_TYPE) else None,
            capacity_types,
        )
        snap.cls_it[c] = encode_value_set(
            reqs.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
            if reqs.has(labels_api.LABEL_INSTANCE_TYPE_STABLE)
            else None,
            it_names,
        )
        requests = dict(cls.requests)
        requests[resources_util.PODS] = 1.0
        for r, name in enumerate(resources):
            snap.cls_requests[c, r] = requests.get(name, 0.0)
        snap.cls_count[c] = cls.count
        example = cls.pods[0]
        for t, tmpl in enumerate(templates):
            snap.cls_tol[c, t] = Taints.of(tmpl.taints).tolerates(example) is None
        if cls.zone_anti_affinity:
            snap.cls_zone_cap[c] = 1
        if cls.zone_spread_skew is not None:
            snap.cls_zone_skew[c] = cls.zone_spread_skew
        if cls.host_anti_affinity:
            snap.cls_host_cap[c] = 1
        elif cls.host_spread_skew is not None:
            # hostname min-count is always 0 (a new node is always possible,
            # topologygroup.go:184-188), so per-node cap = maxSkew
            snap.cls_host_cap[c] = cls.host_spread_skew
        snap.cls_zone_aff[c] = cls.zone_affinity
        snap.cls_host_aff[c] = cls.host_affinity

    return snap
