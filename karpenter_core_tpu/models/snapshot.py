"""Dense cluster-snapshot encoding for the TPU solve kernel.

Encodes the solver's inputs (SURVEY.md §7 step 2) into numpy tensors:

  - instance types: general-key requirement masks, allocatable vectors, and
    offering availability/price over the zone × capacity-type axes
  - machine templates (per provisioner, weight-ordered): requirement masks,
    structural-axis masks, daemonset overhead, taints (pre-evaluated against
    pod classes)
  - pod *classes*: pods deduplicated by (requirements, requests, tolerations,
    topology spec) — the kernel's scan runs over classes, not pods, which is
    what makes 50k-pod solves tractable: cost scales with distinct pod shapes

Structural keys (hostname / instance-type / zone / capacity-type) are encoded
as dedicated axes rather than general masks (models.vocab.STRUCTURAL_KEYS).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import SCHEDULE_ANYWAY, Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.cloudprovider import InstanceType
from karpenter_core_tpu.models.vocab import (
    Vocabulary,
    encode_value_sets,
)
from karpenter_core_tpu.scheduling import Requirements, Taints
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.utils import resources as resources_util

UNLIMITED = np.int32(1 << 30)


GRP_SPREAD = 0
GRP_AFFINITY = 1
GRP_ANTI = 2


@dataclass(frozen=True)
class GroupSpec:
    """A topology group: the hash-deduped identity the reference tracks
    (topologygroup.go:137-153) — one per distinct (type, key, namespaces,
    selector, skew) across the whole batch, shared by every class that owns
    or matches it.  ``namespaces`` scopes membership exactly as the
    reference's group namespace set does: spreads count only the owner's
    namespace (topology.go:280-282), affinity terms count term.namespaces or
    the owner's namespace (topology.go:287-320 buildNamespaceList)."""

    gtype: int  # GRP_SPREAD | GRP_AFFINITY | GRP_ANTI
    is_zone: bool  # zone key vs hostname key
    selector_sig: tuple
    skew: int
    namespaces: frozenset = frozenset()


@dataclass(frozen=True)
class GroupScope:
    """Membership test for a group: label selector AND namespace scope."""

    selector: object  # Optional[LabelSelector]
    namespaces: frozenset

    def matches_pod(self, pod: Pod) -> bool:
        if (pod.namespace or "") not in self.namespaces:
            return False
        return self.selector is not None and self.selector.matches(pod.metadata.labels)


@dataclass
class PodClass:
    """One equivalence class of identical pods."""

    pods: List[Pod]
    requirements: Requirements
    requests: resources_util.ResourceList
    # owned topology groups, at most one per (type, key) pair — multiple
    # same-kind constraints on one pod take the host path
    zone_spread: Optional[GroupSpec] = None
    host_spread: Optional[GroupSpec] = None
    zone_affinity: Optional[GroupSpec] = None
    host_affinity: Optional[GroupSpec] = None
    zone_anti: Optional[GroupSpec] = None
    host_anti: Optional[GroupSpec] = None
    # GroupScope (selector + namespace set) per owned group, for
    # membership evaluation
    selectors: Dict[GroupSpec, "GroupScope"] = field(default_factory=dict)
    # preference ladder (preferences.go:38-46 pre-applied): the next, more
    # relaxed variant of this shape.  The kernel rolls failed counts down the
    # chain between scan passes; variants carry one relaxed representative
    # pod and schedule pods from the root's list (solver.tpu.decode)
    relax_to: Optional["PodClass"] = None
    is_ladder_variant: bool = False
    # anti-affinity slots filled from a PREFERRED term: the owner still seeks
    # zero-count domains, but never registers inverse counts — the reference
    # intentionally doesn't track inverse anti preferences (topology.go:203-206)
    zone_anti_soft: bool = False
    host_anti_soft: bool = False
    # the already-derived _class_signature of this class's shape, when the
    # producer holds it (PodIngest slots, the controller's interner) — lets
    # the encode's class-plane reuse key skip re-deriving O(C) signatures
    # per tick.  MUST equal _class_signature(pods[0]) when set; None makes
    # the key fall back to the derivation.
    interned_sig: Optional[tuple] = None

    @property
    def count(self) -> int:
        return len(self.pods)

    def owned_groups(self):
        return [
            g
            for g in (
                self.zone_spread,
                self.host_spread,
                self.zone_affinity,
                self.host_affinity,
                self.zone_anti,
                self.host_anti,
            )
            if g is not None
        ]


@dataclass
class EncodedSnapshot:
    vocab: Vocabulary
    resources: List[str]  # R axis
    zones: List[str]  # Z axis
    capacity_types: List[str]  # CT axis
    it_names: List[str]  # I axis
    classes: List[PodClass]  # C axis (solve order: FFD cpu/mem descending)

    # instance types [I, ...]
    it_mask: np.ndarray = None
    it_defined: np.ndarray = None
    it_negative: np.ndarray = None
    it_gt: np.ndarray = None
    it_lt: np.ndarray = None
    it_alloc: np.ndarray = None  # f32[I, R]
    it_avail: np.ndarray = None  # bool[I, Z, CT] offering available
    it_price: np.ndarray = None  # f32[I, Z, CT] (+inf unavailable)

    # templates [T, ...] (weight-ordered)
    tmpl_mask: np.ndarray = None
    tmpl_defined: np.ndarray = None
    tmpl_negative: np.ndarray = None
    tmpl_gt: np.ndarray = None
    tmpl_lt: np.ndarray = None
    tmpl_zone: np.ndarray = None  # bool[T, Z]
    tmpl_ct: np.ndarray = None  # bool[T, CT]
    tmpl_it: np.ndarray = None  # bool[T, I] catalog membership ∧ it-name reqs
    tmpl_daemon: np.ndarray = None  # f32[T, R]
    tmpl_limits: np.ndarray = None  # f32[T, R] provisioner limits minus usage (+inf none)
    it_capacity: np.ndarray = None  # f32[I, R] (limits compare against capacity)

    # pod classes [C, ...]
    cls_mask: np.ndarray = None
    cls_defined: np.ndarray = None
    cls_negative: np.ndarray = None
    cls_gt: np.ndarray = None
    cls_lt: np.ndarray = None
    cls_zone: np.ndarray = None  # bool[C, Z]
    cls_ct: np.ndarray = None  # bool[C, CT]
    cls_it: np.ndarray = None  # bool[C, I]
    cls_requests: np.ndarray = None  # f32[C, R]
    cls_count: np.ndarray = None  # i32[C]
    cls_relax_next: np.ndarray = None  # i32[C] ladder successor index (-1 none)
    cls_anti_soft: np.ndarray = None  # bool[C, 2] (zone, host) anti slot is preferred
    cls_root: np.ndarray = None  # i32[C] ladder root index (self when not a variant)
    cls_tol: np.ndarray = None  # bool[C, T] tolerates template taints
    # host ports [P axis: distinct (port, protocol) pairs in play]
    ports: List[tuple] = None
    cls_ports: np.ndarray = None  # bool[C, P] ports each class's pod binds
    # topology groups [G1] (shared across classes; last row = dummy "none")
    groups: List[GroupSpec] = None  # host-side identities, len G
    group_selectors: list = None  # selector object per group (membership tests)
    grp_skew: np.ndarray = None  # i32[G1]
    grp_is_zone: np.ndarray = None  # bool[G1]
    grp_is_anti: np.ndarray = None  # bool[G1]
    grp_member: np.ndarray = None  # bool[C, G1] selector matches class labels
    cls_groups: np.ndarray = None  # i32[C, 6] owned group per kind (G = none):
    #   [zone_spread, host_spread, zone_aff, host_aff, zone_anti, host_anti]

    # vocabulary statics
    valid: np.ndarray = None  # bool[K, V+1]
    is_custom: np.ndarray = None  # bool[K]
    vocab_ints: np.ndarray = None  # f32[K, V]

    # kernel scan passes (cross-group affinity retry rounds, the host queue's
    # re-push equivalent — affinity_scan_passes)
    scan_passes: int = 1

    # static phase-plan flag: some class carries REQUIRED zonal anti-affinity,
    # so the kernel must emit the per-zone committal phases (ops/solve.py
    # _class_step's owned-anti loop — n_zones extra run_phase instances).
    # False lets solve_core skip emitting them entirely: with no required
    # zonal-anti class every committal quota is statically zero, and the
    # phases are pure compile time + per-step cost
    has_required_zonal_anti: bool = False

    # full static phase plan (ops/solve.SnapshotFeatures): one flag per
    # constraint family, computed from the classes + bound-pod anti groups.
    # has_required_zonal_anti above is its required_zone_anti bit, kept for
    # compatibility.  volume_limits is refined at solve time (TPUSolver) —
    # it depends on the existing-node CSI planes this encode cannot see.
    features: object = None

    # per-class resolved volumes (volumeusage.go:33-236 resolution, filled by
    # TPUSolver when a kube client is available).  Each entry:
    #   {"shared": {driver: {pvc ids}}, "per_pod": {driver: count}}
    # shared = every pod mounts the same set (count-independent per node);
    # per_pod = each pod its own disjoint claims (count-dependent per node)
    class_volumes: list = None

    # policy-objective planes (policy.planes.attach_planes, filled by
    # TPUSolver post-encode): the offering price sheet, interruption-risk
    # priors, and per-type throughput weights on this snapshot's I/Z/CT axes.
    # Digested as the ``policy`` plane group in models.store so a price-sheet
    # change escalates the incremental path exactly like a supply change.
    pol_price: np.ndarray = None  # f32[I, Z, CT]
    pol_risk: np.ndarray = None  # f32[I, Z, CT]
    pol_throughput: np.ndarray = None  # f32[I]

    # delta-consuming encode provenance: True when every class-shape-derived
    # plane above was shared BY REFERENCE from the previous same-shape encode
    # (cache_host._class_plane_cache) and only the count vector was rebuilt.
    # The store's commit and the solver's warm-prep reuse both key on that
    # array identity (docs/KERNEL_PERF.md "Layer 6").
    encode_reused: bool = False


def _class_signature(pod: Pod) -> tuple:
    """Equivalence key computed from the raw spec — cheap enough to run per pod
    at 50k scale; Requirements construction happens once per class."""
    selector_sig = tuple(sorted(pod.spec.node_selector.items()))
    affinity_req_sig = ()
    if pod.spec.affinity is not None and pod.spec.affinity.node_affinity is not None:
        na = pod.spec.affinity.node_affinity
        req_terms = (
            tuple(
                tuple(
                    (e.key, e.operator, tuple(e.values))
                    for e in term.match_expressions
                )
                for term in na.required.node_selector_terms
            )
            if na.required is not None
            else ()
        )
        pref_terms = tuple(
            (
                p.weight,
                tuple((e.key, e.operator, tuple(e.values)) for e in p.preference.match_expressions),
            )
            for p in na.preferred
        )
        affinity_req_sig = (req_terms, pref_terms)
    req_sig = (selector_sig, affinity_req_sig)
    # fast path for the dominant shape: one plain container, no limits/init
    spec = pod.spec
    if len(spec.containers) == 1 and not spec.init_containers and not spec.containers[0].resources.limits:
        req_vec = tuple(sorted(spec.containers[0].resources.requests.items()))
    else:
        requests = resources_util.ceiling(pod)
        req_vec = tuple(sorted((k, round(v, 9)) for k, v in requests.items()))
    tol_sig = tuple(
        sorted((t.key, t.operator, t.value, t.effect) for t in pod.spec.tolerations)
    )
    spread_sig = tuple(
        sorted(
            (
                c.topology_key,
                c.max_skew,
                c.when_unsatisfiable,
                _selector_sig(c.label_selector),
            )
            for c in pod.spec.topology_spread_constraints
        )
    )
    affinity_sig = ()
    if pod.spec.affinity is not None:
        aff = pod.spec.affinity
        terms = []
        # namespace scope is part of term identity: same-selector terms over
        # different explicit namespaces (or a live namespaceSelector) must not
        # collapse into one class, or the first pod's scope silently wins
        def ns_sig(t):
            return (
                tuple(sorted(t.namespaces or ())),
                _selector_sig(t.namespace_selector)
                if t.namespace_selector is not None
                else None,
            )

        if aff.pod_affinity is not None:
            for t in aff.pod_affinity.required:
                terms.append(("aff", t.topology_key, _selector_sig(t.label_selector), ns_sig(t)))
            for w in aff.pod_affinity.preferred:
                t = w.pod_affinity_term
                terms.append(
                    ("aff-pref", w.weight, t.topology_key, _selector_sig(t.label_selector), ns_sig(t))
                )
        if aff.pod_anti_affinity is not None:
            for t in aff.pod_anti_affinity.required:
                terms.append(("anti", t.topology_key, _selector_sig(t.label_selector), ns_sig(t)))
            for w in aff.pod_anti_affinity.preferred:
                t = w.pod_affinity_term
                terms.append(
                    ("anti-pref", w.weight, t.topology_key, _selector_sig(t.label_selector), ns_sig(t))
                )
        affinity_sig = tuple(sorted(terms))
    # namespace is part of identity: group membership is (namespace, labels)
    labels_sig = (pod.namespace or "", tuple(sorted(pod.metadata.labels.items())))
    ports_sig = tuple(
        sorted(
            (p.host_port, p.protocol, p.host_ip)
            for c in pod.spec.containers
            for p in c.ports
            if p.host_port
        )
    )
    # claim COUNT (not identity) keeps one-PVC-per-pod StatefulSets in a
    # single class; volume resolution (solver.tpu._resolve_class_volumes)
    # distinguishes shared vs per-pod claim sets per class.  Namespace scopes
    # PVC ids, so it joins the signature only when claims exist.
    claims = {
        v.persistent_volume_claim.claim_name
        for v in pod.spec.volumes
        if v.persistent_volume_claim is not None
    }
    vol_sig = (pod.namespace or "", len(claims)) if claims else ()
    return (req_sig, req_vec, tol_sig, spread_sig, affinity_sig, labels_sig, ports_sig, vol_sig)


def _selector_sig(selector) -> tuple:
    if selector is None:
        return ()
    return (
        tuple(sorted(selector.match_labels.items())),
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in selector.match_expressions
            )
        ),
    )


class KernelUnsupported(Exception):
    """The batch uses a feature the tensor kernel does not cover; callers fall
    back to the host solver (solver.scheduler.Scheduler)."""


def build_pod_class(pod: Pod) -> PodClass:
    """Build the class-level derived state (requirements, requests, owned
    topology groups) from one representative pod's CURRENT spec — soft
    constraints still on the spec count as hard.  Raises KernelUnsupported
    for shapes the kernel doesn't model."""
    cls = PodClass(
        pods=[],
        requirements=Requirements.from_pod(pod),
        requests=resources_util.ceiling(pod),
    )
    _derive_topology_spec(pod, cls)
    return cls


MAX_LADDER_VARIANTS = 5


def build_pod_ladder(pod: Pod) -> PodClass:
    """The root of a strict-to-bare variant chain for one pod shape.

    The reference schedules with every soft constraint treated as hard, then
    relaxes one constraint per failed round (preferences.go:38-46,
    scheduler.go:117-123).  The kernel can't mutate specs mid-scan, so the
    ladder is materialized ahead of time: apply Preferences.relax stepwise to
    a copied representative and build one PodClass per step.  Variants whose
    shape the kernel can't model are skipped (their preference level is
    silently forfeited — a soft-placement-quality deviation only); if no
    variant is representable the whole shape routes to the host path.  The
    kernel rolls failed counts down the chain between scan passes
    (ops/solve.solve_core), which is the tensor form of relax-and-requeue.

    Returns the first (strictest representable) variant with an empty pods
    list; successors hang off ``relax_to`` carrying one relaxed representative
    each."""
    import copy

    from karpenter_core_tpu.solver.preferences import Preferences

    specs = [pod]  # build_pod_class only reads the spec
    if _has_relaxable(pod):
        rep = copy.deepcopy(pod)
        prefs = Preferences()
        while prefs.relax(rep):
            specs.append(copy.deepcopy(rep))
    variants: List[PodClass] = []
    last_error: Optional[KernelUnsupported] = None
    for spec_pod in specs:
        try:
            cls = build_pod_class(spec_pod)
        except KernelUnsupported as e:
            last_error = e
            continue
        cls.pods = [spec_pod]
        variants.append(cls)
    if not variants:
        raise last_error or KernelUnsupported("no kernel-supported variant")
    if len(variants) > MAX_LADDER_VARIANTS:
        raise KernelUnsupported(
            f"preference ladder depth {len(variants)} exceeds the kernel's "
            f"{MAX_LADDER_VARIANTS}-variant cap"
        )
    for parent, child in zip(variants, variants[1:]):
        parent.relax_to = child
    for child in variants[1:]:
        child.is_ladder_variant = True
    root = variants[0]
    root.pods = []
    return root


def _has_relaxable(pod: Pod) -> bool:
    """Whether Preferences.relax would find anything — cheap pre-check so the
    dominant no-soft-constraint shape skips the ladder deepcopies."""
    if any(
        c.when_unsatisfiable == SCHEDULE_ANYWAY
        for c in pod.spec.topology_spread_constraints
    ):
        return True
    affinity = pod.spec.affinity
    if affinity is None:
        return False
    na = affinity.node_affinity
    if na is not None and (
        na.preferred
        or (na.required is not None and len(na.required.node_selector_terms) > 1)
    ):
        return True
    return bool(
        (affinity.pod_affinity is not None and affinity.pod_affinity.preferred)
        or (affinity.pod_anti_affinity is not None and affinity.pod_anti_affinity.preferred)
    )


def _with_prefer_no_schedule_rungs(
    classes: List[PodClass], templates: List[MachineTemplate]
) -> List[PodClass]:
    """Append the host path's final relaxation rung — tolerate PreferNoSchedule
    taints — to every ladder when some template carries one (the same gate as
    solver.scheduler and preferences.go ToleratePreferNoSchedule).  Chains are
    shallow-copied before relinking so shared class prototypes (columnar
    slots) are never mutated with template-specific state."""
    import copy
    from dataclasses import replace as dc_replace

    from karpenter_core_tpu.apis.objects import TAINT_EFFECT_PREFER_NO_SCHEDULE

    if not any(
        taint.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
        for tmpl in templates
        for taint in tmpl.taints
    ):
        return classes
    from karpenter_core_tpu.solver.preferences import Preferences

    prefs = Preferences(tolerate_prefer_no_schedule=True)
    out: List[PodClass] = []
    for cls in classes:
        if cls.is_ladder_variant:
            continue  # re-emitted with its (possibly extended) chain below
        chain = ladder_chain(cls)
        source = chain[-1].pods[0] if chain[-1].pods else cls.pods[0]
        if Preferences.tolerates_prefer_no_schedule(source):
            out.extend(chain)
            continue  # deepcopy only when a rung must actually be built
        rep = copy.deepcopy(source)
        prefs._tolerate_prefer_no_schedule_taints(rep)
        try:
            rung = build_pod_class(rep)
        except KernelUnsupported:
            out.extend(chain)
            continue
        rung.pods = [rep]
        rung.is_ladder_variant = True
        new_chain = [dc_replace(c) for c in chain]
        for parent, child in zip(new_chain, new_chain[1:]):
            parent.relax_to = child
        new_chain[-1].relax_to = rung
        out.extend(new_chain)
        out.append(rung)
    return out


def ladder_chain(root: PodClass) -> List[PodClass]:
    """[root, variant1, ...] in relax order."""
    chain = [root]
    node = root.relax_to
    while node is not None:
        chain.append(node)
        node = node.relax_to
    return chain


def finalize_classes(classes: List[PodClass]) -> List[PodClass]:
    """Order classes for the kernel scan (mutates in place, returns a new
    flattened list).  FFD over ladder roots: cpu desc, then memory desc
    (queue.go:74-110); each root's relaxation variants follow it immediately
    so failed counts roll forward in scan order."""
    roots = [c for c in classes if not c.is_ladder_variant]
    roots.sort(
        key=lambda c: (
            -c.requests.get(resources_util.CPU, 0.0),
            -c.requests.get(resources_util.MEMORY, 0.0),
        )
    )
    return [cls for root in roots for cls in ladder_chain(root)]


MAX_SCAN_PASSES = 3


def affinity_scan_passes(classes: List[PodClass]) -> int:
    """Scan passes the kernel needs for cross-group affinity whose targets
    scan later.  The host path retries followers after their targets schedule
    (queue re-push, scheduler.go:117-123); the kernel's equivalent is an extra
    scan pass over the still-failed pods, seeded by the earlier passes'
    topology counts.  pass(i) = max over affinity targets j of pass(j), +1
    when j scans after i.  Chains deeper than MAX_SCAN_PASSES (or cyclic
    cross-group dependencies) route to the host path."""
    n = len(classes)
    passes = [1] * n
    reps = [cls.pods[0] for cls in classes]
    for _ in range(n + 1):
        changed = False
        for i, cls in enumerate(classes):
            for spec in (cls.zone_affinity, cls.host_affinity):
                if spec is None:
                    continue
                scope = cls.selectors[spec]
                if scope.selector is None or scope.matches_pod(reps[i]):
                    continue  # self-affinity bootstraps in-pass
                need = passes[i]
                for j in range(n):
                    if j != i and scope.matches_pod(reps[j]):
                        need = max(need, passes[j] + (1 if j > i else 0))
                if need > MAX_SCAN_PASSES:
                    raise KernelUnsupported(
                        "cross-group affinity chain deeper than "
                        f"{MAX_SCAN_PASSES} passes not kernel-supported"
                    )
                if need != passes[i]:
                    passes[i] = need
                    changed = True
        if not changed:
            return max(passes, default=1)
    raise KernelUnsupported("cyclic cross-group affinity not kernel-supported")


def classify_pods(pods: List[Pod]) -> List[PodClass]:
    """Group pods into equivalence classes and derive each class's owned
    topology groups.  Groups are shared across classes by identity (type, key,
    selector, skew) — the reference's hash dedup — so selectors may span
    classes (cross-group affinity, inverse anti-affinity).  Raises
    KernelUnsupported for shapes the kernel doesn't model: host ports,
    region/custom-key topologies, multiple same-kind constraints per pod."""
    groups: Dict[tuple, PodClass] = {}
    order: List[tuple] = []
    for pod in pods:
        sig = _class_signature(pod)
        cls = groups.get(sig)
        if cls is None:
            cls = build_pod_ladder(pod)
            groups[sig] = cls
            order.append(sig)
        cls.pods.append(pod)
    return finalize_classes([groups[sig] for sig in order])


def _group_spec(
    gtype: int, topology_key: str, selector, skew: int, namespaces: frozenset
) -> GroupSpec:
    if topology_key == labels_api.LABEL_TOPOLOGY_ZONE:
        is_zone = True
    elif topology_key == labels_api.LABEL_HOSTNAME:
        is_zone = False
    else:
        raise KernelUnsupported(f"topology on {topology_key} not kernel-supported")
    return GroupSpec(
        gtype=gtype, is_zone=is_zone, selector_sig=_selector_sig(selector), skew=skew,
        namespaces=namespaces,
    )


def term_namespaces(pod: Pod, term) -> frozenset:
    """The namespace scope of an affinity term (topology.go buildNamespaceList):
    explicit term.namespaces, else the owner pod's namespace.  A live
    namespaceSelector needs an apiserver listing — host path only."""
    if term.namespace_selector is not None:
        raise KernelUnsupported("affinity namespaceSelector not kernel-supported")
    if term.namespaces:
        return frozenset(term.namespaces)
    return frozenset({pod.namespace or ""})


def _derive_topology_spec(pod: Pod, cls: PodClass) -> None:
    def set_slot(attr: str, spec: GroupSpec, selector) -> None:
        if getattr(cls, attr) is not None:
            raise KernelUnsupported(f"multiple {attr} constraints not kernel-supported")
        setattr(cls, attr, spec)
        cls.selectors[spec] = GroupScope(selector, spec.namespaces)

    # ALL spreads — ScheduleAnyway included — and both required and preferred
    # affinity terms act as hard constraints while present on the spec
    # (topology.go:280-320 builds groups from soft terms too); build_pod_ladder
    # materializes the relaxed variants by removing soft terms stepwise, so
    # strictness lives in the spec, not here.
    # Self-selecting spreads water-fill (counts move with each placement);
    # non-self-selecting ones reduce to a static within-skew domain mask —
    # the kernel handles both (ops/solve.py zone-spread phases, host caps)
    own_ns = frozenset({pod.namespace or ""})
    for constraint in pod.spec.topology_spread_constraints:
        spec = _group_spec(
            GRP_SPREAD, constraint.topology_key, constraint.label_selector,
            constraint.max_skew, own_ns,
        )
        set_slot("zone_spread" if spec.is_zone else "host_spread", spec, constraint.label_selector)
    affinity = pod.spec.affinity
    if affinity is not None:
        if affinity.pod_affinity is not None:
            terms = list(affinity.pod_affinity.required) + [
                w.pod_affinity_term for w in affinity.pod_affinity.preferred
            ]
            for term in terms:
                spec = _group_spec(
                    GRP_AFFINITY, term.topology_key, term.label_selector, UNLIMITED,
                    term_namespaces(pod, term),
                )
                set_slot(
                    "zone_affinity" if spec.is_zone else "host_affinity", spec, term.label_selector
                )
        if affinity.pod_anti_affinity is not None:
            n_required = len(affinity.pod_anti_affinity.required)
            terms = list(affinity.pod_anti_affinity.required) + [
                w.pod_affinity_term for w in affinity.pod_anti_affinity.preferred
            ]
            for i, term in enumerate(terms):
                spec = _group_spec(
                    GRP_ANTI, term.topology_key, term.label_selector, UNLIMITED,
                    term_namespaces(pod, term),
                )
                slot = "zone_anti" if spec.is_zone else "host_anti"
                set_slot(slot, spec, term.label_selector)
                if i >= n_required:
                    setattr(cls, f"{slot}_soft", True)
    for container in pod.spec.containers:
        for p in container.ports:
            if p.host_port and p.host_ip not in ("", "0.0.0.0", "::"):
                # specific-IP host ports only conflict with same/unspecified
                # IPs (hostportusage.go:44-56) — finer than the kernel's
                # (port, proto) bitset models
                raise KernelUnsupported("host ports with specific hostIP not kernel-supported")
    if cls.zone_affinity is not None and (cls.zone_spread is not None or cls.zone_anti is not None):
        raise KernelUnsupported("combined zone affinity + spread/anti not kernel-supported")
    if cls.host_affinity is not None and (cls.host_spread is not None or cls.host_anti is not None):
        raise KernelUnsupported("combined hostname affinity + spread/anti not kernel-supported")
    # the kernel schedules each class through exactly one phase family; these
    # combos need intersected phase plans (and under the reference's
    # pessimistic new-node committal they schedule ~1 pod before deadlocking,
    # topology_test.go:1896) — the host path keeps exact per-pod semantics
    if cls.zone_spread is not None and cls.zone_anti is not None:
        raise KernelUnsupported("combined zone spread + zone anti-affinity not kernel-supported")
    if cls.host_affinity is not None and (cls.zone_spread is not None or cls.zone_anti is not None):
        raise KernelUnsupported("combined hostname affinity + zonal spread/anti not kernel-supported")
    # required zonal anti-affinity IS kernel-supported (since round 5): the
    # scan derives per-zone counts from nodes' CURRENT zone masks at every
    # class step (ops/solve.TopoCounts) and the owned-anti phases are
    # zone-committal (one member per admissible zone, the node pinned to it),
    # reaching the host's batch-two fixpoint in batch one.  encode_snapshot
    # adds min(count, zones) scan passes for these classes so later
    # de-poisoning (co-location narrowing) is replayed to quiescence.


def encode_snapshot(
    pods: List[Pod],
    provisioners: List[Provisioner],
    templates: List[MachineTemplate],
    instance_types: Dict[str, List[InstanceType]],
    extra_requirement_sets: Optional[List[Requirements]] = None,
    extra_anti_groups: Optional[list] = None,
    cache_host: Optional[object] = None,
    extra_host_ports: Optional[List[tuple]] = None,
    classes: Optional[List[PodClass]] = None,
    catalog_pad_multiple: int = 1,
) -> EncodedSnapshot:
    """Encode a solve input.  ``templates`` must be weight-ordered (the order
    is the kernel's template preference order, scheduler.go:174-219).
    ``extra_requirement_sets`` widen the vocabulary (e.g. existing-node label
    values, which must be representable for NotIn semantics to stay exact).
    ``classes`` short-circuits classification when the caller maintains pod
    classes incrementally (models.columnar.PodIngest).

    ``catalog_pad_multiple`` emits the instance-type axis shard-aligned: the
    I extent pads up to a multiple of the solve mesh's catalog axis
    (parallel.mesh.catalog_pad_multiple, threaded by TPUSolver) with INERT
    sentinel types — ``~catalog-pad-N`` names, no offerings, zero
    allocatable/capacity, excluded from every template catalog — so the
    shard_map dispatcher's even-split requirement is met at encode time and
    every downstream consumer (decode, store digests, policy planes, the
    wire) sees one consistent padded extent.  Padded columns can never be
    viable; the solve is bit-identical to the unpadded encode's."""
    if classes is None:
        classes = classify_pods(pods)
    classes = _with_prefer_no_schedule_rungs(classes, templates)
    # each relax step needs its own scan pass for the rolled counts to be
    # retried (the host path's fail -> Relax -> re-push round)
    ladder_extra = max(
        (len(ladder_chain(c)) - 1 for c in classes if not c.is_ladder_variant),
        default=0,
    )
    scan_passes = affinity_scan_passes(classes) + ladder_extra

    # -- axes -----------------------------------------------------------------
    all_its: List[InstanceType] = []
    it_index: Dict[str, int] = {}
    for tmpl in templates:
        for it in instance_types.get(tmpl.provisioner_name, []):
            if it.name not in it_index:
                it_index[it.name] = len(all_its)
                all_its.append(it)
    it_names = [it.name for it in all_its]
    # shard-aligned catalog extent (docstring): inert sentinel types fill the
    # tail so the mesh's catalog axis divides I evenly
    pad_multiple = max(int(catalog_pad_multiple or 1), 1)
    n_pad_types = ((-len(it_names)) % pad_multiple) if it_names else 0
    it_names += [f"~catalog-pad-{j}" for j in range(n_pad_types)]

    zones: List[str] = []
    capacity_types: List[str] = []
    for it in all_its:
        for off in it.offerings:
            if off.zone not in zones:
                zones.append(off.zone)
            if off.capacity_type not in capacity_types:
                capacity_types.append(off.capacity_type)
    zones = sorted(zones)
    capacity_types = sorted(capacity_types)

    # required zonal anti-affinity converges one pod per pass (pessimistic
    # committal: a placed member poisons every zone its node could be in until
    # co-location narrows the mask) — give each such class enough passes to
    # reach the host's retry-to-quiescence fixpoint; progress caps at one pod
    # per distinct zone, so min(count, |zones|) bounds the chain depth
    anti_extra = max(
        (
            min(len(c.pods), max(len(zones), 1)) - 1
            for c in classes
            if not c.is_ladder_variant
            and c.zone_anti is not None
            and not c.zone_anti_soft
        ),
        default=0,
    )
    scan_passes += anti_extra
    # any class (ladder variants included — they inherit the anti term) with
    # required zonal anti makes the per-zone committal phases reachable
    has_required_zonal_anti = any(
        c.zone_anti is not None and not c.zone_anti_soft for c in classes
    )

    resources: List[str] = [resources_util.CPU, resources_util.MEMORY, resources_util.PODS]
    for cls in classes:
        for name in cls.requests:
            if name not in resources:
                resources.append(name)
    for it in all_its:
        for name in it.capacity:
            if name not in resources:
                resources.append(name)

    # -- vocabulary -----------------------------------------------------------
    # demand side defines the keys; catalog/node labels only widen the value
    # lists of keys the demand side references (Vocabulary.build docstring) —
    # the kernel's mask compute scales with the widest key, so supply-only
    # label families (e.g. a per-instance serial label) must not enter
    demand_sets = [cls.requirements for cls in classes]
    demand_sets += [tmpl.requirements for tmpl in templates]
    supply_sets = [it.requirements for it in all_its]
    supply_sets += list(extra_requirement_sets or [])
    vocab = Vocabulary.build(demand_sets, supply_sets=supply_sets)

    snap = EncodedSnapshot(
        vocab=vocab,
        resources=resources,
        zones=zones,
        capacity_types=capacity_types,
        it_names=it_names,
        classes=classes,
        scan_passes=scan_passes,
        has_required_zonal_anti=has_required_zonal_anti,
    )
    vocab_content = (
        tuple(vocab.keys),
        tuple((k, tuple(v)) for k, v in sorted(vocab.values.items())),
    )

    # -- instance types -------------------------------------------------------
    # catalog planes only depend on the vocabulary content + catalog +
    # resource/zone/ct axes — identical across reconcile loops, so cache them
    # (cache_host carries the dict across encodes, e.g. a TPUSolver)
    I, Z, CT, R = len(it_names), len(zones), len(capacity_types), len(resources)
    cache = getattr(cache_host, "_catalog_cache", None) if cache_host is not None else None
    cache_key = vocab_content + (
        tuple(it_names),
        tuple(resources),
        tuple(zones),
        tuple(capacity_types),
        # offering content is part of the key: prices/availability can move
        # between encodes on one live solver (dynamic spot pricing —
        # FakeCloudProvider.set_price), and the cached it_price/it_avail
        # planes must not outlive the sheet they encoded.  Capacity content
        # is NOT keyed — it_alloc has always assumed catalog capacity is
        # immutable on a live solver, and it_capacity (cached here too now)
        # rides the same assumption.
        tuple(
            (o.zone, o.capacity_type, o.available, o.price)
            for it in all_its
            for o in it.offerings
        ),
    )
    if cache is not None and cache.get("key") == cache_key:
        (
            snap.it_mask, snap.it_defined, snap.it_negative, snap.it_gt, snap.it_lt,
            snap.it_alloc, snap.it_avail, snap.it_price, snap.it_capacity,
        ) = cache["planes"]
    else:
        it_planes = [vocab.encode_requirements(it.requirements) for it in all_its]
        snap.it_mask, snap.it_defined, snap.it_negative, snap.it_gt, snap.it_lt = (
            np.stack([p[j] for p in it_planes]) for j in range(5)
        )
        if n_pad_types:
            # inert ReqTensor rows for the sentinel types: nothing defined, so
            # every compatibility check skips them (they are also excluded
            # from availability/templates below — belt and suspenders).
            # Fill values MATCH ops.solve.pad_catalog's row-padding convention
            # (mask=False, defined=False, ±inf bounds) so the two padding
            # paths can never diverge on the tail even if the kernel ever
            # starts consulting mask where defined is False.
            K, W = snap.it_mask.shape[1], snap.it_mask.shape[2]
            snap.it_mask = np.concatenate(
                [snap.it_mask, np.zeros((n_pad_types, K, W), dtype=bool)]
            )
            snap.it_defined = np.concatenate(
                [snap.it_defined, np.zeros((n_pad_types, K), dtype=bool)]
            )
            snap.it_negative = np.concatenate(
                [snap.it_negative, np.zeros((n_pad_types, K), dtype=bool)]
            )
            snap.it_gt = np.concatenate(
                [snap.it_gt, np.full((n_pad_types, K), -np.inf, dtype=np.float32)]
            )
            snap.it_lt = np.concatenate(
                [snap.it_lt, np.full((n_pad_types, K), np.inf, dtype=np.float32)]
            )
        # one vectorized scatter per plane instead of a Python store per
        # (type, resource) / (type, offering) cell — at 2k-type catalogs the
        # cell loops were the cold encode's floor
        snap.it_alloc = np.zeros((I, R), dtype=np.float32)
        snap.it_capacity = np.zeros((I, R), dtype=np.float32)
        snap.it_avail = np.zeros((I, Z, CT), dtype=bool)
        snap.it_price = np.full((I, Z, CT), np.inf, dtype=np.float32)
        res_index = {name: r for r, name in enumerate(resources)}
        zone_idx2 = {z: i for i, z in enumerate(zones)}
        ct_idx2 = {c: i for i, c in enumerate(capacity_types)}
        a_cells: List[tuple] = []  # (i, r, value) for it_alloc
        c_cells: List[tuple] = []  # (i, r, value) for it_capacity
        o_cells: List[tuple] = []  # (i, z, ct, price) for available offerings
        for i, it in enumerate(all_its):
            for name, quantity in it.allocatable().items():
                r = res_index.get(name)
                if r is not None:
                    a_cells.append((i, r, quantity))
            for name, quantity in it.capacity.items():
                r = res_index.get(name)
                if r is not None:
                    c_cells.append((i, r, quantity))
            for off in it.offerings:
                if off.available:
                    o_cells.append((
                        i, zone_idx2[off.zone], ct_idx2[off.capacity_type],
                        off.price,
                    ))
        if a_cells:
            rows, cols, vals = zip(*a_cells)
            snap.it_alloc[list(rows), list(cols)] = np.asarray(vals, dtype=np.float32)
        if c_cells:
            rows, cols, vals = zip(*c_cells)
            snap.it_capacity[list(rows), list(cols)] = np.asarray(vals, dtype=np.float32)
        if o_cells:
            rows, zcols, ccols, prices = zip(*o_cells)
            snap.it_avail[list(rows), list(zcols), list(ccols)] = True
            snap.it_price[list(rows), list(zcols), list(ccols)] = np.asarray(
                prices, dtype=np.float32
            )
        if cache_host is not None:
            cache_host._catalog_cache = {
                "key": cache_key,
                "planes": (
                    snap.it_mask, snap.it_defined, snap.it_negative, snap.it_gt,
                    snap.it_lt, snap.it_alloc, snap.it_avail, snap.it_price,
                    snap.it_capacity,
                ),
            }

    # -- class/template/group/port planes: the delta-consuming seam ----------
    # Everything below this point is a pure function of the class SHAPES
    # (signatures), the templates, the vocabulary, the axes, and the extra
    # groups/ports — NOT of the per-class pod counts.  A churn tick that only
    # moves members between existing shapes therefore reuses the previous
    # encode's plane arrays by reference (bit-identical by construction; the
    # arrays are treated as immutable everywhere downstream), and the store's
    # commit skips re-digesting the untouched plane groups by the same
    # identity (models.store.snapshot_digests).  The fresh cls_count vector
    # is the only thing a steady-state re-encode actually computes.
    reuse_key = None
    prev_snap: Optional[EncodedSnapshot] = None
    if cache_host is not None:
        reuse_key = _class_plane_key(
            vocab_content, snap, classes, templates, provisioners,
            instance_types, extra_requirement_sets, extra_anti_groups,
            extra_host_ports,
        )
        cached_cls = getattr(cache_host, "_class_plane_cache", None)
        if cached_cls is not None and cached_cls.get("key") == reuse_key:
            prev_snap = cached_cls["snap"]
    if prev_snap is not None:
        _share_class_planes(snap, prev_snap, classes)
        snap.encode_reused = True
        return snap

    snap.valid = vocab.valid_mask()
    snap.is_custom = vocab.is_custom()
    snap.vocab_ints = vocab.ints_table()
    _populate_class_planes(
        snap, classes, templates, provisioners, instance_types,
        extra_anti_groups, extra_host_ports,
    )

    # -- static phase plan ----------------------------------------------------
    # which constraint families any class can exercise; a False flag lets the
    # kernel skip tracing the family's phases entirely (ops/solve._class_step).
    # Deferred import: ops.solve imports this module at load time.
    from karpenter_core_tpu.ops.solve import SnapshotFeatures

    def owns(attr: str) -> bool:
        return any(getattr(c, attr) is not None for c in classes)

    extra_groups = [spec for spec, _ in (extra_anti_groups or [])]
    snap.features = SnapshotFeatures(
        zone_spread=owns("zone_spread"),
        host_spread=owns("host_spread"),
        zone_affinity=owns("zone_affinity"),
        host_affinity=owns("host_affinity"),
        zone_anti=owns("zone_anti"),
        required_zone_anti=has_required_zonal_anti,
        host_anti=owns("host_anti"),
        # inverse planes: groups whose owners register inverse counts —
        # required class-owned anti terms or already-bound pods' terms
        inv_zone_anti=has_required_zonal_anti
        or any(g.is_zone for g in extra_groups),
        inv_host_anti=any(
            c.host_anti is not None and not c.host_anti_soft for c in classes
        )
        or any(not g.is_zone for g in extra_groups),
        host_ports=bool(snap.cls_ports.any()),
        volume_limits=False,  # refined by TPUSolver.solve_encoded
    ).canonical()

    if cache_host is not None:
        cache_host._class_plane_cache = {"key": reuse_key, "snap": snap}
    return snap


# plane fields shared by reference on a class-plane reuse hit — everything
# class-shape-derived; cls_count (the only count-derived plane) is rebuilt
# fresh every encode and re-shared only when its values are unchanged
_SHAPE_PLANE_FIELDS = (
    "valid", "is_custom", "vocab_ints",
    "tmpl_mask", "tmpl_defined", "tmpl_negative", "tmpl_gt", "tmpl_lt",
    "tmpl_zone", "tmpl_ct", "tmpl_it", "tmpl_daemon", "tmpl_limits",
    "cls_mask", "cls_defined", "cls_negative", "cls_gt", "cls_lt",
    "cls_zone", "cls_ct", "cls_it", "cls_requests", "cls_relax_next",
    "cls_anti_soft", "cls_root", "cls_tol", "cls_ports",
    "grp_skew", "grp_is_zone", "grp_is_anti", "grp_member", "cls_groups",
)


def _requirements_content(reqs) -> tuple:
    """Order-independent content key of one Requirements set."""
    entries = []
    for key in reqs.keys():
        r = reqs.get(key)
        entries.append((
            key, r.complement, tuple(sorted(r.values)),
            r.greater_than, r.less_than,
        ))
    return tuple(sorted(entries))


def _class_plane_key(
    vocab_content, snap, classes, templates, provisioners, instance_types,
    extra_requirement_sets, extra_anti_groups, extra_host_ports,
) -> tuple:
    """Reuse key of the class-shape-derived plane block.  Covers every input
    those planes read: the finalized class-signature sequence (counts
    excluded — they are the delta), vocabulary content, the axis name
    spaces, template content (requirements, taints, daemon overhead
    requests), provisioner limits, per-template catalog membership, and the
    extra group/port/requirement inputs."""
    return (
        vocab_content,
        tuple(snap.resources), tuple(snap.zones), tuple(snap.capacity_types),
        tuple(snap.it_names),
        # the finalized ROOT-signature sequence, interned when the producer
        # carried it (PodIngest / SignatureInterner) so steady-state ticks
        # derive zero signatures here.  Ladder variants are implied: the
        # chain (relax rungs, prefer-no-schedule rungs) is a deterministic
        # function of the root's spec — which the signature captures — and
        # of the templates, which this key covers below.
        tuple(
            c.interned_sig
            if c.interned_sig is not None
            else _class_signature(c.pods[0])
            for c in classes
            if not c.is_ladder_variant
        ),
        tuple(
            (
                t.provisioner_name,
                _requirements_content(t.requirements),
                tuple(sorted(
                    (tt.key, tt.value, tt.effect, getattr(tt, "operator", ""))
                    for tt in t.taints
                )),
                tuple(sorted((t.requests or {}).items())),
            )
            for t in templates
        ),
        tuple(
            (
                p.name,
                tuple(sorted(p.spec.limits.resources.items()))
                if p.spec.limits is not None
                else None,
            )
            for p in provisioners
        ),
        tuple(
            (
                t.provisioner_name,
                tuple(
                    it.name
                    for it in instance_types.get(t.provisioner_name, ())
                ),
            )
            for t in templates
        ),
        tuple(
            _requirements_content(r) for r in (extra_requirement_sets or ())
        ),
        tuple(
            (spec, _selector_sig(sel) if sel is not None else None)
            for spec, sel in (extra_anti_groups or ())
        ),
        tuple(extra_host_ports or ()),
    )


def _share_class_planes(snap: EncodedSnapshot, prev: EncodedSnapshot, classes) -> None:
    """Populate ``snap`` from a previous same-shape encode: every
    shape-derived plane by reference (identity — the store digest reuse and
    the solver's warm-prep reuse both key on it), the count vector fresh
    (shared back only when values are unchanged, so an idle tick stays
    fully identity-stable)."""
    for f in _SHAPE_PLANE_FIELDS:
        setattr(snap, f, getattr(prev, f))
    snap.ports = prev.ports
    snap.groups = prev.groups
    snap.group_selectors = prev.group_selectors
    snap.features = prev.features
    counts = np.array(
        [0 if c.is_ladder_variant else c.count for c in classes],
        dtype=np.int32,
    )
    if prev.cls_count is not None and np.array_equal(counts, prev.cls_count):
        snap.cls_count = prev.cls_count
    else:
        snap.cls_count = counts


def _populate_class_planes(
    snap: EncodedSnapshot, classes, templates, provisioners, instance_types,
    extra_anti_groups, extra_host_ports,
) -> None:
    """Build the class/template/group/port planes (the shape-derived block
    ``_share_class_planes`` reuses on delta ticks) as batch operations over
    interned name spaces — no per-universe-value Python on the hot path."""
    vocab = snap.vocab
    zones, capacity_types, it_names = snap.zones, snap.capacity_types, snap.it_names
    resources = snap.resources
    I, Z, CT, R = len(it_names), len(zones), len(capacity_types), len(resources)

    # -- templates ------------------------------------------------------------
    T = len(templates)
    tmpl_planes = [vocab.encode_requirements(t.requirements) for t in templates]
    snap.tmpl_mask, snap.tmpl_defined, snap.tmpl_negative, snap.tmpl_gt, snap.tmpl_lt = (
        np.stack([p[j] for p in tmpl_planes]) for j in range(5)
    )

    def req_of(reqs, label):
        return reqs.get(label) if reqs.has(label) else None

    snap.tmpl_zone = encode_value_sets(
        [req_of(t.requirements, labels_api.LABEL_TOPOLOGY_ZONE) for t in templates],
        zones,
    )
    snap.tmpl_ct = encode_value_sets(
        [req_of(t.requirements, labels_api.LABEL_CAPACITY_TYPE) for t in templates],
        capacity_types,
    )
    # catalog membership by interned name index, then AND the instance-type
    # name requirement row — same cells as the old per-name Python walk
    it_name_index = {name: i for i, name in enumerate(it_names)}
    snap.tmpl_it = np.zeros((T, I), dtype=bool)
    for t, tmpl in enumerate(templates):
        members = [
            it_name_index[it.name]
            for it in instance_types.get(tmpl.provisioner_name, [])
            if it.name in it_name_index
        ]
        if members:
            snap.tmpl_it[t, members] = True
    snap.tmpl_it &= encode_value_sets(
        [req_of(t.requirements, labels_api.LABEL_INSTANCE_TYPE_STABLE) for t in templates],
        it_names,
    )
    snap.tmpl_daemon = np.zeros((T, R), dtype=np.float32)
    # raw provisioner limits (scheduler.go:69-75); in-solve usage is the
    # capacity of the solve's own state nodes, subtracted in-kernel per
    # open-mask (scheduler.go:244-246 calculateExistingMachines) so
    # consolidation subsets release their nodes' budget per lane
    snap.tmpl_limits = np.full((T, R), np.inf, dtype=np.float32)
    prov_by_name = {p.name: p for p in provisioners}
    for t, tmpl in enumerate(templates):
        prov = prov_by_name.get(tmpl.provisioner_name)
        if prov is not None and prov.spec.limits is not None:
            for r, name in enumerate(resources):
                if name in prov.spec.limits.resources:
                    snap.tmpl_limits[t, r] = prov.spec.limits.resources[name]
        for r, name in enumerate(resources):
            snap.tmpl_daemon[t, r] = tmpl.requests.get(name, 0.0) if tmpl.requests else 0.0

    # -- pod classes ----------------------------------------------------------
    C = len(classes)
    if C == 0:
        K, W = vocab.n_keys, vocab.width
        snap.cls_mask = np.zeros((0, K, W), dtype=bool)
        snap.cls_defined = np.zeros((0, K), dtype=bool)
        snap.cls_negative = np.zeros((0, K), dtype=bool)
        snap.cls_gt = np.zeros((0, K), dtype=np.float32)
        snap.cls_lt = np.zeros((0, K), dtype=np.float32)
    else:
        cls_planes = [vocab.encode_requirements(c.requirements) for c in classes]
        snap.cls_mask, snap.cls_defined, snap.cls_negative, snap.cls_gt, snap.cls_lt = (
            np.stack([p[j] for p in cls_planes]) for j in range(5)
        )
    snap.cls_zone = encode_value_sets(
        [req_of(c.requirements, labels_api.LABEL_TOPOLOGY_ZONE) for c in classes],
        zones,
    ) if C else np.zeros((0, Z), dtype=bool)
    snap.cls_ct = encode_value_sets(
        [req_of(c.requirements, labels_api.LABEL_CAPACITY_TYPE) for c in classes],
        capacity_types,
    ) if C else np.zeros((0, CT), dtype=bool)
    snap.cls_it = encode_value_sets(
        [req_of(c.requirements, labels_api.LABEL_INSTANCE_TYPE_STABLE) for c in classes],
        it_names,
    ) if C else np.zeros((0, I), dtype=bool)
    snap.cls_requests = np.zeros((C, R), dtype=np.float32)
    snap.cls_count = np.zeros(C, dtype=np.int32)
    snap.cls_relax_next = np.full(C, -1, dtype=np.int32)
    snap.cls_anti_soft = np.zeros((C, 2), dtype=bool)
    for c, cls in enumerate(classes):
        snap.cls_anti_soft[c, 0] = cls.zone_anti_soft
        snap.cls_anti_soft[c, 1] = cls.host_anti_soft
    index_of = {id(cls): c for c, cls in enumerate(classes)}
    for c, cls in enumerate(classes):
        if cls.relax_to is not None:
            snap.cls_relax_next[c] = index_of[id(cls.relax_to)]
    snap.cls_root = np.arange(C, dtype=np.int32)
    for c in range(C):
        nxt = snap.cls_relax_next[c]
        if nxt >= 0:  # successors always follow their root
            snap.cls_root[nxt] = snap.cls_root[c]
    snap.cls_tol = np.zeros((C, T), dtype=bool)
    # -- topology groups (hash-deduped, topologygroup.go:137-153) -------------
    group_index: Dict[GroupSpec, int] = {}
    group_selectors: list = []
    for cls in classes:
        for spec in cls.owned_groups():
            if spec not in group_index:
                group_index[spec] = len(group_index)
                group_selectors.append(cls.selectors[spec])
    # anti-affinity groups owned only by already-bound cluster pods still gate
    # the pods they select (inverse topologies, topology.go:185-198)
    for spec, selector in extra_anti_groups or []:
        if spec not in group_index:
            group_index[spec] = len(group_index)
            group_selectors.append(GroupScope(selector, spec.namespaces))
    G = len(group_index)
    snap.groups = list(group_index)
    snap.group_selectors = group_selectors
    snap.grp_skew = np.full(G + 1, UNLIMITED, dtype=np.int32)
    snap.grp_is_zone = np.zeros(G + 1, dtype=bool)
    snap.grp_is_anti = np.zeros(G + 1, dtype=bool)
    snap.grp_member = np.zeros((C, G + 1), dtype=bool)
    snap.cls_groups = np.full((C, 6), G, dtype=np.int32)
    for spec, g in group_index.items():
        snap.grp_skew[g] = spec.skew
        snap.grp_is_zone[g] = spec.is_zone
        snap.grp_is_anti[g] = spec.gtype == GRP_ANTI
    for c, cls in enumerate(classes):
        rep = cls.pods[0]
        for g, scope in enumerate(group_selectors):
            snap.grp_member[c, g] = scope is not None and scope.matches_pod(rep)
        for slot, spec in enumerate(
            (cls.zone_spread, cls.host_spread, cls.zone_affinity,
             cls.host_affinity, cls.zone_anti, cls.host_anti)
        ):
            if spec is not None:
                snap.cls_groups[c, slot] = group_index[spec]
    for c, cls in enumerate(classes):
        requests = dict(cls.requests)
        requests[resources_util.PODS] = 1.0
        for r, name in enumerate(resources):
            snap.cls_requests[c, r] = requests.get(name, 0.0)
        # variants start empty: the kernel rolls failed root counts into
        # them between scan passes (one relax step per pass)
        snap.cls_count[c] = 0 if cls.is_ladder_variant else cls.count
        example = cls.pods[0]
        for t, tmpl in enumerate(templates):
            snap.cls_tol[c, t] = Taints.of(tmpl.taints).tolerates(example) is None

    # -- host ports (hostportusage.go:31-144 as a (port, proto) bitset) -------
    port_universe: Dict[tuple, None] = {}
    for cls in classes:
        for key in pod_port_keys(cls.pods[0]):
            port_universe.setdefault(key)
    for key in extra_host_ports or []:
        port_universe.setdefault(key)
    snap.ports = list(port_universe) or [(0, "TCP")]  # >=1 column for XLA
    port_idx = {key: i for i, key in enumerate(snap.ports)}
    snap.cls_ports = np.zeros((C, len(snap.ports)), dtype=bool)
    for c, cls in enumerate(classes):
        for key in pod_port_keys(cls.pods[0]):
            snap.cls_ports[c, port_idx[key]] = True


def pod_port_keys(pod: Pod) -> List[tuple]:
    """(host_port, protocol) pairs a pod binds (protocol defaults to TCP)."""
    return [
        (p.host_port, p.protocol or "TCP")
        for container in pod.spec.containers
        for p in container.ports
        if p.host_port
    ]
