"""Loader for the ``kc_sig`` CPython extension (native/kc_sig.cc) — the C
twin of the ingest fast key (models/columnar._fast_sig_key_py).

Builds the extension on first use (g++ via the checked-in Makefile) and
imports it; falls back to the Python implementation when no toolchain or no
Python headers are available.  ``KC_NATIVE_SIG=0`` disables the extension
unconditionally (triage / parity bisection).  Same build discipline as
models.native: one thread builds outside the lock, latecomers wait on the
in-flight event (kcanalyze lock-order: no blocking under a held mutex).
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)
_SO_PATH = os.path.join(_NATIVE_DIR, "kc_sig.so")
_lock = threading.Lock()
_mod = None
_load_failed = False
_in_flight: "Optional[threading.Event]" = None


def enabled() -> bool:
    return os.environ.get("KC_NATIVE_SIG", "1") != "0"


def load():
    """The ``kc_sig`` module, or None (build/import failed or disabled)."""
    global _mod, _load_failed, _in_flight
    if not enabled():
        return None
    while True:
        with _lock:
            if _mod is not None or _load_failed:
                return _mod
            building = _in_flight
            if building is None:
                building = _in_flight = threading.Event()
                break  # this thread builds
        building.wait(timeout=180.0)
    mod = None
    try:
        mod = _build_and_import()
    finally:
        with _lock:
            if mod is None:
                _load_failed = True
            else:
                _mod = mod
            _in_flight = None
        building.set()
    return mod


def _build_and_import():
    """Build (if needed) and import the extension.  Runs with NO lock held —
    the g++ subprocess must not stall other threads; the caller holds the
    in-flight slot, so the build still runs once."""
    if not os.path.exists(_SO_PATH):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "kc_sig.so"],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception as e:  # noqa: BLE001 - fall back to the Python twin
            log.warning("kc_sig build failed, using Python fast key: %s", e)
            return None
    if not os.path.exists(_SO_PATH):
        # headerless toolchain: the Makefile skipped the target gracefully
        log.info("kc_sig.so not built (no Python headers); Python fast key in use")
        return None
    try:
        loader = importlib.machinery.ExtensionFileLoader("kc_sig", _SO_PATH)
        spec = importlib.util.spec_from_loader("kc_sig", loader, origin=_SO_PATH)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
    except Exception as e:  # noqa: BLE001 - fall back to the Python twin
        log.warning("kc_sig import failed, using Python fast key: %s", e)
        return None
    return mod
