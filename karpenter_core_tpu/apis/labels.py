"""Label taxonomy: well-known, restricted, and normalized label keys.

Mirrors /root/reference/pkg/apis/v1alpha5/labels.go:26-109.
"""

from __future__ import annotations

GROUP = "karpenter.sh"
TESTING_GROUP = "testing.karpenter.sh"
COMPATIBILITY_GROUP = "compatibility." + GROUP

# Standard kubernetes label keys (k8s.io/api/core/v1 well-known labels)
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE_STABLE = "node.kubernetes.io/instance-type"
LABEL_ARCH_STABLE = "kubernetes.io/arch"
LABEL_OS_STABLE = "kubernetes.io/os"
LABEL_FAILURE_DOMAIN_BETA_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_FAILURE_DOMAIN_BETA_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_INSTANCE_TYPE_BETA = "beta.kubernetes.io/instance-type"
LABEL_NODE_EXCLUDE_BALANCERS = "node.kubernetes.io/exclude-from-external-load-balancers"
LABEL_NAMESPACE_SUFFIX_NODE = "node.kubernetes.io"

# Well-known values
ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

# Karpenter-specific labels
PROVISIONER_NAME_LABEL_KEY = GROUP + "/provisioner-name"
MACHINE_NAME_LABEL_KEY = GROUP + "/machine-name"
LABEL_NODE_INITIALIZED = GROUP + "/initialized"
LABEL_CAPACITY_TYPE = GROUP + "/capacity-type"

# Karpenter-specific annotations
DO_NOT_EVICT_POD_ANNOTATION_KEY = GROUP + "/do-not-evict"
DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY = GROUP + "/do-not-consolidate"
EMPTINESS_TIMESTAMP_ANNOTATION_KEY = GROUP + "/emptiness-timestamp"
VOLUNTARY_DISRUPTION_ANNOTATION_KEY = GROUP + "/voluntary-disruption"
PROVIDER_COMPATIBILITY_ANNOTATION_KEY = COMPATIBILITY_GROUP + "/provider"
VOLUNTARY_DISRUPTION_DRIFTED_ANNOTATION_VALUE = "drifted"

# Finalizers
TERMINATION_FINALIZER = GROUP + "/termination"

# Restricted label domains: prohibited by the kubelet or reserved by the framework
RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

LABEL_DOMAIN_EXCEPTIONS = frozenset({
    "kops.k8s.io",
    LABEL_NAMESPACE_SUFFIX_NODE,
    TESTING_GROUP,
})

# Mutable: cloud providers may register additional well-known labels
# (mirrors v1alpha5.WellKnownLabels.Insert in the reference's fake provider).
WELL_KNOWN_LABELS = {
    PROVISIONER_NAME_LABEL_KEY,
    LABEL_TOPOLOGY_ZONE,
    LABEL_TOPOLOGY_REGION,
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_ARCH_STABLE,
    LABEL_OS_STABLE,
    LABEL_CAPACITY_TYPE,
}


def register_well_known_labels(*keys: str) -> None:
    WELL_KNOWN_LABELS.update(keys)

RESTRICTED_LABELS = frozenset({
    EMPTINESS_TIMESTAMP_ANNOTATION_KEY,
    LABEL_HOSTNAME,
})

# Aliased labels translated into their canonical forms on requirement construction
NORMALIZED_LABELS = {
    LABEL_FAILURE_DOMAIN_BETA_ZONE: LABEL_TOPOLOGY_ZONE,
    "beta.kubernetes.io/arch": LABEL_ARCH_STABLE,
    "beta.kubernetes.io/os": LABEL_OS_STABLE,
    LABEL_INSTANCE_TYPE_BETA: LABEL_INSTANCE_TYPE_STABLE,
    LABEL_FAILURE_DOMAIN_BETA_REGION: LABEL_TOPOLOGY_REGION,
}


def _domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def is_restricted_label(key: str) -> "str | None":
    """Returns an error string if the label is restricted (labels.go:112-124)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label domain {_domain(key)!r} is restricted; "
            f"specify a well-known label or a custom label that does not use a restricted domain"
        )
    return None


def is_restricted_node_label(key: str) -> bool:
    """True for labels Karpenter must not inject onto nodes itself
    (labels.go:123-138): well-known labels are the CLOUD PROVIDER's to stamp
    (it knows the resolved zone/instance type; rendering them from a
    multi-valued requirement would pick an arbitrary value), restricted
    labels/domains are owned by other software."""
    if key in WELL_KNOWN_LABELS:
        return True
    if key in RESTRICTED_LABELS:
        return True
    domain = _domain(key)
    if domain in LABEL_DOMAIN_EXCEPTIONS or any(
        domain.endswith("." + exc) for exc in LABEL_DOMAIN_EXCEPTIONS
    ):
        return False
    return any(
        domain == restricted or domain.endswith("." + restricted)
        for restricted in RESTRICTED_LABEL_DOMAINS
    )
