"""Provisioner admission validation.

Mirror of /root/reference/pkg/apis/v1alpha5/provisioner_validation.go:34-307:
requirements (supported operators, restricted labels, qualified names, Gt/Lt
integer rules), labels, taints (valid effects, no duplicate key/effect),
TTLs non-negative, consolidation ⊕ ttlSecondsAfterEmpty mutual exclusion, and
kubelet configuration (reserved resources, eviction thresholds).
"""

from __future__ import annotations

import re
from typing import List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    NodeSelectorRequirement,
    Taint,
)
from karpenter_core_tpu.apis.v1alpha5 import KubeletConfiguration, Provisioner
from karpenter_core_tpu.utils import resources as resources_util

SUPPORTED_NODE_SELECTOR_OPS = {OP_IN, OP_NOT_IN, OP_GT, OP_LT, OP_EXISTS, OP_DOES_NOT_EXIST}
SUPPORTED_TAINT_EFFECTS = {"NoSchedule", "PreferNoSchedule", "NoExecute"}

_NAME_RE = re.compile(r"^[a-z0-9A-Z]([-a-z0-9A-Z_.]*[a-z0-9A-Z])?$")
_DNS1123_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")


def _is_qualified_name(key: str) -> Optional[str]:
    """k8s qualified name: optional dns-subdomain prefix + '/' + name ≤63."""
    parts = key.split("/")
    if len(parts) > 2 or not key:
        return "a qualified name must consist of alphanumeric characters"
    name = parts[-1]
    if len(parts) == 2:
        prefix = parts[0]
        if not prefix or len(prefix) > 253 or not _DNS1123_RE.match(prefix):
            return f"prefix part {prefix!r} must be a valid DNS subdomain"
    if not name or len(name) > 63 or not _NAME_RE.match(name):
        return (
            "name part must consist of alphanumeric characters, '-', '_' or '.', "
            "and must start and end with an alphanumeric character"
        )
    return None


def _is_valid_label_value(value: str) -> Optional[str]:
    if len(value) > 63:
        return "must be no more than 63 characters"
    if value and not _NAME_RE.match(value):
        return (
            "a valid label value must be an empty string or consist of alphanumeric "
            "characters, '-', '_' or '.'"
        )
    return None


def validate_requirement(requirement: NodeSelectorRequirement) -> List[str]:
    """ValidateRequirement (provisioner_validation.go:274-307)."""
    errs: List[str] = []
    key = labels_api.NORMALIZED_LABELS.get(requirement.key, requirement.key)
    # the provisioner-name label is managed by the controller and may not be
    # constrained by users (provisioner_validation.go:178)
    if key == labels_api.PROVISIONER_NAME_LABEL_KEY:
        errs.append(f"key {key} is restricted")
    if requirement.operator not in SUPPORTED_NODE_SELECTOR_OPS:
        errs.append(
            f"key {key} has an unsupported operator {requirement.operator} "
            f"not in {sorted(SUPPORTED_NODE_SELECTOR_OPS)}"
        )
    restricted = labels_api.is_restricted_label(key)
    if restricted is not None:
        errs.append(restricted)
    name_err = _is_qualified_name(key)
    if name_err is not None:
        errs.append(f"key {key} is not a qualified name, {name_err}")
    for value in requirement.values:
        value_err = _is_valid_label_value(value)
        if value_err is not None:
            errs.append(f"invalid value {value} for key {key}, {value_err}")
    if requirement.operator == OP_IN and not requirement.values:
        errs.append(f"key {key} with operator {requirement.operator} must have a value defined")
    if requirement.operator in (OP_GT, OP_LT):
        if len(requirement.values) != 1 or not _is_non_negative_int(requirement.values[:1]):
            errs.append(
                f"key {key} with operator {requirement.operator} must have a "
                "single positive integer value"
            )
    return errs


def _is_non_negative_int(values: List[str]) -> bool:
    try:
        return int(values[0]) >= 0
    except (ValueError, IndexError):
        return False


def validate_provisioner(provisioner: Provisioner) -> List[str]:
    """Provisioner.Validate (provisioner_validation.go:65-108)."""
    errs: List[str] = []
    if not provisioner.name:
        errs.append("metadata.name: required")
    spec = provisioner.spec
    if spec.ttl_seconds_until_expired is not None and spec.ttl_seconds_until_expired < 0:
        errs.append("ttlSecondsUntilExpired: cannot be negative")
    if spec.ttl_seconds_after_empty is not None and spec.ttl_seconds_after_empty < 0:
        errs.append("ttlSecondsAfterEmpty: cannot be negative")
    # consolidation and emptiness TTL are mutually exclusive (validation.go:93-96)
    if (
        spec.consolidation is not None
        and spec.consolidation.enabled
        and spec.ttl_seconds_after_empty is not None
    ):
        errs.append("expected exactly one of: ttlSecondsAfterEmpty, consolidation.enabled")

    errs.extend(_validate_labels(provisioner))
    errs.extend(_validate_taints(spec.taints, spec.startup_taints))
    for i, requirement in enumerate(spec.requirements):
        for err in validate_requirement(requirement):
            errs.append(f"requirements[{i}]: {err}")
    if spec.kubelet_configuration is not None:
        errs.extend(_validate_kubelet(spec.kubelet_configuration))
    return errs


def _validate_labels(provisioner: Provisioner) -> List[str]:
    errs: List[str] = []
    for key, value in provisioner.spec.labels.items():
        if key == labels_api.PROVISIONER_NAME_LABEL_KEY:
            errs.append(f"labels[{key}]: restricted")
            continue
        name_err = _is_qualified_name(key)
        if name_err is not None:
            errs.append(f"labels[{key}]: {name_err}")
        value_err = _is_valid_label_value(value)
        if value_err is not None:
            errs.append(f"labels[{key}]={value}: {value_err}")
        if labels_api.is_restricted_label(key) is not None:
            errs.append(f"labels[{key}]: label domain is restricted")
    return errs


def _validate_taints(taints: List[Taint], startup_taints: List[Taint]) -> List[str]:
    """No empty keys, valid effects, no duplicate key/effect pairs across both
    lists (provisioner_validation.go:132-173)."""
    errs: List[str] = []
    existing = set()
    for field_name, taint_list in (("taints", taints), ("startupTaints", startup_taints)):
        for i, taint in enumerate(taint_list):
            if not taint.key:
                errs.append(f"{field_name}[{i}]: taint key is required")
            elif _is_qualified_name(taint.key) is not None:
                errs.append(f"{field_name}[{i}]: invalid taint key {taint.key!r}")
            if taint.value and _is_valid_label_value(taint.value) is not None:
                errs.append(f"{field_name}[{i}]: invalid taint value {taint.value!r}")
            if taint.effect not in SUPPORTED_TAINT_EFFECTS:
                errs.append(f"{field_name}[{i}]: invalid taint effect {taint.effect!r}")
            pair = (taint.key, taint.effect)
            if pair in existing:
                errs.append(
                    f"duplicate taint Key/Effect pair {taint.key}={taint.effect} in {field_name}"
                )
            existing.add(pair)
    return errs


def _validate_kubelet(kc: KubeletConfiguration) -> List[str]:
    errs: List[str] = []
    for field_name, reserved in (
        ("systemReserved", kc.system_reserved),
        ("kubeReserved", kc.kube_reserved),
    ):
        for key, value in reserved.items():
            if value < 0:
                errs.append(
                    f"kubeletConfiguration.{field_name}[{key}]: "
                    "Value cannot be a negative resource quantity"
                )
    for field_name, thresholds in (
        ("evictionHard", kc.eviction_hard),
        ("evictionSoft", kc.eviction_soft),
    ):
        for key, value in thresholds.items():
            err = _validate_threshold(value)
            if err is not None:
                errs.append(f"kubeletConfiguration.{field_name}[{key}]: {err}")
    if kc.max_pods is not None and kc.max_pods < 0:
        errs.append("kubeletConfiguration.maxPods: cannot be negative")
    if kc.pods_per_core is not None and kc.pods_per_core < 0:
        errs.append("kubeletConfiguration.podsPerCore: cannot be negative")
    return errs


def _validate_threshold(value: str) -> Optional[str]:
    if value.endswith("%"):
        try:
            pct = float(value[:-1])
        except ValueError:
            return f"could not be parsed as a percentage value: {value!r}"
        if pct < 0:
            return "percentage values cannot be negative"
        if pct > 100:
            return "percentage values cannot be greater than 100"
        return None
    try:
        resources_util.parse_quantity(value)
    except ValueError:
        return f"could not be parsed as a resource quantity: {value!r}"
    return None


def set_defaults(provisioner: Provisioner) -> Provisioner:
    """Provisioner.SetDefaults (provisioner_defaults.go:22 — a no-op upstream;
    kept as the admission seam)."""
    return provisioner
