"""Wire codec for API objects: dict ⇄ dataclass.

The snapshot channel (service.snapshot_channel) ships pods/provisioners/nodes
between the controller plane and the solver sidecar, and the apiserver-backed
KubeClient (kubeapi/) round-trips every stored kind through these dicts; this
codec keeps the wire format explicit and versionable.  The snapshot channel
consumes only the solver-relevant subset; the kubeapi backend needs full
durability metadata (resourceVersion, finalizers, deletionTimestamp,
ownerReferences) so controller state survives a process restart.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from karpenter_core_tpu.apis.objects import (
    Affinity,
    Container,
    ContainerPort,
    CSINode,
    CSINodeDriver,
    LabelSelector,
    LabelSelectorRequirement,
    Lease,
    LeaseSpec,
    Namespace,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource,
    PersistentVolumeSpec,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodCondition,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodDisruptionBudgetStatus,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    ResourceRequirements,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.apis.v1alpha5 import (
    Consolidation,
    Limits,
    Machine,
    MachineSpec,
    MachineStatus,
    ProviderRef,
    Provisioner,
    ProvisionerSpec,
)


def _meta_to_dict(meta: ObjectMeta) -> Dict[str, Any]:
    out = {
        "name": meta.name,
        "namespace": meta.namespace,
        "uid": meta.uid,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
        "creationTimestamp": meta.creation_timestamp,
        "resourceVersion": meta.resource_version,
        "generation": meta.generation,
    }
    if meta.deletion_timestamp is not None:
        out["deletionTimestamp"] = meta.deletion_timestamp
    if meta.finalizers:
        out["finalizers"] = list(meta.finalizers)
    if meta.owner_references:
        out["ownerReferences"] = [
            {
                "apiVersion": r.api_version,
                "kind": r.kind,
                "name": r.name,
                "uid": r.uid,
                "controller": r.controller,
            }
            for r in meta.owner_references
        ]
    return out


def _meta_from_dict(d: Dict[str, Any]) -> ObjectMeta:
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid") or ObjectMeta().uid,
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        creation_timestamp=d.get("creationTimestamp", 0.0),
        resource_version=int(d.get("resourceVersion", 0) or 0),
        generation=int(d.get("generation", 0) or 0),
        deletion_timestamp=d.get("deletionTimestamp"),
        finalizers=list(d.get("finalizers", [])),
        owner_references=[
            OwnerReference(
                api_version=r.get("apiVersion", ""),
                kind=r.get("kind", ""),
                name=r.get("name", ""),
                uid=r.get("uid", ""),
                controller=r.get("controller", False),
            )
            for r in d.get("ownerReferences", [])
        ],
    )


def _selector_to_dict(s: Optional[LabelSelector]) -> Optional[Dict[str, Any]]:
    if s is None:
        return None
    return {
        "matchLabels": dict(s.match_labels),
        "matchExpressions": [
            {"key": e.key, "operator": e.operator, "values": list(e.values)}
            for e in s.match_expressions
        ],
    }


def _selector_from_dict(d: Optional[Dict[str, Any]]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels", {})),
        match_expressions=[
            LabelSelectorRequirement(e["key"], e["operator"], list(e.get("values", [])))
            for e in d.get("matchExpressions", [])
        ],
    )


def _nsr_to_dict(r: NodeSelectorRequirement) -> Dict[str, Any]:
    return {"key": r.key, "operator": r.operator, "values": list(r.values)}


def _nsr_from_dict(d: Dict[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(d["key"], d["operator"], list(d.get("values", [])))


def _affinity_term_to_dict(t: PodAffinityTerm) -> Dict[str, Any]:
    return {
        "topologyKey": t.topology_key,
        "labelSelector": _selector_to_dict(t.label_selector),
        "namespaces": list(t.namespaces),
    }


def _affinity_term_from_dict(d: Dict[str, Any]) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=d["topologyKey"],
        label_selector=_selector_from_dict(d.get("labelSelector")),
        namespaces=list(d.get("namespaces", [])),
    )


def pod_to_dict(pod: Pod) -> Dict[str, Any]:
    spec = pod.spec
    out: Dict[str, Any] = {
        "metadata": _meta_to_dict(pod.metadata),
        "spec": {
            "nodeSelector": dict(spec.node_selector),
            "nodeName": spec.node_name,
            "tolerations": [
                {"key": t.key, "operator": t.operator, "value": t.value, "effect": t.effect}
                for t in spec.tolerations
            ],
            "containers": [
                {
                    "requests": dict(c.resources.requests),
                    "limits": dict(c.resources.limits),
                    "hostPorts": [
                        {"port": p.host_port, "protocol": p.protocol, "hostIP": p.host_ip}
                        for p in c.ports
                        if p.host_port
                    ],
                }
                for c in spec.containers
            ],
            "topologySpreadConstraints": [
                {
                    "maxSkew": c.max_skew,
                    "topologyKey": c.topology_key,
                    "whenUnsatisfiable": c.when_unsatisfiable,
                    "labelSelector": _selector_to_dict(c.label_selector),
                }
                for c in spec.topology_spread_constraints
            ],
            "priority": spec.priority,
            "priorityClassName": spec.priority_class_name,
            "pvcs": [
                v.persistent_volume_claim.claim_name
                for v in spec.volumes
                if v.persistent_volume_claim is not None
            ],
        },
        "status": {
            "phase": pod.status.phase,
            "conditions": [
                {"type": c.type, "status": c.status, "reason": c.reason}
                for c in pod.status.conditions
            ],
            "startTime": pod.status.start_time,
            "nominatedNodeName": pod.status.nominated_node_name,
        },
    }
    if spec.affinity is not None:
        affinity: Dict[str, Any] = {}
        if spec.affinity.node_affinity is not None:
            na = spec.affinity.node_affinity
            affinity["nodeAffinity"] = {
                "required": (
                    [
                        [_nsr_to_dict(e) for e in term.match_expressions]
                        for term in na.required.node_selector_terms
                    ]
                    if na.required is not None
                    else None
                ),
                "preferred": [
                    {
                        "weight": p.weight,
                        "matchExpressions": [_nsr_to_dict(e) for e in p.preference.match_expressions],
                    }
                    for p in na.preferred
                ],
            }
        if spec.affinity.pod_affinity is not None:
            affinity["podAffinity"] = {
                "required": [_affinity_term_to_dict(t) for t in spec.affinity.pod_affinity.required],
                "preferred": [
                    {"weight": w.weight, "term": _affinity_term_to_dict(w.pod_affinity_term)}
                    for w in spec.affinity.pod_affinity.preferred
                ],
            }
        if spec.affinity.pod_anti_affinity is not None:
            affinity["podAntiAffinity"] = {
                "required": [
                    _affinity_term_to_dict(t) for t in spec.affinity.pod_anti_affinity.required
                ],
                "preferred": [
                    {"weight": w.weight, "term": _affinity_term_to_dict(w.pod_affinity_term)}
                    for w in spec.affinity.pod_anti_affinity.preferred
                ],
            }
        out["spec"]["affinity"] = affinity
    return out


def pod_from_dict(d: Dict[str, Any]) -> Pod:
    spec_d = d.get("spec", {})
    containers = [
        Container(
            resources=ResourceRequirements(
                requests=dict(c.get("requests", {})), limits=dict(c.get("limits", {}))
            ),
            ports=[
                ContainerPort(
                    host_port=p["port"], protocol=p.get("protocol", "TCP"), host_ip=p.get("hostIP", "")
                )
                for p in c.get("hostPorts", [])
            ],
        )
        for c in spec_d.get("containers", [])
    ]
    affinity = None
    aff_d = spec_d.get("affinity")
    if aff_d:
        node_affinity = None
        if "nodeAffinity" in aff_d:
            na = aff_d["nodeAffinity"]
            required = None
            if na.get("required") is not None:
                required = NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(match_expressions=[_nsr_from_dict(e) for e in term])
                        for term in na["required"]
                    ]
                )
            node_affinity = NodeAffinity(
                required=required,
                preferred=[
                    PreferredSchedulingTerm(
                        weight=p["weight"],
                        preference=NodeSelectorTerm(
                            match_expressions=[_nsr_from_dict(e) for e in p["matchExpressions"]]
                        ),
                    )
                    for p in na.get("preferred", [])
                ],
            )
        pod_affinity = None
        if "podAffinity" in aff_d:
            pa = aff_d["podAffinity"]
            pod_affinity = PodAffinity(
                required=[_affinity_term_from_dict(t) for t in pa.get("required", [])],
                preferred=[
                    WeightedPodAffinityTerm(w["weight"], _affinity_term_from_dict(w["term"]))
                    for w in pa.get("preferred", [])
                ],
            )
        pod_anti = None
        if "podAntiAffinity" in aff_d:
            pa = aff_d["podAntiAffinity"]
            pod_anti = PodAntiAffinity(
                required=[_affinity_term_from_dict(t) for t in pa.get("required", [])],
                preferred=[
                    WeightedPodAffinityTerm(w["weight"], _affinity_term_from_dict(w["term"]))
                    for w in pa.get("preferred", [])
                ],
            )
        affinity = Affinity(
            node_affinity=node_affinity, pod_affinity=pod_affinity, pod_anti_affinity=pod_anti
        )
    return Pod(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=PodSpec(
            node_selector=dict(spec_d.get("nodeSelector", {})),
            node_name=spec_d.get("nodeName", ""),
            affinity=affinity,
            tolerations=[
                Toleration(
                    key=t.get("key", ""),
                    operator=t.get("operator", "Equal"),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                )
                for t in spec_d.get("tolerations", [])
            ],
            containers=containers,
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=c["maxSkew"],
                    topology_key=c["topologyKey"],
                    when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                    label_selector=_selector_from_dict(c.get("labelSelector")),
                )
                for c in spec_d.get("topologySpreadConstraints", [])
            ],
            priority=spec_d.get("priority"),
            priority_class_name=spec_d.get("priorityClassName", ""),
            volumes=[
                Volume(
                    name=f"vol-{claim}",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                        claim_name=claim
                    ),
                )
                for claim in spec_d.get("pvcs", [])
            ],
        ),
        status=PodStatus(
            phase=d.get("status", {}).get("phase", "Pending"),
            conditions=[
                PodCondition(
                    type=c.get("type", ""),
                    status=c.get("status", ""),
                    reason=c.get("reason", ""),
                )
                for c in d.get("status", {}).get("conditions", [])
            ],
            start_time=d.get("status", {}).get("startTime"),
            nominated_node_name=d.get("status", {}).get("nominatedNodeName", ""),
        ),
    )


def provisioner_to_dict(p: Provisioner) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(p.metadata),
        "spec": {
            "labels": dict(p.spec.labels),
            "annotations": dict(p.spec.annotations),
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in p.spec.taints
            ],
            "startupTaints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in p.spec.startup_taints
            ],
            "requirements": [_nsr_to_dict(r) for r in p.spec.requirements],
            "ttlSecondsAfterEmpty": p.spec.ttl_seconds_after_empty,
            "ttlSecondsUntilExpired": p.spec.ttl_seconds_until_expired,
            "weight": p.spec.weight,
            "limits": dict(p.spec.limits.resources) if p.spec.limits else None,
            "consolidation": (
                {"enabled": p.spec.consolidation.enabled} if p.spec.consolidation else None
            ),
            "policy": dict(p.spec.policy) if p.spec.policy else None,
        },
    }


def provisioner_from_dict(d: Dict[str, Any]) -> Provisioner:
    spec_d = d.get("spec", {})
    return Provisioner(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=ProvisionerSpec(
            labels=dict(spec_d.get("labels", {})),
            annotations=dict(spec_d.get("annotations", {})),
            taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints", [])
            ],
            startup_taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("startupTaints", [])
            ],
            requirements=[_nsr_from_dict(r) for r in spec_d.get("requirements", [])],
            ttl_seconds_after_empty=spec_d.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec_d.get("ttlSecondsUntilExpired"),
            weight=spec_d.get("weight"),
            limits=(
                Limits(resources=dict(spec_d["limits"])) if spec_d.get("limits") else None
            ),
            consolidation=(
                Consolidation(enabled=spec_d["consolidation"]["enabled"])
                if spec_d.get("consolidation")
                else None
            ),
            policy=dict(spec_d["policy"]) if spec_d.get("policy") else None,
        ),
    )


def node_to_dict(n: Node) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(n.metadata),
        "spec": {
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in n.spec.taints
            ],
            "unschedulable": n.spec.unschedulable,
            "providerID": n.spec.provider_id,
        },
        "status": {
            "capacity": dict(n.status.capacity),
            "allocatable": dict(n.status.allocatable),
            "conditions": [
                {"type": c.type, "status": c.status} for c in n.status.conditions
            ],
            "phase": n.status.phase,
        },
    }


def node_from_dict(d: Dict[str, Any]) -> Node:
    spec_d = d.get("spec", {})
    status_d = d.get("status", {})
    return Node(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=NodeSpec(
            taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints", [])
            ],
            unschedulable=spec_d.get("unschedulable", False),
            provider_id=spec_d.get("providerID", ""),
        ),
        status=NodeStatus(
            capacity=dict(status_d.get("capacity", {})),
            allocatable=dict(status_d.get("allocatable", {})),
            conditions=[
                NodeCondition(type=c.get("type", ""), status=c.get("status", ""))
                for c in status_d.get("conditions", [])
            ],
            phase=status_d.get("phase", ""),
        ),
    )


# -- kubeapi-only kinds -------------------------------------------------------
# Everything the in-memory KubeClient stores must survive an apiserver
# round-trip for restart rebuild (kubeapi/); these kinds never ride the
# snapshot channel, so their codecs carry full (not solver-subset) state.


def machine_to_dict(m: Machine) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(m.metadata),
        "spec": {
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in m.spec.taints
            ],
            "startupTaints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in m.spec.startup_taints
            ],
            "requirements": [_nsr_to_dict(r) for r in m.spec.requirements],
            "resourceRequests": dict(m.spec.resources_requests),
            "machineTemplateRef": (
                {
                    "apiVersion": m.spec.machine_template_ref.api_version,
                    "kind": m.spec.machine_template_ref.kind,
                    "name": m.spec.machine_template_ref.name,
                }
                if m.spec.machine_template_ref is not None
                else None
            ),
        },
        "status": {
            "providerID": m.status.provider_id,
            "capacity": dict(m.status.capacity),
            "allocatable": dict(m.status.allocatable),
        },
    }


def machine_from_dict(d: Dict[str, Any]) -> Machine:
    spec_d = d.get("spec", {})
    status_d = d.get("status", {})
    ref_d = spec_d.get("machineTemplateRef")
    return Machine(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=MachineSpec(
            taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints", [])
            ],
            startup_taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("startupTaints", [])
            ],
            requirements=[_nsr_from_dict(r) for r in spec_d.get("requirements", [])],
            resources_requests=dict(spec_d.get("resourceRequests", {})),
            machine_template_ref=(
                ProviderRef(
                    api_version=ref_d.get("apiVersion", ""),
                    kind=ref_d.get("kind", ""),
                    name=ref_d.get("name", ""),
                )
                if ref_d
                else None
            ),
        ),
        status=MachineStatus(
            provider_id=status_d.get("providerID", ""),
            capacity=dict(status_d.get("capacity", {})),
            allocatable=dict(status_d.get("allocatable", {})),
        ),
    )


def namespace_to_dict(ns: Namespace) -> Dict[str, Any]:
    return {"metadata": _meta_to_dict(ns.metadata)}


def namespace_from_dict(d: Dict[str, Any]) -> Namespace:
    return Namespace(metadata=_meta_from_dict(d.get("metadata", {})))


def pdb_to_dict(pdb: PodDisruptionBudget) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(pdb.metadata),
        "spec": {
            "selector": _selector_to_dict(pdb.spec.selector),
            "minAvailable": pdb.spec.min_available,
            "maxUnavailable": pdb.spec.max_unavailable,
        },
        "status": {"disruptionsAllowed": pdb.status.disruptions_allowed},
    }


def pdb_from_dict(d: Dict[str, Any]) -> PodDisruptionBudget:
    spec_d = d.get("spec", {})
    return PodDisruptionBudget(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=PodDisruptionBudgetSpec(
            selector=_selector_from_dict(spec_d.get("selector")),
            min_available=spec_d.get("minAvailable"),
            max_unavailable=spec_d.get("maxUnavailable"),
        ),
        status=PodDisruptionBudgetStatus(
            disruptions_allowed=d.get("status", {}).get("disruptionsAllowed", 0)
        ),
    )


def pvc_to_dict(pvc: PersistentVolumeClaim) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(pvc.metadata),
        "spec": {
            "storageClassName": pvc.spec.storage_class_name,
            "volumeName": pvc.spec.volume_name,
        },
    }


def pvc_from_dict(d: Dict[str, Any]) -> PersistentVolumeClaim:
    spec_d = d.get("spec", {})
    return PersistentVolumeClaim(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=PersistentVolumeClaimSpec(
            storage_class_name=spec_d.get("storageClassName"),
            volume_name=spec_d.get("volumeName", ""),
        ),
    )


def _node_selector_to_dict(ns: Optional[NodeSelector]) -> Optional[list]:
    if ns is None:
        return None
    return [
        [_nsr_to_dict(e) for e in term.match_expressions]
        for term in ns.node_selector_terms
    ]


def _node_selector_from_dict(terms: Optional[list]) -> Optional[NodeSelector]:
    if terms is None:
        return None
    return NodeSelector(
        node_selector_terms=[
            NodeSelectorTerm(match_expressions=[_nsr_from_dict(e) for e in term])
            for term in terms
        ]
    )


def pv_to_dict(pv: PersistentVolume) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(pv.metadata),
        "spec": {
            "nodeAffinityRequired": _node_selector_to_dict(pv.spec.node_affinity_required),
            "csiDriver": pv.spec.csi_driver,
        },
    }


def pv_from_dict(d: Dict[str, Any]) -> PersistentVolume:
    spec_d = d.get("spec", {})
    return PersistentVolume(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=PersistentVolumeSpec(
            node_affinity_required=_node_selector_from_dict(
                spec_d.get("nodeAffinityRequired")
            ),
            csi_driver=spec_d.get("csiDriver", ""),
        ),
    )


def storageclass_to_dict(sc: StorageClass) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(sc.metadata),
        "provisioner": sc.provisioner,
        "allowedTopologies": [
            [_nsr_to_dict(e) for e in term.match_expressions]
            for term in sc.allowed_topologies
        ],
    }


def storageclass_from_dict(d: Dict[str, Any]) -> StorageClass:
    return StorageClass(
        metadata=_meta_from_dict(d.get("metadata", {})),
        provisioner=d.get("provisioner", ""),
        allowed_topologies=[
            NodeSelectorTerm(match_expressions=[_nsr_from_dict(e) for e in term])
            for term in d.get("allowedTopologies", [])
        ],
    )


def csinode_to_dict(cn: CSINode) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(cn.metadata),
        "drivers": [
            {"name": drv.name, "allocatableCount": drv.allocatable_count}
            for drv in cn.drivers
        ],
    }


def csinode_from_dict(d: Dict[str, Any]) -> CSINode:
    return CSINode(
        metadata=_meta_from_dict(d.get("metadata", {})),
        drivers=[
            CSINodeDriver(
                name=drv.get("name", ""),
                allocatable_count=drv.get("allocatableCount"),
            )
            for drv in d.get("drivers", [])
        ],
    )


def lease_to_dict(lease: Lease) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(lease.metadata),
        "spec": {
            "holderIdentity": lease.spec.holder_identity,
            "leaseDurationSeconds": lease.spec.lease_duration_seconds,
            "acquireTime": lease.spec.acquire_time,
            "renewTime": lease.spec.renew_time,
            "leaseTransitions": lease.spec.lease_transitions,
        },
    }


def lease_from_dict(d: Dict[str, Any]) -> Lease:
    spec_d = d.get("spec", {})
    return Lease(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=LeaseSpec(
            holder_identity=spec_d.get("holderIdentity", ""),
            lease_duration_seconds=spec_d.get("leaseDurationSeconds", 15),
            acquire_time=spec_d.get("acquireTime", 0.0),
            renew_time=spec_d.get("renewTime", 0.0),
            lease_transitions=spec_d.get("leaseTransitions", 0),
        ),
    )
