"""Wire codec for API objects: dict ⇄ dataclass.

The snapshot channel (service.snapshot_channel) ships pods/provisioners/nodes
between the controller plane and the solver sidecar; this codec keeps the wire
format explicit and versionable.  Only solver-relevant fields travel.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from karpenter_core_tpu.apis.objects import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PersistentVolumeClaimVolumeSource,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.apis.v1alpha5 import (
    Consolidation,
    Limits,
    Provisioner,
    ProvisionerSpec,
)


def _meta_to_dict(meta: ObjectMeta) -> Dict[str, Any]:
    return {
        "name": meta.name,
        "namespace": meta.namespace,
        "uid": meta.uid,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
        "creationTimestamp": meta.creation_timestamp,
    }


def _meta_from_dict(d: Dict[str, Any]) -> ObjectMeta:
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid") or ObjectMeta().uid,
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        creation_timestamp=d.get("creationTimestamp", 0.0),
    )


def _selector_to_dict(s: Optional[LabelSelector]) -> Optional[Dict[str, Any]]:
    if s is None:
        return None
    return {
        "matchLabels": dict(s.match_labels),
        "matchExpressions": [
            {"key": e.key, "operator": e.operator, "values": list(e.values)}
            for e in s.match_expressions
        ],
    }


def _selector_from_dict(d: Optional[Dict[str, Any]]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels", {})),
        match_expressions=[
            LabelSelectorRequirement(e["key"], e["operator"], list(e.get("values", [])))
            for e in d.get("matchExpressions", [])
        ],
    )


def _nsr_to_dict(r: NodeSelectorRequirement) -> Dict[str, Any]:
    return {"key": r.key, "operator": r.operator, "values": list(r.values)}


def _nsr_from_dict(d: Dict[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(d["key"], d["operator"], list(d.get("values", [])))


def _affinity_term_to_dict(t: PodAffinityTerm) -> Dict[str, Any]:
    return {
        "topologyKey": t.topology_key,
        "labelSelector": _selector_to_dict(t.label_selector),
        "namespaces": list(t.namespaces),
    }


def _affinity_term_from_dict(d: Dict[str, Any]) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=d["topologyKey"],
        label_selector=_selector_from_dict(d.get("labelSelector")),
        namespaces=list(d.get("namespaces", [])),
    )


def pod_to_dict(pod: Pod) -> Dict[str, Any]:
    spec = pod.spec
    out: Dict[str, Any] = {
        "metadata": _meta_to_dict(pod.metadata),
        "spec": {
            "nodeSelector": dict(spec.node_selector),
            "nodeName": spec.node_name,
            "tolerations": [
                {"key": t.key, "operator": t.operator, "value": t.value, "effect": t.effect}
                for t in spec.tolerations
            ],
            "containers": [
                {
                    "requests": dict(c.resources.requests),
                    "limits": dict(c.resources.limits),
                    "hostPorts": [
                        {"port": p.host_port, "protocol": p.protocol, "hostIP": p.host_ip}
                        for p in c.ports
                        if p.host_port
                    ],
                }
                for c in spec.containers
            ],
            "topologySpreadConstraints": [
                {
                    "maxSkew": c.max_skew,
                    "topologyKey": c.topology_key,
                    "whenUnsatisfiable": c.when_unsatisfiable,
                    "labelSelector": _selector_to_dict(c.label_selector),
                }
                for c in spec.topology_spread_constraints
            ],
            "priority": spec.priority,
            "pvcs": [
                v.persistent_volume_claim.claim_name
                for v in spec.volumes
                if v.persistent_volume_claim is not None
            ],
        },
        "status": {"phase": pod.status.phase},
    }
    if spec.affinity is not None:
        affinity: Dict[str, Any] = {}
        if spec.affinity.node_affinity is not None:
            na = spec.affinity.node_affinity
            affinity["nodeAffinity"] = {
                "required": (
                    [
                        [_nsr_to_dict(e) for e in term.match_expressions]
                        for term in na.required.node_selector_terms
                    ]
                    if na.required is not None
                    else None
                ),
                "preferred": [
                    {
                        "weight": p.weight,
                        "matchExpressions": [_nsr_to_dict(e) for e in p.preference.match_expressions],
                    }
                    for p in na.preferred
                ],
            }
        if spec.affinity.pod_affinity is not None:
            affinity["podAffinity"] = {
                "required": [_affinity_term_to_dict(t) for t in spec.affinity.pod_affinity.required],
                "preferred": [
                    {"weight": w.weight, "term": _affinity_term_to_dict(w.pod_affinity_term)}
                    for w in spec.affinity.pod_affinity.preferred
                ],
            }
        if spec.affinity.pod_anti_affinity is not None:
            affinity["podAntiAffinity"] = {
                "required": [
                    _affinity_term_to_dict(t) for t in spec.affinity.pod_anti_affinity.required
                ],
                "preferred": [
                    {"weight": w.weight, "term": _affinity_term_to_dict(w.pod_affinity_term)}
                    for w in spec.affinity.pod_anti_affinity.preferred
                ],
            }
        out["spec"]["affinity"] = affinity
    return out


def pod_from_dict(d: Dict[str, Any]) -> Pod:
    spec_d = d.get("spec", {})
    containers = [
        Container(
            resources=ResourceRequirements(
                requests=dict(c.get("requests", {})), limits=dict(c.get("limits", {}))
            ),
            ports=[
                ContainerPort(
                    host_port=p["port"], protocol=p.get("protocol", "TCP"), host_ip=p.get("hostIP", "")
                )
                for p in c.get("hostPorts", [])
            ],
        )
        for c in spec_d.get("containers", [])
    ]
    affinity = None
    aff_d = spec_d.get("affinity")
    if aff_d:
        node_affinity = None
        if "nodeAffinity" in aff_d:
            na = aff_d["nodeAffinity"]
            required = None
            if na.get("required") is not None:
                required = NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(match_expressions=[_nsr_from_dict(e) for e in term])
                        for term in na["required"]
                    ]
                )
            node_affinity = NodeAffinity(
                required=required,
                preferred=[
                    PreferredSchedulingTerm(
                        weight=p["weight"],
                        preference=NodeSelectorTerm(
                            match_expressions=[_nsr_from_dict(e) for e in p["matchExpressions"]]
                        ),
                    )
                    for p in na.get("preferred", [])
                ],
            )
        pod_affinity = None
        if "podAffinity" in aff_d:
            pa = aff_d["podAffinity"]
            pod_affinity = PodAffinity(
                required=[_affinity_term_from_dict(t) for t in pa.get("required", [])],
                preferred=[
                    WeightedPodAffinityTerm(w["weight"], _affinity_term_from_dict(w["term"]))
                    for w in pa.get("preferred", [])
                ],
            )
        pod_anti = None
        if "podAntiAffinity" in aff_d:
            pa = aff_d["podAntiAffinity"]
            pod_anti = PodAntiAffinity(
                required=[_affinity_term_from_dict(t) for t in pa.get("required", [])],
                preferred=[
                    WeightedPodAffinityTerm(w["weight"], _affinity_term_from_dict(w["term"]))
                    for w in pa.get("preferred", [])
                ],
            )
        affinity = Affinity(
            node_affinity=node_affinity, pod_affinity=pod_affinity, pod_anti_affinity=pod_anti
        )
    return Pod(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=PodSpec(
            node_selector=dict(spec_d.get("nodeSelector", {})),
            node_name=spec_d.get("nodeName", ""),
            affinity=affinity,
            tolerations=[
                Toleration(
                    key=t.get("key", ""),
                    operator=t.get("operator", "Equal"),
                    value=t.get("value", ""),
                    effect=t.get("effect", ""),
                )
                for t in spec_d.get("tolerations", [])
            ],
            containers=containers,
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=c["maxSkew"],
                    topology_key=c["topologyKey"],
                    when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                    label_selector=_selector_from_dict(c.get("labelSelector")),
                )
                for c in spec_d.get("topologySpreadConstraints", [])
            ],
            priority=spec_d.get("priority"),
            volumes=[
                Volume(
                    name=f"vol-{claim}",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                        claim_name=claim
                    ),
                )
                for claim in spec_d.get("pvcs", [])
            ],
        ),
        status=PodStatus(phase=d.get("status", {}).get("phase", "Pending")),
    )


def provisioner_to_dict(p: Provisioner) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(p.metadata),
        "spec": {
            "labels": dict(p.spec.labels),
            "annotations": dict(p.spec.annotations),
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in p.spec.taints
            ],
            "startupTaints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in p.spec.startup_taints
            ],
            "requirements": [_nsr_to_dict(r) for r in p.spec.requirements],
            "ttlSecondsAfterEmpty": p.spec.ttl_seconds_after_empty,
            "ttlSecondsUntilExpired": p.spec.ttl_seconds_until_expired,
            "weight": p.spec.weight,
            "limits": dict(p.spec.limits.resources) if p.spec.limits else None,
            "consolidation": (
                {"enabled": p.spec.consolidation.enabled} if p.spec.consolidation else None
            ),
        },
    }


def provisioner_from_dict(d: Dict[str, Any]) -> Provisioner:
    spec_d = d.get("spec", {})
    return Provisioner(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=ProvisionerSpec(
            labels=dict(spec_d.get("labels", {})),
            annotations=dict(spec_d.get("annotations", {})),
            taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints", [])
            ],
            startup_taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("startupTaints", [])
            ],
            requirements=[_nsr_from_dict(r) for r in spec_d.get("requirements", [])],
            ttl_seconds_after_empty=spec_d.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec_d.get("ttlSecondsUntilExpired"),
            weight=spec_d.get("weight"),
            limits=(
                Limits(resources=dict(spec_d["limits"])) if spec_d.get("limits") else None
            ),
            consolidation=(
                Consolidation(enabled=spec_d["consolidation"]["enabled"])
                if spec_d.get("consolidation")
                else None
            ),
        ),
    )


def node_to_dict(n: Node) -> Dict[str, Any]:
    return {
        "metadata": _meta_to_dict(n.metadata),
        "spec": {
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in n.spec.taints
            ],
            "unschedulable": n.spec.unschedulable,
            "providerID": n.spec.provider_id,
        },
        "status": {
            "capacity": dict(n.status.capacity),
            "allocatable": dict(n.status.allocatable),
        },
    }


def node_from_dict(d: Dict[str, Any]) -> Node:
    spec_d = d.get("spec", {})
    status_d = d.get("status", {})
    return Node(
        metadata=_meta_from_dict(d.get("metadata", {})),
        spec=NodeSpec(
            taints=[
                Taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
                for t in spec_d.get("taints", [])
            ],
            unschedulable=spec_d.get("unschedulable", False),
            provider_id=spec_d.get("providerID", ""),
        ),
        status=NodeStatus(
            capacity=dict(status_d.get("capacity", {})),
            allocatable=dict(status_d.get("allocatable", {})),
        ),
    )
