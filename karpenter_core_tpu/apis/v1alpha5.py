"""Provisioner and Machine API types.

Mirrors /root/reference/pkg/apis/v1alpha5/{provisioner.go:32-140, machine.go:23-117,
limits.go}.  These are declarative configuration objects: a Provisioner describes
the shape of capacity the framework may launch; a Machine is a launch request
handed to the cloud provider.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    NodeSelectorRequirement,
    ObjectMeta,
    Taint,
)
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class KubeletConfiguration:
    cluster_dns: List[str] = field(default_factory=list)
    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    system_reserved: resources_util.ResourceList = field(default_factory=dict)
    kube_reserved: resources_util.ResourceList = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)
    eviction_soft: Dict[str, str] = field(default_factory=dict)
    container_runtime: Optional[str] = None


@dataclass
class Consolidation:
    enabled: bool = False


@dataclass
class Limits:
    """Provisioner-wide resource ceilings (limits.go)."""

    resources: resources_util.ResourceList = field(default_factory=dict)

    def exceeded_by(self, usage: resources_util.ResourceList) -> Optional[str]:
        """Error string if usage >= limit for any used resource; iterates usage
        keys so a limit on an absent resource does not trip (limits.go:29-40)."""
        for name, used in usage.items():
            if name in self.resources and resources_util.cmp(used, self.resources[name]) >= 0:
                return (
                    f"{name} resource usage of {resources_util.format_quantity(used)} exceeds "
                    f"limit of {resources_util.format_quantity(self.resources[name])}"
                )
        return None


@dataclass
class ProviderRef:
    api_version: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class ProvisionerSpec:
    # Constraints applied to all nodes launched by this provisioner
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    kubelet_configuration: Optional[KubeletConfiguration] = None
    provider: Optional[Dict[str, Any]] = None
    provider_ref: Optional[ProviderRef] = None
    # Deprovisioning behavior
    ttl_seconds_after_empty: Optional[int] = None
    ttl_seconds_until_expired: Optional[int] = None
    consolidation: Optional[Consolidation] = None
    # Scheduling priority across provisioners (higher wins; provisioner.go:132)
    weight: Optional[int] = None
    limits: Optional[Limits] = None
    # Policy-objective block (docs/POLICY.md): wire-cased knobs consumed by
    # policy.PolicyConfig.merged — enabled / costWeight / throughputWeight /
    # riskAversion / spotPreference / counterProposals / maxResizeFraction /
    # throughput.  None = objective off, today's behavior exactly.
    policy: Optional[Dict[str, Any]] = None


@dataclass
class ProvisionerStatus:
    resources: resources_util.ResourceList = field(default_factory=dict)
    last_scale_time: Optional[float] = None


@dataclass
class Provisioner:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProvisionerSpec = field(default_factory=ProvisionerSpec)
    status: ProvisionerStatus = field(default_factory=ProvisionerStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


def order_by_weight(provisioners: List[Provisioner]) -> List[Provisioner]:
    """Highest weight first (provisioner.go:132 OrderByWeight)."""
    return sorted(provisioners, key=lambda p: p.spec.weight or 0, reverse=True)


@dataclass
class MachineSpec:
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[NodeSelectorRequirement] = field(default_factory=list)
    kubelet: Optional[KubeletConfiguration] = None
    resources_requests: resources_util.ResourceList = field(default_factory=dict)
    machine_template_ref: Optional[ProviderRef] = None


@dataclass
class MachineStatus:
    provider_id: str = ""
    capacity: resources_util.ResourceList = field(default_factory=dict)
    allocatable: resources_util.ResourceList = field(default_factory=dict)


@dataclass
class Machine:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MachineSpec = field(default_factory=MachineSpec)
    status: MachineStatus = field(default_factory=MachineStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


def provisioner_name_of(obj) -> Optional[str]:
    """The owning provisioner of a node/machine, from its labels."""
    return obj.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
