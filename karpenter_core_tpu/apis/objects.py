"""Kubernetes-shaped object model (pods, nodes, selectors, affinity).

The framework is standalone — there is no apiserver — so we carry a lightweight
but faithful object model covering everything the scheduler and controllers
consume.  Field names follow k8s conventions in snake_case.  Semantics of
matching/toleration helpers mirror k8s.io/api/core/v1 as exercised by the
reference (taints.go:28, topology.go:366-402).
"""

from __future__ import annotations

import itertools
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.utils import resources as resources_util

# --- metadata ---------------------------------------------------------------

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"{next(_uid_counter):08x}-{uuid.uuid4().hex[:12]}"


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    resource_version: int = 0
    generation: int = 0


# --- selectors --------------------------------------------------------------


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for key, value in self.match_labels.items():
            if labels.get(key) != value:
                return False
        for expr in self.match_expressions:
            present = expr.key in labels
            if expr.operator == "In":
                if not present or labels[expr.key] not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if present and labels[expr.key] in expr.values:
                    return False
            elif expr.operator == "Exists":
                if not present:
                    return False
            elif expr.operator == "DoesNotExist":
                if present:
                    return False
            else:
                return False
        return True


# --- node selection / affinity ----------------------------------------------

# NodeSelectorOperator values
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# --- taints / tolerations ---------------------------------------------------

TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_EFFECT_NO_SCHEDULE


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates_taint(self, taint: Taint) -> bool:
        """Mirror of v1.Toleration.ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        # Equal (default): empty key requires Exists to match all
        if not self.key and self.operator != "Exists":
            return False
        return self.value == taint.value


# --- topology spread --------------------------------------------------------

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None


# --- containers / pods ------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class ResourceRequirements:
    requests: resources_util.ResourceList = field(default_factory=dict)
    limits: resources_util.ResourceList = field(default_factory=dict)


@dataclass
class Container:
    name: str = "app"
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None


@dataclass
class PodSpec:
    node_selector: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    volumes: List[Volume] = field(default_factory=list)
    scheduler_name: str = "default-scheduler"
    termination_grace_period_seconds: Optional[int] = None


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    start_time: Optional[float] = None
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def uid(self) -> str:
        return self.metadata.uid

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


# --- nodes ------------------------------------------------------------------


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""


NODE_READY = "Ready"


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    provider_id: str = ""


@dataclass
class NodeStatus:
    capacity: resources_util.ResourceList = field(default_factory=dict)
    allocatable: resources_util.ResourceList = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)
    phase: str = ""


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


# --- disruption budgets -----------------------------------------------------


@dataclass
class PodDisruptionBudgetSpec:
    selector: Optional[LabelSelector] = None
    min_available: "int | str | None" = None
    max_unavailable: "int | str | None" = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)


# --- storage ----------------------------------------------------------------


@dataclass
class PersistentVolumeClaimSpec:
    storage_class_name: Optional[str] = None
    volume_name: str = ""


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)


@dataclass
class PersistentVolumeSpec:
    node_affinity_required: Optional[NodeSelector] = None
    csi_driver: str = ""


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    allowed_topologies: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class CSINodeDriver:
    name: str = ""
    allocatable_count: Optional[int] = None


@dataclass
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: List[CSINodeDriver] = field(default_factory=list)


# --- coordination (leader election) ------------------------------------------


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 LeaseSpec — the leader-election primitive
    (reference runs leader-elected replicas, operator.go:111-126)."""

    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


# --- namespace --------------------------------------------------------------


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)


def deep_copy(obj):
    """Structural copy of any of the dataclasses above."""
    import copy

    return copy.deepcopy(obj)


__all__ = [name for name in dir() if not name.startswith("_")]
