"""Per-solve decision audit: *why* a pod failed to schedule.

The reference treats scheduling-decision explainability as a product surface
(pod events carry the failure string; karpenter's FAQ is largely "why is my
pod unschedulable").  Here the host scheduler's per-candidate rejection
strings are classified into the predicate that fired — resources, taints,
affinity, topology, host ports, volumes, requirements — and attached to the
active trace as structured ``decision.audit`` span events, one per
unschedulable pod, listing each candidate and the predicate that rejected it.
``/debug/traces`` surfaces them; ``Trace.audits()`` collects them.

Audits are recorded only while tracing is enabled — the rejection lists are
debug artifacts and the hot path must not accumulate them unconditionally.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from karpenter_core_tpu.tracing import trace as _trace

# most-specific first: the first matching needle names the predicate
_PREDICATE_NEEDLES = (
    ("tolerate", "taints"),
    ("taint", "taints"),
    (" port=", "host-ports"),
    ("host port", "host-ports"),
    ("volume", "volumes"),
    ("exceeds node resources", "resources"),
    ("no instance type satisfied", "resources"),
    ("pod anti-affinity", "affinity"),
    ("pod affinity", "affinity"),
    ("anti-affinit", "affinity"),
    ("topology spread", "topology"),
    ("topology", "topology"),
    ("incompatible requirements", "requirements"),
    ("does not have known values", "requirements"),
    ("not in", "requirements"),
    ("provisioner limits", "limits"),
)

# cap the per-pod candidate list: on a 1000-node cluster one unschedulable
# pod would otherwise record 1000 rejections per relaxation attempt
MAX_REJECTIONS_PER_POD = 40


def classify_rejection(err: Optional[str]) -> str:
    """Map a scheduler rejection string to the predicate that fired."""
    if not err:
        return "unknown"
    lowered = err.lower()
    for needle, predicate in _PREDICATE_NEEDLES:
        if needle in lowered:
            return predicate
    return "other"


def rejection(candidate: str, err: str) -> Dict[str, Any]:
    """One structured candidate-rejection entry."""
    return {
        "candidate": candidate,
        "predicate": classify_rejection(err),
        "error": err[:200],
    }


def record_unschedulable(
    pod,
    rejections: Optional[List[Dict[str, Any]]] = None,
    error: Optional[str] = None,
    engine: str = "host",
    count: int = 1,
) -> None:
    """Attach one ``decision.audit`` event for an unschedulable pod (or, for
    the kernel path, a whole class of identical pods) to the active span."""
    rejections = rejections or []
    predicates = sorted({r["predicate"] for r in rejections})
    _trace.add_event(
        "decision.audit",
        pod=getattr(pod.metadata, "name", "") or "",
        namespace=pod.namespace or "",
        uid=pod.uid,
        engine=engine,
        count=count,
        error=(error or "")[:300],
        predicates=predicates,
        rejections=rejections[:MAX_REJECTIONS_PER_POD],
        truncated=len(rejections) > MAX_REJECTIONS_PER_POD,
    )
