"""Tracing: spans over the solve pipeline, a ring-buffer trace store,
decision audits, and exporters (JSON-lines + Chrome trace-event format).

See docs/OBSERVABILITY.md for the operator surface (``/debug/traces``).
"""

from karpenter_core_tpu.tracing.trace import (
    MAX_EVENTS_PER_SPAN,
    Span,
    Trace,
    TraceStore,
    TRACE_STORE,
    add_event,
    current,
    disable,
    enable,
    enabled,
    span,
    span_remote,
    traced,
    wire_context,
)
from karpenter_core_tpu.tracing.export import from_jsonl, to_chrome, to_jsonl
from karpenter_core_tpu.tracing.audit import (
    classify_rejection,
    record_unschedulable,
    rejection,
)

__all__ = [
    "MAX_EVENTS_PER_SPAN",
    "Span",
    "Trace",
    "TraceStore",
    "TRACE_STORE",
    "add_event",
    "classify_rejection",
    "current",
    "disable",
    "enable",
    "enabled",
    "from_jsonl",
    "record_unschedulable",
    "rejection",
    "span",
    "span_remote",
    "to_chrome",
    "to_jsonl",
    "traced",
    "wire_context",
]
