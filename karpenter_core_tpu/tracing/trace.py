"""Span-based solve tracing: contexts, ids, and the in-memory trace store.

The role the reference delegates to controller-runtime's logging/tracing
context (knative logging + the scheduling loop's structured messages) is
re-centered here as explicit spans, because the hot path this repo cares
about is a *pipeline* (ingest → encode → dispatch → solve → decode →
materialize) whose cost attribution is invisible in wall-clock logging —
BENCH_r05 showed `solve_decode_s` at 98% of warm time with no internal
breakdown.

Design constraints:

  - Near-zero cost when disabled (one module-global check per ``span()``).
    Tracing is opt-in: ``enable()``, or the ``KC_TRACE=1`` environment
    variable at import time.
  - Thread-aware: the current span propagates through a ``contextvars``
    context, so concurrent reconciles interleave without clobbering each
    other.  A span opened on a worker thread with no inherited context
    becomes the root of its own trace.
  - JAX-aware: device work is asynchronously dispatched, so a naive span
    around a kernel call measures dispatch, not compute — and the cost
    folds into whichever later span first touches the result.  A span
    given a ``sync`` target calls ``jax.block_until_ready`` on it at close
    so device time lands in the span that dispatched it.
  - Bounded memory: completed traces land in a thread-safe ring buffer
    (``TraceStore``); old traces fall off the end.

Spans also feed ``metrics.registry.SOLVE_STAGE_DURATION`` (one histogram
time series per span name) with a ``trace_id`` exemplar, so a scrape can
link a latency outlier back to the exact trace that produced it.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

_enabled = os.environ.get("KC_TRACE", "") == "1"
# completion-order appends can arrive from several threads of one trace
_finish_lock = threading.Lock()
_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "kc_tracing_current", default=None
)

# span-event payloads are debug artifacts, not a database: cap the per-span
# event count so a pathological solve (50k failed pods) cannot balloon a trace
MAX_EVENTS_PER_SPAN = 256


def enabled() -> bool:
    return _enabled


def enable(capacity: Optional[int] = None) -> None:
    global _enabled
    if capacity is not None:
        TRACE_STORE.set_capacity(capacity)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def _new_id(nbytes: int) -> str:
    return uuid.uuid4().hex[: nbytes * 2]


class Span:
    """One timed operation.  Created by ``span()``; closed spans serialize to
    plain dicts (the exchange format of the exporters and ``/debug/traces``)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs", "events",
        "start_wall", "_t0", "duration_s", "_root", "_finished", "_sync",
    )

    def __init__(self, name: str, parent: Optional["Span"], attrs: Dict[str, Any]) -> None:
        self.name = name
        self.attrs = dict(attrs)
        # the tenant attribute is baggage: a span opened under a tenant-owned
        # span belongs to that tenant, so per-tenant trace filtering sees the
        # WHOLE server-side subtree (encode/dispatch/decode), not just the
        # envelope span — only paid when tracing is on
        if parent is not None and "tenant" not in self.attrs:
            tenant = parent.attrs.get("tenant")
            if tenant is not None:
                self.attrs["tenant"] = tenant
        self.events: List[Dict[str, Any]] = []
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = parent.trace_id if parent is not None else _new_id(8)
        self.span_id = _new_id(4)
        self._root = parent._root if parent is not None else self
        self._finished: List[Dict[str, Any]] = [] if parent is None else None
        self._sync = None
        self.duration_s = None
        self.start_wall = time.time()
        self._t0 = time.perf_counter()

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs: Any) -> None:
        if len(self.events) >= MAX_EVENTS_PER_SPAN:
            return
        self.events.append({"name": name, "wall": time.time(), "attrs": attrs})

    def sync_on(self, value: Any) -> Any:
        """Register a (possibly still-dispatching) jax pytree to block on at
        span close, so async device work is attributed to THIS span."""
        self._sync = value
        return value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "startWall": self.start_wall,
            "durationS": self.duration_s,
            "attrs": self.attrs,
            "events": self.events,
        }

    def _finish(self) -> None:
        if self._sync is not None:
            try:
                import jax

                jax.block_until_ready(self._sync)
            except Exception:  # noqa: BLE001 - tracing must never break the solve
                pass
            self._sync = None
        self.duration_s = time.perf_counter() - self._t0
        record = self.to_dict()
        root = self._root
        with _finish_lock:
            if root._finished is not None:
                root._finished.append(record)
        try:
            from karpenter_core_tpu.metrics.registry import SOLVE_STAGE_DURATION

            SOLVE_STAGE_DURATION.labels(self.name).observe(
                self.duration_s,
                exemplar={"trace_id": self.trace_id, "span_id": self.span_id},
            )
        except Exception:  # noqa: BLE001 - metrics failures must not surface
            pass
        if root is self:
            spans, self._finished = self._finished, None
            TRACE_STORE.add(
                Trace(
                    trace_id=self.trace_id,
                    name=self.name,
                    start_wall=self.start_wall,
                    duration_s=self.duration_s,
                    spans=spans,
                )
            )


class _NoopSpan:
    """The disabled-path span: every method is a cheap no-op."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    name = ""
    duration_s = None

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def sync_on(self, value: Any) -> Any:
        return value


_NOOP = _NoopSpan()


@contextlib.contextmanager
def span(name: str, sync: Any = None, **attrs: Any) -> Iterator[object]:
    """Open a span under the current one (or start a new trace).  ``sync``
    (or a later ``sp.sync_on(x)``) blocks on a jax pytree at close so device
    time is attributed here.  When tracing is disabled this is one branch."""
    if not _enabled:
        yield _NOOP
        return
    parent = _current.get()
    sp = Span(name, parent, attrs)
    if sync is not None:
        sp.sync_on(sync)
    token = _current.set(sp)
    try:
        yield sp
    except BaseException as e:
        sp.attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
        raise
    finally:
        _current.reset(token)
        sp._finish()


def current() -> Optional[Span]:
    """The active span, or None (also None when tracing is disabled)."""
    return _current.get()


def wire_context() -> Optional[Dict[str, str]]:
    """The active span's identity as a wire-portable context dict
    (``{"traceId", "spanId"}``) for stamping into RPC envelopes and journal
    records, or None when tracing is off / no span is active.  The W3C
    traceparent idea without the header spelling: trace id + parent span id
    are all a remote side needs to join the tree."""
    if not _enabled:
        return None
    sp = _current.get()
    if sp is None or not sp.trace_id:
        return None
    return {"traceId": sp.trace_id, "spanId": sp.span_id}


@contextlib.contextmanager
def span_remote(
    name: str, ctx: Optional[Dict[str, Any]], sync: Any = None, **attrs: Any
) -> Iterator[object]:
    """Open a span that ADOPTS a remote trace context: same disabled-path
    contract as ``span()`` (one flag check), but when ``ctx`` carries a
    ``traceId`` the new span joins that trace — it records the remote span as
    its parent while remaining a store-root on THIS side, so its completed
    segment lands in the local ``TRACE_STORE`` under the adopted trace id
    (``TraceStore.tree`` merges the segments back into one tree).  A missing
    or empty ``ctx`` degrades to a plain ``span()``."""
    if not _enabled:
        yield _NOOP
        return
    trace_id = str((ctx or {}).get("traceId") or "")
    if not trace_id:
        with span(name, sync=sync, **attrs) as sp:
            yield sp
        return
    sp = Span(name, None, attrs)
    sp.trace_id = trace_id
    sp.parent_id = str(ctx.get("spanId") or "") or None
    if sync is not None:
        sp.sync_on(sync)
    token = _current.set(sp)
    try:
        yield sp
    except BaseException as e:
        sp.attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
        raise
    finally:
        _current.reset(token)
        sp._finish()


def add_event(name: str, **attrs: Any) -> None:
    """Attach a structured event to the active span (no-op without one)."""
    sp = _current.get()
    if sp is not None:
        sp.event(name, **attrs)


def traced(name: str, **attrs: Any):
    """Decorator form of ``span()`` for controller entry points; the static
    gate (tools/check_instrumented.py) accepts either spelling."""

    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any):
            with span(name, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return decorate


@dataclass
class Trace:
    """One completed trace: the root span's identity plus every span that
    closed under it, in completion order (sort by ``startWall`` to replay)."""

    trace_id: str
    name: str
    start_wall: float
    duration_s: float
    spans: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "name": self.name,
            "startWall": self.start_wall,
            "durationS": self.duration_s,
            "spans": self.spans,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Trace":
        return cls(
            trace_id=data["traceId"],
            name=data["name"],
            start_wall=data["startWall"],
            duration_s=data["durationS"],
            spans=list(data.get("spans") or ()),
        )

    def stage_durations(self) -> Dict[str, float]:
        """span name -> summed duration (seconds) across the trace."""
        out: Dict[str, float] = {}
        for rec in self.spans:
            if rec.get("durationS") is not None:
                out[rec["name"]] = out.get(rec["name"], 0.0) + rec["durationS"]
        return out

    def audits(self) -> List[Dict[str, Any]]:
        """Every decision-audit event in the trace (tracing.audit)."""
        out = []
        for rec in self.spans:
            for event in rec.get("events") or ():
                if event.get("name") == "decision.audit":
                    out.append(event.get("attrs") or {})
        return out


class TraceStore:
    """Thread-safe ring buffer of the last N completed traces."""

    def __init__(self, capacity: int = 64) -> None:
        self._lock = threading.Lock()
        self._traces: deque = deque(maxlen=max(capacity, 1))

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._traces.append(trace)

    def last(self, n: Optional[int] = None) -> List[Trace]:
        """The most recent ``n`` traces (all when None), oldest first."""
        with self._lock:
            traces = list(self._traces)
        return traces if n is None or n <= 0 else traces[-n:]

    def find(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            for trace in reversed(self._traces):
                if trace.trace_id == trace_id:
                    return trace
        return None

    def tree(self, trace_id: str) -> Optional[Trace]:
        """All stored segments of one trace merged into a single tree.

        Cross-boundary propagation (``span_remote``) lands each side's
        segment as its own ``Trace`` entry sharing the trace id — the client
        RPC span, the server session tick, a warm-restart replay.  This
        merges them: spans combined in wall-clock order, the earliest
        segment's root named, duration spanning first start to last end."""
        with self._lock:
            matches = [t for t in self._traces if t.trace_id == trace_id]
        if not matches:
            return None
        if len(matches) == 1:
            return matches[0]
        spans: List[Dict[str, Any]] = []
        for t in matches:
            spans.extend(t.spans)
        spans.sort(key=lambda rec: rec.get("startWall") or 0.0)
        first = min(matches, key=lambda t: t.start_wall)
        end = max(t.start_wall + (t.duration_s or 0.0) for t in matches)
        return Trace(
            trace_id=trace_id,
            name=first.name,
            start_wall=first.start_wall,
            duration_s=end - first.start_wall,
            spans=spans,
        )

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._traces = deque(self._traces, maxlen=max(capacity, 1))

    @property
    def capacity(self) -> int:
        return self._traces.maxlen

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


def _capacity_from_env() -> int:
    try:
        return int(os.environ.get("KC_TRACE_CAPACITY", "64") or 64)
    except ValueError:
        return 64  # a tuning-knob typo must not take the operator down


TRACE_STORE = TraceStore(_capacity_from_env())
