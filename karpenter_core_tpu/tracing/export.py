"""Trace exporters: JSON-lines and Chrome trace-event format.

JSON-lines is the archival/round-trip format (one span per line, prefixed by
one trace-header line) — greppable, streamable, and loadable back into
``Trace`` objects with ``from_jsonl``.

The Chrome format (``to_chrome``) emits the trace-event JSON that
``chrome://tracing`` and Perfetto's legacy loader read: complete events
(``ph: "X"``, microsecond ``ts``/``dur``) per span, instant events
(``ph: "i"``) per span event, one ``tid`` lane per trace so concurrent
solves render side by side.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List

from karpenter_core_tpu.tracing.trace import Trace


def to_jsonl(trace: Trace) -> str:
    """One header line + one line per span; ends with a newline."""
    lines = [
        json.dumps(
            {
                "kind": "trace",
                "traceId": trace.trace_id,
                "name": trace.name,
                "startWall": trace.start_wall,
                "durationS": trace.duration_s,
            }
        )
    ]
    for rec in trace.spans:
        lines.append(json.dumps({"kind": "span", **rec}))
    return "\n".join(lines) + "\n"


def from_jsonl(text: str) -> List[Trace]:
    """Inverse of ``to_jsonl`` over a concatenation of exported traces."""
    traces: List[Trace] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("kind") == "trace":
            traces.append(
                Trace(
                    trace_id=rec["traceId"],
                    name=rec["name"],
                    start_wall=rec["startWall"],
                    duration_s=rec["durationS"],
                )
            )
        elif rec.get("kind") == "span" and traces:
            rec.pop("kind")
            traces[-1].spans.append(rec)
    return traces


def to_chrome(traces: Iterable[Trace]) -> Dict[str, Any]:
    """Chrome trace-event JSON object for a set of traces (load the dumped
    file in chrome://tracing or ui.perfetto.dev)."""
    events: List[Dict[str, Any]] = []
    for tid, trace in enumerate(traces, start=1):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": f"{trace.name} {trace.trace_id}"},
            }
        )
        for rec in trace.spans:
            ts_us = rec["startWall"] * 1e6
            events.append(
                {
                    "name": rec["name"],
                    "cat": "solve",
                    "ph": "X",
                    "ts": ts_us,
                    "dur": (rec.get("durationS") or 0.0) * 1e6,
                    "pid": 1,
                    "tid": tid,
                    "args": {
                        "traceId": rec["traceId"],
                        "spanId": rec["spanId"],
                        "parentId": rec.get("parentId"),
                        **(rec.get("attrs") or {}),
                    },
                }
            )
            for event in rec.get("events") or ():
                events.append(
                    {
                        "name": event["name"],
                        "cat": "event",
                        "ph": "i",
                        "s": "t",
                        "ts": event["wall"] * 1e6,
                        "pid": 1,
                        "tid": tid,
                        "args": event.get("attrs") or {},
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
