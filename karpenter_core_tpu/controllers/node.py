"""Node lifecycle: initialization, emptiness stamping, finalizer, drift.

Mirror of /root/reference/pkg/controllers/node/{controller.go:86-137,
initialization.go:39-125, emptiness.go:44-92, finalizer.go:36-49,
drift.go:39-60}: a sub-reconciler chain over nodes owned by a provisioner.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node, OwnerReference
from karpenter_core_tpu.apis.v1alpha5 import Machine, MachineSpec, MachineStatus, Provisioner
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils import node as node_util
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import resources as resources_util
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

DRIFT_POLL_INTERVAL = 5 * 60.0  # drift.go: 5 minute requeue


class Initialization:
    """Sets karpenter.sh/initialized=true once Ready + startup taints removed +
    extended resources registered (initialization.go:39-125)."""

    def __init__(self, cloud_provider) -> None:
        self.cloud_provider = cloud_provider

    def reconcile(self, provisioner: Optional[Provisioner], node: Node) -> Optional[float]:
        if node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) == "true":
            return None
        instance_type = self._get_instance_type(
            provisioner, node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
        )
        if not self._is_initialized(node, provisioner, instance_type):
            return None
        node.metadata.labels[labels_api.LABEL_NODE_INITIALIZED] = "true"
        return None

    def _get_instance_type(self, provisioner, name):
        if provisioner is None:
            return None
        for it in self.cloud_provider.get_instance_types(provisioner):
            if it.name == name:
                return it
        return None

    def _is_initialized(self, node: Node, provisioner, instance_type) -> bool:
        condition = node_util.get_condition(node, "Ready")
        if condition is None or condition.status != "True":
            return False
        if not startup_taint_removed(node, provisioner)[1]:
            return False
        if not extended_resource_registered(node, instance_type)[1]:
            return False
        return True


def startup_taint_removed(node: Node, provisioner) -> Tuple[Optional[object], bool]:
    if provisioner is not None:
        for startup_taint in provisioner.spec.startup_taints:
            for taint in node.spec.taints:
                if (
                    startup_taint.key == taint.key
                    and startup_taint.value == taint.value
                    and startup_taint.effect == taint.effect
                ):
                    return taint, False
    return None, True


def extended_resource_registered(node: Node, instance_type) -> Tuple[str, bool]:
    """Device-plugin resources show as zero allocatable until registered
    (initialization.go:108-125)."""
    if instance_type is None:
        return "", True
    for name, quantity in instance_type.capacity.items():
        if resources_util.is_zero(quantity):
            continue
        if resources_util.is_zero(node.status.allocatable.get(name, 0.0)):
            return name, False
    return "", True


class EmptinessStamper:
    """Stamps/clears the emptiness-timestamp annotation (emptiness.go:44-92)."""

    def __init__(self, clock: Clock, kube_client, cluster: Cluster) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.cluster = cluster

    def reconcile(self, provisioner: Optional[Provisioner], node: Node) -> Optional[float]:
        if provisioner is None or provisioner.spec.ttl_seconds_after_empty is None:
            return None
        if node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) != "true":
            return None
        empty = self._is_empty(node)
        if self.cluster.is_node_nominated(node.name):
            return None
        has_timestamp = labels_api.EMPTINESS_TIMESTAMP_ANNOTATION_KEY in node.metadata.annotations
        if not empty and has_timestamp:
            del node.metadata.annotations[labels_api.EMPTINESS_TIMESTAMP_ANNOTATION_KEY]
            log.info("removed emptiness TTL from node %s", node.name)
        elif empty and not has_timestamp:
            node.metadata.annotations[labels_api.EMPTINESS_TIMESTAMP_ANNOTATION_KEY] = str(
                self.clock.now()
            )
            log.info("added TTL to empty node %s", node.name)
        return 60.0

    def _is_empty(self, node: Node) -> bool:
        for pod in self.kube_client.list_pods(selector=lambda p: p.spec.node_name == node.name):
            if (
                not pod_util.is_terminal(pod)
                and not pod_util.is_owned_by_daemon_set(pod)
                and not pod_util.is_owned_by_node(pod)
            ):
                return False
        return True


class Finalizer:
    """Ensures the termination finalizer and provisioner owner-ref
    (finalizer.go:36-49)."""

    def reconcile(self, provisioner: Optional[Provisioner], node: Node) -> Optional[float]:
        if node.metadata.deletion_timestamp is not None:
            return None
        if labels_api.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(labels_api.TERMINATION_FINALIZER)
        if provisioner is not None and not any(
            ref.kind == "Provisioner" for ref in node.metadata.owner_references
        ):
            node.metadata.owner_references.append(
                OwnerReference(
                    api_version="karpenter.sh/v1alpha5",
                    kind="Provisioner",
                    name=provisioner.name,
                    uid=provisioner.metadata.uid,
                )
            )
        return None


class DriftDetector:
    """Polls CloudProvider.is_machine_drifted and annotates (drift.go:39-60)."""

    def __init__(self, cloud_provider, settings) -> None:
        self.cloud_provider = cloud_provider
        self.settings = settings

    def reconcile(self, provisioner: Optional[Provisioner], node: Node) -> Optional[float]:
        if not self.settings.drift_enabled:
            return None
        if (
            node.metadata.annotations.get(labels_api.VOLUNTARY_DISRUPTION_ANNOTATION_KEY)
            == labels_api.VOLUNTARY_DISRUPTION_DRIFTED_ANNOTATION_VALUE
        ):
            return DRIFT_POLL_INTERVAL
        machine = machine_from_node(node)
        if self.cloud_provider.is_machine_drifted(machine):
            node.metadata.annotations[labels_api.VOLUNTARY_DISRUPTION_ANNOTATION_KEY] = (
                labels_api.VOLUNTARY_DISRUPTION_DRIFTED_ANNOTATION_VALUE
            )
        return DRIFT_POLL_INTERVAL


def machine_from_node(node: Node) -> Machine:
    """utils/machine.NewFromNode (machine.go:45)."""
    machine = Machine(
        spec=MachineSpec(taints=list(node.spec.taints)),
        status=MachineStatus(
            provider_id=node.spec.provider_id,
            capacity=dict(node.status.capacity),
            allocatable=dict(node.status.allocatable),
        ),
    )
    machine.metadata.name = node.name
    machine.metadata.labels = dict(node.metadata.labels)
    machine.metadata.annotations = dict(node.metadata.annotations)
    return machine


class NodeController:
    """Sub-reconciler chain over owned, non-deleting nodes (controller.go:86-99)."""

    name = "node"

    def __init__(self, clock, kube_client, cloud_provider, cluster, settings) -> None:
        self.kube_client = kube_client
        self.initialization = Initialization(cloud_provider)
        self.emptiness = EmptinessStamper(clock, kube_client, cluster)
        self.finalizer = Finalizer()
        self.drift = DriftDetector(cloud_provider, settings)

    @tracing.traced("node.reconcile")
    def reconcile(self, node: Node) -> Optional[float]:
        stored = self.kube_client.get_node(node.name)
        if stored is None or stored.metadata.deletion_timestamp is not None:
            return None
        provisioner_name = stored.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
        if not provisioner_name:
            return None
        provisioner = self.kube_client.get(Provisioner, provisioner_name)
        from karpenter_core_tpu.apis.objects import deep_copy

        before = deep_copy(stored)
        requeue: Optional[float] = None
        for sub in (self.initialization, self.emptiness, self.finalizer, self.drift):
            after = sub.reconcile(provisioner, stored)
            if after is not None:
                requeue = after if requeue is None else min(requeue, after)
        # write only on change: an unconditional apply would re-trigger this
        # controller through its own watch forever
        if stored != before:
            self.kube_client.apply(stored)
        return requeue

    def reconcile_all(self) -> None:
        for node in self.kube_client.list_nodes():
            self.reconcile(node)
