"""Metrics scrapers: node, pod, and provisioner gauges.

Mirror of /root/reference/pkg/controllers/metrics/{state/scraper/node.go:42-113,
pod/controller.go:57-69, provisioner/controller.go:48-68}: per-node resource
gauges (allocatable, total pod requests/limits, daemon requests/limits, system
overhead) labeled by node/provisioner/zone/arch/capacity-type/phase; pod state
gauge and startup-time summary; provisioner limit/usage/usage_pct gauges.
"""

from __future__ import annotations

from typing import Dict

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import POD_RUNNING, Pod
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils import resources as resources_util

SCRAPE_PERIOD = 5.0  # state/controller.go:29-56

_NODE_LABELS = ("node_name", "provisioner", "zone", "arch", "capacity_type", "phase", "resource_type")

NODE_ALLOCATABLE = REGISTRY.gauge(
    "karpenter_nodes_allocatable", "Node allocatable", _NODE_LABELS
)
NODE_POD_REQUESTS = REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests", "Total pod requests on node", _NODE_LABELS
)
NODE_POD_LIMITS = REGISTRY.gauge(
    "karpenter_nodes_total_pod_limits", "Total pod limits on node", _NODE_LABELS
)
NODE_DAEMON_REQUESTS = REGISTRY.gauge(
    "karpenter_nodes_total_daemon_requests", "Total daemonset requests on node", _NODE_LABELS
)
NODE_DAEMON_LIMITS = REGISTRY.gauge(
    "karpenter_nodes_total_daemon_limits", "Total daemonset limits on node", _NODE_LABELS
)
NODE_OVERHEAD = REGISTRY.gauge(
    "karpenter_nodes_system_overhead", "Node system overhead", _NODE_LABELS
)

POD_STATE = REGISTRY.gauge(
    "karpenter_pods_state",
    "Pod state",
    ("name", "namespace", "owner", "node", "provisioner", "zone", "arch", "capacity_type", "instance_type", "phase"),
)
POD_STARTUP_TIME = REGISTRY.summary(
    "karpenter_pods_startup_time_seconds",
    "The time from pod creation until the pod is running.",
)

PROVISIONER_LIMIT = REGISTRY.gauge(
    "karpenter_provisioner_limit", "Provisioner resource limits", ("provisioner", "resource_type")
)
PROVISIONER_USAGE = REGISTRY.gauge(
    "karpenter_provisioner_usage", "Provisioner resource usage", ("provisioner", "resource_type")
)
PROVISIONER_USAGE_PCT = REGISTRY.gauge(
    "karpenter_provisioner_usage_pct", "Provisioner usage percentage", ("provisioner", "resource_type")
)


class NodeScraper:
    """5s singleton scrape of cluster state into node gauges."""

    name = "metrics_state"

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def scrape(self) -> float:
        for gauge in (
            NODE_ALLOCATABLE,
            NODE_POD_REQUESTS,
            NODE_POD_LIMITS,
            NODE_DAEMON_REQUESTS,
            NODE_DAEMON_LIMITS,
            NODE_OVERHEAD,
        ):
            gauge.clear()

        def visit(state_node) -> bool:
            node = state_node.node
            base = dict(
                node_name=node.name,
                provisioner=node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, ""),
                zone=node.metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE, ""),
                arch=node.metadata.labels.get(labels_api.LABEL_ARCH_STABLE, ""),
                capacity_type=node.metadata.labels.get(labels_api.LABEL_CAPACITY_TYPE, ""),
                phase=node.status.phase,
            )
            overhead = resources_util.subtract(node.status.capacity, state_node.allocatable())
            for gauge, values in (
                (NODE_ALLOCATABLE, state_node.allocatable()),
                (NODE_POD_REQUESTS, state_node.pod_requests_total()),
                (NODE_POD_LIMITS, state_node.pod_limits_total()),
                (NODE_DAEMON_REQUESTS, state_node.daemon_set_requests()),
                (NODE_DAEMON_LIMITS, state_node.daemon_set_limits()),
                (NODE_OVERHEAD, overhead),
            ):
                for resource_name, quantity in values.items():
                    gauge.labels(**{**base, "resource_type": resource_name}).set(quantity)
            return True

        self.cluster.for_each_node(visit)
        return SCRAPE_PERIOD


class PodScraper:
    name = "metrics_pod"

    def __init__(self, kube_client) -> None:
        self.kube_client = kube_client
        self._started: Dict[str, float] = {}
        # drop series and startup tracking for deleted pods: without this the
        # gauge cardinality and _started grow forever on a churning cluster
        from karpenter_core_tpu.apis.objects import Pod as _Pod

        kube_client.watch(_Pod, self._on_event, replay=False)

    def _on_event(self, event_type: str, pod: Pod) -> None:
        if event_type == "DELETED":
            self._started.pop(pod.uid, None)

    @tracing.traced("metrics_pod.reconcile")
    def reconcile(self, pod: Pod) -> None:
        node = self.kube_client.get_node(pod.spec.node_name) if pod.spec.node_name else None
        node_labels = node.metadata.labels if node is not None else {}
        owner = pod.metadata.owner_references[0].name if pod.metadata.owner_references else ""
        POD_STATE.labels(
            name=pod.name,
            namespace=pod.namespace,
            owner=owner,
            node=pod.spec.node_name,
            provisioner=node_labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, ""),
            zone=node_labels.get(labels_api.LABEL_TOPOLOGY_ZONE, ""),
            arch=node_labels.get(labels_api.LABEL_ARCH_STABLE, ""),
            capacity_type=node_labels.get(labels_api.LABEL_CAPACITY_TYPE, ""),
            instance_type=node_labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE, ""),
            phase=pod.status.phase,
        ).set(1)
        if pod.status.phase == POD_RUNNING and pod.uid not in self._started:
            if pod.status.start_time is not None:
                self._started[pod.uid] = pod.status.start_time
                POD_STARTUP_TIME.observe(
                    pod.status.start_time - pod.metadata.creation_timestamp
                )

    def reconcile_all(self) -> None:
        POD_STATE.clear()
        for pod in self.kube_client.list_pods():
            self.reconcile(pod)


class ProvisionerScraper:
    name = "metrics_provisioner"

    def __init__(self, kube_client) -> None:
        self.kube_client = kube_client

    def reconcile_all(self) -> None:
        for provisioner in self.kube_client.list_provisioners():
            usage = provisioner.status.resources
            for name, quantity in usage.items():
                PROVISIONER_USAGE.labels(provisioner.name, name).set(quantity)
            if provisioner.spec.limits is not None:
                for name, limit in provisioner.spec.limits.resources.items():
                    PROVISIONER_LIMIT.labels(provisioner.name, name).set(limit)
                    if limit > 0:
                        PROVISIONER_USAGE_PCT.labels(provisioner.name, name).set(
                            usage.get(name, 0.0) / limit
                        )
