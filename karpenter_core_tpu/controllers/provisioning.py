"""Provisioning: pod batching, scheduling, machine launch, nomination.

Mirror of /root/reference/pkg/controllers/provisioning/{controller.go,
provisioner.go,batcher.go,volumetopology.go}: a pod-watch trigger feeds a
batching window; the singleton reconciler snapshots cluster state, collects
pending pods (plus pods on deleting nodes), runs the scheduler, launches
machines in parallel, pre-creates node objects, and nominates nodes for pods.

The solve itself routes to the TPU kernel when the batch is kernel-supported
(models.snapshot) and large enough to beat the host path, else to the exact
host scheduler — the Solver-interface seam described in BASELINE.json.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_IN,
    Affinity,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeStatus,
    Pod,
)
from karpenter_core_tpu.apis.v1alpha5 import Provisioner as ProvisionerCRD
from karpenter_core_tpu.cloudprovider import CloudProvider
from karpenter_core_tpu.events import events as evt
from karpenter_core_tpu.metrics import REGISTRY, measure
from karpenter_core_tpu.operator.settings import Settings
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.solver.builder import NoProvisionersError, build_scheduler
from karpenter_core_tpu.solver.scheduler import SchedulerOptions, SchedulingResults
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_provisioner_scheduling_duration_seconds",
    "Duration of the scheduling process in seconds.",
    ("provisioner",),
)
NODES_CREATED = REGISTRY.counter(
    "karpenter_nodes_created", "Number of nodes created in total by Karpenter.", ("reason",)
)
TPU_KERNEL_FALLBACK = REGISTRY.counter(
    "karpenter_tpu_kernel_fallback",
    "Batches that fell back from the TPU kernel to the host scheduler.",
    ("reason",),
)
DEGRADED_SOLVES = REGISTRY.counter(
    "karpenter_degraded_solves_total",
    "Solves served by the bounded host path while the solver-backend "
    "circuit breaker was open.",
    ("controller",),
)
POLICY_COUNTERPROPOSALS = REGISTRY.counter(
    "karpenter_policy_counterproposals_total",
    "ShapeHint counter-proposals emitted for pods a bounded resize would "
    "make schedulable on a strictly cheaper fleet (docs/POLICY.md).",
    ("kind",),
)

# consecutive unexpected kernel failures (backend init/relay faults, not
# KernelUnsupported routing) before the solver-backend circuit breaker opens
# and batches route through the degraded host path until the breaker's
# half-open trial re-proves the backend
TPU_KERNEL_MAX_FAILURES = 2
# seconds the solver breaker stays open before half-opening one trial batch
SOLVER_BREAKER_RESET_S = 30.0
# degraded-mode bound: the host path is O(pods x nodes), so while the breaker
# is open only this many pending pods solve per batch (the rest stay pending
# and re-trigger); KC_DEGRADED_MAX_PODS overrides
DEGRADED_MAX_PODS = 512


def _node_write_rejected(e: Exception) -> bool:
    """True when a failed node write provably never reached the store: a
    chaos fault injected before the write, the client-error surface both
    backends map those onto, or the apiserver itself answering 4xx.
    Connection-level deaths (socket timeout reading the response) return
    False — the write may have committed server-side."""
    from karpenter_core_tpu import chaos
    from karpenter_core_tpu.operator.kubeclient import ConflictError, NotFoundError

    if isinstance(e, (chaos.InjectedFault, NotFoundError, ConflictError)):
        return True
    status = getattr(e, "status", None)  # kubeapi.client.ApiServerError
    return isinstance(status, int) and 400 <= status < 500


class Batcher:
    """Idle/max-duration pod batching window (batcher.go:27-74): an idempotent
    one-slot trigger; Wait blocks for the first trigger then extends while
    triggers keep arriving within the idle window, up to the max window."""

    def __init__(self, clock: Clock, settings: Settings) -> None:
        self.clock = clock
        self.settings = settings
        self._trigger = threading.Event()

    def trigger(self) -> None:
        self._trigger.set()

    def wait(self, poll_interval: float = 0.05) -> bool:
        """True when a batch is ready; False when no trigger arrived."""
        if not self._trigger.wait(timeout=0.001):
            return False
        self._trigger.clear()
        start = self.clock.now()
        last_activity = start
        while True:
            self.clock.sleep(poll_interval)
            now = self.clock.now()
            if self._trigger.is_set():
                self._trigger.clear()
                last_activity = now
            if now - last_activity >= self.settings.batch_idle_duration:
                return True
            if now - start >= self.settings.batch_max_duration:
                return True


class VolumeTopology:
    """Rewrites pod node-affinity to AND in PV/StorageClass zone requirements
    so relaxation can't drop them (volumetopology.go:36-173)."""

    def __init__(self, kube_client) -> None:
        self.kube_client = kube_client

    def inject(self, pod: Pod) -> Optional[str]:
        requirements: List[NodeSelectorRequirement] = []
        for volume in pod.spec.volumes:
            reqs, err = self._requirements_for(pod, volume)
            if err is not None:
                return err
            requirements.extend(reqs)
        if not requirements:
            return None
        if pod.spec.affinity is None:
            pod.spec.affinity = Affinity()
        if pod.spec.affinity.node_affinity is None:
            pod.spec.affinity.node_affinity = NodeAffinity()
        if pod.spec.affinity.node_affinity.required is None:
            pod.spec.affinity.node_affinity.required = NodeSelector()
        terms = pod.spec.affinity.node_affinity.required.node_selector_terms
        if not terms:
            terms.append(NodeSelectorTerm())
        # AND into every OR term so relaxation can't drop the volume zone
        for term in terms:
            term.match_expressions.extend(requirements)
        return None

    def _requirements_for(self, pod: Pod, volume) -> Tuple[List[NodeSelectorRequirement], Optional[str]]:
        if volume.persistent_volume_claim is None:
            return [], None
        pvc = self.kube_client.get_persistent_volume_claim(
            pod.namespace, volume.persistent_volume_claim.claim_name
        )
        if pvc is None:
            return [], f"pvc {volume.persistent_volume_claim.claim_name} not found"
        if pvc.spec.volume_name:
            pv = self.kube_client.get_persistent_volume(pvc.spec.volume_name)
            if pv is None:
                return [], f"pv {pvc.spec.volume_name} not found"
            if pv.spec.node_affinity_required and pv.spec.node_affinity_required.node_selector_terms:
                return list(pv.spec.node_affinity_required.node_selector_terms[0].match_expressions), None
            return [], None
        if pvc.spec.storage_class_name:
            sc = self.kube_client.get_storage_class(pvc.spec.storage_class_name)
            if sc is None:
                return [], f"storage class {pvc.spec.storage_class_name} not found"
            if sc.allowed_topologies:
                return [
                    NodeSelectorRequirement(e.key, OP_IN, list(e.values))
                    for e in sc.allowed_topologies[0].match_expressions
                ], None
        return [], None

    def validate(self, pod: Pod) -> Optional[str]:
        """PVC/StorageClass existence validation (volumetopology.go:145-173)."""
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is None:
                continue
            pvc = self.kube_client.get_persistent_volume_claim(
                pod.namespace, volume.persistent_volume_claim.claim_name
            )
            if pvc is None:
                return f"pvc {volume.persistent_volume_claim.claim_name} not found"
            if pvc.spec.storage_class_name:
                if self.kube_client.get_storage_class(pvc.spec.storage_class_name) is None:
                    return f"storage class {pvc.spec.storage_class_name} not found"
        return None


class PodController:
    """Pod-watch trigger (controller.go:56-66): provisionable pods trip the
    batcher."""

    name = "provisioning_trigger"

    def __init__(self, provisioner: "ProvisioningController") -> None:
        self.provisioner = provisioner

    @tracing.traced("provisioning_trigger.reconcile")
    def reconcile(self, pod: Pod) -> None:
        if pod_util.is_provisionable(pod):
            self.provisioner.trigger()

    def start(self, kube_client) -> None:
        kube_client.watch(Pod, lambda event, pod: event != "DELETED" and self.reconcile(pod))


class ProvisioningController:
    """The Provisioner singleton (provisioner.go:106-360)."""

    name = "provisioning"

    def __init__(
        self,
        kube_client,
        cloud_provider: CloudProvider,
        cluster: Cluster,
        recorder=None,
        settings: Optional[Settings] = None,
        clock: Optional[Clock] = None,
        use_tpu_kernel: bool = False,
        tpu_kernel_min_pods: int = 256,
        solver_endpoint: Optional[str] = None,
    ) -> None:
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.cluster = cluster
        self.recorder = recorder
        self.settings = settings or Settings()
        self.clock = clock or Clock()
        self.batcher = Batcher(self.clock, self.settings)
        self.volume_topology = VolumeTopology(kube_client)
        self.use_tpu_kernel = use_tpu_kernel
        self.tpu_kernel_min_pods = tpu_kernel_min_pods
        # deployed topology: device solves ship to the shared solver service
        # (KC_SOLVER_ADDRESS, deploy/manifests) instead of running in-process
        import os

        self.solver_endpoint = (
            solver_endpoint if solver_endpoint is not None
            else os.environ.get("KC_SOLVER_ADDRESS", "")
        )
        try:
            self.degraded_max_pods = int(
                os.environ.get("KC_DEGRADED_MAX_PODS", DEGRADED_MAX_PODS)
            )
        except ValueError:
            self.degraded_max_pods = DEGRADED_MAX_PODS
        if self.degraded_max_pods < 1:
            # a non-positive bound would make every degraded batch solve an
            # empty subset and re-trigger forever — a no-progress livelock
            self.degraded_max_pods = DEGRADED_MAX_PODS
        self._solver_client = None
        # incremental warm-start solve lineage (solver.incremental): survives
        # across reconciles; its fallback policy decides full vs delta per
        # batch and KC_SOLVER_INCREMENTAL=0 disables it entirely
        self._incremental_session = None
        # the solver-backend breaker: counts unexpected kernel/relay faults
        # (not KernelUnsupported routing); open = degraded mode (bounded host
        # solves here, deprovisioning paused), half-open = one trial batch
        # re-proves the device path.  Shared with the deprovisioning
        # controller's consolidation sweep — same backend, one verdict.
        self.solver_breaker = retry.CircuitBreaker(
            self.clock,
            failure_threshold=TPU_KERNEL_MAX_FAILURES,
            reset_timeout_s=SOLVER_BREAKER_RESET_S,
            name="solver-backend",
        )
        # the quarantine ladder over that breaker (utils/watchdog.py): each
        # half-open window runs a deadline-bounded canary solve (tiny fixed
        # fleet, known answer) instead of risking a real batch — only a
        # verified canary re-admits the device path.  Built lazily (needs
        # the watchdog module); inert when KC_WATCHDOG=0.
        self._quarantine = None
        self._requeue_backoff = retry.Backoff(0.5, 60.0, max_exponent=7)
        self.last_reconcile_s: Optional[float] = None
        # host ingest/classification wall seconds of the last batch split —
        # the soak runner's advisory ``ingest_s`` probe reads this
        # (soak/slo.py; docs/KERNEL_PERF.md "Layer 6")
        self.last_ingest_s: float = 0.0
        # hidden device→host fetch wall of the last kernel solve (the
        # ``pipeline.overlap`` record, utils.pipeline): seconds of copy the
        # loop spent doing other work instead of blocking.  The soak
        # runner's advisory ``tick_overlap_s`` probe reads this; ≈0 on this
        # controller's serial per-reconcile path, >0 when a pipelined loop
        # (bench pipeline_line, deferred session ticks) drove the solve
        # (docs/KERNEL_PERF.md "Layer 7")
        self.last_overlap_s: float = 0.0
        # persistent signature/ladder interner: watch events become
        # membership deltas — a pod shape seen in ANY previous batch never
        # pays signature derivation or ladder construction again
        # (models.columnar.SignatureInterner; exact by construction)
        from karpenter_core_tpu.models.columnar import SignatureInterner

        self._sig_interner = SignatureInterner()
        self._warmup_started = False
        self._warmup_lock = threading.Lock()
        self._warmup_thread: Optional[threading.Thread] = None
        from karpenter_core_tpu.utils.pretty import ChangeMonitor

        self._change_monitor = ChangeMonitor(ttl_seconds=3600.0)

    @property
    def _tpu_failures(self) -> int:
        """Consecutive solver-backend failures (the breaker's counter)."""
        return self.solver_breaker.failure_count

    def degraded(self) -> bool:
        """True while the solver-backend breaker is open: provisioning runs
        bounded host solves and deprovisioning pauses."""
        return self.use_tpu_kernel and self.solver_breaker.state == retry.OPEN

    def trigger(self) -> None:
        self.batcher.trigger()
        self._maybe_start_warmup()

    def _maybe_start_warmup(self) -> None:
        """First trigger kicks a background speculative compile of the solve
        executable for the standard shape buckets (TPUSolver.warmup), so the
        first real batch's compile overlaps the batch window instead of
        following it (VERDICT r2 #3).  Once per process; kernel path only;
        KC_TPU_WARMUP=0 opts out (tests do — they meter compiles)."""
        if self._warmup_started or not self.use_tpu_kernel:
            return
        # test-and-set under a lock: trigger() runs concurrently from watch
        # and batcher threads, and an unguarded check-then-set could start two
        # warmup compiles and track (and later join) only one — leaving the
        # other inside an XLA compile at interpreter teardown (ADVICE r4 #3)
        with self._warmup_lock:
            if self._warmup_started:
                return
            if self.solver_endpoint:
                # remote solves: the solver service owns (and persists) its
                # own compiled executables; nothing to warm in this process
                self._warmup_started = True
                return
            import os

            if os.environ.get("KC_TPU_WARMUP", "1") == "0":
                self._warmup_started = True
                return
            if not self.kube_client.list_provisioners():
                return  # nothing to compile against yet; retry later
            self._warmup_started = True

        def run() -> None:
            try:
                from karpenter_core_tpu.solver.tpu import TPUSolver

                provisioners = self.kube_client.list_provisioners()
                if not provisioners:
                    return
                solver = TPUSolver(
                    self.cloud_provider, provisioners,
                    daemonset_pods=self.get_daemonset_pods(),
                    kube_client=self.kube_client,
                )
                pending = max(len(self.get_pending_pods()), self.tpu_kernel_min_pods)
                solver.warmup(
                    n_pods=pending,
                    state_nodes=[n for n in self.cluster.snapshot_nodes() if not n.marked()],
                    bound_pods=self.kube_client.list_pods(),
                )
            except Exception as e:  # noqa: BLE001 - warmup is best-effort
                log.debug("speculative kernel warmup failed: %s", e)

        thread = threading.Thread(target=run, name="kc-tpu-warmup", daemon=True)
        self._warmup_thread = thread
        thread.start()
        # interpreter finalization while the thread sits inside an XLA compile
        # aborts the process (native exception during thread teardown); a
        # bounded join at exit lets the compile finish first.  Registered
        # through a weakref so a discarded controller isn't pinned (and its
        # handler becomes a no-op) — Operator.stop() joins explicitly anyway.
        import atexit
        import weakref

        ref = weakref.WeakMethod(self.join_warmup)

        def _backstop() -> None:
            join = ref()
            if join is not None:
                join()

        atexit.register(_backstop)

    def join_warmup(self, timeout: float = 120.0) -> None:
        """Wait out an in-flight speculative compile.  Deployed shutdown paths
        must pass a timeout below the pod's terminationGracePeriodSeconds or
        the kubelet's SIGKILL lands mid-compile anyway (Operator.stop passes
        15 s against the manifest's 30 s grace)."""
        thread = self._warmup_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout)

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, wait_for_batch: bool = True) -> Optional[str]:
        # the span opens after the batch window so idle wait time doesn't
        # masquerade as reconcile latency in the stage histogram
        if wait_for_batch and not self.batcher.wait():
            return None
        t0 = time.perf_counter()
        with tracing.span("provisioning.reconcile"):
            err = self._reconcile_batch()
        # wall seconds of the last batch, solve included — the soak runner's
        # per-reconcile solve-latency probe reads this (soak/slo.py)
        self.last_reconcile_s = time.perf_counter() - t0
        if err is not None:
            # requeue-on-error (controller-runtime semantics): the batcher
            # only wakes on pod events, so a failed launch would otherwise
            # sit unretried until unrelated work arrives.  Exponential
            # backoff on consecutive failures — a deterministic error (e.g.
            # exhausted cloud quota) must not become a 1 Hz hot loop of
            # cloud calls (controller-runtime's rate-limited requeue queue).
            delay = self._requeue_backoff.next()
            log.warning("provisioning reconcile: %s (retry in %.1fs)", err, delay)
            timer = threading.Timer(delay, self.batcher.trigger)
            timer.daemon = True
            timer.start()
        else:
            self._requeue_backoff.reset()
        return err

    def _reconcile_batch(self) -> Optional[str]:
        state_nodes = []
        deleting_nodes = []
        for node in self.cluster.snapshot_nodes():
            if not node.marked():
                state_nodes.append(node)
            else:
                deleting_nodes.append(node)

        pods = self.get_pending_pods()
        # pods on deleting (cordoned) nodes also need homes (provisioner.go:137-144)
        deleting_names = {n.node.name for n in deleting_nodes}
        for pod in self.kube_client.list_pods():
            if (
                pod.spec.node_name in deleting_names
                and not pod_util.is_terminal(pod)
                and not pod_util.is_terminating(pod)
                and not pod_util.is_owned_by_daemon_set(pod)
                and not pod_util.is_owned_by_node(pod)
            ):
                pods.append(pod)
        if not pods:
            return None

        results, err = self.schedule(pods, state_nodes)
        if err is not None:
            return err
        self._emit_counterproposals(results.failed_pods)
        if not results.new_nodes:
            return None

        node_names, launch_err = self.launch_machines(results.new_nodes)
        created = sum(1 for n in node_names if n)
        if created:
            NODES_CREATED.labels("provisioning").inc(created)
        return launch_err

    def get_pending_pods(self) -> List[Pod]:
        pods = []
        for pod in self.kube_client.list_pods(selector=lambda p: not p.spec.node_name):
            if not pod_util.is_provisionable(pod):
                continue
            err = self.volume_topology.validate(pod)
            if err is not None:
                log.debug("ignoring pod %s/%s, %s", pod.namespace, pod.name, err)
                continue
            self._consolidation_warnings(pod)
            pods.append(pod)
        return pods

    def _consolidation_warnings(self, pod: Pod) -> None:
        """Warn (hourly, deduped) about constraints that can block consolidation
        (provisioner.go:216-235)."""
        affinity = pod.spec.affinity
        if (
            affinity is not None
            and affinity.pod_anti_affinity is not None
            and affinity.pod_anti_affinity.preferred
        ):
            if self._change_monitor.has_changed((pod.uid, "pod-antiaffinity"), True):
                log.info(
                    "pod %s/%s has a preferred Anti-Affinity which can prevent consolidation",
                    pod.namespace, pod.name,
                )
        for constraint in pod.spec.topology_spread_constraints:
            if constraint.when_unsatisfiable == "ScheduleAnyway":
                if self._change_monitor.has_changed((pod.uid, "pod-topology-spread"), True):
                    log.info(
                        "pod %s/%s has a preferred TopologySpreadConstraint which can "
                        "prevent consolidation",
                        pod.namespace, pod.name,
                    )
                break

    def schedule(self, pods: List[Pod], state_nodes) -> Tuple[Optional[SchedulingResults], Optional[str]]:
        with tracing.span("schedule", pods=len(pods), state_nodes=len(state_nodes)):
            return self._schedule(pods, state_nodes)

    def _schedule(self, pods: List[Pod], state_nodes) -> Tuple[Optional[SchedulingResults], Optional[str]]:
        done = measure(SCHEDULING_DURATION.labels("default"))
        try:
            for pod in pods:
                err = self.volume_topology.inject(pod)
                if err is not None:
                    return None, err
            if self.use_tpu_kernel and len(pods) >= self.tpu_kernel_min_pods:
                if not self.solver_breaker.allow():
                    # breaker open: degraded mode.  Don't stall on (or even
                    # touch) the dead backend — serve a bounded host solve
                    # now; the breaker's half-open trial re-proves the device
                    # path and promotes batches back automatically.
                    TPU_KERNEL_FALLBACK.labels("degraded").inc()
                    return self._schedule_degraded(pods, state_nodes), None
                was_half_open = self.solver_breaker.state == retry.HALF_OPEN
                if was_half_open and not self.solver_endpoint:
                    # (remote topology excluded: a CPU controller replica
                    # must never initialize a device backend, and an
                    # in-process canary would probe the wrong thing — the
                    # half-open trial there stays the real remote batch)
                    from karpenter_core_tpu.utils import watchdog as watchdog_mod

                    if watchdog_mod.watchdog_enabled():
                        # quarantine re-admission: prove the backend on a
                        # deadline-bounded canary BEFORE trusting it with a
                        # real batch.  Verified → the breaker closed and this
                        # batch rides the device path normally; anything else
                        # → the breaker re-opened, serve this batch degraded.
                        if not self._canary_readmit():
                            TPU_KERNEL_FALLBACK.labels("quarantined").inc()
                            return self._schedule_degraded(pods, state_nodes), None
                        was_half_open = False
                try:
                    results = self._schedule_tpu(pods, state_nodes)
                except NoProvisionersError:
                    # precondition error, not a backend verdict: free the
                    # half-open trial slot so a later batch can still probe
                    self.solver_breaker.release_trial()
                    raise
                except Exception as e:  # backend init/relay faults, not routing
                    self.solver_breaker.record_failure()
                    TPU_KERNEL_FALLBACK.labels("backend-error").inc()
                    log.warning(
                        "TPU kernel solve failed (%s: %s); falling back to the "
                        "host scheduler (%d/%d consecutive failures, breaker %s)",
                        type(e).__name__, e, self.solver_breaker.failure_count,
                        TPU_KERNEL_MAX_FAILURES, self.solver_breaker.state,
                    )
                    results = None
                else:
                    if results is not None:
                        self.solver_breaker.record_success()
                        if was_half_open:
                            log.info(
                                "solver backend recovered: breaker closed, "
                                "device path restored"
                            )
                    else:
                        # shape routing (unsupported/entangled/under-min): the
                        # batch runs on the host path by design, not by fault —
                        # and it says NOTHING about the backend, so a half-open
                        # trial must not close the breaker on it (the next
                        # eligible batch probes instead); in the closed state
                        # it keeps resetting the failure streak, as before
                        if was_half_open:
                            self.solver_breaker.release_trial()
                        else:
                            self.solver_breaker.record_success()
                        TPU_KERNEL_FALLBACK.labels("unsupported").inc()
                if results is not None:
                    return results, None
            return self._host_solve(pods, state_nodes), None
        except NoProvisionersError as e:
            return None, str(e)
        finally:
            done()

    def _host_solve(self, pods: List[Pod], state_nodes) -> SchedulingResults:
        """The exact host-oracle solve — the normal fallback path and the
        degraded path build it identically so they cannot diverge."""
        from karpenter_core_tpu.solver.incremental import SOLVE_MODE

        SOLVE_MODE.labels("host").inc()
        scheduler = build_scheduler(
            self.kube_client,
            self.cloud_provider,
            self.cluster,
            pods,
            state_nodes,
            daemonset_pods=self.get_daemonset_pods(),
            recorder=self.recorder,
            opts=SchedulerOptions(),
        )
        return scheduler.solve(pods)

    def _schedule_degraded(self, pods: List[Pod], state_nodes) -> SchedulingResults:
        """Bounded host-path greedy solve while the solver breaker is open.

        The host oracle (solver/scheduler.py) is exact but O(pods x nodes);
        degraded mode trades batch size for latency — at most
        ``degraded_max_pods`` pods solve per batch, the remainder stays
        pending and re-triggers shortly, so the cluster keeps converging
        (slowly, correctly) instead of stalling behind a dead backend.
        Everything this path emits carries ``degraded=true``."""
        from karpenter_core_tpu.solver.incremental import SOLVE_MODE

        subset = pods[: self.degraded_max_pods]
        deferred = len(pods) - len(subset)
        DEGRADED_SOLVES.labels("provisioning").inc()
        SOLVE_MODE.labels("degraded").inc()
        with tracing.span(
            "schedule.degraded", degraded=True, pods=len(subset), deferred=deferred
        ):
            log.warning(
                "degraded solve: solver breaker open, host-solving %d/%d "
                "pending pods", len(subset), len(pods),
            )
            results = self._host_solve(subset, state_nodes)
        if deferred:
            # the deferred tail generates no new pod events, so wake the
            # batcher ourselves once this batch's launches land
            timer = threading.Timer(1.0, self.batcher.trigger)
            timer.daemon = True
            timer.start()
        return results

    def _canary_readmit(self) -> bool:
        """One quarantine-ladder rung: a deadline-bounded canary solve
        against the quarantined backend (utils/watchdog.BackendQuarantine).
        True re-admits the device path (breaker closed); False keeps it
        quarantined (breaker re-opened) — the next half-open window retries,
        so a dead backend is probed periodically at zero risk to real
        batches."""
        from karpenter_core_tpu.utils import watchdog as watchdog_mod

        if self._quarantine is None:
            self._quarantine = watchdog_mod.BackendQuarantine(
                self.solver_breaker, self._run_canary
            )
        return self._quarantine.try_readmit()

    def _run_canary(self) -> Optional[bool]:
        """The canary solve itself: a tiny FIXED fleet with a known answer —
        8 identical small pods against the real catalog must all place, on
        any healthy backend, in well under the canary deadline.  Runs the
        full encode → dispatch → fetch → decode path (each leg individually
        watchdog-bounded), so a device that hangs at ANY stage fails the
        canary instead of wedging a worker.  Returns None (no verdict —
        trial slot released, breaker untouched) when the backend was never
        exercised: no provisioners to solve against, or the canary shape
        itself routed off the kernel."""
        from karpenter_core_tpu.apis.objects import (
            Container,
            ObjectMeta,
            PodSpec,
            ResourceRequirements,
        )
        from karpenter_core_tpu.models.snapshot import KernelUnsupported
        from karpenter_core_tpu.solver.tpu import TPUSolver

        provisioners = self.kube_client.list_provisioners()
        if not provisioners:
            return None  # cluster-config condition, not backend evidence
        solver = TPUSolver(
            self.cloud_provider, provisioners,
            daemonset_pods=self.get_daemonset_pods(),
            kube_client=self.kube_client,
        )
        proto = Pod(
            metadata=ObjectMeta(name="watchdog-canary"),
            spec=PodSpec(containers=[Container(
                resources=ResourceRequirements(
                    requests={"cpu": 0.1, "memory": 128 * 2**20}
                )
            )]),
        )
        pods = [proto] * 8
        try:
            results = solver.solve(pods)
        except KernelUnsupported:
            return None  # shape routing: the device was never dispatched
        placed = sum(len(d.pods) for d in results.new_nodes) + sum(
            len(p) for p in results.existing_assignments.values()
        )
        return (
            placed == len(pods)
            and not results.failed_pods
            and not results.spread_residual_pods
        )

    def _schedule_tpu(self, pods: List[Pod], state_nodes) -> Optional[SchedulingResults]:
        """Route the batch through the TPU kernel; None falls back to the host
        path (batch shape unsupported — models.snapshot.classify_pods).

        Mixed batches split: pods whose shape the kernel doesn't model go to
        the host oracle AFTER the kernel pass (with the kernel's existing-node
        placements applied), so one exotic pod no longer drags 50k ordinary
        pods onto the O(pods × nodes) host path.  The split only happens when
        the two sets are topology- and volume-isolated from each other —
        otherwise shared group counts would diverge and the whole batch stays
        on the host path, as before."""
        from karpenter_core_tpu.models.snapshot import KernelUnsupported
        from karpenter_core_tpu.solver.tpu import TPUSolver

        provisioners = self.kube_client.list_provisioners()
        if not provisioners:
            raise NoProvisionersError("no provisioners found")
        split = self._split_batch(pods)
        if split is None:
            return None  # unsupported pods entangled with the rest: whole-batch host
        tpu_classes, tpu_pods, host_pods = split
        if len(tpu_pods) < self.tpu_kernel_min_pods:
            # post-split remainder too small to amortize the kernel's fixed
            # encode/dispatch overhead — same regime the pre-solve gate covers
            return None
        daemonset_pods = self.get_daemonset_pods()
        solver = TPUSolver(
            self.cloud_provider, provisioners,
            daemonset_pods=daemonset_pods,
            kube_client=self.kube_client,
            # the policy objective stage (docs/POLICY.md): scores feasible
            # offerings after the solve and pins each node's launch to the
            # argmin cell; disabled config = pre-policy pipeline exactly
            policy=self.policy_config(provisioners),
        )
        bound_pods = self.kube_client.list_pods()
        if self.solver_endpoint:
            # the deployed topology: CPU controller replicas, one shared TPU
            # solver service — ship the snapshot over the channel
            remote = self._solve_remote(
                solver, tpu_classes, tpu_pods, state_nodes, daemonset_pods,
                provisioners, bound_pods,
            )
            if remote is None:
                return None  # service judged the batch kernel-unsupported
            tpu_results, new_launchables = remote
        else:
            # sharded dispatch (docs/KERNEL_PERF.md "Layer 5"): the in-process
            # solve routes through the shard_map mesh dispatcher whenever
            # KC_SOLVER_MESH enables it (default: on with >1 device) — the
            # encode pads the catalog shard-aligned and prepare_encoded
            # captures the topology, so this controller needs no mesh
            # plumbing of its own; surface the routing on the span for triage.
            # (Deliberately NOT computed on the remote branch above: a CPU
            # controller replica must never initialize a device backend.)
            from karpenter_core_tpu.parallel import mesh as mesh_mod

            mesh_axes = mesh_mod.solve_mesh_axes()
            sp = tracing.current()
            if sp is not None and mesh_axes is not None:
                sp.set(**{"solve.mesh": repr(mesh_axes)})
            try:
                tpu_results = self._solve_in_process(
                    solver, tpu_classes, state_nodes, bound_pods
                )
            except KernelUnsupported as e:
                # batch-level shapes (deep affinity chains, cross-class PVC
                # sharing) surface here rather than per class
                log.debug("TPU kernel unsupported for batch, falling back: %s", e)
                return None
            new_launchables = [
                solver.to_launchable(decision) for decision in tpu_results.new_nodes
            ]

        results = SchedulingResults(failed_pods=list(tpu_results.failed_pods))
        results.new_nodes = new_launchables
        # nominate existing nodes + publish pod nominations
        for node_name, placed in tpu_results.existing_assignments.items():
            self.cluster.nominate_node_for_pod(node_name)
            node = self.kube_client.get_node(node_name)
            if self.recorder is not None and node is not None:
                for pod in placed:
                    self.recorder.publish(evt.nominate_pod(pod, node))
        if self.recorder is not None:
            for pod in results.failed_pods:
                self.recorder.publish(
                    evt.pod_failed_to_schedule(pod, "no capacity (tpu solve)")
                )
        # spread residuals: the kernel flagged these classes as possibly
        # under-placed vs the host oracle (water-fill round bound / intake
        # overestimate) — re-solve their leftover pods on the host with the
        # kernel's placements seeded into the topology counts, so no batch
        # shape schedules fewer pods than the host would (VERDICT r2 #2)
        residual_pods = list(tpu_results.spread_residual_pods)
        if (residual_pods or host_pods) and self._incremental_session is not None:
            # the host remainder places pods the warm carry cannot see — the
            # lineage is no longer the whole truth, so the next batch must
            # re-anchor with a full solve
            self._incremental_session.reset()
        if residual_pods:
            log.info(
                "re-routing %d spread-residual pods to the host oracle",
                len(residual_pods),
            )
        if host_pods or residual_pods:
            if host_pods:
                log.debug(
                    "solving %d kernel-unsupported pods on the host path "
                    "(%d solved on tpu)", len(host_pods), len(tpu_pods),
                )
            host_results = self._solve_host_remainder(
                host_pods + residual_pods, state_nodes, tpu_results,
                results.new_nodes, daemonset_pods,
                seed_topology=bool(residual_pods),
            )
            results.new_nodes.extend(host_results.new_nodes)
            results.failed_pods.extend(host_results.failed_pods)
            results.errors.update(host_results.errors)
        return results

    def _solve_in_process(self, solver, tpu_classes, state_nodes, bound_pods):
        """One in-process kernel solve, routed through the incremental
        warm-start session (solver.incremental) unless KC_SOLVER_INCREMENTAL=0
        keeps the old full-solve-every-batch path.  The session's fallback
        policy picks full vs delta per batch; the decision rides the
        ``solve.mode`` span attribute and ``karpenter_solve_mode_total``."""
        from karpenter_core_tpu.solver.incremental import (
            SOLVE_MODE,
            FallbackPolicy,
            IncrementalSolveSession,
            incremental_enabled,
        )

        if not incremental_enabled():
            snapshot = solver.encode_classes(
                tpu_classes, state_nodes=state_nodes, bound_pods=bound_pods
            )
            SOLVE_MODE.labels("full").inc()
            sp = tracing.current()
            if sp is not None:
                sp.set(**{"solve.mode": "full", "solve.mode.reason": "disabled"})
            return solver.solve_encoded(snapshot, state_nodes, bound_pods)
        session = self._incremental_session
        if session is None:
            # materialized=True: this session's decisions become real nodes,
            # so repairs additionally require that the previous solve opened
            # no new slots (FallbackPolicy docstring)
            session = self._incremental_session = IncrementalSolveSession(
                policy=FallbackPolicy.from_env(materialized=True)
            )
        session.rebind(solver)
        results = session.solve(tpu_classes, state_nodes, bound_pods)
        # surface the solve's hidden-fetch wall for the soak runner's
        # advisory ``tick_overlap_s`` probe (utils.pipeline, docs/SOAK.md)
        from karpenter_core_tpu.utils import pipeline as pipeline_mod

        self.last_overlap_s = pipeline_mod.last_overlap().get("hidden_s", 0.0)
        return results

    def _solve_remote(self, solver, tpu_classes, tpu_pods, state_nodes,
                      daemonset_pods, provisioners, bound_pods):
        """One snapshot solve over the gRPC channel (service.snapshot_channel,
        SolveClasses — O(distinct shapes) on the wire).

        Returns (tpu_results, launchables) shaped like the in-process path,
        or None when the service judged the batch kernel-unsupported
        (FAILED_PRECONDITION → the caller host-routes the whole batch).
        Transport/backend errors propagate — schedule()'s circuit breaker
        counts them and self-disables the device path after repeated faults.
        """
        import grpc

        from karpenter_core_tpu.apis import codec
        from karpenter_core_tpu.solver.tpu import TPUSolveResults

        client = self._solver_client
        if client is None:
            from karpenter_core_tpu.service.snapshot_channel import (
                SnapshotSolverClient,
            )

            client = self._solver_client = SnapshotSolverClient(self.solver_endpoint)

        bound_by_node: Dict[str, List[Pod]] = {}
        for pod in bound_pods:
            if (
                pod.spec.node_name
                and not pod_util.is_terminal(pod)
                and not pod_util.is_terminating(pod)
            ):
                bound_by_node.setdefault(pod.spec.node_name, []).append(pod)
        nodes = [
            {
                "node": codec.node_to_dict(sn.node),
                "pods": [
                    codec.pod_to_dict(p)
                    for p in bound_by_node.get(sn.node.name, [])
                ],
                "volumeLimits": dict(sn.volume_limits()),
            }
            for sn in (state_nodes or [])
        ]
        # resolve claims for the BOUND pods too: the server counts existing
        # volume attachments from them, and an unresolvable claim reads as
        # zero attachments (VolumeUsage.add drops resolution errors) — the
        # node would look empty and over-admit new PVC pods
        shipped_bound = [
            p for sn in (state_nodes or [])
            for p in bound_by_node.get(sn.node.name, [])
        ]
        # _split_batch laid tpu_pods out class-by-class: membership is the
        # running offsets, no second O(pods) signature pass
        members: List[List[int]] = []
        offset = 0
        for cls in tpu_classes:
            members.append(list(range(offset, offset + len(cls.pods))))
            offset += len(cls.pods)
        try:
            response = client.solve_classes(
                tpu_pods, provisioners,
                nodes=nodes,
                daemonset_pods=daemonset_pods,
                claim_drivers=self._claim_drivers(tpu_pods + shipped_bound),
                members=members,
                # the replica's resolved policy config rides the wire: the
                # remote objective stage must select offerings exactly like
                # an in-process solve would (it previously fell back
                # silently to first-fit — PolicyConfig never crossed)
                policy=solver.policy,
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                log.debug("remote solver: kernel unsupported (%s)", e.details())
                return None
            raise  # transport/backend fault: the circuit breaker counts it

        tpu_results = TPUSolveResults()
        launchables = []
        catalog_skew_pods: List[Pod] = []
        for entry in response["newNodes"]:
            node = solver.launchable_from_wire(
                entry, [tpu_pods[i] for i in entry["podIndices"]]
            )
            if not node.instance_type_options:
                # catalog skew between this replica and the solver (image
                # rollout): nothing launchable from the wire's instance-type
                # names.  Re-route the pods through the host residual path —
                # the local oracle can still place them with whatever catalog
                # THIS replica has — rather than failing real workload pods
                # every reconcile until the rollout converges (ADVICE r4 #4)
                log.warning(
                    "remote solve returned instance types unknown to this "
                    "catalog (%s); re-routing %d pods to the host oracle",
                    entry.get("instanceTypes", [])[:3], len(node.pods),
                )
                catalog_skew_pods.extend(node.pods)
                continue
            launchables.append(node)
        tpu_results.existing_assignments = {
            name: [tpu_pods[i] for i in indices]
            for name, indices in response["existingAssignments"].items()
        }
        tpu_results.failed_pods.extend(
            tpu_pods[i] for i in response["failedPodIndices"]
        )
        tpu_results.spread_residual_pods = [
            tpu_pods[i] for i in response.get("residualPodIndices", [])
        ] + catalog_skew_pods
        tpu_results.existing_committed_zones = dict(
            response.get("existingCommittedZones", {})
        )
        return tpu_results, launchables

    def _claim_drivers(self, pods: List[Pod]) -> Dict[str, str]:
        """Resolve every PVC the batch references to its CSI driver
        (volumeusage.go:65-90 resolution, done on THIS side of the wire where
        the apiserver lives), keyed "<ns>/<claim>" for the channel."""
        drivers: Dict[str, str] = {}
        for pod in pods:
            for volume in pod.spec.volumes:
                if volume.persistent_volume_claim is None:
                    continue
                claim = volume.persistent_volume_claim.claim_name
                key = f"{pod.namespace}/{claim}"
                if key in drivers:
                    continue
                pvc = self.kube_client.get_persistent_volume_claim(
                    pod.namespace, claim
                )
                if pvc is None:
                    continue
                driver = ""
                if pvc.spec.volume_name:
                    pv = self.kube_client.get_persistent_volume(pvc.spec.volume_name)
                    driver = pv.spec.csi_driver if pv is not None else ""
                elif pvc.spec.storage_class_name:
                    sc = self.kube_client.get_storage_class(pvc.spec.storage_class_name)
                    driver = sc.provisioner if sc is not None else ""
                if driver:
                    drivers[key] = driver
        return drivers

    def _split_batch(self, pods: List[Pod]):
        """(tpu_classes, tpu_pods, host_pods), or None when the unsupported
        pods are not isolated from the supported ones (shared topology
        selectors/labels or shared PVC claims — the split would desynchronize
        shared counts).  The built classes feed TPUSolver.encode_classes so
        classification is not repeated on the hot path.

        Classification rides the controller's PERSISTENT interner
        (models.columnar.SignatureInterner): a shape seen in any previous
        reconcile pays neither signature derivation nor ladder construction
        again, so steady-state batches cost O(pods) cheap fast-key reads plus
        O(new shapes) — trace/watch events become membership deltas, not
        pod-list rebuilds.  The wall cost lands on ``last_ingest_s`` (the
        soak runner's advisory ingest probe)."""
        t0 = time.perf_counter()
        try:
            return self._split_batch_impl(pods)
        finally:
            self.last_ingest_s = time.perf_counter() - t0

    def _split_batch_impl(self, pods: List[Pod]):
        from dataclasses import replace as dc_replace

        interner = self._sig_interner
        supported: Dict[tuple, List[Pod]] = {}
        unsupported: Dict[tuple, List[Pod]] = {}
        protos: Dict[tuple, object] = {}
        for pod in pods:
            sig = interner.sig_of(pod)
            proto = protos.get(sig)
            if proto is None and sig not in protos:
                proto, _error = interner.ladder_of(sig, pod)
                protos[sig] = proto
            (supported if proto is not None else unsupported).setdefault(
                sig, []
            ).append(pod)

        host_pods = [p for group in unsupported.values() for p in group]
        tpu_classes = []
        tpu_pods: List[Pod] = []
        for sig, group in supported.items():
            # shallow replace, never mutate: the proto is shared across
            # reconciles (and with PodIngest.classes' convention); the
            # interned signature rides along for the encode's reuse key
            cls = dc_replace(protos[sig], pods=group, interned_sig=sig)
            tpu_classes.append(cls)
            tpu_pods.extend(group)
        if not host_pods:
            return tpu_classes, tpu_pods, []
        if not tpu_pods:
            return None

        # isolation: no topology selector in either set may match labels in
        # the other (label sets are class-invariant, so representatives
        # suffice), and no PVC claim may span both sets (claim identity is
        # NOT class-invariant — check every pod)
        def selectors(pod: Pod):
            for constraint in pod.spec.topology_spread_constraints:
                yield constraint.label_selector
            if pod.spec.affinity is not None:
                for terms in (
                    pod.spec.affinity.pod_affinity,
                    pod.spec.affinity.pod_anti_affinity,
                ):
                    if terms is not None:
                        for term in terms.required + [
                            w.pod_affinity_term for w in terms.preferred
                        ]:
                            yield term.label_selector

        def claims(pod: Pod):
            return {
                (pod.namespace or "", v.persistent_volume_claim.claim_name)
                for v in pod.spec.volumes
                if v.persistent_volume_claim is not None
            }

        host_reps = [group[0] for group in unsupported.values()]
        tpu_reps = [group[0] for group in supported.values()]
        for reps, others in ((host_reps, tpu_reps), (tpu_reps, host_reps)):
            for rep in reps:
                for selector in selectors(rep):
                    if selector is not None and any(
                        selector.matches(o.metadata.labels) for o in others
                    ):
                        return None
        host_claims = set().union(*map(claims, host_pods)) if host_pods else set()
        tpu_claims = set().union(*map(claims, tpu_pods)) if tpu_pods else set()
        if host_claims & tpu_claims:
            return None
        return tpu_classes, tpu_pods, host_pods

    def _solve_host_remainder(
        self, host_pods: List[Pod], state_nodes, tpu_results, tpu_new_nodes,
        daemonset_pods: List[Pod], seed_topology: bool = False,
    ) -> SchedulingResults:
        """Host-oracle solve for the kernel-unsupported remainder, with the
        kernel's existing-node placements applied so capacity is not
        double-booked.  New nodes the kernel opened are not offered to the
        remainder (they are not launched yet); the remainder opens its own,
        but the kernel nodes' pessimistic capacity is charged against the
        provisioner limits first (subtractMax, scheduler.go:273-290) so the
        two solves cannot jointly overspend a limit.

        ``seed_topology`` records every kernel placement into the host
        topology's shared counts first (topology.go:120-143 semantics), which
        spread-residual pods need: unlike the encode-time split (isolated by
        construction), residuals share groups with kernel-placed pods, so the
        host's skew/affinity math must see where those pods landed."""
        from karpenter_core_tpu.solver.scheduler import _subtract_max

        adjusted = []
        for state_node in state_nodes:
            placed = tpu_results.existing_assignments.get(state_node.node.name)
            if placed:
                state_node = state_node.deep_copy()
                for pod in placed:
                    state_node.update_for_pod(pod)
                # a zone-less node the kernel committed (by placing pods under
                # a zone restriction) must read as committed here too — else
                # the two engines could pin the same node to different zones
                committed = tpu_results.existing_committed_zones.get(
                    state_node.node.name
                )
                if committed and labels_api.LABEL_TOPOLOGY_ZONE not in (
                    state_node.node.metadata.labels
                ):
                    state_node.node.metadata.labels[
                        labels_api.LABEL_TOPOLOGY_ZONE
                    ] = committed
            adjusted.append(state_node)
        scheduler = build_scheduler(
            self.kube_client,
            self.cloud_provider,
            self.cluster,
            host_pods,
            adjusted,
            daemonset_pods=daemonset_pods,
            recorder=self.recorder,
            opts=SchedulerOptions(),
        )
        for node in tpu_new_nodes:
            if node.provisioner_name in scheduler.remaining_resources:
                scheduler.remaining_resources[node.provisioner_name] = _subtract_max(
                    scheduler.remaining_resources[node.provisioner_name],
                    node.instance_type_options,
                )
        if seed_topology:
            self._seed_topology_from_kernel(
                scheduler.topology, tpu_results, tpu_new_nodes, adjusted
            )
        return scheduler.solve(host_pods)

    def _seed_topology_from_kernel(
        self, topology, tpu_results, tpu_new_nodes, adjusted_state_nodes
    ) -> None:
        """Commit the kernel's placements into the host topology counts.

        Existing-node placements record under the node's labels; new-node
        placements under the launchable's requirements (zone already pinned by
        decode) plus a synthetic unique hostname per pending node — hostname
        groups then see each kernel node as a frozen-count domain, exactly how
        an already-launched node would read.  Multi-zone nodes skip zone counts
        (domains.len() != 1), matching the reference's record rule
        (topology.go:129-136).  Kernel pods carrying anti-affinity terms also
        register inverse counts so residual pods they repel are blocked
        (topology.go:202-227)."""
        def seed(pod: Pod, requirements: Requirements, domains: dict) -> None:
            topology.record(pod, requirements)
            if pod_util.has_pod_anti_affinity(pod):
                topology._update_inverse_anti_affinity(pod, domains)

        # adjusted nodes carry the kernel's zone stamps — seed from those
        # labels, not the store's, so counts land in the committed zone
        by_name = {n.node.name: n.node for n in adjusted_state_nodes}
        for node_name, placed in tpu_results.existing_assignments.items():
            node = by_name.get(node_name) or self.kube_client.get_node(node_name)
            if node is None:
                continue
            requirements = Requirements.from_labels(node.metadata.labels)
            for pod in placed:
                seed(pod, requirements, node.metadata.labels)
        for i, launchable in enumerate(tpu_new_nodes):
            requirements = Requirements(*launchable.requirements.values())
            hostname = f"tpu-pending-{i}"
            requirements.add(
                Requirement(labels_api.LABEL_HOSTNAME, OP_IN, [hostname])
            )
            domains = {labels_api.LABEL_HOSTNAME: hostname}
            if requirements.has(labels_api.LABEL_TOPOLOGY_ZONE):
                zones = requirements.get(labels_api.LABEL_TOPOLOGY_ZONE)
                if zones.len() == 1:
                    domains[labels_api.LABEL_TOPOLOGY_ZONE] = zones.values_list()[0]
            for pod in launchable.pods:
                seed(pod, requirements, domains)

    def policy_config(self, provisioners=None):
        """The policy-objective config this reconcile runs under: env
        defaults overlaid by the highest-weight provisioner's ``spec.policy``
        block; KC_POLICY=0 kills the stage everywhere (policy.config)."""
        from karpenter_core_tpu.policy import PolicyConfig

        if provisioners is None:
            provisioners = self.kube_client.list_provisioners()
        return PolicyConfig.resolve(provisioners)

    def _emit_counterproposals(self, failed_pods: List[Pod]) -> None:
        """ShapeHint counter-proposals for unschedulable pods (docs/POLICY.md):
        when a bounded resize would fit a strictly cheaper fleet, say so —
        one event per distinct pod shape (not per pod: a 50k-replica batch
        failing identically is ONE proposal), plus
        ``karpenter_policy_counterproposals_total``."""
        if not failed_pods:
            return
        from karpenter_core_tpu.policy import propose_resize
        from karpenter_core_tpu.utils import resources as resources_util

        # one provisioner LIST serves both the config resolve and the catalog
        provisioners = self.kube_client.list_provisioners()
        policy = self.policy_config(provisioners)
        if not (policy.enabled and policy.counter_proposals):
            return
        catalog, seen_types = [], set()
        for provisioner in provisioners:
            for it in self.cloud_provider.get_instance_types(provisioner):
                if it.name not in seen_types:
                    seen_types.add(it.name)
                    catalog.append(it)
        proposed: dict = {}
        for pod in failed_pods:
            requests = resources_util.ceiling(pod)
            shape = tuple(sorted(requests.items()))
            if shape in proposed:
                continue
            proposed[shape] = None
            hint = propose_resize(requests, catalog, policy)
            if hint is None:
                continue
            POLICY_COUNTERPROPOSALS.labels("resize").inc()
            log.info(
                "counter-proposal for pod %s/%s: %s",
                pod.namespace, pod.name, hint.message(),
            )
            if self.recorder is not None:
                self.recorder.publish(evt.shape_hint(pod, hint.message()))

    def get_daemonset_pods(self) -> List[Pod]:
        """Representative daemonset pods for overhead calculation.  The
        reference lists DaemonSet objects (provisioner.go getDaemonSetPods); we
        derive from daemonset-owned pods in the store."""
        seen = {}
        for pod in self.kube_client.list_pods():
            if pod_util.is_owned_by_daemon_set(pod):
                owner = next(
                    (r.name for r in pod.metadata.owner_references if r.kind == "DaemonSet"),
                    pod.name,
                )
                seen.setdefault(owner, pod)
        return list(seen.values())

    # -- launch ---------------------------------------------------------------

    def launch_machines(self, machines) -> Tuple[List[str], Optional[str]]:
        """Parallel machine launches (provisioner.go:169-189)."""
        names: List[Optional[str]] = [None] * len(machines)
        errs: List[Optional[str]] = [None] * len(machines)

        def one(i: int) -> None:
            name, err = self.launch(machines[i])
            names[i] = name or ""
            errs[i] = err

        if len(machines) == 1:
            one(0)
        else:
            with ThreadPoolExecutor(max_workers=min(len(machines), 32)) as pool:
                list(pool.map(one, range(len(machines))))
        messages = [e for e in errs if e]
        return [n or "" for n in names], ("; ".join(messages) if messages else None)

    def launch(self, machine_node) -> Tuple[Optional[str], Optional[str]]:
        """Launch one machine and pre-create its node (provisioner.go:311-358)."""
        latest = self.kube_client.get(ProvisionerCRD, machine_node.provisioner_name)
        if latest is None:
            return None, f"provisioner {machine_node.provisioner_name} not found"
        if latest.spec.limits is not None:
            err = latest.spec.limits.exceeded_by(latest.status.resources)
            if err is not None:
                return None, err

        template = machine_node.template
        template.instance_type_options = machine_node.instance_type_options
        template.requests = machine_node.requests
        machine = template.to_machine(latest)
        try:
            created = self.cloud_provider.create(machine)
        except Exception as e:  # noqa: BLE001 - cloud errors surface as strings
            return None, f"creating cloud provider instance, {e}"

        # merge the template's node view into the provider's (provisioner.go:
        # 331-335 mergo.Merge): provider-resolved labels win, the template
        # backfills the rest — including single-valued requirement labels
        # (e.g. custom provisioner requirements) and annotations
        template_node = template.to_node()
        node = Node(
            metadata=created.metadata,
            spec=template_node.spec,
            status=NodeStatus(),
        )
        for key, value in template_node.metadata.labels.items():
            node.metadata.labels.setdefault(key, value)
        for key, value in template_node.metadata.annotations.items():
            node.metadata.annotations.setdefault(key, value)
        node.metadata.finalizers = [labels_api.TERMINATION_FINALIZER]
        node.spec.provider_id = created.status.provider_id

        # idempotent node pre-create (provisioner.go:338-348): already-exists
        # is tolerable only when it IS this machine (same provider id).  With
        # the durable apiserver backend, node objects outlive the process
        # while a fresh fake/cloud name sequence restarts — adopting a
        # same-name-different-instance node would corrupt cluster state with
        # a phantom, so that collision fails the launch (the next attempt
        # draws a fresh name)
        from karpenter_core_tpu.operator.kubeclient import ConflictError

        try:
            self.kube_client.create(node)
        except ConflictError:
            # a 409 with no cached object means the conflicting node hasn't
            # reached the watch cache yet (apiserver backend lag) — its
            # identity is unknown, so adopting it would be exactly the
            # corruption this guard exists to prevent; error out and let the
            # requeue retry once the cache catches up
            existing = self.kube_client.get_node(node.name)
            if existing is None or existing.spec.provider_id != node.spec.provider_id:
                self._abandon_machine(created)
                return None, (
                    f"node name {node.name} already taken by "
                    f"{existing.spec.provider_id if existing else 'an unsynced object'}; "
                    f"launch produced {node.spec.provider_id}"
                )
            log.debug("node already registered")
        except Exception as e:  # noqa: BLE001 - surfaced to the caller
            # compensate ONLY when the write provably did not land: the cache
            # read alone cannot distinguish "server doesn't own the node"
            # from "watch cache is behind" (the 409 branch above documents
            # exactly that lag), so deleting the machine on a cache miss
            # after an ambiguous transport death could strand a committed
            # node object on a dead instance — the phantom this guard
            # exists to prevent.  Provably-failed = not visibly ours AND the
            # error says the server never applied the write (a pre-write
            # injected fault, or the server itself answered 4xx).  Anything
            # connection-level is ambiguous: keep the machine — the watch
            # either delivers the node or the machine surfaces as a leak in
            # the audit, both recoverable; a phantom is not.
            try:
                existing = self.kube_client.get_node(node.name)
            except Exception:  # noqa: BLE001 - read failure: stay ambiguous
                existing = None
            visibly_ours = (
                existing is not None
                and existing.spec.provider_id == node.spec.provider_id
            )
            if not visibly_ours:
                if _node_write_rejected(e):
                    self._abandon_machine(created)
                else:
                    log.warning(
                        "node %s create outcome ambiguous (%s: %s); keeping "
                        "machine %s pending the watch",
                        node.name, type(e).__name__, e,
                        created.status.provider_id,
                    )
            return None, f"creating node {node.name}, {e}"
        err = self.cluster.update_node(node)
        if err is not None:
            return None, f"updating cluster state, {err}"
        self.cluster.nominate_node_for_pod(node.name)
        if self.recorder is not None:
            for pod in machine_node.pods:
                self.recorder.publish(evt.nominate_pod(pod, node))
        return node.name, None

    def _abandon_machine(self, created) -> None:
        """Compensate a node pre-create that provably never landed by
        deleting the just-launched cloud instance — otherwise a kubeapi
        fault landing between cloud.create and the node POST strands the
        machine forever (no node object ever points at it, so no termination
        path will).  Best-effort: a failed delete is retried by nothing, but
        the chaos matrix's leak invariant is what surfaced the gap."""
        try:
            self.cloud_provider.delete(created)
        except Exception as e:  # noqa: BLE001 - compensation must not mask the launch error
            log.warning(
                "abandoning machine %s after failed node create: %s",
                created.status.provider_id, e,
            )
