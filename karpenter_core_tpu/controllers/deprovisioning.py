"""Deprovisioning: expiration, drift, emptiness, and consolidation.

Mirror of /root/reference/pkg/controllers/deprovisioning/: a singleton polling
loop runs an ordered method chain — Expiration → Drift → Emptiness →
EmptyNodeConsolidation → MultiNodeConsolidation → SingleNodeConsolidation —
and the first method that acts wins (controller.go:142-193).  Every disruption
is validated by scheduling *simulation* (helpers.go:42-115 simulateScheduling
reuses the solver in simulation mode), re-checked after a 15s TTL
(validation.go), and executed as launch-replacements → cordon → mark →
wait-initialized → delete → wait-deleted (controller.go:219-329).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, List, Optional, Tuple

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node, Pod, PodDisruptionBudget
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.controllers.provisioning import ProvisioningController
from karpenter_core_tpu.events import events as evt
from karpenter_core_tpu.metrics import REGISTRY, measure
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.solver.builder import build_scheduler
from karpenter_core_tpu.solver.scheduler import SchedulerOptions
from karpenter_core_tpu.state.cluster import Cluster, StateNode
from karpenter_core_tpu.utils import node as node_util
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

POLLING_PERIOD = 10.0  # controller.go:64
CONSOLIDATION_TTL = 15.0  # consolidation.go:64
WAIT_RETRY_ATTEMPTS = 60  # controller.go:71-76 (~9.5 min)
WAIT_RETRY_DELAY = 2.0
WAIT_RETRY_MAX_DELAY = 10.0

DEGRADED_PAUSES = REGISTRY.counter(
    "karpenter_degraded_pauses_total",
    "Deprovisioning reconciles skipped because the solver-backend circuit "
    "breaker was open (disruption is optional work; a degraded control "
    "plane must not act on stale simulations).",
)

EVALUATION_DURATION = REGISTRY.histogram(
    "karpenter_deprovisioning_evaluation_duration_seconds",
    "Duration of the deprovisioning evaluation process in seconds.",
    ("method",),
)
ACTIONS_PERFORMED = REGISTRY.counter(
    "karpenter_deprovisioning_actions_performed",
    "Number of deprovisioning actions performed.",
    ("action",),
)
REPLACEMENT_INITIALIZED = REGISTRY.histogram(
    "karpenter_deprovisioning_replacement_node_initialized_seconds",
    "Amount of time required for a replacement node to become initialized.",
)
NODES_TERMINATED = REGISTRY.counter(
    "karpenter_nodes_terminated", "Number of nodes terminated in total by Karpenter.", ("reason",)
)


class Result(Enum):
    NOTHING_TO_DO = "nothing-to-do"
    RETRY = "retry"
    FAILED = "failed"
    SUCCESS = "success"


class Action(Enum):
    FAILED = "failed"
    DELETE = "delete"
    REPLACE = "replace"
    RETRY = "retry"
    DO_NOTHING = "do nothing"


@dataclass
class CandidateNode:
    """A node considered for deprovisioning (controller.go:130-139)."""

    node: Node
    state_node: StateNode
    instance_type: InstanceType
    capacity_type: str
    zone: str
    provisioner: Provisioner
    disruption_cost: float
    pods: List[Pod] = field(default_factory=list)


@dataclass
class Command:
    action: Action = Action.DO_NOTHING
    nodes_to_remove: List[Node] = field(default_factory=list)
    replacement_nodes: list = field(default_factory=list)  # SchedulingNode

    def __str__(self) -> str:
        names = ", ".join(n.name for n in self.nodes_to_remove)
        return f"{self.action.value}, terminating {len(self.nodes_to_remove)} nodes {names}"


class CandidateNodeDeleting(Exception):
    pass


# --- helpers (helpers.go) ------------------------------------------------------


def get_pod_eviction_cost(pod: Pod) -> float:
    """Pod-deletion-cost and priority scaled into [-10, 10] (helpers.go:125-146)."""
    cost = 1.0
    deletion_cost = pod.metadata.annotations.get("controller.kubernetes.io/pod-deletion-cost")
    if deletion_cost is not None:
        try:
            cost += float(deletion_cost) / (2.0**27)
        except ValueError:
            log.error("parsing pod-deletion-cost %r", deletion_cost)
    if pod.spec.priority is not None:
        cost += float(pod.spec.priority) / (2.0**25)
    return max(-10.0, min(cost, 10.0))


def disruption_cost(pods: List[Pod]) -> float:
    return sum(get_pod_eviction_cost(p) for p in pods)


def lifetime_remaining(candidate_node: Node, provisioner: Provisioner, clock: Clock) -> float:
    """Fraction of node lifetime remaining; expiring nodes cost less to disrupt
    (helpers.go:276-287)."""
    if provisioner.spec.ttl_seconds_until_expired is None:
        return 1.0
    age = clock.now() - candidate_node.metadata.creation_timestamp
    total = float(provisioner.spec.ttl_seconds_until_expired)
    return max(0.0, min((total - age) / total, 1.0))


def worst_launch_price(offerings, requirements: Requirements) -> float:
    """Spot-preferred worst-case launch price (helpers.go:292-315)."""
    ct = requirements.get(labels_api.LABEL_CAPACITY_TYPE)
    zone = requirements.get(labels_api.LABEL_TOPOLOGY_ZONE)
    if ct.has(labels_api.CAPACITY_TYPE_SPOT):
        spot = [
            o
            for o in offerings
            if o.capacity_type == labels_api.CAPACITY_TYPE_SPOT and zone.has(o.zone)
        ]
        if spot:
            return max(o.price for o in spot)
    if ct.has(labels_api.CAPACITY_TYPE_ON_DEMAND):
        od = [
            o
            for o in offerings
            if o.capacity_type == labels_api.CAPACITY_TYPE_ON_DEMAND and zone.has(o.zone)
        ]
        if od:
            return max(o.price for o in od)
    return float("inf")


def filter_by_price(
    options: List[InstanceType], requirements: Requirements, price: float
) -> List[InstanceType]:
    return [
        it
        for it in options
        if worst_launch_price(it.offerings.available(), requirements) < price
    ]


def instance_types_are_subset(lhs: List[InstanceType], rhs: List[InstanceType]) -> bool:
    return {it.name for it in lhs} <= {it.name for it in rhs}


class PDBLimits:
    """Snapshot of PodDisruptionBudgets (pdblimits.go:28-89)."""

    def __init__(self, kube_client) -> None:
        self.pdbs = kube_client.list(PodDisruptionBudget)

    def can_evict_pods(self, pods: List[Pod]) -> Tuple[Optional[str], bool]:
        for pod in pods:
            for pdb in self.pdbs:
                if pdb.metadata.namespace != pod.namespace:
                    continue
                if pdb.spec.selector is not None and pdb.spec.selector.matches(
                    pod.metadata.labels
                ):
                    if pdb.status.disruptions_allowed == 0:
                        return f"{pdb.metadata.namespace}/{pdb.metadata.name}", False
        return None, True


def pods_prevent_eviction(pods: List[Pod]) -> Tuple[str, bool]:
    """do-not-evict pods block termination (helpers.go:353-367)."""
    for p in pods:
        if pod_util.is_terminating(p) or pod_util.is_terminal(p) or pod_util.is_owned_by_node(p):
            continue
        if pod_util.has_do_not_evict(p):
            return f"pod {p.namespace}/{p.name} has do-not-evict annotation", True
    return "", False


def can_be_terminated(candidate: CandidateNode, pdbs: PDBLimits) -> Tuple[str, bool]:
    if candidate.node.metadata.deletion_timestamp is not None:
        return "in the process of deletion", False
    pdb, ok = pdbs.can_evict_pods(candidate.pods)
    if not ok:
        return f"pdb {pdb} prevents pod evictions", False
    reason, prevented = pods_prevent_eviction(candidate.pods)
    if prevented:
        return reason, False
    return "", True


def candidate_nodes(
    cluster: Cluster,
    kube_client,
    clock: Clock,
    cloud_provider: CloudProvider,
    should_deprovision: Callable,
) -> List[CandidateNode]:
    """Eligibility pipeline (helpers.go:171-249): owned, known instance type /
    zone / capacity type, initialized, not nominated, not marked."""
    provisioners = {p.name: p for p in kube_client.list_provisioners()}
    instance_types = {
        name: {it.name: it for it in cloud_provider.get_instance_types(p)}
        for name, p in provisioners.items()
    }
    out: List[CandidateNode] = []

    def visit(state_node: StateNode) -> bool:
        node = state_node.node
        provisioner_name = node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
        provisioner = provisioners.get(provisioner_name or "")
        if state_node.marked():
            return True
        if provisioner is None:
            return True
        it = instance_types[provisioner.name].get(
            node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE, "")
        )
        if it is None:
            return True
        ct = node.metadata.labels.get(labels_api.LABEL_CAPACITY_TYPE)
        zone = node.metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE)
        if not ct or not zone:
            return True
        if not state_node.initialized():
            return True
        if state_node.nominated(clock):
            return True
        pods = node_util.get_node_pods(kube_client, node)
        if not should_deprovision(state_node, provisioner, pods):
            return True
        cost = disruption_cost(pods) * lifetime_remaining(node, provisioner, clock)
        out.append(
            CandidateNode(
                node=node,
                state_node=state_node,
                instance_type=it,
                capacity_type=ct,
                zone=zone,
                provisioner=provisioner,
                pods=pods,
                disruption_cost=cost,
            )
        )
        return True

    cluster.for_each_node(visit)
    return out


def map_nodes(nodes: List[Node], candidates: List[CandidateNode]) -> List[CandidateNode]:
    names = {n.name for n in nodes}
    return [c for c in candidates if c.node.name in names]


def simulate_scheduling(
    kube_client,
    cluster: Cluster,
    provisioning: ProvisioningController,
    *nodes_to_delete: CandidateNode,
) -> Tuple[list, bool]:
    """Snapshot minus candidates; pods = pending + on-candidates + on-deleting;
    solve in simulation mode; fail when results rely on an uninitialized node
    (helpers.go:42-115).  Raises CandidateNodeDeleting on the race."""
    candidate_names = {c.node.name for c in nodes_to_delete}
    state_nodes = []
    deleting_nodes = []
    candidate_is_deleting = False

    def visit(n: StateNode) -> bool:
        nonlocal candidate_is_deleting
        if n.node.name not in candidate_names:
            if not n.marked():
                state_nodes.append(n.deep_copy())
            else:
                deleting_nodes.append(n.deep_copy())
        elif n.marked():
            candidate_is_deleting = True
        return True

    cluster.for_each_node(visit)
    if candidate_is_deleting:
        raise CandidateNodeDeleting()

    pods = provisioning.get_pending_pods()
    for candidate in nodes_to_delete:
        pods.extend(candidate.pods)
    pods.extend(
        node_util.get_node_pods(kube_client, *(n.node for n in deleting_nodes))
    )

    scheduler = build_scheduler(
        kube_client,
        provisioning.cloud_provider,
        cluster,
        pods,
        state_nodes,
        daemonset_pods=provisioning.get_daemonset_pods(),
        opts=SchedulerOptions(simulation_mode=True),
    )
    results = scheduler.solve(pods)

    scheduled = sum(len(n.pods) for n in results.new_nodes) + sum(
        len(n.pods) for n in results.existing_nodes
    )
    # relying on a not-yet-initialized in-flight node is not allowed
    for existing in results.existing_nodes:
        if existing.pods and existing.node.metadata.labels.get(
            labels_api.LABEL_NODE_INITIALIZED
        ) != "true":
            return results.new_nodes, False
    return results.new_nodes, scheduled == len(pods)


def get_node_prices(nodes: List[CandidateNode]) -> Tuple[float, Optional[str]]:
    price = 0.0
    for n in nodes:
        offering = n.instance_type.offerings.get(n.capacity_type, n.zone)
        if offering is None:
            return 0.0, (
                f"unable to determine offering for {n.instance_type.name}/"
                f"{n.capacity_type}/{n.zone}"
            )
        price += offering.price
    return price, None


# --- reporter (reporter.go) ------------------------------------------------------


class Reporter:
    """Dedupes 'why not consolidatable' events (reporter.go:35-53)."""

    def __init__(self, recorder, clock: Clock) -> None:
        self.recorder = recorder
        self.clock = clock
        self._seen = {}

    def record_unconsolidatable(self, node: Node, reason: str) -> None:
        key = (node.name, reason)
        now = self.clock.now()
        if key in self._seen and now - self._seen[key] < 15 * 60:
            return
        self._seen[key] = now
        if self.recorder is not None:
            self.recorder.publish(evt.unconsolidatable(node, reason))


# --- deprovisioners ---------------------------------------------------------------


class Expiration:
    """Delete/replace nodes past TTLSecondsUntilExpired, oldest first
    (expiration.go:56-130)."""

    name = "expiration"

    def __init__(self, clock, kube_client, cluster, provisioning) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.cluster = cluster
        self.provisioning = provisioning

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        return self.clock.now() > _expiration_time(state_node.node, provisioner)

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        candidates = sorted(
            candidates, key=lambda c: _expiration_time(c.node, c.provisioner)
        )
        pdbs = PDBLimits(self.kube_client)
        for candidate in candidates:
            _, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                continue
            try:
                new_nodes, all_scheduled = simulate_scheduling(
                    self.kube_client, self.cluster, self.provisioning, candidate
                )
            except CandidateNodeDeleting:
                continue
            if not all_scheduled:
                log.debug("continuing to expire node %s despite failed simulation", candidate.node.name)
            if not new_nodes:
                return Command(Action.DELETE, [candidate.node])
            return Command(Action.REPLACE, [candidate.node], new_nodes)
        return Command(Action.DO_NOTHING)


def _expiration_time(node: Node, provisioner: Optional[Provisioner]) -> float:
    if provisioner is None or provisioner.spec.ttl_seconds_until_expired is None:
        return float("inf")
    return node.metadata.creation_timestamp + provisioner.spec.ttl_seconds_until_expired


class Drift:
    """Feature-gated; acts on the drifted voluntary-disruption annotation
    (drift.go:50-105)."""

    name = "drift"

    def __init__(self, kube_client, cluster, provisioning, settings) -> None:
        self.kube_client = kube_client
        self.cluster = cluster
        self.provisioning = provisioning
        self.settings = settings

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        if not self.settings.drift_enabled:
            return False
        return (
            state_node.node.metadata.annotations.get(
                labels_api.VOLUNTARY_DISRUPTION_ANNOTATION_KEY
            )
            == labels_api.VOLUNTARY_DISRUPTION_DRIFTED_ANNOTATION_VALUE
        )

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        pdbs = PDBLimits(self.kube_client)
        for candidate in candidates:
            _, ok = can_be_terminated(candidate, pdbs)
            if not ok:
                continue
            try:
                new_nodes, all_scheduled = simulate_scheduling(
                    self.kube_client, self.cluster, self.provisioning, candidate
                )
            except CandidateNodeDeleting:
                continue
            if not all_scheduled:
                log.debug("terminating drifted node %s despite failed simulation", candidate.node.name)
            if not new_nodes:
                return Command(Action.DELETE, [candidate.node])
            return Command(Action.REPLACE, [candidate.node], new_nodes)
        return Command(Action.DO_NOTHING)


class Emptiness:
    """TTL-based removal of empty nodes via the emptiness-timestamp annotation
    (emptiness.go:52-90)."""

    name = "emptiness"

    def __init__(self, clock, kube_client, cluster) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.cluster = cluster

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        if provisioner is None or provisioner.spec.ttl_seconds_after_empty is None or pods:
            return False
        timestamp = state_node.node.metadata.annotations.get(
            labels_api.EMPTINESS_TIMESTAMP_ANNOTATION_KEY
        )
        if timestamp is None:
            return False
        try:
            emptiness_time = float(timestamp)
        except ValueError:
            log.error("unable to parse emptiness timestamp %r", timestamp)
            return True
        return self.clock.now() > emptiness_time + provisioner.spec.ttl_seconds_after_empty

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        empty = [c for c in candidates if not c.pods]
        if not empty:
            return Command(Action.DO_NOTHING)
        return Command(Action.DELETE, [c.node for c in empty])


class _ConsolidationBase:
    """Shared consolidation logic (consolidation.go:55-290)."""

    name = "consolidation"

    def __init__(self, clock, cluster, kube_client, provisioning, cloud_provider, reporter) -> None:
        self.clock = clock
        self.cluster = cluster
        self.kube_client = kube_client
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.reporter = reporter
        self.last_consolidation_state = -1.0

    def record_last_state(self, state: float) -> None:
        self.last_consolidation_state = state

    def should_attempt(self) -> bool:
        return self.last_consolidation_state != self.cluster.cluster_consolidation_state()

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        annotation = state_node.node.metadata.annotations.get(
            labels_api.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY
        )
        if annotation is not None:
            self.reporter.record_unconsolidatable(
                state_node.node,
                f"{labels_api.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY} annotation exists",
            )
            return annotation != "true"
        if provisioner is None:
            self.reporter.record_unconsolidatable(state_node.node, "provisioner is unknown")
            return False
        if provisioner.spec.consolidation is None or not provisioner.spec.consolidation.enabled:
            self.reporter.record_unconsolidatable(
                state_node.node,
                f"provisioner {provisioner.name} has consolidation disabled",
            )
            return False
        return True

    def sort_and_filter_candidates(self, candidates: List[CandidateNode]) -> List[CandidateNode]:
        pdbs = PDBLimits(self.kube_client)
        filtered = []
        for c in candidates:
            reason, ok = can_be_terminated(c, pdbs)
            if not ok:
                self.reporter.record_unconsolidatable(c.node, reason)
                continue
            filtered.append(c)
        return sorted(filtered, key=lambda c: c.disruption_cost)

    def compute_consolidation(self, *nodes: CandidateNode) -> Command:
        """Simulate → delete if 0 new nodes / replace if exactly 1 cheaper node;
        spot→spot forbidden; OD→[OD,spot] forces spot (consolidation.go:190-290)."""
        done = measure(EVALUATION_DURATION.labels("Replace/Delete"))
        try:
            try:
                new_nodes, all_scheduled = simulate_scheduling(
                    self.kube_client, self.cluster, self.provisioning, *nodes
                )
            except CandidateNodeDeleting:
                return Command(Action.DO_NOTHING)
            if not all_scheduled:
                if len(nodes) == 1:
                    self.reporter.record_unconsolidatable(
                        nodes[0].node, "not all pods would schedule"
                    )
                return Command(Action.DO_NOTHING)
            if not new_nodes:
                return Command(Action.DELETE, [n.node for n in nodes])
            if len(new_nodes) != 1:
                if len(nodes) == 1:
                    self.reporter.record_unconsolidatable(
                        nodes[0].node,
                        f"can't remove without creating {len(new_nodes)} nodes",
                    )
                return Command(Action.DO_NOTHING)

            nodes_price, err = get_node_prices(list(nodes))
            if err is not None:
                log.error("getting offering price from candidate node, %s", err)
                return Command(Action.FAILED)
            replacement = new_nodes[0]
            replacement.instance_type_options = filter_by_price(
                replacement.instance_type_options, replacement.requirements, nodes_price
            )
            if not replacement.instance_type_options:
                if len(nodes) == 1:
                    self.reporter.record_unconsolidatable(
                        nodes[0].node, "can't replace with a cheaper node"
                    )
                return Command(Action.DO_NOTHING)

            all_existing_spot = all(
                n.capacity_type == labels_api.CAPACITY_TYPE_SPOT for n in nodes
            )
            ct_req = replacement.requirements.get(labels_api.LABEL_CAPACITY_TYPE)
            if all_existing_spot and ct_req.has(labels_api.CAPACITY_TYPE_SPOT):
                if len(nodes) == 1:
                    self.reporter.record_unconsolidatable(
                        nodes[0].node, "can't replace a spot node with a spot node"
                    )
                return Command(Action.DO_NOTHING)

            # OD→[OD,spot]: pin to spot so a more expensive OD can't launch
            if ct_req.has(labels_api.CAPACITY_TYPE_SPOT) and ct_req.has(
                labels_api.CAPACITY_TYPE_ON_DEMAND
            ):
                replacement.requirements.add(
                    Requirement(
                        labels_api.LABEL_CAPACITY_TYPE, "In", [labels_api.CAPACITY_TYPE_SPOT]
                    )
                )
            return Command(Action.REPLACE, [n.node for n in nodes], new_nodes)
        finally:
            done()

    def validate_command(self, cmd: Command, candidates: List[CandidateNode]) -> bool:
        """Re-simulation shape check (validation.go:110-172)."""
        nodes_to_delete = map_nodes(cmd.nodes_to_remove, candidates)
        if not nodes_to_delete:
            return False
        try:
            new_nodes, all_scheduled = simulate_scheduling(
                self.kube_client, self.cluster, self.provisioning, *nodes_to_delete
            )
        except CandidateNodeDeleting:
            return False
        if not all_scheduled:
            return False
        if not new_nodes:
            return not cmd.replacement_nodes
        if len(new_nodes) > 1:
            return False
        if not cmd.replacement_nodes:
            return False
        return instance_types_are_subset(
            cmd.replacement_nodes[0].instance_type_options, new_nodes[0].instance_type_options
        )


class Validation:
    """TTL-delayed revalidation (validation.go:36-107)."""

    def __init__(self, period, clock, cluster, kube_client, provisioning, cloud_provider, base) -> None:
        self.period = period
        self.clock = clock
        self.cluster = cluster
        self.kube_client = kube_client
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.base = base
        self.start: Optional[float] = None
        self.candidates: List[CandidateNode] = []

    def should_deprovision(self, state_node, provisioner, pods) -> bool:
        annotation = state_node.node.metadata.annotations.get(
            labels_api.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY
        )
        if annotation is not None:
            return annotation != "true"
        return (
            provisioner is not None
            and provisioner.spec.consolidation is not None
            and provisioner.spec.consolidation.enabled
        )

    def is_valid(self, cmd: Command) -> bool:
        if self.start is None:
            self.start = self.clock.now()
        wait = self.period - (self.clock.now() - self.start)
        if wait > 0:
            self.clock.sleep(wait)
        if not self.candidates:
            self.candidates = candidate_nodes(
                self.cluster,
                self.kube_client,
                self.clock,
                self.cloud_provider,
                self.should_deprovision,
            )
        for node in cmd.nodes_to_remove:
            if self.cluster.is_node_nominated(node.name):
                return False
        return self.base.validate_command(cmd, self.candidates)


class SingleNodeConsolidation(_ConsolidationBase):
    """Cheapest-disruption-first, first valid delete/replace wins
    (singlenodeconsolidation.go:43-85)."""

    name = "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if not self.should_attempt():
            return Command(Action.DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        validation = Validation(
            CONSOLIDATION_TTL, self.clock, self.cluster, self.kube_client,
            self.provisioning, self.cloud_provider, self,
        )
        failed_validation = False
        for candidate in candidates:
            cmd = self.compute_consolidation(candidate)
            if cmd.action in (Action.DO_NOTHING, Action.RETRY, Action.FAILED):
                continue
            if not validation.is_valid(cmd):
                failed_validation = True
                continue
            if cmd.action in (Action.REPLACE, Action.DELETE):
                return cmd
        if failed_validation:
            return Command(Action.RETRY)
        return Command(Action.DO_NOTHING)


class MultiNodeConsolidation(_ConsolidationBase):
    """Binary search over the first-N disruption-sorted prefix for the largest
    simultaneously-consolidatable set, m→1 replacement only
    (multinodeconsolidation.go:41-165).  With ``use_tpu_kernel`` the search
    runs as a parallel subset sweep on device (solver.consolidation) and only
    the TTL validation stays on the host path."""

    name = "consolidation"
    use_tpu_kernel = False
    # remote sweep: ship /Consolidate to the solver service instead of
    # compiling in-process (set alongside use_tpu_kernel by the controller)
    solver_endpoint = ""
    _solver_client = None
    # the solver-backend circuit breaker, SHARED with the provisioning
    # controller (set by DeprovisioningController) — one backend, one
    # verdict; None (standalone construction) means no gating
    solver_breaker: Optional[retry.CircuitBreaker] = None

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if not self.should_attempt():
            return Command(Action.DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        cmd = None
        if self.use_tpu_kernel:
            cmd = self._tpu_search(candidates)
        if cmd is None:
            cmd = self.first_n_consolidation_option(candidates, len(candidates))
        if cmd.action == Action.DO_NOTHING:
            return cmd
        validation = Validation(
            CONSOLIDATION_TTL, self.clock, self.cluster, self.kube_client,
            self.provisioning, self.cloud_provider, self,
        )
        if not validation.is_valid(cmd):
            return Command(Action.RETRY)
        return cmd

    def _tpu_search(self, candidates: List[CandidateNode]) -> Optional[Command]:
        """Device subset sweep — remote over the snapshot channel when a
        solver service is configured, in-process otherwise; None falls back
        to the host binary search."""
        from karpenter_core_tpu.models.snapshot import KernelUnsupported
        from karpenter_core_tpu.solver.consolidation import TPUConsolidationSearch

        if len(candidates) < 2:
            return Command(Action.DO_NOTHING)
        if self.solver_breaker is not None and not self.solver_breaker.allow():
            # breaker open: don't touch the dead backend — host binary search
            return None
        try:
            if self.solver_endpoint:
                cmd = self._remote_search(candidates)
                if cmd is None:
                    # no backend verdict: free a half-open trial slot
                    if self.solver_breaker is not None:
                        self.solver_breaker.release_trial()
                    return None  # service judged the shape kernel-unsupported
            else:
                provisioners = self.kube_client.list_provisioners()
                search = TPUConsolidationSearch(
                    self.cloud_provider, provisioners,
                    # policy objective: lanes score by fleet-cost delta
                    # instead of node count when enabled (docs/POLICY.md);
                    # resolved per sweep like provisioning resolves per batch
                    policy=self._policy_config(provisioners),
                )
                cmd = search.compute_command(
                    candidates,
                    pending_pods=self.provisioning.get_pending_pods(),
                    state_nodes=self.cluster.snapshot_nodes(),
                    bound_pods=self.kube_client.list_pods(),
                )
        except KernelUnsupported as e:
            log.debug("TPU consolidation unsupported for cluster shape, %s", e)
            if self.solver_breaker is not None:
                self.solver_breaker.release_trial()  # shape verdict, not backend
            return None
        except Exception as e:  # backend init/relay faults: host binary search
            if self.solver_breaker is not None:
                self.solver_breaker.record_failure()
                state = self.solver_breaker.state
            else:
                state = "unbrokered"
            log.warning(
                "TPU consolidation sweep failed (%s: %s); falling back to the "
                "host binary search (breaker %s)",
                type(e).__name__, e, state,
            )
            return None
        if self.solver_breaker is not None:
            self.solver_breaker.record_success()
        return cmd

    def _policy_config(self, provisioners):
        """The policy-objective config for this sweep: the provisioning
        controller's resolver when it exposes one (one fleet, one objective),
        else env defaults (standalone / stub embeddings)."""
        resolver = getattr(self.provisioning, "policy_config", None)
        if resolver is not None:
            return resolver(provisioners)
        from karpenter_core_tpu.policy import PolicyConfig

        return PolicyConfig.resolve(provisioners)

    def _remote_search(self, candidates: List[CandidateNode]) -> Optional[Command]:
        """Ship the sweep to the solver service (/Consolidate).  Returns None
        on FAILED_PRECONDITION (host binary search takes over); transport
        faults propagate to _tpu_search's failure breaker."""
        import grpc

        from karpenter_core_tpu.apis import codec

        client = self._solver_client
        if client is None:
            from karpenter_core_tpu.service.snapshot_channel import (
                SnapshotSolverClient,
            )

            client = self._solver_client = SnapshotSolverClient(self.solver_endpoint)

        provisioners = self.kube_client.list_provisioners()
        state_nodes = self.cluster.snapshot_nodes()
        bound_pods = self.kube_client.list_pods()
        bound_by_node: Dict[str, List[Pod]] = {}
        for pod in bound_pods:
            if (
                pod.spec.node_name
                and not pod_util.is_terminal(pod)
                and not pod_util.is_terminating(pod)
            ):
                bound_by_node.setdefault(pod.spec.node_name, []).append(pod)
        nodes = [
            {
                "node": codec.node_to_dict(sn.node),
                "pods": [codec.pod_to_dict(p) for p in bound_by_node.get(sn.node.name, [])],
                "volumeLimits": dict(sn.volume_limits()),
            }
            for sn in state_nodes
        ]
        pending = self.provisioning.get_pending_pods()
        daemonset_pods = self.provisioning.get_daemonset_pods()
        wire_candidates = [
            {
                "name": c.node.name,
                "instanceType": c.instance_type.name if c.instance_type else "",
                "capacityType": c.capacity_type,
                "zone": c.zone,
                "provisioner": c.provisioner.name,
                "disruptionCost": float(c.disruption_cost),
            }
            for c in candidates
        ]
        try:
            response = client.consolidate(
                wire_candidates, pending, provisioners,
                nodes=nodes,
                claim_drivers=self.provisioning._claim_drivers(bound_pods + pending),
                # same policy the in-process sweep would run under — remote
                # lanes score by fleet-cost delta too (PR 9 leftover: the
                # config previously never crossed the channel)
                policy=self._policy_config(provisioners),
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                log.debug("remote consolidation: kernel unsupported (%s)", e.details())
                return None
            raise

        action = Action(response["action"])
        if action == Action.DO_NOTHING:
            return Command(Action.DO_NOTHING)
        nodes_to_remove = [
            node for name in response["nodesToRemove"]
            if (node := self.kube_client.get_node(name)) is not None
        ]
        replacements = []
        if response.get("replacements"):
            # templates + catalogs are only needed to rebuild launchables —
            # the common DELETE outcome skips the construction entirely
            from karpenter_core_tpu.solver.tpu import TPUSolver

            solver = TPUSolver(
                self.cloud_provider, provisioners,
                daemonset_pods=daemonset_pods,
                kube_client=self.kube_client,
            )
            for entry in response["replacements"]:
                pods = [
                    bound_by_node[name][i]
                    for name, i in entry.get("podRefs", [])
                    if name in bound_by_node and i < len(bound_by_node[name])
                ]
                node = solver.launchable_from_wire(entry, pods)
                if not node.instance_type_options:
                    log.warning(
                        "remote consolidation returned instance types unknown "
                        "to this catalog; skipping the command this round"
                    )
                    return Command(Action.DO_NOTHING)
                replacements.append(node)
        return Command(action, nodes_to_remove=nodes_to_remove,
                       replacement_nodes=replacements)

    def first_n_consolidation_option(
        self, candidates: List[CandidateNode], max_parallel: int
    ) -> Command:
        if len(candidates) < 2:
            return Command(Action.DO_NOTHING)
        lo_idx, hi_idx = 1, min(max_parallel, len(candidates) - 1)
        last_saved = Command(Action.DO_NOTHING)
        while lo_idx <= hi_idx:
            mid = (lo_idx + hi_idx) // 2
            subset = candidates[: mid + 1]
            cmd = self.compute_consolidation(*subset)
            if cmd.action == Action.REPLACE:
                cmd.replacement_nodes[0].instance_type_options = self.filter_out_same_type(
                    cmd.replacement_nodes[0], subset
                )
                if not cmd.replacement_nodes[0].instance_type_options:
                    cmd = Command(Action.DO_NOTHING)
            if cmd.action in (Action.REPLACE, Action.DELETE):
                last_saved = cmd
                lo_idx = mid + 1
            else:
                hi_idx = mid - 1
        return last_saved

    @staticmethod
    def filter_out_same_type(new_node, consolidate: List[CandidateNode]) -> List[InstanceType]:
        """Price-sanity filter: a replacement of the same type as a deleted node
        must be cheaper than that node (multinodeconsolidation.go:132-165)."""
        existing_types = set()
        prices_by_type = {}
        for c in consolidate:
            existing_types.add(c.instance_type.name)
            offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
            if offering is None:
                continue
            prices_by_type[c.instance_type.name] = min(
                prices_by_type.get(c.instance_type.name, float("inf")), offering.price
            )
        max_price = float("inf")
        for it in new_node.instance_type_options:
            if it.name in existing_types:
                max_price = min(max_price, prices_by_type.get(it.name, float("inf")))
        return filter_by_price(new_node.instance_type_options, new_node.requirements, max_price)


class EmptyNodeConsolidation(_ConsolidationBase):
    """Batch-delete empty candidates; validation waits the TTL then re-checks
    emptiness + nomination — no simulation (emptynodeconsolidation.go:44-88)."""

    name = "consolidation"

    def compute_command(self, candidates: List[CandidateNode]) -> Command:
        if not self.should_attempt():
            return Command(Action.DO_NOTHING)
        candidates = self.sort_and_filter_candidates(candidates)
        empty = [c for c in candidates if not c.pods]
        if not empty:
            return Command(Action.DO_NOTHING)
        cmd = Command(Action.DELETE, [c.node for c in empty])

        self.clock.sleep(CONSOLIDATION_TTL)
        validation_candidates = candidate_nodes(
            self.cluster, self.kube_client, self.clock, self.cloud_provider, self.should_deprovision
        )
        for candidate in map_nodes(cmd.nodes_to_remove, validation_candidates):
            if candidate.pods and not self.cluster.is_node_nominated(candidate.node.name):
                return Command(Action.RETRY)
        return cmd


# --- the controller ------------------------------------------------------------------


class DeprovisioningController:
    name = "deprovisioning"

    def __init__(
        self,
        clock,
        kube_client,
        provisioning: ProvisioningController,
        cloud_provider: CloudProvider,
        recorder,
        cluster: Cluster,
        settings,
        use_tpu_kernel: bool = False,
    ) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.provisioning = provisioning
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        self.cluster = cluster
        self.settings = settings
        self.reporter = Reporter(recorder, clock)
        base_args = (clock, cluster, kube_client, provisioning, cloud_provider, self.reporter)
        self.expiration = Expiration(clock, kube_client, cluster, provisioning)
        self.drift = Drift(kube_client, cluster, provisioning, settings)
        self.emptiness = Emptiness(clock, kube_client, cluster)
        self.empty_node_consolidation = EmptyNodeConsolidation(*base_args)
        self.multi_node_consolidation = MultiNodeConsolidation(*base_args)
        # device sweeps follow the provisioning controller's routing: with a
        # solver service configured (KC_SOLVER_ADDRESS / solver_endpoint), the
        # sweep ships over /Consolidate instead of compiling in-process on a
        # CPU-only controller replica
        self.multi_node_consolidation.use_tpu_kernel = use_tpu_kernel
        self.multi_node_consolidation.solver_endpoint = getattr(
            provisioning, "solver_endpoint", ""
        )
        # one backend, one breaker: the sweep shares the provisioning
        # controller's solver-backend verdict.  A stub/embedded provisioning
        # object without a breaker gets a local one — otherwise a dead
        # backend would be re-probed (full timeout + warning) on every sweep
        # for the life of the process, the safeguard the old
        # disable-after-2-failures flag used to provide.
        breaker = getattr(provisioning, "solver_breaker", None)
        if breaker is None:
            from karpenter_core_tpu.controllers.provisioning import (
                SOLVER_BREAKER_RESET_S,
                TPU_KERNEL_MAX_FAILURES,
            )

            breaker = retry.CircuitBreaker(
                clock,
                failure_threshold=TPU_KERNEL_MAX_FAILURES,
                reset_timeout_s=SOLVER_BREAKER_RESET_S,
                name="sweep-solver-backend",
            )
        self.multi_node_consolidation.solver_breaker = breaker
        self.single_node_consolidation = SingleNodeConsolidation(*base_args)
        # test hook: invoked after replacements launch so suites can initialize
        # the nodes that the readiness wait polls for
        self.on_replacements_launched: Optional[Callable[[List[str]], None]] = None
        self._wait_attempts = WAIT_RETRY_ATTEMPTS
        # reconcile requeue backoff (the reference's rate-limited workqueue):
        # 1, 2, 4, 8, then the polling period — pinned by tests/test_retry.py
        self._retry_backoff = retry.Backoff(1.0, POLLING_PERIOD)

    def reconcile(self) -> Tuple[Result, float]:
        """(result, requeue_after_seconds) — controller.go:107-128.  RETRY and
        FAILED back off exponentially (the reference's rate-limited workqueue
        requeue) instead of spinning."""
        with tracing.span("deprovisioning.reconcile") as sp:
            result, requeue = self._reconcile()
            sp.set(result=result.name)
            return result, requeue

    def _reconcile(self) -> Tuple[Result, float]:
        degraded = getattr(self.provisioning, "degraded", None)
        if degraded is not None and degraded():
            # the solver breaker is open: deprovisioning is OPTIONAL work —
            # disrupting nodes against a control plane already in a failure
            # mode risks acting on a stale simulation, so pause entirely and
            # let provisioning's degraded path keep the cluster converging
            DEGRADED_PAUSES.labels().inc()
            tracing.add_event("deprovisioning.paused", degraded=True)
            log.info("deprovisioning paused: solver-backend breaker open")
            return Result.NOTHING_TO_DO, POLLING_PERIOD
        current_state = self.cluster.cluster_consolidation_state()
        result, err = self.process_cluster()
        if result == Result.FAILED:
            log.error("processing cluster, %s", err)
            return result, self._retry_backoff.next()
        if result == Result.RETRY:
            return result, self._retry_backoff.next()
        self._retry_backoff.reset()
        if result == Result.NOTHING_TO_DO:
            self.empty_node_consolidation.record_last_state(current_state)
            self.single_node_consolidation.record_last_state(current_state)
            self.multi_node_consolidation.record_last_state(current_state)
        return result, POLLING_PERIOD

    def process_cluster(self) -> Tuple[Result, Optional[str]]:
        for deprovisioner in (
            self.expiration,
            self.drift,
            self.emptiness,
            self.empty_node_consolidation,
            self.multi_node_consolidation,
            self.single_node_consolidation,
        ):
            candidates = candidate_nodes(
                self.cluster,
                self.kube_client,
                self.clock,
                self.cloud_provider,
                deprovisioner.should_deprovision,
            )
            if not candidates:
                continue
            cmd = deprovisioner.compute_command(candidates)
            if cmd.action == Action.FAILED:
                return Result.FAILED, "computing command"
            if cmd.action == Action.DO_NOTHING:
                continue
            if cmd.action == Action.RETRY:
                return Result.RETRY, None
            result, err = self.execute_command(cmd, deprovisioner)
            if err is not None:
                return Result.FAILED, err
            return result, None
        return Result.NOTHING_TO_DO, None

    def execute_command(self, cmd: Command, deprovisioner) -> Tuple[Result, Optional[str]]:
        ACTIONS_PERFORMED.labels(f"{deprovisioner.name}/{cmd.action.value}").inc()
        log.info("deprovisioning via %s %s", deprovisioner.name, cmd)

        if cmd.action == Action.REPLACE:
            err = self.launch_replacement_nodes(cmd)
            if err is not None:
                return Result.FAILED, f"launching replacement node, {err}"

        for old_node in cmd.nodes_to_remove:
            if self.recorder is not None:
                self.recorder.publish(evt.terminating_node(old_node, str(cmd)))
            try:
                self.kube_client.delete(old_node)
                NODES_TERMINATED.labels(f"{deprovisioner.name}/{cmd.action.value}").inc()
            except Exception as e:  # noqa: BLE001
                log.error("deleting node, %s", e)

        for old_node in cmd.nodes_to_remove:
            self.wait_for_deletion(old_node)
        return Result.SUCCESS, None

    def launch_replacement_nodes(self, cmd: Command) -> Optional[str]:
        """Cordon old → launch → mark → wait initialized; rollback on failure
        (controller.go:274-329)."""
        done = measure(REPLACEMENT_INITIALIZED.labels())
        names_to_remove = [n.name for n in cmd.nodes_to_remove]
        err = self._set_unschedulable(True, *names_to_remove)
        if err is not None:
            return f"cordoning nodes, {err}"

        node_names, launch_err = self.provisioning.launch_machines(cmd.replacement_nodes)
        if launch_err is not None:
            self._set_unschedulable(False, *names_to_remove)
            return launch_err
        from karpenter_core_tpu.controllers.provisioning import NODES_CREATED

        NODES_CREATED.labels("deprovisioning").inc(len(node_names))
        self.cluster.mark_for_deletion(*names_to_remove)

        if self.on_replacements_launched is not None:
            self.on_replacements_launched(node_names)

        # wait for initialization with capped exponential backoff
        failed = []
        for name in node_names:
            if not self._wait_for_initialized(name):
                failed.append(name)
        if failed:
            self.cluster.unmark_for_deletion(*names_to_remove)
            self._set_unschedulable(False, *names_to_remove)
            return f"timed out checking node readiness for {failed}"
        done()
        return None

    def _wait_for_initialized(self, node_name: str) -> bool:
        backoff = retry.Backoff(WAIT_RETRY_DELAY, WAIT_RETRY_MAX_DELAY)
        for attempt in range(self._wait_attempts):
            node = self.kube_client.get_node(node_name)
            if node is not None and labels_api.LABEL_NODE_INITIALIZED in node.metadata.labels:
                return True
            if node is not None and self.recorder is not None:
                self.recorder.publish(evt.waiting_on_readiness(node_name))
            self.clock.sleep(backoff.next())
        return False

    def wait_for_deletion(self, node: Node) -> None:
        backoff = retry.Backoff(WAIT_RETRY_DELAY, WAIT_RETRY_MAX_DELAY)
        for attempt in range(self._wait_attempts):
            if self.kube_client.get_node(node.name) is None:
                return
            self.clock.sleep(backoff.next())
        log.error("waiting on node deletion for %s", node.name)

    def _set_unschedulable(self, unschedulable: bool, *names: str) -> Optional[str]:
        errs = []
        for name in names:
            node = self.kube_client.get_node(name)
            if node is None:
                errs.append(f"getting node {name}")
                continue
            if not unschedulable and node.metadata.deletion_timestamp is not None:
                continue
            node.spec.unschedulable = unschedulable
            self.kube_client.apply(node)
        return "; ".join(errs) if errs else None
