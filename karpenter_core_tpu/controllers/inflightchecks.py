"""Inflight checks: periodic node health audits surfaced as events.

Mirror of /root/reference/pkg/controllers/inflightchecks/{controller.go:84-93,
failedinit.go:34-90, termination.go:40-66, nodeshape.go:40-85}: FailedInit
(uninitialized >1h and why), Termination (stuck deleting: PDB / do-not-evict
blockers), NodeShape (capacity <90% of the instance type's expectation);
issues dedupe so each is reported once per node per condition.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.controllers.deprovisioning import PDBLimits, pods_prevent_eviction
from karpenter_core_tpu.controllers.node import (
    extended_resource_registered,
    startup_taint_removed,
)
from karpenter_core_tpu.events import events as evt
from karpenter_core_tpu.utils import node as node_util
from karpenter_core_tpu.utils import resources as resources_util
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

INIT_FAILURE_TIME = 3600.0  # failedinit.go:34
SCAN_PERIOD = 10 * 60.0  # controller.go: 10 min per node


@dataclass
class Issue:
    node: Node
    message: str


class FailedInit:
    def __init__(self, clock: Clock, provider) -> None:
        self.clock = clock
        self.provider = provider

    def check(self, node: Node, provisioner: Optional[Provisioner], pdbs: PDBLimits, kube) -> List[Issue]:
        if node.metadata.deletion_timestamp is not None:
            return []
        age = self.clock.now() - node.metadata.creation_timestamp
        if node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) == "true" or age < INIT_FAILURE_TIME:
            return []
        it_name = node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
        instance_type = next(
            (it for it in self.provider.get_instance_types(provisioner) if it.name == it_name),
            None,
        )
        if instance_type is None:
            return [Issue(node, f"Instance Type {it_name!r} not found")]
        issues = []
        taint, removed = startup_taint_removed(node, provisioner)
        if not removed:
            issues.append(
                Issue(node, f"Startup taint {taint.key}={taint.value}:{taint.effect} is still on the node")
            )
        resource, registered = extended_resource_registered(node, instance_type)
        if not registered:
            issues.append(Issue(node, f"Expected resource {resource!r} didn't register on the node"))
        return issues


class TerminationCheck:
    def check(self, node: Node, provisioner, pdbs: PDBLimits, kube) -> List[Issue]:
        if node.metadata.deletion_timestamp is None:
            return []
        pods = node_util.get_node_pods(kube, node)
        issues = []
        pdb, ok = pdbs.can_evict_pods(pods)
        if not ok:
            issues.append(Issue(node, f"Can't drain node, PDB {pdb} is blocking evictions"))
        reason, prevented = pods_prevent_eviction(pods)
        if prevented:
            issues.append(Issue(node, f"Can't drain node, {reason}"))
        return issues


class NodeShape:
    def __init__(self, provider) -> None:
        self.provider = provider

    def check(self, node: Node, provisioner, pdbs: PDBLimits, kube) -> List[Issue]:
        if node.metadata.deletion_timestamp is not None:
            return []
        if node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) != "true":
            return []
        it_name = node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
        instance_type = next(
            (it for it in self.provider.get_instance_types(provisioner) if it.name == it_name),
            None,
        )
        if instance_type is None:
            return [Issue(node, f"Instance Type {it_name!r} not found")]
        issues = []
        for name, expected in instance_type.capacity.items():
            if resources_util.is_zero(expected):
                continue
            actual = node.status.capacity.get(name)
            if actual is None:
                issues.append(Issue(node, f"Expected resource {name} not found"))
                continue
            pct = actual / expected
            if pct < 0.90:
                issues.append(
                    Issue(
                        node,
                        f"Expected {expected} of resource {name}, but found {actual} "
                        f"({pct * 100:.1f}% of expected)",
                    )
                )
        return issues


class InflightChecksController:
    """Runs every check per node at most once per SCAN_PERIOD; dedupes issue
    events (controller.go:84-93)."""

    name = "inflightchecks"

    def __init__(self, clock: Clock, kube_client, cloud_provider, recorder) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.recorder = recorder
        self.checks = [
            FailedInit(clock, cloud_provider),
            TerminationCheck(),
            NodeShape(cloud_provider),
        ]
        self._last_scan = {}
        self._reported = {}

    @tracing.traced("inflightchecks.reconcile")
    def reconcile(self, node: Node) -> Optional[float]:
        provisioner_name = node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
        if not provisioner_name:
            return None
        now = self.clock.now()
        last = self._last_scan.get(node.name)
        if last is not None and now - last < SCAN_PERIOD:
            return SCAN_PERIOD - (now - last)
        self._last_scan[node.name] = now
        provisioner = self.kube_client.get(Provisioner, provisioner_name)
        pdbs = PDBLimits(self.kube_client)
        for check in self.checks:
            for issue in check.check(node, provisioner, pdbs, self.kube_client):
                key = (node.name, issue.message)
                if key in self._reported:
                    continue
                self._reported[key] = now
                log.info("inflight check failed for node %s, %s", node.name, issue.message)
                if self.recorder is not None:
                    self.recorder.publish(evt.node_inflight_check(node, issue.message))
        return SCAN_PERIOD

    def reconcile_all(self) -> None:
        for node in self.kube_client.list_nodes():
            self.reconcile(node)
