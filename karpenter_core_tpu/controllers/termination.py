"""Termination: finalizer-driven graceful drain and instance deletion.

Mirror of /root/reference/pkg/controllers/termination/{controller.go:44-116,
terminate.go:50-170, eviction.go:40-120}: when a node has a deletion timestamp
and carries the termination finalizer — cordon (plus exclude-balancers label),
drain (do-not-evict aborts; skip tolerating/static pods; critical pods last)
through a rate-limited eviction queue, then CloudProvider.delete and finalizer
removal.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Set, Tuple

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node, Pod
from karpenter_core_tpu.cloudprovider import MachineNotFoundError
from karpenter_core_tpu.controllers.node import machine_from_node
from karpenter_core_tpu.events import events as evt
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

TERMINATION_SUMMARY = REGISTRY.summary(
    "karpenter_nodes_termination_time_seconds",
    "The time taken between a node's deletion request and the removal of its finalizer",
)

EVICTION_QUEUE_BASE_DELAY = 0.1
EVICTION_QUEUE_MAX_DELAY = 10.0


class NodeDrainError(Exception):
    pass


class EvictionQueue:
    """Rate-limited async eviction worker (eviction.go:40-120).  In the
    standalone framework 'evicting' a pod = deleting it through the kube store,
    honoring PDBs the way the Evict API's 429 does."""

    def __init__(self, kube_client, recorder, clock: Optional[Clock] = None, synchronous: bool = True) -> None:
        self.kube_client = kube_client
        self.recorder = recorder
        self.clock = clock or Clock()
        self._set: Set[Tuple[str, str]] = set()
        self._queue: List[Tuple[str, str]] = []
        self._failures = {}
        self._lock = threading.Lock()
        self.synchronous = synchronous

    def add(self, pods: List[Pod]) -> None:
        with self._lock:
            for pod in pods:
                key = (pod.namespace, pod.name)
                if key not in self._set:
                    self._set.add(key)
                    self._queue.append(key)
        if self.synchronous:
            self.drain_queue()

    def drain_queue(self) -> None:
        """Process everything currently queued (one pass)."""
        while True:
            with self._lock:
                if not self._queue:
                    return
                key = self._queue.pop(0)
            if self._evict(key):
                with self._lock:
                    self._set.discard(key)
                    self._failures.pop(key, None)
            else:
                with self._lock:
                    failures = self._failures.get(key, 0) + 1
                    self._failures[key] = failures
                    self._queue.append(key)
                delay = min(
                    EVICTION_QUEUE_BASE_DELAY * (2 ** (failures - 1)), EVICTION_QUEUE_MAX_DELAY
                )
                self.clock.sleep(delay)
                if failures > 8:  # bounded retries per pass in synchronous mode
                    return

    def _evict(self, key: Tuple[str, str]) -> bool:
        namespace, name = key
        pod = self.kube_client.get_pod(namespace, name)
        if pod is None:
            return True  # 404: already gone
        # PDB check stands where the Evict API's 429 stands
        from karpenter_core_tpu.controllers.deprovisioning import PDBLimits

        pdbs = PDBLimits(self.kube_client)
        violated, ok = pdbs.can_evict_pods([pod])
        if not ok:
            if self.recorder is not None:
                self.recorder.publish(
                    evt.node_failed_to_drain(
                        Node(), f"evicting pod {namespace}/{name} violates pdb {violated}"
                    )
                )
            return False
        try:
            self.kube_client.delete(pod, force=True)
        except Exception:  # noqa: BLE001 - delete races are eviction failures
            return False
        if self.recorder is not None:
            self.recorder.publish(evt.evict_pod(pod))
        return True


class Terminator:
    def __init__(self, clock: Clock, kube_client, cloud_provider, eviction_queue: EvictionQueue) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.eviction_queue = eviction_queue

    def cordon(self, node: Node) -> None:
        node.spec.unschedulable = True
        node.metadata.labels[labels_api.LABEL_NODE_EXCLUDE_BALANCERS] = "karpenter"
        self.kube_client.apply(node)
        log.info("cordoned node %s", node.name)

    def drain(self, node: Node) -> Optional[str]:
        """Error string while pods remain (drain is re-entrant, terminate.go:71-96)."""
        pods = self._get_pods(node)
        pods_to_evict = []
        for p in pods:
            if pod_util.has_do_not_evict(p):
                return f"pod {p.namespace}/{p.name} has do-not-evict annotation"
            if pod_util.tolerates_unschedulable_taint(p):
                continue
            if pod_util.is_owned_by_node(p):
                continue
            pods_to_evict.append(p)
        self._evict(pods_to_evict)
        if pods_to_evict:
            return f"{len(pods_to_evict)} pods are waiting to be evicted"
        return None

    def terminate(self, node: Node) -> Optional[str]:
        try:
            self.cloud_provider.delete(machine_from_node(node))
        except MachineNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001
            return f"terminating cloudprovider instance, {e}"
        self.kube_client.remove_finalizer(node, labels_api.TERMINATION_FINALIZER)
        log.info("deleted node %s", node.name)
        return None

    def _get_pods(self, node: Node) -> List[Pod]:
        pods = []
        for p in self.kube_client.list_pods(selector=lambda p: p.spec.node_name == node.name):
            if pod_util.is_terminal(p):
                continue
            if self._is_stuck_terminating(p):
                continue
            pods.append(p)
        return pods

    def _evict(self, pods: List[Pod]) -> None:
        """Critical pods evict last (terminate.go:136-156)."""
        critical, non_critical = [], []
        for pod in pods:
            if pod.metadata.deletion_timestamp is not None:
                continue
            if pod.spec.priority_class_name in ("system-cluster-critical", "system-node-critical"):
                critical.append(pod)
            else:
                non_critical.append(pod)
        if not non_critical:
            self.eviction_queue.add(critical)
        else:
            self.eviction_queue.add(non_critical)

    def _is_stuck_terminating(self, pod: Pod) -> bool:
        if pod.metadata.deletion_timestamp is None:
            return False
        return self.clock.now() > pod.metadata.deletion_timestamp + 60.0


class TerminationController:
    """Finalizes deleting nodes (controller.go:92-116)."""

    name = "termination"

    def __init__(self, clock: Clock, kube_client, cloud_provider, recorder=None) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.eviction_queue = EvictionQueue(kube_client, recorder, clock)
        self.terminator = Terminator(clock, kube_client, cloud_provider, self.eviction_queue)

    @tracing.traced("termination.reconcile")
    def reconcile(self, node: Node) -> Optional[float]:
        """Requeue seconds while draining, None when finalized."""
        stored = self.kube_client.get_node(node.name)
        if stored is None:
            return None
        if stored.metadata.deletion_timestamp is None:
            return None
        if labels_api.TERMINATION_FINALIZER not in stored.metadata.finalizers:
            return None
        self.terminator.cordon(stored)
        err = self.terminator.drain(stored)
        if err is not None:
            log.debug("draining node %s, %s", stored.name, err)
            return 1.0  # requeue while pods remain
        err = self.terminator.terminate(stored)
        if err is not None:
            log.error("%s", err)
            return 1.0
        TERMINATION_SUMMARY.observe(
            max(self.clock.now() - (stored.metadata.deletion_timestamp or 0.0), 0.0)
        )
        return None

    def reconcile_all(self) -> None:
        """Drive every deleting node to completion (or stuck-on-drain)."""
        for node in list(self.kube_client.list_nodes()):
            for _ in range(8):
                if self.reconcile(node) is None:
                    break
