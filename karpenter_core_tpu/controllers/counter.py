"""Counter: maintains provisioner.status.resources.

Mirror of /root/reference/pkg/controllers/counter/controller.go:62-148: the
provisioner's status carries the summed capacity of its state nodes; the
reference waits until the state cache and list cache agree before writing.
"""

from __future__ import annotations

from typing import Optional

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.utils import resources as resources_util


class CounterController:
    name = "counter"

    def __init__(self, kube_client, cluster: Cluster) -> None:
        self.kube_client = kube_client
        self.cluster = cluster

    @tracing.traced("counter.reconcile")
    def reconcile(self, provisioner: Provisioner) -> Optional[float]:
        stored = self.kube_client.get(Provisioner, provisioner.name)
        if stored is None:
            return None
        resources: resources_util.ResourceList = {}

        def visit(node) -> bool:
            nonlocal resources
            if (
                node.node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
                == stored.name
            ):
                resources = resources_util.merge(resources, node.capacity())
            return True

        self.cluster.for_each_node(visit)
        # write only on change (the reference waits for state/list agreement
        # and compares before writing, controller.go:121-148)
        if stored.status.resources != resources:
            stored.status.resources = resources
            self.kube_client.apply(stored)
        return None

    def reconcile_all(self) -> None:
        for provisioner in self.kube_client.list_provisioners():
            self.reconcile(provisioner)
