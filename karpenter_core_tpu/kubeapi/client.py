"""Apiserver-backed KubeClient: the in-memory client's surface over real HTTP.

Speaks the Kubernetes list/watch protocol (typed GET/LIST/POST/PUT/DELETE plus
chunked watch streams with resourceVersion resume) against any server that
implements the subset — the hermetic ``testing.fakeapiserver`` or a real
kube-apiserver proxy.  Design decisions that keep it drop-in compatible with
``operator.kubeclient.KubeClient`` (the whole controller stack is written
against that surface):

  - **Reads come from the reflector store.**  Every kind lazily gets a
    Reflector whose start blocks on the initial LIST, so a fresh process
    warm-starts cluster state from the server (the §5.4 restart-rebuild gap).
    get/list return the store's live references — the same aliasing the
    in-memory client exposes.

  - **Self-originated mutations dispatch synchronously.**  After a successful
    write, the writing thread applies the event (through the per-key
    resourceVersion guard) and runs watch callbacks itself, exactly like the
    in-memory client's synchronous delivery; the watch stream's later replay
    of the same event is dropped by the guard.  External writers' events
    arrive through the reflector thread.

  - **Optimistic concurrency is opt-in**, mirroring in-memory semantics:
    ``update`` sends resourceVersion 0 (unconditional replace, real-apiserver
    behavior for an empty resourceVersion) while ``update_with_version`` sends
    the expected version and maps HTTP 409 to ConflictError — the CAS leader
    election needs.

  - **Deletion timestamps come from the client's clock**, not the server's
    wall clock, so FakeClock-driven TTL semantics (expiry, emptiness) hold in
    tests; finalizer handling composes the same primitives as the in-memory
    client (MODIFIED-with-deletionTimestamp, then DELETED once clear).

  - Mutations meter through the shared RateLimiter (``--kube-client-qps``),
    and every request carries a timeout; watch streams ride long-poll
    timeouts with server bookmarks as keepalives.
"""

from __future__ import annotations

import json
import logging
import threading
from http.client import HTTPConnection
from typing import Dict, List, Optional
from urllib.parse import urlsplit

from karpenter_core_tpu.apis.objects import (
    CSINode,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    deep_copy,
)
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.kubeapi.reflector import Reflector
from karpenter_core_tpu.kubeapi.resources import spec_for
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.operator.kubeclient import (
    KUBEAPI_PUT,
    ConflictError,
    NotFoundError,
    RateLimiter,
    WatchFunc,
    raise_injected_kubeapi_fault,
)

log = logging.getLogger(__name__)

REQUESTS = REGISTRY.counter(
    "karpenter_kubeapi_requests_total",
    "Apiserver requests by verb and HTTP status code.",
    ("verb", "code"),
)


class ApiServerError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"apiserver returned {status}: {body[:300]}")
        self.status = status


class _Transport:
    """One apiserver endpoint: request/response plumbing with timeouts.

    Plain requests open a short-lived connection each (the operator's request
    rate is QPS-limited well below connection-setup costs mattering); watch
    streams own a dedicated connection with a long read timeout."""

    def __init__(self, base_url: str, timeout_s: float = 30.0) -> None:
        parts = urlsplit(base_url)
        if parts.scheme not in ("http", ""):
            raise ValueError(
                f"unsupported apiserver scheme {parts.scheme!r} (http only; "
                f"terminate TLS in a sidecar/kubectl-proxy)"
            )
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or 80
        self.timeout_s = timeout_s

    def request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        if method != "GET":
            # same chaos point, fault mapping, AND kind filter as the
            # in-memory backend, so one scenario replays against either
            fault = KUBEAPI_PUT.hit(
                kinds=("error", "timeout"),
                backend="apiserver", verb=method, path=path,
            )
            if fault is not None and fault.kind in ("error", "timeout"):
                raise_injected_kubeapi_fault(fault)
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout_s)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read().decode()
            REQUESTS.labels(method, str(resp.status)).inc()
            if resp.status == 404:
                raise NotFoundError(data or path)
            if resp.status == 409:
                raise ConflictError(data or path)
            if resp.status >= 400:
                raise ApiServerError(resp.status, data)
            return json.loads(data) if data else {}
        finally:
            conn.close()

    def stream(self, method: str, path: str, timeout: float):
        """Open a watch stream; returns the live HTTPResponse (caller closes).
        The connection is parked on the response object so closing the
        response tears the socket down."""
        conn = HTTPConnection(self.host, self.port, timeout=timeout)
        conn.request(method, path)
        resp = conn.getresponse()
        REQUESTS.labels("WATCH", str(resp.status)).inc()
        resp._kc_conn = conn  # keep the connection alive with the stream
        _orig_close = resp.close

        def close():
            _orig_close()
            conn.close()

        resp.close = close
        return resp


class ApiServerClient:
    """KubeClient-compatible facade over a kube-apiserver endpoint."""

    def __init__(
        self,
        base_url: str,
        clock=None,
        qps: Optional[float] = None,
        burst: Optional[int] = None,
        *,
        request_timeout_s: float = 30.0,
        watch_timeout_s: float = 60.0,
        backoff_base_s: float = 0.2,
        backoff_cap_s: float = 30.0,
        rng=None,
    ) -> None:
        import time as _time

        self._clock = clock
        self._now = clock.now if clock is not None else _time.time
        self._sleep = clock.sleep if clock is not None else _time.sleep
        self._limiter = RateLimiter(qps, burst, now=self._now, sleep=self._sleep)
        self.transport = _Transport(base_url, timeout_s=request_timeout_s)
        self._watch_timeout_s = watch_timeout_s
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        # seedable watch-recovery jitter source, shared across this client's
        # reflectors (tests/chaos scenarios pass retry.DeterministicRNG(seed))
        self._rng = rng
        self._reflectors: Dict[type, Reflector] = {}
        self._lock = threading.Lock()
        self._closed = False

    # -- reflector management --------------------------------------------------

    def reflector(self, kind: type) -> Reflector:
        """The kind's reflector, started (initial LIST synced) on first use."""
        with self._lock:
            refl = self._reflectors.get(kind)
        if refl is not None:
            # a concurrent creator may still be inside start(): reads must
            # not see the store before the initial LIST has been applied
            refl.wait_synced()
            return refl
        with self._lock:
            refl = self._reflectors.get(kind)
            if refl is not None:
                refl.wait_synced()
                return refl
            if self._closed:
                raise RuntimeError("client is closed")
            refl = Reflector(
                spec_for(kind),
                self.transport,
                backoff_base_s=self._backoff_base_s,
                backoff_cap_s=self._backoff_cap_s,
                watch_timeout_s=self._watch_timeout_s,
                rng=self._rng,
                clock=self._clock,
            )
            self._reflectors[kind] = refl
        refl.start()
        return refl

    def close(self) -> None:
        with self._lock:
            self._closed = True
            reflectors = list(self._reflectors.values())
        for refl in reflectors:
            refl.stop()

    # -- generic CRUD (KubeClient surface) -------------------------------------

    def create(self, obj) -> object:
        self._limiter.take()
        return self._post(obj)

    def get(self, kind: type, name: str, namespace: Optional[str] = None):
        refl = self.reflector(kind)
        key = (namespace, name) if refl.spec.namespaced else (name,)
        return refl.get(key)

    def update(self, obj) -> object:
        self._limiter.take()
        return self._put(obj, expected_version=None)

    def update_with_version(self, obj, expected_resource_version: int) -> object:
        """CAS update (client-go semantics): ConflictError when the stored
        resourceVersion moved past ``expected``.  Unlike the in-memory client
        the apiserver hands out decoded copies, so the caller's object is
        already private — but the contract (pass your own copy + the version
        snapshotted at read) stays the same."""
        self._limiter.take()
        return self._put(obj, expected_version=expected_resource_version)

    def _put(self, obj, expected_version: Optional[int]) -> object:
        spec = spec_for(type(obj))
        wire = spec.to_dict(obj)
        # rv 0 = unconditional replace (apiserver treats an empty
        # resourceVersion as "no optimistic check"), matching in-memory update
        wire["metadata"]["resourceVersion"] = (
            expected_version if expected_version is not None else 0
        )
        ns = obj.metadata.namespace if spec.namespaced else None
        out = self.transport.request(
            "PUT", spec.object_path(obj.metadata.name, ns), wire
        )
        return self._absorb_write("MODIFIED", obj, out)

    def apply(self, obj) -> object:
        """create-or-update, composed from the unconditional primitives."""
        self._limiter.take()
        try:
            return self._post(obj)
        except ConflictError:
            return self._put(obj, expected_version=None)

    def _post(self, obj):
        spec = spec_for(type(obj))
        if not obj.metadata.creation_timestamp:
            obj.metadata.creation_timestamp = self._now()
        wire = spec.to_dict(obj)
        wire["metadata"]["resourceVersion"] = 0
        ns = obj.metadata.namespace if spec.namespaced else None
        out = self.transport.request("POST", spec.base_path(ns), wire)
        return self._absorb_write("ADDED", obj, out)

    def delete(self, obj, *, force: bool = False) -> None:
        """k8s deletion semantics, composed client-side so deletionTimestamp
        comes from this client's clock (FakeClock-driven TTL tests): with
        finalizers present the first delete stamps deletionTimestamp via PUT;
        the object is removed once finalizers clear (or immediately with
        ``force``)."""
        self._limiter.take()
        spec = spec_for(type(obj))
        ns = obj.metadata.namespace if spec.namespaced else None
        refl = self.reflector(type(obj))
        key = (ns, obj.metadata.name) if spec.namespaced else (obj.metadata.name,)
        stored = refl.get(key)
        if stored is None:
            raise NotFoundError(f"{type(obj).__name__} {key} not found")
        if stored.metadata.finalizers and not force:
            if stored.metadata.deletion_timestamp is None:
                # stamp a COPY: mutating the live store object before the PUT
                # would desync the cache if the request fails (and make the
                # caller's retry a silent no-op).  On success the PUT's
                # self-applied event installs the stamped copy in the store.
                stamped = deep_copy(stored)
                stamped.metadata.deletion_timestamp = self._now()
                self._put(stamped, expected_version=None)
            return
        out = self.transport.request(
            "DELETE", spec.object_path(obj.metadata.name, ns)
        )
        rv = int(out.get("metadata", {}).get("resourceVersion", 0) or 0)
        refl.apply_event("DELETED", stored, rv)

    def remove_finalizer(self, obj, finalizer: str) -> None:
        stored = self.get(
            type(obj),
            obj.metadata.name,
            obj.metadata.namespace if spec_for(type(obj)).namespaced else None,
        )
        if stored is None:
            return
        # strip on a copy (same failed-PUT cache-desync concern as delete())
        stripped = deep_copy(stored)
        stripped.metadata.finalizers = [
            f for f in stripped.metadata.finalizers if f != finalizer
        ]
        should_remove = (
            stripped.metadata.deletion_timestamp is not None
            and not stripped.metadata.finalizers
        )
        self.update(stripped)
        if should_remove:
            self.delete(stripped, force=True)

    def list(self, kind: type, namespace: Optional[str] = None, selector=None) -> list:
        refl = self.reflector(kind)
        out = []
        for key, obj in refl.items():
            if namespace is not None and refl.spec.namespaced and key[0] != namespace:
                continue
            if selector is not None and not _selector_matches(selector, obj):
                continue
            out.append(obj)
        return out

    def watch(self, kind: type, callback: WatchFunc, *, replay: bool = True) -> None:
        refl = self.reflector(kind)
        # snapshot AND replay under the dispatch lock: live events are held
        # off until the replay finishes, so the callback can never see a
        # stale replayed ADDED after a fresher live DELETED/MODIFIED
        with refl.dispatch_lock:
            with refl.lock:
                refl.callbacks.append(callback)
                existing = refl.snapshot() if replay else []
            for obj in existing:
                callback("ADDED", obj)

    # -- write absorption ------------------------------------------------------

    def _absorb_write(self, event_type: str, obj, out: dict) -> object:
        """Reflect a successful write locally: adopt the server-assigned
        resourceVersion onto the caller's object (in-memory client mutates it
        the same way) and deliver the event synchronously through the per-key
        guard, so a caller observes its own write immediately."""
        rv = int(out.get("metadata", {}).get("resourceVersion", 0) or 0)
        obj.metadata.resource_version = rv
        refl = self.reflector(type(obj))
        refl.apply_event(event_type, obj, rv)
        return obj

    # -- typed conveniences (KubeClient parity) --------------------------------

    def list_pods(self, namespace: Optional[str] = None, selector=None) -> List[Pod]:
        return self.list(Pod, namespace=namespace, selector=selector)

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self.get(Pod, name, namespace)

    def get_node(self, name: str) -> Optional[Node]:
        return self.get(Node, name)

    def list_nodes(self) -> List[Node]:
        return self.list(Node)

    def list_namespaces(self, selector=None) -> List[Namespace]:
        return self.list(Namespace, selector=selector)

    def list_provisioners(self) -> List[Provisioner]:
        return self.list(Provisioner)

    def get_persistent_volume_claim(self, namespace: str, name: str):
        return self.get(PersistentVolumeClaim, name, namespace)

    def get_persistent_volume(self, name: str):
        return self.get(PersistentVolume, name)

    def get_storage_class(self, name: str):
        from karpenter_core_tpu.apis.objects import StorageClass

        return self.get(StorageClass, name)

    def get_csi_node(self, name: str):
        return self.get(CSINode, name)

    def deep_copy(self, obj):
        return deep_copy(obj)


def _selector_matches(selector, obj) -> bool:
    from karpenter_core_tpu.apis.objects import LabelSelector

    if isinstance(selector, LabelSelector):
        return selector.matches(obj.metadata.labels)
    if isinstance(selector, dict):
        return all(obj.metadata.labels.get(k) == v for k, v in selector.items())
    if callable(selector):
        return selector(obj)
    raise TypeError(f"unsupported selector {selector!r}")
