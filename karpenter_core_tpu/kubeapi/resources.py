"""Kind registry for the apiserver-backed KubeClient.

Maps every dataclass kind the framework stores to its Kubernetes REST
coordinates (group/version/plural, namespaced-ness) and its wire codec
(apis.codec dict round-trip).  Both sides of the protocol share this table:
the client (kubeapi.client) builds request paths from it, and the hermetic
fake apiserver (testing.fakeapiserver) serves exactly these routes — so a
path-construction bug cannot hide behind a matching server-side bug for a
kind the real apiserver would route differently.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from karpenter_core_tpu.apis import codec
from karpenter_core_tpu.apis.objects import (
    CSINode,
    Lease,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    StorageClass,
)
from karpenter_core_tpu.apis.v1alpha5 import Machine, Provisioner
from karpenter_core_tpu.operator.settingsstore import ConfigMap


@dataclass(frozen=True)
class ResourceSpec:
    kind: type
    kind_name: str
    group: str  # "" = core
    version: str
    plural: str
    namespaced: bool
    to_dict: Callable[[Any], Dict[str, Any]]
    from_dict: Callable[[Dict[str, Any]], Any]

    @property
    def api_version(self) -> str:
        return self.version if not self.group else f"{self.group}/{self.version}"

    def base_path(self, namespace: Optional[str] = None) -> str:
        """Collection path: /api/v1/pods, /api/v1/namespaces/{ns}/pods,
        /apis/karpenter.sh/v1alpha5/provisioners, ..."""
        root = "/api/v1" if not self.group else f"/apis/{self.group}/{self.version}"
        if self.namespaced and namespace is not None:
            return f"{root}/namespaces/{namespace}/{self.plural}"
        return f"{root}/{self.plural}"

    def object_path(self, name: str, namespace: Optional[str] = None) -> str:
        return f"{self.base_path(namespace)}/{name}"


def _configmap_to_dict(cm: ConfigMap) -> Dict[str, Any]:
    return {"metadata": codec._meta_to_dict(cm.metadata), "data": dict(cm.data)}


def _configmap_from_dict(d: Dict[str, Any]) -> ConfigMap:
    return ConfigMap(
        metadata=codec._meta_from_dict(d.get("metadata", {})),
        data=dict(d.get("data", {})),
    )


_SPECS = [
    ResourceSpec(Pod, "Pod", "", "v1", "pods", True,
                 codec.pod_to_dict, codec.pod_from_dict),
    ResourceSpec(Node, "Node", "", "v1", "nodes", False,
                 codec.node_to_dict, codec.node_from_dict),
    ResourceSpec(Namespace, "Namespace", "", "v1", "namespaces", False,
                 codec.namespace_to_dict, codec.namespace_from_dict),
    ResourceSpec(ConfigMap, "ConfigMap", "", "v1", "configmaps", True,
                 _configmap_to_dict, _configmap_from_dict),
    ResourceSpec(PersistentVolumeClaim, "PersistentVolumeClaim", "", "v1",
                 "persistentvolumeclaims", True,
                 codec.pvc_to_dict, codec.pvc_from_dict),
    ResourceSpec(PersistentVolume, "PersistentVolume", "", "v1",
                 "persistentvolumes", False,
                 codec.pv_to_dict, codec.pv_from_dict),
    ResourceSpec(Provisioner, "Provisioner", "karpenter.sh", "v1alpha5",
                 "provisioners", False,
                 codec.provisioner_to_dict, codec.provisioner_from_dict),
    ResourceSpec(Machine, "Machine", "karpenter.sh", "v1alpha5",
                 "machines", False,
                 codec.machine_to_dict, codec.machine_from_dict),
    ResourceSpec(PodDisruptionBudget, "PodDisruptionBudget", "policy", "v1",
                 "poddisruptionbudgets", True,
                 codec.pdb_to_dict, codec.pdb_from_dict),
    ResourceSpec(StorageClass, "StorageClass", "storage.k8s.io", "v1",
                 "storageclasses", False,
                 codec.storageclass_to_dict, codec.storageclass_from_dict),
    ResourceSpec(CSINode, "CSINode", "storage.k8s.io", "v1", "csinodes", False,
                 codec.csinode_to_dict, codec.csinode_from_dict),
    ResourceSpec(Lease, "Lease", "coordination.k8s.io", "v1", "leases", True,
                 codec.lease_to_dict, codec.lease_from_dict),
]

BY_KIND: Dict[type, ResourceSpec] = {s.kind: s for s in _SPECS}
# route key the server dispatches on: (group, plural)
BY_ROUTE: Dict[tuple, ResourceSpec] = {(s.group, s.plural): s for s in _SPECS}


def spec_for(kind: type) -> ResourceSpec:
    spec = BY_KIND.get(kind)
    if spec is None:
        raise TypeError(
            f"{kind.__name__} is not registered with the apiserver backend "
            f"(kubeapi.resources); the in-memory KubeClient accepts ad-hoc kinds, "
            f"the wire protocol cannot"
        )
    return spec


def parse_path(path: str):
    """Server-side route parse → (spec, namespace, name).  ``namespace`` and
    ``name`` are None for collection requests; raises KeyError on unknown
    routes (the server turns that into a 404)."""
    parts = [p for p in path.split("/") if p]
    # /api/v1/... vs /apis/{group}/{version}/...
    if parts and parts[0] == "api":
        group, rest = "", parts[2:]
    elif parts and parts[0] == "apis":
        group, rest = parts[1], parts[3:]
    else:
        raise KeyError(path)
    namespace = None
    if len(rest) >= 2 and rest[0] == "namespaces" and (group, rest[1]) not in BY_ROUTE:
        namespace, rest = rest[1], rest[2:]
    if not rest:
        # /api/v1/namespaces/{name}: the consumed segment addresses the
        # Namespace object itself, not a scope
        if namespace is not None and group == "":
            return BY_ROUTE[("", "namespaces")], None, namespace
        raise KeyError(path)
    spec = BY_ROUTE[(group, rest[0])]
    name = rest[1] if len(rest) > 1 else None
    return spec, namespace, name
