"""Apiserver-backed watch/list plane (the reference's controller-runtime role).

``make_kube_client`` is the backend selector the operator composes through:
``--kube-backend=memory`` (default; hermetic in-process store) or
``--kube-backend=apiserver`` (real list/watch protocol against
``--kube-apiserver`` / ``KC_KUBE_APISERVER``).  See docs/KUBEAPI.md.
"""

from __future__ import annotations

from karpenter_core_tpu.kubeapi.client import ApiServerClient, ApiServerError
from karpenter_core_tpu.kubeapi.reflector import Reflector
from karpenter_core_tpu.kubeapi.resources import spec_for

BACKEND_MEMORY = "memory"
BACKEND_APISERVER = "apiserver"


def make_kube_client(options, clock=None):
    """Build the KubeClient implementation ``options.kube_backend`` names.

    The in-memory client stays the default so every embedded/test composition
    is unchanged unless the backend is asked for explicitly."""
    backend = getattr(options, "kube_backend", BACKEND_MEMORY) or BACKEND_MEMORY
    if backend == BACKEND_MEMORY:
        from karpenter_core_tpu.operator.kubeclient import KubeClient

        return KubeClient(
            clock,
            qps=options.kube_client_qps,
            burst=options.kube_client_burst,
        )
    if backend == BACKEND_APISERVER:
        url = getattr(options, "kube_apiserver", "")
        if not url:
            raise ValueError(
                "--kube-backend=apiserver needs --kube-apiserver (or "
                "KC_KUBE_APISERVER) naming the endpoint"
            )
        return ApiServerClient(
            url,
            clock,
            qps=options.kube_client_qps,
            burst=options.kube_client_burst,
        )
    raise ValueError(f"unknown kube backend {backend!r} (memory|apiserver)")


__all__ = [
    "ApiServerClient",
    "ApiServerError",
    "BACKEND_APISERVER",
    "BACKEND_MEMORY",
    "Reflector",
    "make_kube_client",
    "spec_for",
]
