"""Reflector: the list/watch pump behind the apiserver-backed KubeClient.

Mirror of client-go's reflector/informer pair (the plane the reference gets
from controller-runtime, operator.go:91-133): one thread per kind runs

    LIST (capture resourceVersion) → WATCH from it → apply events → repeat

with the full robustness ladder:

  - exponential backoff with jitter on stream drops / connection errors
  - BOOKMARK events advance the resume resourceVersion without dispatch
  - ``410 Gone`` (compacted history, as an ERROR event or HTTP status)
    triggers a relist that DIFFS against the local store — vanished objects
    get synthesized DELETED events, changed ones MODIFIED — so downstream
    caches (state.Cluster) reconverge without a process restart
  - per-key resourceVersion guards drop stale/duplicate events, which lets
    the client deliver self-originated mutations synchronously (in-memory
    KubeClient semantics) while the watch stream replays them later

The store the reflector maintains is the read path for get/list, which is
what makes a fresh process warm-start from a LIST: start() blocks until the
initial sync completes.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_core_tpu.chaos import plane as chaos
from karpenter_core_tpu.kubeapi.resources import ResourceSpec
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.utils import retry

log = logging.getLogger(__name__)

# watch.stream: faults watch establishment (error/timeout/410) and event
# delivery (duplicate) — the reflector's whole recovery ladder under one name
WATCH_STREAM = chaos.point("watch.stream")

WATCH_RESTARTS = REGISTRY.counter(
    "karpenter_kubeapi_watch_restarts_total",
    "Watch stream restarts by kind and reason (drop/gone/error).",
    ("kind", "reason"),
)
RELISTS = REGISTRY.counter(
    "karpenter_kubeapi_relists_total",
    "Full relists by kind (initial sync and 410-Gone recoveries).",
    ("kind",),
)


class Reflector:
    """One kind's list/watch loop feeding a keyed store + watch callbacks."""

    def __init__(
        self,
        spec: ResourceSpec,
        transport,  # kubeapi.client._Transport
        *,
        backoff_base_s: float = 0.2,
        backoff_cap_s: float = 30.0,
        watch_timeout_s: float = 60.0,
        rng: Optional[retry.DeterministicRNG] = None,
        clock=None,
    ) -> None:
        self.spec = spec
        self.transport = transport
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.watch_timeout_s = watch_timeout_s
        # watch-recovery backoff used to call module-level random.random()
        # with an unseeded global RNG, making recovery timing unreplayable;
        # the injected DeterministicRNG (seedable by tests/chaos scenarios)
        # keeps the same min(base*2^n, cap) * [0.5, 1.5) shape
        self._backoff = retry.Backoff(
            backoff_base_s, backoff_cap_s,
            max_exponent=16, jitter=retry.JITTER_HALF, rng=rng,
        )
        # restart budget: the backoff resets on every successful LIST, so a
        # server that accepts the connect and instantly drops the stream
        # would otherwise hot-loop at base_s forever; once the budget drains,
        # every further restart in the window waits the full cap.  The clock
        # is injected (like the rng) so the window is steppable by FakeClock
        # suites and unperturbed by chaos clock.skew scenarios
        if clock is None:
            from karpenter_core_tpu.utils.clock import Clock

            clock = Clock()
        self._restart_budget = retry.RetryBudget(
            clock, budget=10, window_s=60.0,
            name=f"watch-{spec.kind_name}",
        )

        self.lock = threading.RLock()
        # serializes callback DISPATCH (not store access): a watch()
        # registration snapshot-replays ADDED events under this lock so a
        # concurrent live DELETED/MODIFIED can't interleave with (or precede)
        # the stale replay and resurrect an object downstream.  RLock because
        # callbacks re-enter the client (informer -> controller -> write ->
        # self-delivery -> apply_event) on the same thread.
        self.dispatch_lock = threading.RLock()
        self.store: Dict[tuple, object] = {}  # key -> decoded object
        # per-key applied-resourceVersion high-water marks; deleted keys keep
        # a tombstone so a late watch replay of the pre-delete MODIFIED can't
        # resurrect the object (pruned on relist)
        self.applied_rv: Dict[tuple, int] = {}
        self.callbacks: List[Callable[[str, object], None]] = []
        self._resume_rv = 0
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._current_response = None

    # -- lifecycle -------------------------------------------------------------

    def start(self, sync_timeout_s: float = 30.0) -> "Reflector":
        self._thread = threading.Thread(
            target=self._run, name=f"reflector-{self.spec.plural}", daemon=True
        )
        self._thread.start()
        if not self._synced.wait(timeout=sync_timeout_s):
            raise TimeoutError(
                f"reflector for {self.spec.kind_name} failed initial LIST "
                f"within {sync_timeout_s}s"
            )
        return self

    def wait_synced(self, timeout_s: float = 30.0) -> None:
        """Block until the initial LIST has been applied (no-op once set)."""
        if not self._synced.wait(timeout=timeout_s):
            raise TimeoutError(
                f"reflector for {self.spec.kind_name} not synced within {timeout_s}s"
            )

    def stop(self) -> None:
        self._stop.set()
        resp = self._current_response
        if resp is not None:
            try:
                resp.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- key helpers -----------------------------------------------------------

    def key_of(self, obj) -> tuple:
        meta = obj.metadata
        return (meta.namespace, meta.name) if self.spec.namespaced else (meta.name,)

    # -- event application (shared with the client's self-delivery path) -------

    def apply_event(self, event_type: str, obj, rv: int) -> bool:
        """Apply one event to the store and dispatch callbacks; returns False
        when the event is stale (per-key rv guard) and was dropped.  Callbacks
        run outside the store lock (in-memory KubeClient discipline: informer
        callbacks take Cluster locks whose holders call back into the
        client)."""
        key = self.key_of(obj)
        with self.dispatch_lock:
            with self.lock:
                if rv <= self.applied_rv.get(key, 0):
                    return False
                self.applied_rv[key] = rv
                if event_type == "DELETED":
                    self.store.pop(key, None)
                else:
                    self.store[key] = obj
                callbacks = list(self.callbacks)
            for cb in callbacks:
                cb(event_type, obj)
        return True

    # -- the loop --------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._list_and_sync()
                self._synced.set()
                self._backoff.reset()
                self._watch()
            except _Gone:
                WATCH_RESTARTS.labels(self.spec.kind_name, "gone").inc()
                log.info("watch %s: history compacted (410), relisting",
                         self.spec.plural)
                self._resume_rv = 0  # force a fresh LIST next round
                # a lone 410 relists immediately (the designed recovery), but
                # each iteration's successful LIST resets the backoff, so a
                # server stuck answering 410 would spin full relists at line
                # rate — the restart budget floors that storm at the cap
                if not self._restart_budget.allow():
                    self._stop.wait(self.backoff_cap_s)
                continue
            except Exception as e:  # noqa: BLE001 - stream drops are routine
                if self._stop.is_set():
                    return
                WATCH_RESTARTS.labels(self.spec.kind_name, "drop").inc()
                delay = self._next_restart_delay()
                log.warning(
                    "watch %s dropped (%s: %s); retry %d in %.2fs",
                    self.spec.plural, type(e).__name__, e,
                    self._backoff.failures, delay,
                )
                self._stop.wait(delay)

    def _next_restart_delay(self) -> float:
        """Jittered exponential backoff, floored at the cap once the rolling
        restart budget is spent — the per-kind storm backstop."""
        delay = self._backoff.next()
        if not self._restart_budget.allow():
            return max(delay, self.backoff_cap_s)
        return delay

    def _list_and_sync(self) -> None:
        """LIST and reconcile the store against it: the initial sync and every
        410 recovery.  Objects present only locally get DELETED synthesized;
        listed objects apply through the per-key rv guard (so a relist racing
        a concurrent self-delivered write can't regress the store)."""
        if self._resume_rv and self._synced.is_set():
            return  # healthy resume: watch continues from the last-seen rv
        RELISTS.labels(self.spec.kind_name).inc()
        body = self.transport.request("GET", self.spec.base_path())
        listed = body.get("items", [])
        list_rv = int(body.get("metadata", {}).get("resourceVersion", 0) or 0)
        decoded = [self.spec.from_dict(item) for item in listed]
        listed_keys = {self.key_of(obj) for obj in decoded}
        with self.lock:
            vanished = [
                (key, obj) for key, obj in self.store.items() if key not in listed_keys
            ]
            # prune tombstones of keys the server no longer knows: their
            # history is gone, so no stale replay can arrive for them
            for key in list(self.applied_rv):
                if key not in listed_keys and key not in self.store:
                    del self.applied_rv[key]
        for key, obj in vanished:
            with self.lock:
                rv = self.applied_rv.get(key, 0)
            self.apply_event("DELETED", obj, max(rv + 1, list_rv))
        for obj in decoded:
            event = "MODIFIED" if self.key_of(obj) in self.store else "ADDED"
            self.apply_event(event, obj, obj.metadata.resource_version)
        self._resume_rv = max(self._resume_rv, list_rv)

    def _watch(self) -> None:
        duplicate_events = False
        fault = WATCH_STREAM.hit(
            kinds=(chaos.KIND_ERROR, chaos.KIND_TIMEOUT, chaos.KIND_DUPLICATE),
            kind_name=self.spec.kind_name, rv=self._resume_rv,
        )
        if fault is not None:
            if fault.code == 410:
                raise _Gone()
            if fault.kind in (chaos.KIND_ERROR, chaos.KIND_TIMEOUT):
                raise IOError(fault.describe())
            duplicate_events = fault.kind == chaos.KIND_DUPLICATE
        path = (
            f"{self.spec.base_path()}?watch=true&resourceVersion={self._resume_rv}"
            f"&allowWatchBookmarks=true"
        )
        resp = self.transport.stream("GET", path, timeout=self.watch_timeout_s)
        if resp.status == 410:
            resp.close()
            raise _Gone()
        if resp.status != 200:
            body = resp.read()
            resp.close()
            raise IOError(f"watch {self.spec.plural}: HTTP {resp.status} {body[:200]!r}")
        self._current_response = resp
        try:
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    WATCH_RESTARTS.labels(self.spec.kind_name, "eof").inc()
                    return  # orderly end of stream: re-watch from resume rv
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                etype, wire = event.get("type"), event.get("object", {})
                rv = int(wire.get("metadata", {}).get("resourceVersion", 0) or 0)
                if etype == "BOOKMARK":
                    self._resume_rv = max(self._resume_rv, rv)
                    continue
                if etype == "ERROR":
                    if wire.get("code") == 410:
                        raise _Gone()
                    raise IOError(f"watch error event: {wire}")
                self.apply_event(etype, self.spec.from_dict(wire), rv)
                if duplicate_events:
                    # duplicate delivery: the per-key rv guard must drop the
                    # replay — exactly the at-least-once semantics a real
                    # watch resume exhibits
                    self.apply_event(etype, self.spec.from_dict(wire), rv)
                self._resume_rv = max(self._resume_rv, rv)
        finally:
            self._current_response = None
            try:
                resp.close()
            except Exception:  # noqa: BLE001 - teardown
                pass

    # -- read surface ----------------------------------------------------------

    def get(self, key: tuple):
        with self.lock:
            return self.store.get(key)

    def snapshot(self) -> List[object]:
        with self.lock:
            return list(self.store.values())

    def items(self) -> List[Tuple[tuple, object]]:
        with self.lock:
            return list(self.store.items())


class _Gone(Exception):
    """Watch history compacted past the resume rv (HTTP/event 410)."""
