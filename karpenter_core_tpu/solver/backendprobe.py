"""Instrumented TPU backend probing.

The accelerator behind the axon relay fails by HANGING, not by erroring —
BENCH_r05 burned 5 × 60 s in probe timeouts with zero telemetry (the only
evidence was the wall clock).  This module is the shared, *observable* probe
primitive: every attempt records

  - a counter  ``karpenter_backend_probe_total{outcome}``
  - a histogram ``karpenter_backend_probe_duration_seconds{outcome}``
    (buckets reach past the 60 s hang regime)
  - one structured JSON log line (``event: backend_probe``)
  - a ``backend.probe`` event on the active tracing span, if any

Each probe runs a tiny device op in a FRESH interpreter: JAX caches a failed
backend init for the life of a process, and a relay hang can only be bounded
by a subprocess timeout.  ``bench.py`` drives this from its bring-up ladder;
an operator process can call ``acquire_backend`` the same way.

This module must stay importable before any backend decision is made: nothing
here imports jax (the probe child does).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from karpenter_core_tpu import tracing
from karpenter_core_tpu.chaos import plane as chaos
from karpenter_core_tpu.metrics import REGISTRY

log = logging.getLogger(__name__)

# solver.dispatch: faults device-backend work — probes here (error/timeout
# kinds fail the attempt without spawning the child) and kernel dispatch in
# solver/tpu.py (which imports this Point; error kinds surface as the
# backend RuntimeError the provisioning breaker counts)
SOLVER_DISPATCH = chaos.point("solver.dispatch")

PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp;"
    "jnp.ones((8, 8)).sum().block_until_ready();"
    "print('PLATFORM=' + jax.default_backend())"
)

# durations cluster at either "fast success" (<5 s) or "full hang" (the
# caller's timeout, typically 60 s) — the buckets must resolve both regimes
PROBE_BUCKETS = [0.5, 1, 2.5, 5, 10, 20, 30, 45, 60, 90, 120]

DEFAULT_PROBE_TIMEOUT_S = 60.0
DEFAULT_LIVENESS_TIMEOUT_S = 2.0
_STDERR_TAIL_CHARS = 2000


def _tail(text) -> str:
    """Last ``_STDERR_TAIL_CHARS`` of a child's stderr (bytes or str)."""
    if not text:
        return ""
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    return text.strip()[-_STDERR_TAIL_CHARS:]


def _liveness_timeout_s() -> float:
    """``KC_PROBE_LIVENESS_TIMEOUT_S`` (seconds, default 2; 0 disables)."""
    try:
        return float(
            os.environ.get("KC_PROBE_LIVENESS_TIMEOUT_S", DEFAULT_LIVENESS_TIMEOUT_S)
        )
    except ValueError:
        return DEFAULT_LIVENESS_TIMEOUT_S


def _parse_endpoint(entry: str) -> Optional[Tuple[str, Optional[int]]]:
    """``(host, port-or-None)`` for one relay-pool entry, or ``None`` when the
    format can't be trusted.  Handles ``host``, ``host:port``, ``[v6]``, and
    ``[v6]:port``; a bare IPv6 address (multiple colons, no brackets) is
    ambiguous — the trailing group may be a port or a hextet — so it is kept
    whole as a host with no port rather than split at the wrong colon."""
    if entry.startswith("["):
        host, sep, rest = entry[1:].partition("]")
        if not sep or not host:
            return None
        if not rest:
            return host, None
        if not rest.startswith(":"):
            return None
        try:
            return host, int(rest[1:])
        except ValueError:
            return None
    if entry.count(":") > 1:
        return entry, None
    host, _, port_s = entry.rpartition(":")
    if not host:
        return port_s, None
    try:
        return host, int(port_s)
    except ValueError:
        return None


def liveness_check() -> Optional[str]:
    """Cheap pre-probe relay liveness: TCP-reach the axon relay endpoints
    before paying a potentially-60 s hanging device probe.

    A dead relay fails by HANGING the full probe timeout; a 2 s socket
    connect detects the common down states (refused, no route, dead DNS) at
    ~1/30th the cost.  Best-effort and conservative: returns an error string
    only when EVERY parsed endpoint is definitively unreachable — any
    reachable endpoint, unparseable entry, or port-less entry that resolves
    means "proceed to the real probe".  No relay env at all (local CPU/TPU
    backend) skips the check entirely."""
    timeout = _liveness_timeout_s()
    if timeout <= 0:
        return None
    raw = os.environ.get("PALLAS_AXON_POOL_IPS", "").strip()
    if not raw:
        return None
    failures: List[str] = []
    entries = [e.strip() for e in raw.split(",") if e.strip()]
    for entry in entries:
        parsed = _parse_endpoint(entry)
        if parsed is None:
            return None  # unparseable format: don't guess, run the probe
        host, port = parsed
        if port is not None:
            try:
                with socket.create_connection((host, port), timeout=timeout):
                    return None  # one live endpoint is enough
            except OSError as e:
                failures.append(f"{entry}: {e}")
        else:
            try:
                socket.getaddrinfo(host, None)
                return None  # resolvable, no port to connect: proceed
            except socket.gaierror as e:
                failures.append(f"{entry}: DNS {e}")
    if entries and len(failures) == len(entries):
        return "all relay endpoints unreachable: " + "; ".join(failures)
    return None


def probe_timeout_s() -> float:
    """Per-attempt probe timeout: ``KC_PROBE_TIMEOUT_S`` (seconds), default
    60.  A dead relay fails by hanging the FULL timeout, so this is the single
    biggest lever on how long an unattended bench/operator bring-up burns
    before falling back to CPU — BENCH_r05 spent 6 minutes discovering one
    dead relay at the old fixed value."""
    try:
        return float(os.environ.get("KC_PROBE_TIMEOUT_S", DEFAULT_PROBE_TIMEOUT_S))
    except ValueError:
        return DEFAULT_PROBE_TIMEOUT_S

PROBE_TOTAL = REGISTRY.counter(
    "karpenter_backend_probe_total",
    "Backend bring-up probe attempts by outcome (ok/timeout/error).",
    ("outcome",),
)
PROBE_DURATION = REGISTRY.histogram(
    "karpenter_backend_probe_duration_seconds",
    "Duration of backend bring-up probes by outcome.",
    ("outcome",),
    buckets=PROBE_BUCKETS,
)


@dataclass
class ProbeResult:
    platform: Optional[str]  # e.g. "tpu"/"cpu" on success, None on failure
    outcome: str  # "ok" | "timeout" | "error" | "cached"
    error: str  # empty on success
    duration_s: float
    attempt: int = 0
    cached: bool = False  # served from the failure TTL cache (no subprocess)
    # probe-side diagnosis: the child's stderr tail (import errors, backend
    # tracebacks, relay noise) — BENCH_r02..r05 had NOTHING to debug a hang
    # with except the wall clock, so the failure record now carries the
    # evidence (truncated; rides the structured log + bench JSON)
    stderr_tail: str = ""


# -- failure TTL cache --------------------------------------------------------
# A dead relay fails by hanging the full probe timeout; without a cache every
# caller in a bench/perfgate run re-pays it (VERDICT r5 "what's weak" #2:
# 5 × 60 s of wall clock for one fact).  A failed probe is remembered for
# KC_PROBE_FAIL_TTL_S (default 60 s): within the window further probes return
# the cached failure instantly (outcome "cached" — separately visible in
# metrics/logs), and acquire_backend short-circuits its retry ladder.  A
# successful probe clears the cache.  TTL 0 disables.

_fail_lock = threading.Lock()
_fail_cache: Optional[tuple] = None  # (monotonic_at, ProbeResult)


def _fail_ttl_s() -> float:
    try:
        return float(os.environ.get("KC_PROBE_FAIL_TTL_S", "60"))
    except ValueError:
        return 60.0


def reset_fail_cache() -> None:
    global _fail_cache
    with _fail_lock:
        _fail_cache = None


def _cached_failure() -> Optional[ProbeResult]:
    ttl = _fail_ttl_s()
    if ttl <= 0:
        return None
    with _fail_lock:
        if _fail_cache is None:
            return None
        at, result = _fail_cache
        if time.monotonic() - at >= ttl:
            return None
        return result


@dataclass
class BackendState:
    """The verdict of one bring-up ladder (bench JSON ``detail`` shape)."""

    platform: Optional[str] = None
    attempts: int = 0
    fell_back: bool = False
    probe_failures: List[str] = field(default_factory=list)
    probes: List[dict] = field(default_factory=list)  # per-attempt records


def probe_once(timeout_s: Optional[float] = None, attempt: int = 0) -> ProbeResult:
    """One fresh-interpreter device probe: init backend + run a tiny op.

    ``timeout_s`` defaults to KC_PROBE_TIMEOUT_S (60 s).  Never raises; the
    outcome (including a killed hang) lands in metrics, a structured log
    line, and the active tracing span.  A failure within the last
    KC_PROBE_FAIL_TTL_S seconds is served from cache (outcome "cached")
    without spawning — a dead relay costs one real probe per window."""
    global _fail_cache
    if timeout_s is None:
        timeout_s = probe_timeout_s()
    prior = _cached_failure()
    if prior is not None:
        PROBE_TOTAL.labels("cached").inc()
        PROBE_DURATION.labels("cached").observe(0.0)
        record = {
            "event": "backend_probe",
            "attempt": attempt,
            "outcome": "cached",
            "platform": None,
            "duration_s": 0.0,
            "error": f"cached failure ({prior.outcome}): {prior.error}",
        }
        log.info("%s", json.dumps(record))
        tracing.add_event("backend.probe", **record)
        return ProbeResult(
            platform=None, outcome="cached", error=record["error"],
            duration_s=0.0, attempt=attempt, cached=True,
        )
    fault = SOLVER_DISPATCH.hit(
        kinds=(chaos.KIND_ERROR, chaos.KIND_TIMEOUT), op="probe", attempt=attempt
    )
    if fault is not None and fault.kind in (chaos.KIND_ERROR, chaos.KIND_TIMEOUT):
        outcome = "timeout" if fault.kind == chaos.KIND_TIMEOUT else "error"
        PROBE_TOTAL.labels(outcome).inc()
        PROBE_DURATION.labels(outcome).observe(0.0)
        record = {
            "event": "backend_probe",
            "attempt": attempt,
            "outcome": outcome,
            "platform": None,
            "duration_s": 0.0,
            "error": fault.describe(),
        }
        log.info("%s", json.dumps(record))
        tracing.add_event("backend.probe", **record)
        result = ProbeResult(
            platform=None, outcome=outcome, error=fault.describe(),
            duration_s=0.0, attempt=attempt,
        )
        with _fail_lock:
            _fail_cache = (time.monotonic(), result)
        return result
    t0 = time.perf_counter()
    platform, outcome, error, stderr_tail = None, "error", "", ""
    liveness_error = liveness_check()
    if liveness_error is not None:
        # the relay is provably down: fail in seconds instead of hanging the
        # full probe timeout (the failure still lands in the TTL cache, so
        # the ladder short-circuits exactly as it would after a real hang)
        error = f"liveness: {liveness_error}"
    else:
        try:
            proc = subprocess.run(
                [sys.executable, "-c", PROBE_SNIPPET],
                capture_output=True, text=True, timeout=timeout_s,
            )
        except subprocess.TimeoutExpired as e:
            outcome, error = "timeout", f"probe hung past {timeout_s:.0f}s (killed)"
            stderr_tail = _tail(e.stderr)
        except Exception as e:  # noqa: BLE001 - spawn failures must not surface
            error = f"probe spawn failed: {e}"
        else:
            if proc.returncode == 0:
                for line in proc.stdout.splitlines():
                    if line.startswith("PLATFORM="):
                        platform, outcome = line.split("=", 1)[1].strip(), "ok"
                        break
                else:
                    error = "probe exited 0 but printed no platform"
                    stderr_tail = _tail(proc.stderr)
            else:
                tail = (proc.stderr or proc.stdout).strip().splitlines()
                error = tail[-1][:300] if tail else f"probe rc={proc.returncode}"
                # the full child traceback, not just its last line — the
                # structured record is the only place a probe-side crash is
                # ever diagnosable from
                stderr_tail = _tail(proc.stderr or proc.stdout)
    duration_s = time.perf_counter() - t0

    PROBE_TOTAL.labels(outcome).inc()
    PROBE_DURATION.labels(outcome).observe(duration_s)
    record = {
        "event": "backend_probe",
        "attempt": attempt,
        "outcome": outcome,
        "platform": platform,
        "duration_s": round(duration_s, 3),
        "error": error,
    }
    if stderr_tail:
        record["stderr_tail"] = stderr_tail
    log.info("%s", json.dumps(record))
    tracing.add_event("backend.probe", **record)
    result = ProbeResult(
        platform=platform, outcome=outcome, error=error,
        duration_s=duration_s, attempt=attempt, stderr_tail=stderr_tail,
    )
    with _fail_lock:
        _fail_cache = None if outcome == "ok" else (time.monotonic(), result)
    return result


def acquire_backend(
    max_attempts: int = 5,
    probe_timeout_s: Optional[float] = None,
    deadline_s: float = 360.0,
    sleep=time.sleep,
) -> BackendState:
    """Bounded-retry backend bring-up; never raises.

    Probes with exponential backoff under an overall deadline; the first
    success wins.  ``probe_timeout_s`` defaults to KC_PROBE_TIMEOUT_S (60 s)
    per attempt.  All-fail returns ``platform="cpu", fell_back=True`` — the
    caller decides how to pin itself there (bench re-execs the process).
    Every attempt is individually visible in ``state.probes``, /metrics, and
    the log.

    Deliberate interaction with the failure TTL cache: within one window a
    dead relay costs exactly ONE real probe — the ladder short-circuits the
    moment a probe is served from the failure cache (the first cached hit
    breaks the retry loop; no sleeps, no further spawns) instead of
    re-paying the hang per attempt (the 5×60 s VERDICT r5 regression).  The
    trade is that an intra-window relay recovery is only noticed at the next
    window; set ``KC_PROBE_FAIL_TTL_S`` below the first backoff (or 0) to
    restore full intra-ladder retries."""
    state = BackendState()
    t0 = time.monotonic()
    attempt = 0
    while attempt < max_attempts:
        attempt += 1
        result = probe_once(probe_timeout_s, attempt=attempt)
        probe_record = {
            "attempt": attempt,
            "outcome": result.outcome,
            "duration_s": round(result.duration_s, 3),
            "error": result.error,
        }
        if result.stderr_tail:
            probe_record["stderr_tail"] = result.stderr_tail
        state.probes.append(probe_record)
        if result.platform is not None:
            state.platform = result.platform
            state.attempts = attempt
            return state
        state.probe_failures.append(f"attempt {attempt}: {result.error}")
        log.warning(
            "backend probe %d/%d failed: %s", attempt, max_attempts, result.error
        )
        if result.cached:
            # the window's one real probe already failed: retrying the cache
            # (and sleeping between hits) buys nothing — fall back now
            state.probe_failures.append("failure cache hit: ladder short-circuited")
            break
        if attempt < max_attempts and time.monotonic() - t0 < deadline_s:
            sleep(min(5.0 * 2 ** (attempt - 1), 60.0))
        elif time.monotonic() - t0 >= deadline_s:
            state.probe_failures.append(f"deadline {deadline_s:.0f}s exhausted")
            break
    state.platform = "cpu"
    state.attempts = attempt
    state.fell_back = True
    return state
