"""Pod scheduling queue with no-progress cycle detection.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/queue.go:27-110:
pods are sorted CPU-then-memory descending for first-fit-decreasing bin-packing;
Pop stops once a pod comes back around with the queue the same length it had
when the pod was last pushed (no progress was made in a full cycle).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.utils import resources as resources_util


def _sort_key(pod: Pod) -> Tuple:
    requests = resources_util.requests_for_pods(pod)
    return (
        -requests.get(resources_util.CPU, 0.0),
        -requests.get(resources_util.MEMORY, 0.0),
        pod.metadata.creation_timestamp,
        pod.uid,
    )


class Queue:
    def __init__(self, *pods: Pod) -> None:
        self.pods: "deque[Pod]" = deque(sorted(pods, key=_sort_key))
        self.last_len: Dict[str, int] = {}

    def pop(self) -> Optional[Pod]:
        if not self.pods:
            return None
        p = self.pods[0]
        if self.last_len.get(p.uid) == len(self.pods):
            return None  # cycled without progress
        self.pods.popleft()
        return p

    def push(self, pod: Pod, relaxed: bool) -> None:
        self.pods.append(pod)
        if relaxed:
            self.last_len = {}
        else:
            self.last_len[pod.uid] = len(self.pods)

    def list(self) -> List[Pod]:
        return list(self.pods)
