from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.topology import Topology, TopologyGroup, TopologyNodeFilter, TopologyType
from karpenter_core_tpu.solver.queue import Queue
from karpenter_core_tpu.solver.preferences import Preferences
from karpenter_core_tpu.solver.node import SchedulingNode, ExistingNode
from karpenter_core_tpu.solver.scheduler import Scheduler, SchedulerOptions

__all__ = [
    "MachineTemplate",
    "Topology",
    "TopologyGroup",
    "TopologyNodeFilter",
    "TopologyType",
    "Queue",
    "Preferences",
    "SchedulingNode",
    "ExistingNode",
    "Scheduler",
    "SchedulerOptions",
]
