"""Incremental warm-start solving over the versioned snapshot store.

The full kernel solve re-encodes and re-solves the whole cluster snapshot
every reconcile.  At steady-state churn rates only a handful of pods change
between ticks, so this module amortizes: a ``IncrementalSolveSession`` keeps
the previous solve's padded tensors (solver.tpu.SolvePrep), its final scan
carry (ops.solve.WarmCarry, device-resident), and host-side placement
bookkeeping; each reconcile a ``FallbackPolicy`` decides **full** vs
**delta**:

  full    encode → commit to the SnapshotStore → solve from scratch → adopt
          the carry.  Chosen on the first solve, on any supply-side change
          (nodes / bound pods / catalog / templates), on a class-shape change
          (new/removed equivalence classes — the tensor axes moved), when the
          delta fraction exceeds ``max_delta_fraction``, and periodically as
          the optimality **audit** (``audit_interval``) that measures and
          resets accumulated repair drift.
  delta   no encode at all: evicted pods' capacity/topology counts are
          returned to the carry (``ops.solve.repair_free``), then ONE repair
          executable runs over the previous padded tensors with a class-count
          vector holding only the new (plus previously-failed) pods, resumed
          from the carry.  Same class step, same phases, same constraint
          semantics — the repair is literally the full solve's scan continued.

Decisions surface as the ``solve.mode`` span attribute and the
``karpenter_solve_mode_total{mode}`` counter so the amortization is
observable.  ``KC_SOLVER_INCREMENTAL=0`` disables the session entirely — the
degenerate case is exactly the old full-solve-every-reconcile path.
See docs/INCREMENTAL.md.
"""

from __future__ import annotations

import copy
import logging
import os
from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.models import store as store_mod
from karpenter_core_tpu.models.store import (
    SnapshotStore,
    VersionedSnapshot,
    class_key,
    diff_members,
)
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.utils import pipeline as pipeline_mod
from karpenter_core_tpu.utils.watchdog import SolveTimeout

log = logging.getLogger(__name__)

SOLVE_MODE = REGISTRY.counter(
    "karpenter_solve_mode_total",
    "Kernel solve dispatches by mode: full re-solve vs incremental delta "
    "repair (docs/INCREMENTAL.md).",
    ("mode",),
)

MODE_FULL = "full"
MODE_DELTA = "delta"


def incremental_enabled() -> bool:
    """Process-wide kill switch: KC_SOLVER_INCREMENTAL=0 keeps the old
    full-solve-every-reconcile path as the degenerate case."""
    return os.environ.get("KC_SOLVER_INCREMENTAL", "1") != "0"


def _resolve_solve_mode(solver) -> str:
    """The solver family this session's anchors are configured to route
    through (solver.modes.resolve_mode over the solver's policy config)."""
    from karpenter_core_tpu.solver import modes as modes_mod

    return modes_mod.resolve_mode(getattr(solver, "policy", None))


@dataclass
class FallbackPolicy:
    """Per-reconcile full-vs-delta decision (module docstring)."""

    enabled: bool = True
    # delta fraction (added+evicted over population) above which a repair
    # stops being the right amortization — the phases run per dirty class
    # anyway, so past this a full solve is both faster and drift-free
    max_delta_fraction: float = 0.25
    # delta reconciles between full-solve audits (0 = never audit); the audit
    # both measures repair drift (objective = opened-node count) and resets it
    audit_interval: int = 16
    # materialized sessions (the provisioning controller, whose previous
    # decisions become real nodes) may only repair when the previous solve
    # opened no new slots — an opened slot was launched and must re-enter as
    # a real existing node (supply change ⇒ full) rather than be re-decided
    materialized: bool = False

    @classmethod
    def from_env(cls, materialized: bool = False) -> "FallbackPolicy":
        def _f(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        return cls(
            enabled=incremental_enabled(),
            max_delta_fraction=_f("KC_DELTA_MAX_FRACTION", 0.25),
            audit_interval=int(_f("KC_DELTA_AUDIT_INTERVAL", 16)),
            materialized=materialized,
        )

    def decide(self, delta, delta_ticks: int, prev_slots_used: int,
               known_classes=None, mesh_changed: bool = False,
               mode_changed: bool = False) -> Tuple[str, str]:
        """(mode, reason).  ``delta`` is a models.store.SnapshotDelta (or None
        on the first solve); ``delta_ticks`` counts repairs since the last
        full solve; ``prev_slots_used`` the slots the previous solve opened;
        ``known_classes`` the class keys the previous padded tensors can
        express — a "new" class returning to a known (emptied) row repairs
        fine, while a genuinely unseen key means the class axis moved and the
        snapshot must re-encode.  Removed classes never force a full solve:
        an emptied row idles as a zero-count scan step.  ``mesh_changed``:
        the live solve-mesh topology (parallel.mesh.solve_mesh_axes) no
        longer matches the one the warm prep was built for — the carry's
        planes are sharded for the OLD layout and the catalog pad multiple
        moved with it, so the lineage re-anchors with a full solve on the
        new topology.  ``mode_changed``: the configured solver family
        (solver.modes.resolve_mode — env flip or spec change) no longer
        matches the one the anchor solved under; a relax anchor IS a valid
        lineage anchor (its outputs are scan-shaped and exactly audited),
        but repairs always run the scan, so a family flip re-anchors the
        same way a mesh flip does."""
        if not self.enabled:
            return MODE_FULL, "disabled"
        if delta is None:
            return MODE_FULL, "first"
        if mesh_changed:
            return MODE_FULL, "mesh-changed"
        if mode_changed:
            return MODE_FULL, "mode-changed"
        if delta.node_side_changed:
            return MODE_FULL, "supply-changed:" + ",".join(delta.changed_planes)
        unknown = tuple(
            k for k in delta.new_classes
            if known_classes is None or k not in known_classes
        )
        if unknown:
            return MODE_FULL, "class-shape"
        if self.materialized and prev_slots_used > 0:
            return MODE_FULL, "materialized-slots"
        if self.audit_interval and delta_ticks >= self.audit_interval:
            return MODE_FULL, "audit"
        if delta.delta_fraction > self.max_delta_fraction:
            return MODE_FULL, f"delta-fraction:{delta.delta_fraction:.3f}"
        return MODE_DELTA, "delta"


@dataclass
class _WarmState:
    """Everything one delta reconcile needs, carried from the last full solve
    and updated by every repair."""

    versioned: VersionedSnapshot
    prep: object  # solver.tpu.SolvePrep (padded tensors; reused verbatim)
    carry: object  # ops.solve.WarmCarry (device)
    assign: np.ndarray  # i32[C_pad, N] cumulative new-slot placements
    assign_ex: np.ndarray  # i32[C_pad, E_pad] cumulative existing placements
    n_next: int  # slots the scan has opened so far
    members: Dict[tuple, Tuple[str, ...]]  # class key -> live member uids
    class_index: Dict[tuple, int]  # class key -> class row
    pod_loc: Dict[str, Tuple[int, str, int]]  # uid -> (row, "new"|"ex", idx)
    row_key: Dict[int, tuple]  # class row -> class key (pod_loc's inverse leg)
    failed_pods: Dict[str, Tuple[int, object]]  # uid -> (row, Pod), unplaced
    member_rows: np.ndarray  # i32[C_pad, G1] topology membership per class
    own_inv_rows: np.ndarray  # i32[C_pad, G1] inverse-ownership per class
    supply: str
    state_nodes: list = field(default_factory=list)
    delta_ticks: int = 0
    initial_slots_used: int = 0  # slots open at full-solve time
    # solver family the anchor was CONFIGURED to run under
    # (solver.modes.resolve_mode at adopt time — the routing intent, not the
    # per-batch relax-fallback outcome): a later config flip scan<->relax
    # escalates with reason "mode-changed"
    solve_mode: str = "scan"
    # lineage-placed pods that have since BOUND: physically on their node now,
    # still counted by the carry, excluded from the membership and supply
    # views (IncrementalSolveSession._absorb_bound)
    materialized: set = field(default_factory=set)


@dataclass
class _PendingTick:
    """One dispatched-but-unsettled deferred tick (the pipeline's in-flight
    slot).  ``kind`` is "delta" (a repair: ``data`` holds the dispatch
    record, the post-tick membership, and the captured population snapshot a
    settle-time window/slot exhaustion re-anchors from) or "full" (an
    anchor: ``data`` holds the committed snapshot, the prep, the device
    outputs, and the fetch ticket whose copies are already in flight)."""

    kind: str  # "delta" | "full"
    box: "PendingResults"
    data: dict


class PendingResults:
    """Deferred TPUSolveResults handle (``solve(deferred=True)``).

    ``result()`` settles the session's pending tick if it still is pending
    (the completion barrier), then materializes the decode — by the time the
    canonical double-buffered loop calls it, the barrier already ran at the
    next solve's entry and only host materialize is left, overlapped with
    that solve's device compute.  Safe to call any number of times; raises
    whatever the tick's settle raised."""

    __slots__ = ("_session", "_results", "_error", "_decode", "_settled")

    def __init__(self, session, results=None, error=None) -> None:
        self._session = session
        self._results = results
        self._error = error
        self._decode = None  # set at settle for delta ticks
        self._settled = results is not None or error is not None

    def _settle_with(self, results=None, error=None, decode=None) -> None:
        self._results = results
        self._error = error
        self._decode = decode
        self._settled = True

    def done(self) -> bool:
        return self._settled

    def result(self):
        if not self._settled:
            self._session.settle()
        if self._error is not None:
            raise self._error
        if self._results is None and self._decode is not None:
            decode, self._decode = self._decode, None
            try:
                self._results = decode()
            except BaseException as e:  # noqa: BLE001 - cached, then raised
                # record the failure so every later result() re-raises it
                # instead of silently returning None
                self._error = e
                raise
        return self._results


class IncrementalSolveSession:
    """One warm-start solve lineage: full solves adopt state, delta solves
    repair it.  Bind a fresh TPUSolver each reconcile via ``rebind`` (the
    controller rebuilds its solver per batch); the session survives as long
    as the fallback policy keeps judging deltas safe.

    ``solve(..., deferred=True)`` runs the tick through the double-buffered
    pipeline (docs/KERNEL_PERF.md "Layer 7"): the repair dispatches and the
    call returns a PendingResults immediately; the completion barrier,
    bookkeeping, and decode settle at the NEXT solve's entry (or at
    ``result()``), so the next tick's planning and the previous tick's host
    materialize overlap this tick's device compute and device→host copy.
    ``KC_PIPELINE=0`` makes deferred calls settle inline — the serial loop
    exactly."""

    def __init__(self, solver=None, policy: Optional[FallbackPolicy] = None,
                 run_prepared=None) -> None:
        self.solver = solver
        self.policy = policy or FallbackPolicy.from_env()
        self.store = SnapshotStore()
        self._warm: Optional[_WarmState] = None
        self.last_mode: Optional[str] = None
        self.last_reason: Optional[str] = None
        self.last_audit_drift_nodes: Optional[int] = None
        self.mode_counts: Dict[str, int] = {MODE_FULL: 0, MODE_DELTA: 0}
        # dispatch hook: ``run_prepared(prep, **kw)`` replaces
        # ``solver.run_prepared`` so a host (the multi-tenant solver service)
        # can route the device execution through its batch coalescer — the
        # prep/decode bookkeeping around it is unchanged.  Full solves AND
        # delta repairs route through it: compatible repair windows from
        # different tenants fuse on one vmapped dispatch (docs/SERVICE.md
        # "Solve fusion").  Hooked repairs never donate the carry — the
        # coalescer may stack it into a batched program whose member buffers
        # must stay readable — so the hook passes donate_carry=False through.
        self._run_prepared = run_prepared
        self._forced_reason: Optional[str] = None
        # pipelined-loop state: the in-flight deferred tick, the two-deep
        # ring of reusable host staging buffers its fetches land in, and the
        # last settled-but-undecoded box (materialized before its staging
        # slot can be rewritten)
        self._pending: Optional[_PendingTick] = None
        self._staging = None
        self._undecoded: Optional[PendingResults] = None

    def rebind(self, solver) -> None:
        self.solver = solver

    def reset(self) -> None:
        """Drop the warm lineage (next solve is full).  A pending deferred
        tick settles first so its handle stays consumable."""
        if self._pending is not None:
            try:
                self.settle()
            except Exception:  # noqa: BLE001 - the handle carries the error
                pass
        self._warm = None

    def force_full(self, reason: str) -> None:
        """Make the NEXT solve a full re-anchor with this reason, whatever the
        fallback policy would have decided.  The multi-tenant service uses it
        for lineage trust failures the policy cannot see server-side: a
        client claiming a session version this process doesn't hold
        (``session-lost`` after a server restart or an LRU/TTL eviction), a
        client that itself restarted, or a supply-digest mismatch."""
        self._forced_reason = reason

    def lineage_version(self) -> int:
        """The warm lineage's snapshot-store version (0 = no lineage) — what
        the tenant protocol echoes to clients so a restarted server is
        detectable (docs/SERVICE.md)."""
        self.settle()
        if self._warm is None:
            return 0
        return int(self._warm.versioned.version)

    def lineage_state(self) -> Dict[str, object]:
        """Cross-process-stable verification summary of the warm lineage —
        what the durable-session journal (service/journal.py) writes with
        every record and what recovery compares a REPLAYED lineage against
        before trusting it (never-trust: any field differing downgrades the
        tenant to the ``session-lost`` re-anchor).  Everything here is a
        plain msgpack-able scalar/str/dict: the store's per-plane content
        digests and supply anchor are sha256 hex (PYTHONHASHSEED-free by
        construction), and the placement signature canonicalizes its class
        keys through models.store.stable_digest because they hold frozensets
        whose raw repr order is hash-randomized."""
        self.settle()
        w = self._warm
        if w is None:
            return {"version": 0}
        return {
            "version": int(w.versioned.version),
            "supply": w.supply,
            "planes": dict(w.versioned.digests),
            "aggregates": self.aggregates(),
            "signature": store_mod.stable_digest(self.node_signature()),
            "delta_ticks": int(w.delta_ticks),
        }

    # -- membership extraction -------------------------------------------------

    @staticmethod
    def _members_of(pods_or_classes):
        """(class key -> uids, uid -> Pod getter, classes-or-None) from a
        PodIngest or a prebuilt PodClass list — riding the ingest's
        bookkeeping, no signature re-derivation per pod and no per-pod
        materialization (the getter resolves only the delta's uids)."""
        from karpenter_core_tpu.models.columnar import PodIngest

        if isinstance(pods_or_classes, PodIngest):
            return pods_or_classes.class_members(), pods_or_classes.get, None
        classes = list(pods_or_classes)
        members = {}
        by_uid = {}
        for cls in classes:
            if getattr(cls, "is_ladder_variant", False):
                continue
            key = class_key(cls)
            members[key] = tuple(p.uid for p in cls.pods)
            for p in cls.pods:
                by_uid[p.uid] = p
        return members, by_uid.get, classes

    # -- the solve entry -------------------------------------------------------

    def solve(
        self,
        pods_or_classes,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[list] = None,
        deferred: bool = False,
    ):
        """TPUSolveResults for the current population.  Full reconciles see
        the whole picture (every node decision); delta reconciles return only
        this tick's placements (new pods onto new/existing capacity), which
        is exactly what the controller needs to act on.  Raises
        models.snapshot.KernelUnsupported exactly like TPUSolver.solve.

        ``deferred=True`` returns a PendingResults handle instead of
        results: delta ticks dispatch and settle at the NEXT solve call (the
        pipelined loop — class docstring); full solves settle inline and the
        handle is immediately consumable.  With KC_PIPELINE=0 the handle is
        always settled inline — the serial loop bit-for-bit."""
        from karpenter_core_tpu.solver.backendprobe import SOLVER_DISPATCH

        # settle the in-flight deferred tick FIRST: this tick's membership
        # diff and eviction plan read the bookkeeping that tick rewrites
        self.settle()
        # ``deferred`` shapes the RETURN TYPE (a handle); ``pipelined``
        # whether the tick actually stays in flight — KC_PIPELINE=0 settles
        # inline, so the handle is just the serial results in a box
        pipelined = deferred and pipeline_mod.pipeline_enabled()
        members, by_uid, classes = self._members_of(pods_or_classes)
        if self._warm is not None:
            self._absorb_bound({p.uid for p in (bound_pods or [])})
        from karpenter_core_tpu.policy import planes as policy_planes

        catalog = store_mod.catalog_digest(
            self.solver.provisioners, self.solver.instance_types
        ) + policy_planes.policy_input_digest(
            # the policy side of the supply: offering prices + interruption
            # priors + objective knobs + the provider's pending-ICE set.  A
            # set_price between reconciles (the spot market moving), a weight
            # change, or a type starting to fail creates flips this digest
            # and the fallback policy escalates to a full solve — a repair
            # would otherwise keep optimizing against a stale price/risk
            # sheet (docs/INCREMENTAL.md "Policy-digest escalation")
            self.solver.instance_types, getattr(self.solver, "policy", None),
            provider=getattr(self.solver, "cloud_provider", None),
        )
        # the comparison digest excludes bound pods this lineage placed itself
        # (their binding is the lineage's own work materializing, not a supply
        # change); the ANCHOR a full solve stores is unfiltered, because a
        # fresh encode sees — and accounts — every bound pod
        known = self._warm.materialized if self._warm is not None else ()
        supply = store_mod.supply_digest(
            state_nodes,
            [p for p in (bound_pods or []) if p.uid not in known]
            if known else bound_pods,
        ) + catalog
        supply_anchor = supply if not known else (
            store_mod.supply_digest(state_nodes, bound_pods) + catalog
        )

        delta = None
        if self._warm is not None:
            delta = diff_members(
                self._warm.members, members,
                from_version=self._warm.versioned.version,
                supply_changed=() if supply == self._warm.supply else ("supply",),
            )
        # mesh-topology watch: the warm carry is sharded for (and its repair
        # executable keyed on) the topology captured at prepare time — a
        # KC_SOLVER_MESH flip or a device-count change escalates to full
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        mesh_changed = self._warm is not None and (
            getattr(self._warm.prep, "mesh_axes", None)
            != mesh_mod.solve_mesh_axes()
        )
        # solver-family watch (solver/modes.py): same contract as the mesh —
        # the anchor records which family it was configured for, a flip
        # re-anchors so the lineage's carry matches the routed program
        mode_changed = self._warm is not None and (
            _resolve_solve_mode(self.solver) != self._warm.solve_mode
        )
        mode, reason = self.policy.decide(
            delta,
            self._warm.delta_ticks if self._warm is not None else 0,
            self._warm.n_next - self._warm.initial_slots_used
            if self._warm is not None else 0,
            known_classes=self._warm.class_index
            if self._warm is not None else None,
            mesh_changed=mesh_changed,
            mode_changed=mode_changed,
        )
        forced = self._forced_reason
        if forced is not None:
            # lineage trust override (force_full): full re-anchor, one shot
            mode, reason = MODE_FULL, forced
            self._forced_reason = None

        try:
            fault = SOLVER_DISPATCH.hit(
                kinds=("error", "timeout"), op="solve", classes=len(members)
            )
            if fault is not None and fault.kind in ("error", "timeout"):
                raise RuntimeError(fault.describe())

            with tracing.span("solve.incremental") as sp:
                if mode == MODE_DELTA and pipelined:
                    handle = self._delta_dispatch_deferred(
                        delta, by_uid,
                        pods_or_classes if classes is None else classes,
                        members, state_nodes, bound_pods, supply_anchor,
                    )
                    if handle is not None:
                        sp.set(**{"solve.mode": mode,
                                  "solve.mode.reason": reason,
                                  "solve.deferred": True})
                        # mode accounting waits for the settle — a window
                        # exhaustion discovered there escalates to full
                        return handle
                    mode, reason = MODE_FULL, "slots-exhausted"
                elif mode == MODE_DELTA:
                    results = self._delta_solve(delta, by_uid, state_nodes)
                    if results is None:  # repair ran out of room: escalate
                        mode, reason = MODE_FULL, "slots-exhausted"
                if mode == MODE_FULL:
                    results = self._full_solve(
                        pods_or_classes if classes is None else classes,
                        members, state_nodes, bound_pods, supply_anchor, reason,
                        deferred=pipelined,
                    )
                    if isinstance(results, PendingResults):
                        sp.set(**{"solve.mode": mode,
                                  "solve.mode.reason": reason,
                                  "solve.deferred": True})
                        # mode accounting waits for the settle
                        return results
                sp.set(**{"solve.mode": mode, "solve.mode.reason": reason})
        except Exception:
            if forced is not None:
                # the forced re-anchor never answered (fault/ejection): it is
                # still owed, so the RETRY carries the same reason — a
                # post-restart session-lost must not relabel itself "first"
                # just because chaos ate the first attempt
                self._forced_reason = forced
            raise
        SOLVE_MODE.labels(mode).inc()
        self.last_mode, self.last_reason = mode, reason
        self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1
        if deferred:
            return PendingResults(self, results=results)
        return results

    def _absorb_bound(self, bound_uids) -> None:
        """Lineage-placed pods that have since BOUND leave the pending
        population as the lineage's own work materializing, not as evictions:
        their capacity stays committed in the carry (they now physically
        occupy the node the repair placed them on), they leave the membership
        view so the diff never frees them, and the supply comparison excludes
        them so their binding doesn't read as a supply change.  Genuinely
        foreign bound pods still flip the supply digest ⇒ full solve."""
        w = self._warm
        moved = [uid for uid in w.pod_loc if uid in bound_uids]
        if not moved:
            return
        trimmed: Dict[tuple, List[str]] = {}
        for uid in moved:
            row, _kind, _idx = w.pod_loc.pop(uid)
            key = w.row_key.get(row)
            if key is not None:
                trimmed.setdefault(key, []).append(uid)
            w.materialized.add(uid)
        for key, uids in trimmed.items():
            gone = set(uids)
            left = tuple(u for u in w.members.get(key, ()) if u not in gone)
            if left:
                w.members[key] = left
            else:
                w.members.pop(key, None)

    # -- full path -------------------------------------------------------------

    def _full_solve(self, pods_or_classes, members, state_nodes, bound_pods,
                    supply, reason, deferred: bool = False):
        import jax

        solver = self.solver
        prev_nodes = self.node_count() if self._warm is not None else None
        try:
            if isinstance(pods_or_classes, list):
                snapshot = solver.encode_classes(
                    pods_or_classes, state_nodes=state_nodes, bound_pods=bound_pods
                )
            else:
                snapshot = solver.encode(pods_or_classes, state_nodes, bound_pods)
            versioned = self.store.commit(snapshot, supply=supply)
            prep = solver.prepare_encoded(snapshot, state_nodes, bound_pods)
            run = self._run_prepared or solver.run_prepared
            outputs = run(prep)
            if deferred and pipeline_mod.pipeline_enabled():
                # the pipelined anchor: the encode/commit/prepare above ran
                # host-side; the device solve is in flight — settle (barrier,
                # slot-exhaustion retry, adoption) waits for the next solve's
                # entry and decode for the handle, both overlapping this
                # solve's device compute
                if self._staging is None:
                    self._staging = pipeline_mod.HostStagingRing()
                ticket = solver.begin_fetch(outputs, ring=self._staging)
                box = PendingResults(self)
                self._pending = _PendingTick(
                    kind="full", box=box, data=dict(
                        snapshot=snapshot, versioned=versioned, prep=prep,
                        run=run, outputs=outputs, ticket=ticket,
                        members=dict(members), supply=supply,
                        state_nodes=list(state_nodes or ()),
                        prev_nodes=prev_nodes, reason=reason, solver=solver,
                    ),
                )
                return box
            from karpenter_core_tpu.utils import watchdog

            n_next_h, failed_h = watchdog.run(
                "pipeline.fetch", jax.device_get,
                (outputs.state.n_next, outputs.failed), key="anchor-check",
            )
            slots = outputs.assign.shape[1]
            if int(np.sum(failed_h)) > 0 and int(n_next_h) >= slots:
                outputs = run(prep, n_slots=slots * 2)
            results = solver.decode(snapshot, outputs, state_nodes or [])
        except Exception:
            self._warm = None  # a half-built lineage must not seed repairs
            raise
        self._adopt(versioned, prep, outputs, results, members, supply,
                    state_nodes, prev_nodes, reason)
        return results

    def _settle_full(self, pending: _PendingTick) -> None:
        """Retire a deferred anchor: completion barrier, the slot-exhaustion
        retry (synchronous, rare), adoption; decode stays deferred to the
        handle's ``result()``."""
        f = pending.data
        from karpenter_core_tpu.solver.tpu import TPUSolver

        fetched = f["ticket"].wait()
        slots = f["outputs"].assign.shape[1]
        if TPUSolver.fetch_exhausted(fetched, slots):
            outputs = f["run"](f["prep"], n_slots=slots * 2)
            ticket = f["solver"].begin_fetch(outputs, ring=self._staging)
            # adopt the retry's ticket BEFORE its barrier: a wait() that
            # fails must leave THIS ticket reachable for the settle error
            # path's invalidate, not leak it behind the consumed original
            f["outputs"], f["ticket"] = outputs, ticket
            ticket.wait()
        self._adopt(
            f["versioned"], f["prep"], f["outputs"], None, f["members"],
            f["supply"], f["state_nodes"], f["prev_nodes"], f["reason"],
        )
        pending.box._settle_with(decode=lambda: f["solver"].decode(
            f["snapshot"], f["outputs"], f["state_nodes"], fetched=f["ticket"]
        ))
        self._undecoded = pending.box

    def _adopt(self, versioned, prep, outputs, results, members, supply,
               state_nodes, prev_nodes, reason):
        import jax

        from karpenter_core_tpu.utils import watchdog

        carry = solve_ops.warm_carry_of(outputs)
        assign, assign_ex, n_next = watchdog.run(
            "pipeline.fetch", jax.device_get,
            (outputs.assign, outputs.assign_existing, outputs.state.n_next),
            key="adopt",
        )
        assign = np.asarray(assign, dtype=np.int32).copy()
        assign_ex = np.asarray(assign_ex, dtype=np.int32).copy()
        snapshot = versioned.snapshot
        pod_loc, unplaced = _locate_pods(snapshot, assign, assign_ex)
        all_pods = {
            p.uid: p for cls in snapshot.classes for p in cls.pods
        }
        failed_pods = {uid: (row, all_pods[uid]) for uid, row in unplaced}
        member_rows, own_inv_rows = _topology_rows(prep)
        if pipeline_mod.pipeline_enabled():
            # upload the padded planes ONCE: every repair in this lineage
            # then re-dispatches over the same device buffers — only the
            # per-tick count vector crosses the host→device boundary again
            # (KC_PIPELINE=0 keeps the old re-upload-per-tick path)
            prep = self.solver.upload_prep(prep)
        index = versioned.index_of()
        row_key = {i: row.key for i, row in enumerate(versioned.rows)}
        self.last_audit_drift_nodes = None
        if prev_nodes is not None and reason.startswith("audit"):
            fresh = int(np.sum(np.sum(assign, axis=0) > 0))
            self.last_audit_drift_nodes = prev_nodes - fresh
            if self.last_audit_drift_nodes:
                log.info(
                    "incremental solve audit: repair lineage carried %+d "
                    "node(s) of drift vs the fresh full solve",
                    self.last_audit_drift_nodes,
                )
        self._warm = _WarmState(
            versioned=versioned,
            prep=prep,
            carry=carry,
            assign=assign,
            assign_ex=assign_ex,
            n_next=int(n_next),
            members=dict(members),
            class_index=index,
            pod_loc=pod_loc,
            row_key=row_key,
            failed_pods=failed_pods,
            member_rows=member_rows,
            own_inv_rows=own_inv_rows,
            supply=supply,
            state_nodes=list(state_nodes or []),
            initial_slots_used=0,
            # the CONFIGURED family (routing intent), not the per-batch
            # outcome: a relax-fallback batch still anchors as "relax" so a
            # steady config doesn't thrash full solves on transient fallbacks
            solve_mode=_resolve_solve_mode(self.solver),
        )
        if carry is None:
            self._warm = None  # outputs predate the carry fields

    def adopt_restored(self, versioned, prep, carry, *, assign, assign_ex,
                       n_next, members, pod_loc, failed_rows, supply,
                       state_nodes, delta_ticks=0, initial_slots_used=0,
                       materialized=()) -> None:
        """Adopt a deserialized warm lineage (fleet/checkpoint.py restore).

        The tensor-level twin of ``_adopt``: instead of fetching a just-run
        solve's outputs it takes checkpointed planes verbatim — the padded
        prep, the warm scan carry, the cumulative assignment planes, the
        membership bookkeeping — and rebuilds the exact ``_WarmState`` the
        originating replica held, so the next delta repairs over the restored
        carry bit-for-bit instead of replaying the request chain.  The CALLER
        owns the never-trust verification: ``versioned`` must be a fresh
        commit whose plane digests equal the checkpointed ones before this
        runs, and ``lineage_state()`` must equal the checkpointed state after
        (fleet/checkpoint.restore_session).  Any inconsistency raises — the
        restore ladder falls to journal replay, never a stale answer."""
        import jax

        if self._pending is not None:
            self.settle()
        if getattr(prep, "mesh_axes", None) is not None:
            # a mesh-sharded carry would need resharding onto THIS replica's
            # device topology; the ladder's replay rung covers that case
            raise ValueError("mesh-sharded lineage cannot adopt a checkpoint")
        carry = jax.device_put(carry)
        assign = np.asarray(assign, dtype=np.int32).copy()
        assign_ex = np.asarray(assign_ex, dtype=np.int32).copy()
        snapshot = versioned.snapshot
        all_pods = {p.uid: p for cls in snapshot.classes for p in cls.pods}
        if snapshot.cls_root is not None:
            root_of = [int(r) for r in snapshot.cls_root]
        else:
            root_of = list(range(len(snapshot.classes)))
        failed_pods = {}
        for uid, row in dict(failed_rows or {}).items():
            pod = all_pods.get(uid)
            if pod is None:
                # a pod that joined on a DELTA tick after the anchor: absent
                # from the anchor snapshot, but class members are fungible
                # copies of the representative differing only in uid
                # (service tenant path, _materialize_class) — rebuild it
                root = root_of[int(row)] if int(row) < len(root_of) else -1
                reps = (snapshot.classes[root].pods
                        if 0 <= root < len(snapshot.classes) else ())
                if not reps:
                    raise ValueError(
                        f"checkpointed failed pod {uid!r} has no class "
                        f"representative in the re-encoded anchor snapshot"
                    )
                pod = copy.copy(reps[0])
                pod.metadata = copy.copy(reps[0].metadata)
                pod.metadata.uid = uid
            failed_pods[uid] = (int(row), pod)
        member_rows, own_inv_rows = _topology_rows(prep)
        if pipeline_mod.pipeline_enabled():
            prep = self.solver.upload_prep(prep)
        self._warm = _WarmState(
            versioned=versioned,
            prep=prep,
            carry=carry,
            assign=assign,
            assign_ex=assign_ex,
            n_next=int(n_next),
            members={k: tuple(v) for k, v in members.items()},
            class_index=versioned.index_of(),
            pod_loc={u: (int(r), str(kind), int(i))
                     for u, (r, kind, i) in pod_loc.items()},
            row_key={i: row.key for i, row in enumerate(versioned.rows)},
            failed_pods=failed_pods,
            member_rows=member_rows,
            own_inv_rows=own_inv_rows,
            supply=supply,
            state_nodes=list(state_nodes or []),
            delta_ticks=int(delta_ticks),
            initial_slots_used=int(initial_slots_used),
            materialized=set(materialized),
            # record THIS replica's resolved family: the restored carry is
            # scan state either way, and an immediate family mismatch should
            # escalate on the next reconcile exactly like a live flip
            solve_mode=_resolve_solve_mode(self.solver),
        )

    def export_lineage(self) -> Optional[Dict[str, object]]:
        """The warm lineage as host-side data — what the fleet checkpoint
        (fleet/checkpoint.py) serializes, and the exact argument set
        ``adopt_restored`` consumes on the adopting replica.  Device-resident
        leaves (the scan carry, an uploaded prep) are fetched here under the
        pipeline-fetch watchdog; class keys — frozenset-bearing tuples, not
        msgpack-able — are translated to class ROWS, which the restorer
        inverts through its freshly committed ``versioned.rows``.  None when
        there is no warm lineage (nothing to checkpoint)."""
        import jax

        from karpenter_core_tpu.utils import watchdog

        self.settle()
        w = self._warm
        if w is None or w.carry is None:
            return None
        if getattr(w.prep, "mesh_axes", None) is not None:
            return None  # sharded carries restore via replay, never tensors
        prep, carry = watchdog.run(
            "pipeline.fetch", jax.device_get, (w.prep, w.carry),
            key="lineage-export",
        )
        # strict: a members key outside the committed class index would mean
        # the lineage invariant broke — let the KeyError surface; the
        # checkpoint plane degrades that tenant to the replay rung
        members_rows = sorted(
            (int(w.class_index[key]), sorted(uids))
            for key, uids in w.members.items()
        )
        return {
            "version": int(w.versioned.version),
            "supply": w.supply,
            "state": self.lineage_state(),
            "prep": prep,
            "carry": carry,
            "assign": w.assign.copy(),
            "assign_ex": w.assign_ex.copy(),
            "n_next": int(w.n_next),
            "members_rows": members_rows,
            "pod_loc": {uid: [int(r), str(kind), int(i)]
                        for uid, (r, kind, i) in w.pod_loc.items()},
            "failed_rows": {uid: int(row)
                            for uid, (row, _pod) in w.failed_pods.items()},
            "delta_ticks": int(w.delta_ticks),
            "initial_slots_used": int(w.initial_slots_used),
            "materialized": sorted(w.materialized),
        }

    # -- delta path ------------------------------------------------------------
    #
    # One delta tick is four stages — plan (host), dispatch (device, async),
    # settle (completion barrier + bookkeeping), decode (host materialize).
    # The serial path (_delta_solve) runs them back to back in the exact
    # pre-pipeline order; the deferred path (_delta_dispatch_deferred) stops
    # after dispatch and settles at the next solve's entry, so the stages of
    # consecutive ticks overlap (docs/KERNEL_PERF.md "Layer 7").

    def _delta_plan(self, delta, by_uid):
        """The host-side tick plan: eviction free planes, the delta count
        vector, and the post-tick membership.  None when an unseen class key
        means the padded tensors cannot express the delta (caller escalates
        to a full solve)."""
        w = self._warm
        c_pad = w.prep.cls.count.shape[0]  # shape read only: may be device
        n_slots = w.assign.shape[1]
        e_pad = w.assign_ex.shape[1]

        # evictions: return departed pods' capacity and counts to the carry
        free_new = np.zeros((c_pad, n_slots), dtype=np.int32)
        free_ex = np.zeros((c_pad, e_pad), dtype=np.int32)
        evicted_locs: List[Tuple[str, Tuple[int, str, int]]] = []
        for key, uids in delta.evicted.items():
            for uid in uids:
                loc = w.pod_loc.get(uid)
                if loc is None:
                    continue  # was failed/unplaced: nothing to free
                row, kind, idx = loc
                (free_new if kind == "new" else free_ex)[row, idx] += 1
                evicted_locs.append((uid, loc))

        # additions (+ retry of previously-failed pods): a count vector with
        # only the delta, scanned over the SAME padded tensors
        evicted_set = {u for us in delta.evicted.values() for u in us}
        pods_by_root: Dict[int, List[object]] = {}
        for key, uids in delta.added.items():
            row = w.class_index.get(key)
            if row is None:
                return None  # unseen class key: tensors can't express it
            pods_by_root.setdefault(row, []).extend(by_uid(uid) for uid in uids)
        # still-pending failures retry every repair tick under their own class
        # row — their capacity was never committed to the carry, so a retry is
        # a plain re-placement (the host queue's re-push equivalent).  Iterates
        # the (tiny) failure set, not the whole membership.
        for uid, (row, pod) in w.failed_pods.items():
            if uid not in evicted_set:
                pods_by_root.setdefault(row, []).append(pod)
        counts = np.zeros(c_pad, dtype=np.int32)
        for row, pods in pods_by_root.items():
            counts[row] = len(pods)

        # membership after this tick lands: previous minus evicted plus added
        members = {k: list(v) for k, v in w.members.items()}
        for key, uids in delta.evicted.items():
            gone = set(uids)
            if key in members:
                members[key] = [u for u in members[key] if u not in gone]
        for key, uids in delta.added.items():
            members.setdefault(key, []).extend(uids)
        members_after = {k: tuple(v) for k, v in members.items() if v}
        return {
            "delta": delta, "free_new": free_new, "free_ex": free_ex,
            "evicted_locs": evicted_locs, "pods_by_root": pods_by_root,
            "counts": counts, "members_after": members_after,
        }

    def _delta_dispatch(self, plan):
        """Dispatch the repair onto the device (asynchronously) and start
        its device→host fetch.  The dispatch routes through the
        ``_run_prepared`` hook when one is set — the tenant service's batch
        coalescer fuses compatible repair windows from different tenants
        onto one vmapped dispatch (docs/SERVICE.md "Solve fusion"); hooked
        repairs never donate.  Unhooked warm dispatches donate the carry
        when the pipeline is armed (utils.pipeline): the pre-dispatch carry
        is dead after this call — only ``keep_carry`` (the full-width carry
        of a WINDOWED repair, which the settle's scatter consumes) may be
        read again, and an exception anywhere past the donating call drops
        the lineage (the except below and its twins in _delta_solve/settle):
        a kept ``_warm`` pointing at a donated buffer would turn one
        transient fault into a crash loop on every later repair."""
        w = self._warm
        free_new, free_ex = plan["free_new"], plan["free_ex"]
        evicted_locs, counts = plan["evicted_locs"], plan["counts"]
        n_slots = w.assign.shape[1]
        # hooked dispatches (the tenant service's coalescer) never donate:
        # the batch program stacks COPIES of member carries, so the solo
        # donation bookkeeping would free buffers the fused path still reads
        # — donation is a solo-dispatch optimization only
        run = self._run_prepared or self.solver.run_prepared
        hooked = self._run_prepared is not None
        donate = pipeline_mod.donation_enabled() and not hooked and not (
            self.solver.policy is not None
            and getattr(self.solver.policy, "enabled", False)
        )
        carry = w.carry
        donated = False

        # bounded repair window (docs/INCREMENTAL.md): gather the dirty slots
        # — freed holes plus a fresh tail — into a fixed power-of-two window
        # so the repair's per-class-step cost scales with the dirty region,
        # not the fleet.  The freed-hole planes double as the placement
        # preference: fills refill the exact slots departures vacated before
        # falling back to the normal order, so steady-state churn keeps the
        # lineage's assignments identical to a from-scratch solve.
        g1 = w.member_rows.shape[1]
        n_zones = w.prep.statics_arrays.tmpl_zone.shape[1]
        hole_slots = sorted({loc[2] for _, loc in evicted_locs if loc[1] == "new"})
        window = _window_indices(hole_slots, w.n_next, n_slots)
        try:
            if evicted_locs:
                free_fn = (
                    solve_ops.repair_free_donated if donate
                    else solve_ops.repair_free
                )
                donated = donate
                carry = free_fn(
                    carry, free_new, free_ex,
                    _as_request_plane(w.prep.cls.requests),
                    w.member_rows, w.own_inv_rows,
                )
            if window is not None:
                idx, n_open_w = window
                win_carry, base = solve_ops.gather_repair_window(
                    carry, idx, np.int32(n_open_w)
                )
                repair_plan = solve_ops.RepairPlan(
                    pref_new=free_new[:, idx],
                    pref_ex=free_ex,
                    base_fwd_sing=base[0],
                    base_fwd_full=base[1],
                    base_inv_full=base[2],
                )
                keep_carry = carry
                outputs = run(
                    w.prep, count=counts, warm_carry=win_carry,
                    repair_plan=repair_plan, n_slots=len(idx),
                    donate_carry=donate,
                )
                donated = donated or donate
            else:
                zeros_gz = np.zeros((g1, n_zones), dtype=np.int32)
                repair_plan = solve_ops.RepairPlan(
                    pref_new=free_new, pref_ex=free_ex,
                    base_fwd_sing=zeros_gz, base_fwd_full=zeros_gz,
                    base_inv_full=zeros_gz,
                )
                keep_carry = None
                outputs = run(
                    w.prep, count=counts, warm_carry=carry,
                    repair_plan=repair_plan, donate_carry=donate,
                )
                donated = donated or donate
            if self._staging is None and pipeline_mod.pipeline_enabled():
                self._staging = pipeline_mod.HostStagingRing()
            ticket = self.solver.begin_fetch(outputs, ring=self._staging)
        except BaseException:
            if donated or donate:
                self._warm = None  # the carry was donated: lineage is gone
            raise
        # decode consumes a delta VIEW of the snapshot: same planes, classes
        # carry only this tick's pods (built here, while the device works)
        delta_view = _delta_view(w.versioned.snapshot, plan["pods_by_root"])
        return {
            "plan": plan, "outputs": outputs, "ticket": ticket,
            "window": window, "keep_carry": keep_carry, "donated": donated,
            "delta_view": delta_view, "state_nodes": w.state_nodes,
            "solver": self.solver,
        }

    @staticmethod
    def _delta_exhausted(disp, fetched) -> bool:
        """Out of slots/window: the repair could not place everything it was
        given room for — the tick escalates to a full solve."""
        w_slots = (
            len(disp["window"][0]) if disp["window"] is not None
            else disp["outputs"].assign.shape[1]
        )
        from karpenter_core_tpu.solver.tpu import TPUSolver

        return TPUSolver.fetch_exhausted(fetched, w_slots)

    def _delta_results(self, disp):
        """Host materialize: decode over the delta view (the fetch ticket's
        staged arrays — no device re-touch), dropping node decisions the
        repair placed nothing on (previously-decided nodes must not be
        re-launched)."""
        results = disp["solver"].decode(
            disp["delta_view"], disp["outputs"], disp["state_nodes"],
            fetched=disp["ticket"],
        )
        results.new_nodes = [d for d in results.new_nodes if d.pods]
        return results

    def _delta_adopt(self, disp, fetched) -> None:
        """Bookkeeping: fold the repair's placements into the lineage.  Runs
        only after the device work succeeded (the ticket's barrier)."""
        w = self._warm
        plan = disp["plan"]
        window = disp["window"]
        outputs = disp["outputs"]
        c_pad = w.prep.cls.count.shape[0]
        n_slots = w.assign.shape[1]
        from karpenter_core_tpu.solver.tpu import TPUSolver

        assign_d = np.asarray(fetched[TPUSolver.FETCH_ASSIGN], dtype=np.int32)
        assign_ex_d = np.asarray(
            fetched[TPUSolver.FETCH_ASSIGN_EX], dtype=np.int32
        )
        n_next_h = int(fetched[TPUSolver.FETCH_N_NEXT])
        loc_d, unplaced = _locate_pods(disp["delta_view"], assign_d, assign_ex_d)
        if window is not None:
            # scatter the windowed repair back to the full-width lineage:
            # assignment columns, pod locations, and the device carry.  The
            # donating twin writes the window into the full carry's device
            # memory in place (the full carry is dead after this call).
            idx, n_open_w = window
            scatter = (
                solve_ops.scatter_repair_window_donated if disp["donated"]
                else solve_ops.scatter_repair_window
            )
            new_carry = scatter(
                disp["keep_carry"], solve_ops.warm_carry_of(outputs), idx,
                np.int32(n_open_w),
            )
            assign_g = np.zeros((c_pad, n_slots), dtype=np.int32)
            assign_g[:, idx] = assign_d
            assign_d = assign_g
            loc_d = {
                uid: (row, kind, int(idx[i]) if kind == "new" else i)
                for uid, (row, kind, i) in loc_d.items()
            }
            n_next_h = w.n_next + (n_next_h - n_open_w)
        else:
            new_carry = solve_ops.warm_carry_of(outputs)
        for uid, loc in plan["evicted_locs"]:
            row, kind, slot = loc
            (w.assign if kind == "new" else w.assign_ex)[row, slot] -= 1
            del w.pod_loc[uid]
        w.assign += assign_d
        w.assign_ex += assign_ex_d
        w.pod_loc.update(loc_d)
        # every non-evicted failure was retried this tick, so the repair's
        # unplaced tail IS the new failure set
        delta_pods = {
            p.uid: p for pods in plan["pods_by_root"].values() for p in pods
        }
        w.failed_pods = {
            uid: (row, delta_pods[uid]) for uid, row in unplaced
        }
        w.carry = new_carry
        w.n_next = n_next_h
        w.members = plan["members_after"]
        w.delta_ticks += 1

    def _delta_solve(self, delta, by_uid, state_nodes):
        """The serial delta tick, stage order exactly as before the
        pipelined loop: dispatch → barrier → exhaustion check → decode →
        adopt.  None escalates to a full solve."""
        plan = self._delta_plan(delta, by_uid)
        if plan is None:
            return None
        disp = self._delta_dispatch(plan)
        try:
            fetched = disp["ticket"].wait()
        except BaseException:
            # ANY failed barrier — the device going quiet (SolveTimeout) or
            # throwing — cancels the tick cleanly: ticket retired from the
            # open ledger, donation ledger balanced, lineage dropped so
            # nothing is ever half-applied.  The error surfaces to the
            # caller's breaker; the next solve re-anchors from scratch.
            self._cancel_tick(disp)
            raise
        try:
            if self._delta_exhausted(disp, fetched):
                return None
            results = self._delta_results(disp)
            self._delta_adopt(disp, fetched)
        except BaseException:
            if disp["donated"]:
                # the carry was donated: a kept lineage would re-read the
                # deleted buffer on every later repair — drop it so the
                # next solve re-anchors (KC_PIPELINE=0 keeps the old
                # keep-the-lineage behavior, nothing was donated there)
                self._warm = None
            raise
        return results

    def _cancel_tick(self, disp) -> None:
        """Invalidate a timed-out tick's in-flight device state: the
        FetchTicket retires from the open ledger (its device refs drop, so
        an abandoned copy cannot pin buffers into the next tick), a donated
        dispatch's ledger entry is balanced, and the warm lineage drops —
        its carry is either donated-dead or aliased by the abandoned fetch,
        and a half-applied lineage must never seed repairs."""
        disp["ticket"].invalidate()
        if disp["donated"]:
            pipeline_mod.record_donation_canceled()
        self._warm = None

    def _delta_dispatch_deferred(self, delta, by_uid, pods_or_classes,
                                 members, state_nodes, bound_pods,
                                 supply_anchor):
        """The pipelined tick: plan + dispatch now, settle at the next
        solve's entry.  Returns the PendingResults handle, or None when the
        plan cannot be expressed (caller escalates inline, exactly like the
        serial path).  The current population's classes are captured so a
        settle-time exhaustion re-anchors from THIS tick's population even
        though the caller's ingest has moved on by then."""
        plan = self._delta_plan(delta, by_uid)
        if plan is None:
            return None
        disp = self._delta_dispatch(plan)
        # capture AFTER dispatch so the snapshot build overlaps device work.
        # PodIngest.classes() is a fresh finalized list (fresh pods lists);
        # a prebuilt class list gets shallow pod-list copies for the same
        # isolation from caller-side churn.
        from karpenter_core_tpu.models.columnar import PodIngest

        try:
            if isinstance(pods_or_classes, PodIngest):
                captured = pods_or_classes.classes()
            else:
                captured = [
                    cls if getattr(cls, "is_ladder_variant", False)
                    else dc_replace(cls, pods=list(cls.pods))
                    for cls in pods_or_classes
                ]
        except BaseException:
            if disp["donated"]:
                self._warm = None  # dispatched with a donated carry
            raise
        box = PendingResults(self)
        self._pending = _PendingTick(
            kind="delta", box=box, data=dict(
                disp=disp, members_after=plan["members_after"],
                captured_classes=captured, members_at=dict(members),
                state_nodes=list(state_nodes or ()),
                bound_pods=list(bound_pods or ()),
                supply_anchor=supply_anchor,
            ),
        )
        return box

    def settle(self) -> None:
        """Retire the in-flight deferred tick: completion barrier, window
        exhaustion check (a delta escalates to a full re-anchor of the
        CAPTURED population — same semantics as the serial escalation; a
        full retries with doubled slots), bookkeeping adoption, and mode
        accounting.  Decode stays deferred to the handle's ``result()`` so
        it overlaps the next tick's device compute; a handle still undecoded
        by the NEXT settle materializes here first (its staging-ring slot is
        about to be rewritten).  Never raises: a settle failure lands in the
        handle and drops the lineage (the next solve re-anchors)."""
        # flush the last settled-but-undecoded handle FIRST — and do it even
        # when nothing is pending: its staged arrays live in the shared ring,
        # and ANY later tick (a serial one included) would rewrite that slot
        # under the handle.  In the canonical loop the consumer already
        # called result(), making this a no-op; failures are cached in the
        # box (PendingResults.result) and re-raised to its consumer.
        if self._undecoded is not None:
            try:
                self._undecoded.result()
            except Exception:  # noqa: BLE001 - recorded in the box
                pass
            self._undecoded = None
        pending = self._pending
        if pending is None:
            return
        self._pending = None
        if pending.kind == "full":
            mode, reason = MODE_FULL, pending.data["reason"]
        else:
            mode, reason = MODE_DELTA, "delta"
        try:
            if pending.kind == "full":
                self._settle_full(pending)
            else:
                disp = pending.data["disp"]
                try:
                    fetched = disp["ticket"].wait()
                except SolveTimeout:
                    # fault-triggered re-anchor: the watchdog abandoned this
                    # tick's barrier, so cancel its in-flight state cleanly
                    # and rebuild the lineage from the DISPATCH-TIME
                    # population capture — the same escalation the deferred
                    # window overflow takes, now driven by a hang instead of
                    # slot pressure.  The re-anchor's own dispatch is still
                    # watchdog-bounded: a persistently quiet device surfaces
                    # as a SolveTimeout in the handle and the caller's
                    # breaker quarantines the backend.
                    self._cancel_tick(disp)
                    mode, reason = MODE_FULL, "watchdog-timeout"
                    results = self._full_solve(
                        pending.data["captured_classes"],
                        pending.data["members_at"],
                        pending.data["state_nodes"],
                        pending.data["bound_pods"],
                        pending.data["supply_anchor"], reason,
                    )
                    pending.box._settle_with(results=results)
                except BaseException:
                    # a non-timeout barrier fault: same clean cancellation
                    # (ticket/donation ledgers must not leak on ANY error),
                    # but no re-anchor — the error routes to the handle.
                    # (A SolveTimeout raised by the RE-ANCHOR above is not
                    # caught here — sibling except clauses don't catch
                    # exceptions raised inside each other.)
                    self._cancel_tick(disp)
                    raise
                else:
                    if self._delta_exhausted(disp, fetched):
                        mode, reason = MODE_FULL, "slots-exhausted"
                        results = self._full_solve(
                            pending.data["captured_classes"],
                            pending.data["members_at"],
                            pending.data["state_nodes"],
                            pending.data["bound_pods"],
                            pending.data["supply_anchor"], reason,
                        )
                        pending.box._settle_with(results=results)
                    else:
                        self._delta_adopt(disp, fetched)
                        pending.box._settle_with(
                            decode=lambda: self._delta_results(disp)
                        )
                        self._undecoded = pending.box
        except BaseException as e:  # noqa: BLE001 - routed to the handle
            if pending.kind == "full" or pending.data["disp"]["donated"]:
                self._warm = None  # serial parity: a failed anchor resets
            # keep the ticket ledger leak-free on EVERY error path — a
            # failed anchor barrier (timed out or thrown) leaves a ticket
            # whose copy was never consumed
            ticket = (
                pending.data.get("ticket") if pending.kind == "full"
                else pending.data["disp"]["ticket"]
            )
            if ticket is not None and not ticket.done():
                ticket.invalidate()
            pending.box._settle_with(error=e)
            SOLVE_MODE.labels(mode).inc()
            self.last_mode, self.last_reason = mode, f"{reason}:failed"
            self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1
            return
        SOLVE_MODE.labels(mode).inc()
        self.last_mode, self.last_reason = mode, reason
        self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1

    # -- aggregate views (bench / parity tests) --------------------------------
    # Each settles the in-flight deferred tick first: the view must reflect
    # every dispatched solve (a no-op outside the pipelined loop).

    def node_count(self) -> int:
        self.settle()
        w = self._warm
        if w is None:
            return 0
        return int(np.sum(np.sum(w.assign, axis=0) > 0))

    def aggregates(self) -> Dict[str, int]:
        """The session lineage's current placement totals."""
        self.settle()
        w = self._warm
        if w is None:
            return {"scheduled": 0, "failed": 0, "nodes": 0}
        return {
            "scheduled": int(w.assign.sum() + w.assign_ex.sum()),
            "failed": len(w.failed_pods),
            "nodes": self.node_count(),
        }

    def node_signature(self):
        """Canonical multiset of per-node class loads, labeled by stable
        class identity — the assignment-identity view the churn bench
        compares against a from-scratch full solve (order- and
        row-index-independent)."""
        self.settle()
        w = self._warm
        if w is None:
            return ()
        keys = [w.row_key.get(i, i) for i in range(w.assign.shape[0])]
        return node_signature_of(w.assign, keys) + node_signature_of(
            w.assign_ex, keys
        )


_WINDOW_MIN = 256
_WINDOW_FRESH = 64


def _window_indices(hole_slots, n_next: int, n_slots: int):
    """The bounded repair window's global slot indices: every freed-hole slot
    (ascending — all open, they held placed pods), open filler below
    ``n_next`` if the power-of-two bucket needs it, then the fresh tail.
    Returns (idx i32[S], open_count) or None when windowing is off
    (KC_DELTA_WINDOW=0), the bucket would not shrink the solve, or the
    geometry doesn't fit — callers then run the repair at full width, which
    is always correct.  S rides a power-of-two ladder (min
    max(KC_DELTA_WINDOW, holes + fresh headroom)) so steady churn reuses ONE
    windowed executable per bucket."""
    env = os.environ.get("KC_DELTA_WINDOW", "")
    if env == "0":
        return None
    try:
        min_s = max(int(env), 1) if env else min(_WINDOW_MIN, n_slots // 4)
    except ValueError:
        min_s = _WINDOW_MIN
    # fresh headroom scales down with tiny fleets so small solves window too
    fresh_headroom = min(_WINDOW_FRESH, max(8, n_slots // 16))
    want = max(min_s, len(hole_slots) + fresh_headroom)
    s = 1
    while s < want:
        s <<= 1
    if s >= n_slots:
        return None
    fresh = list(range(n_next, min(n_next + (s - len(hole_slots)), n_slots)))
    filler_needed = s - len(hole_slots) - len(fresh)
    open_w = list(hole_slots)
    if filler_needed > 0:
        holes = set(hole_slots)
        filler = []
        slot = n_next - 1
        while slot >= 0 and len(filler) < filler_needed:
            if slot not in holes:
                filler.append(slot)
            slot -= 1
        if len(filler) < filler_needed:
            return None
        open_w = sorted(open_w + filler)
    idx = np.asarray(open_w + fresh, dtype=np.int32)
    return idx, len(open_w)


def node_signature_of(assign: np.ndarray, keys=None):
    """Sorted tuple of per-node (class, count) loads, empty slots dropped —
    two solves with identical placements (up to slot naming) produce equal
    signatures.  ``keys`` maps class row -> a stable class identity; without
    it the raw row index labels the load, which only compares correctly
    between solves that share ONE encode's class order (a fully-churned
    class re-enters a fresh encode at a different row)."""
    sig = []
    arr = np.asarray(assign)
    # class keys are nested tuples that may hold unorderable members
    # (frozensets), so canonicalize by repr — identical values repr equal
    for col in range(arr.shape[1]):
        loads = tuple(sorted(
            (
                ((keys[int(c)] if keys is not None else int(c)), int(arr[c, col]))
                for c in np.nonzero(arr[:, col])[0]
            ),
            key=repr,
        ))
        if loads:
            sig.append(loads)
    return tuple(sorted(sig, key=repr))


def _locate_pods(snapshot, assign, assign_ex):
    """uid -> (class row, "new"|"ex", index) plus the unplaced tail as
    (uid, root row) pairs, in the exact cursor order TPUSolver.decode
    consumes pods (ladder rows share their root's cursor)."""
    n_classes = len(snapshot.classes)
    if snapshot.cls_root is not None:
        root_of = [int(r) for r in snapshot.cls_root]
    else:
        root_of = list(range(n_classes))
    cursors = [0] * n_classes
    loc: Dict[str, Tuple[int, str, int]] = {}
    unplaced: List[str] = []
    for c in range(n_classes):
        r = root_of[c]
        pods = snapshot.classes[r].pods
        cursor = cursors[r]
        ex_idx = np.nonzero(assign_ex[c] > 0)[0]
        for e, take in zip(ex_idx.tolist(), assign_ex[c][ex_idx].tolist()):
            for pod in pods[cursor:cursor + take]:
                loc[pod.uid] = (c, "ex", int(e))
            cursor += take
        node_idx = np.nonzero(assign[c] > 0)[0]
        for n, take in zip(node_idx.tolist(), assign[c][node_idx].tolist()):
            for pod in pods[cursor:cursor + take]:
                loc[pod.uid] = (c, "new", int(n))
            cursor += take
        cursors[r] = cursor
    for c in range(n_classes):
        if root_of[c] != c:
            continue
        unplaced.extend((p.uid, c) for p in snapshot.classes[c].pods[cursors[c]:])
    return loc, unplaced


def _as_request_plane(requests):
    """The per-pod request plane for repair_free: a device-resident prep's
    plane passes straight through (already f32 on device — no host round
    trip per tick); a host prep's numpy plane gets the f32 cast the jit
    expects."""
    if isinstance(requests, np.ndarray):
        return np.asarray(requests, dtype=np.float32)
    return requests


def _topology_rows(prep) -> Tuple[np.ndarray, np.ndarray]:
    """(member, own_inv) i32[C_pad, G1] rows for ops.solve.repair_free: which
    group counts each class's placements incremented — membership from the
    padded grp_member plane, inverse ownership from the owned anti slots
    (preferred terms register no inverse counts, matching the record step)."""
    member = np.asarray(prep.statics_arrays.grp_member).astype(np.int32)
    c_pad, g1 = member.shape
    own_inv = np.zeros((c_pad, g1), dtype=np.int32)
    groups = np.asarray(prep.cls.groups)
    anti_soft = np.asarray(prep.cls.anti_soft)
    g_dummy = g1 - 1
    for c in range(c_pad):
        g_zan, g_han = int(groups[c, 4]), int(groups[c, 5])
        if g_zan < g_dummy and not bool(anti_soft[c, 0]):
            own_inv[c, g_zan] += 1
        if g_han < g_dummy and not bool(anti_soft[c, 1]):
            own_inv[c, g_han] += 1
    return member, own_inv


def _delta_view(snapshot, pods_by_root: Dict[int, List[object]]):
    """A shallow snapshot view whose root classes carry only this tick's
    pods (delta additions + retried failures) — what decode's cursor walk
    consumes; every tensor plane is shared with the original."""
    view = copy.copy(snapshot)
    classes = []
    for c, cls in enumerate(snapshot.classes):
        if cls.is_ladder_variant:
            classes.append(cls)
            continue
        classes.append(dc_replace(cls, pods=list(pods_by_root.get(c, ()))))
    view.classes = classes
    return view
