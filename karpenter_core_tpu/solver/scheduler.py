"""The scheduler: greedy first-fit-decreasing bin-pack with constraint
propagation and preference relaxation.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:42-309.
This host-side path is the exact-semantics engine used by the controllers and as
the oracle for the TPU kernel (karpenter_core_tpu.ops.solve), which accelerates
the dominant homogeneous-batch workloads; the Scheduler can transparently route
eligible batches to the TPU kernel (use_tpu_kernel=True).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    TAINT_EFFECT_PREFER_NO_SCHEDULE,
    Pod,
)
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.cloudprovider import InstanceType
from karpenter_core_tpu.scheduling import Requirements, Taints
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.node import ExistingNode, SchedulingNode
from karpenter_core_tpu.solver.preferences import Preferences
from karpenter_core_tpu.solver.queue import Queue
from karpenter_core_tpu.solver.topology import Topology
from karpenter_core_tpu.utils import resources as resources_util

log = logging.getLogger(__name__)


@dataclass
class SchedulerOptions:
    # Simulation mode suppresses nomination events/records (used by consolidation)
    simulation_mode: bool = False


@dataclass
class SchedulingResults:
    new_nodes: List[SchedulingNode] = field(default_factory=list)
    existing_nodes: List[ExistingNode] = field(default_factory=list)
    errors: Dict[str, str] = field(default_factory=dict)  # pod uid -> error
    failed_pods: List[Pod] = field(default_factory=list)


class Scheduler:
    def __init__(
        self,
        kube_client,
        machine_templates: List[MachineTemplate],
        provisioners: List[Provisioner],
        cluster,
        state_nodes: list,
        topology: Topology,
        instance_types: Dict[str, List[InstanceType]],
        daemonset_pods: List[Pod],
        recorder=None,
        opts: Optional[SchedulerOptions] = None,
    ) -> None:
        opts = opts if opts is not None else SchedulerOptions()
        self.kube_client = kube_client
        self.machine_templates = machine_templates
        self.topology = topology
        self.cluster = cluster
        self.instance_types = instance_types
        self.recorder = recorder
        self.opts = opts
        # tolerate PreferNoSchedule during relaxation only when some provisioner
        # actually carries such a taint (scheduler.go:47-56)
        tolerate = any(
            taint.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
            for prov in provisioners
            for taint in prov.spec.taints
        )
        self.preferences = Preferences(tolerate_prefer_no_schedule=tolerate)
        self.remaining_resources: Dict[str, resources_util.ResourceList] = {
            p.name: dict(p.spec.limits.resources)
            for p in provisioners
            if p.spec.limits is not None
        }
        self.daemon_overhead = _daemon_overhead(machine_templates, daemonset_pods)
        self.new_nodes: List[SchedulingNode] = []
        self.existing_nodes: List[ExistingNode] = []
        # decision audit (tracing enabled only): pod uid -> the most recent
        # attempt's per-candidate rejections, attached as a decision.audit
        # span event for pods that end the solve unschedulable
        self._audit: Dict[str, List[dict]] = {}
        self._calculate_existing_machines(state_nodes, daemonset_pods)

    # -- the solve loop -------------------------------------------------------

    def solve(self, pods: List[Pod]) -> SchedulingResults:
        """Loop pods through the queue while progress is being made
        (scheduler.go:96-133).  Requeue-with-relaxation handles batch
        pod-affinity and order-dependent skew constraints.
        """
        with tracing.span("scheduler.solve", pods=len(pods)) as sp:
            errors: Dict[str, str] = {}
            q = Queue(*pods)
            while True:
                pod = q.pop()
                if pod is None:
                    break
                err = self._add(pod)
                errors[pod.uid] = err
                if err is None:
                    continue
                relaxed = self.preferences.relax(pod)
                q.push(pod, relaxed)
                if relaxed:
                    update_err = self.topology.update(pod)
                    if update_err is not None:
                        log.error("updating topology, %s", update_err)

            for n in self.new_nodes:
                n.finalize_scheduling()

            failed = q.list()
            if tracing.enabled():
                for pod in failed:
                    tracing.record_unschedulable(
                        pod,
                        rejections=self._audit.get(pod.uid, []),
                        error=errors.get(pod.uid),
                        engine="host",
                    )
                sp.set(
                    new_nodes=len(self.new_nodes),
                    failed=len(failed),
                )
            if not self.opts.simulation_mode:
                self._record_results(pods, failed, errors)
            return SchedulingResults(
                new_nodes=self.new_nodes,
                existing_nodes=self.existing_nodes,
                errors={uid: e for uid, e in errors.items() if e is not None},
                failed_pods=failed,
            )

    def _add(self, pod: Pod) -> Optional[str]:
        """existing nodes → open new nodes (fewest pods first) → a fresh node
        per weighted template (scheduler.go:174-219)."""
        # rejection audit, kept per attempt (the LAST attempt's rejections —
        # post-relaxation — are what a failed pod's audit reports)
        rejections: Optional[List[dict]] = [] if tracing.enabled() else None

        def reject(candidate: str, err: str) -> None:
            if rejections is not None and len(rejections) < tracing.audit.MAX_REJECTIONS_PER_POD:
                rejections.append(tracing.rejection(candidate, err))

        def fail(err: Optional[str]) -> Optional[str]:
            if rejections is not None:
                self._audit[pod.uid] = rejections
            return err

        for node in self.existing_nodes:
            err = node.add(pod)
            if err is None:
                return None
            reject(f"existing/{node.name}", err)

        self.new_nodes.sort(key=lambda n: len(n.pods))
        for node in self.new_nodes:
            err = node.add(pod)
            if err is None:
                return None
            reject(f"inflight/{node.hostname}", err)

        errs: List[str] = []
        for template in self.machine_templates:
            instance_types = self.instance_types.get(template.provisioner_name, [])
            if template.provisioner_name in self.remaining_resources:
                remaining = self.remaining_resources[template.provisioner_name]
                filtered = _filter_by_remaining_resources(instance_types, remaining)
                if not filtered:
                    errs.append("all available instance types exceed provisioner limits")
                    reject(
                        f"template/{template.provisioner_name}",
                        "all available instance types exceed provisioner limits",
                    )
                    continue
                if len(filtered) != len(instance_types) and not self.opts.simulation_mode:
                    log.debug(
                        "%d out of %d instance types were excluded because they would "
                        "breach provisioner limits",
                        len(instance_types) - len(filtered),
                        len(instance_types),
                    )
                instance_types = filtered

            node = SchedulingNode(
                template,
                self.topology,
                self.daemon_overhead.get(id(template), {}),
                instance_types,
            )
            err = node.add(pod)
            if err is not None:
                errs.append(f"incompatible with provisioner {template.provisioner_name!r}, {err}")
                reject(f"template/{template.provisioner_name}", err)
                continue
            self.new_nodes.append(node)
            # pessimistic limit tracking: assume the largest surviving instance
            # type launches (scheduler.go:273-290 subtractMax)
            if template.provisioner_name in self.remaining_resources:
                self.remaining_resources[template.provisioner_name] = _subtract_max(
                    self.remaining_resources[template.provisioner_name],
                    node.instance_type_options,
                )
            return None
        return fail("; ".join(errs) if errs else "no provisioner available")

    # -- setup ----------------------------------------------------------------

    def _calculate_existing_machines(self, state_nodes, daemonset_pods: List[Pod]) -> None:
        """Wrap owned state nodes as ExistingNodes and charge their capacity
        against provisioner limits (scheduler.go:221-248)."""
        for state_node in state_nodes:
            if not state_node.owned():
                continue
            daemons = []
            for p in daemonset_pods:
                if Taints.of(state_node.node.spec.taints).tolerates(p) is not None:
                    continue
                labels_reqs = Requirements.from_labels(state_node.node.metadata.labels)
                if labels_reqs.compatible(Requirements.from_pod(p)) is not None:
                    continue
                daemons.append(p)
            self.existing_nodes.append(
                ExistingNode(state_node, self.topology, resources_util.requests_for_pods(*daemons))
            )
            provisioner_name = state_node.node.metadata.labels.get(
                labels_api.PROVISIONER_NAME_LABEL_KEY
            )
            if provisioner_name in self.remaining_resources:
                self.remaining_resources[provisioner_name] = resources_util.subtract(
                    self.remaining_resources[provisioner_name], state_node.capacity()
                )

    def _record_results(
        self, pods: List[Pod], failed: List[Pod], errors: Dict[str, str]
    ) -> None:
        from karpenter_core_tpu.events import events as evt

        for pod in failed:
            log.error(
                "Could not schedule pod %s/%s, %s", pod.namespace, pod.name, errors.get(pod.uid)
            )
            if self.recorder is not None:
                self.recorder.publish(evt.pod_failed_to_schedule(pod, errors.get(pod.uid, "")))
        for node in self.existing_nodes:
            if node.pods and self.cluster is not None:
                self.cluster.nominate_node_for_pod(node.name)
            if self.recorder is not None:
                for pod in node.pods:
                    self.recorder.publish(evt.nominate_pod(pod, node.node))
        new_count = sum(len(n.pods) for n in self.new_nodes)
        if new_count == 0:
            return
        log.info("found provisionable pod(s): %d", len(pods))
        log.info("computed new node(s) to fit pod(s): %d nodes, %d pods", len(self.new_nodes), new_count)


def _daemon_overhead(
    templates: List[MachineTemplate], daemonset_pods: List[Pod]
) -> Dict[int, resources_util.ResourceList]:
    """Per-template daemonset resource overhead (scheduler.go:250-267); keyed by
    id(template) since templates are mutable."""
    overhead: Dict[int, resources_util.ResourceList] = {}
    for template in templates:
        daemons = []
        for p in daemonset_pods:
            if template.taints.tolerates(p) is not None:
                continue
            if template.requirements.compatible(Requirements.from_pod(p)) is not None:
                continue
            daemons.append(p)
        overhead[id(template)] = resources_util.requests_for_pods(*daemons)
    return overhead


def _subtract_max(
    remaining: resources_util.ResourceList, instance_types: List[InstanceType]
) -> resources_util.ResourceList:
    if not instance_types:
        return remaining
    it_max = resources_util.max_resources(*(it.capacity for it in instance_types))
    return {k: v - it_max.get(k, 0.0) for k, v in remaining.items()}


def _filter_by_remaining_resources(
    instance_types: List[InstanceType], remaining: resources_util.ResourceList
) -> List[InstanceType]:
    """Drop instance types whose launch would breach provisioner limits
    (scheduler.go:292-309)."""
    out = []
    for it in instance_types:
        viable = all(
            resources_util.cmp(it.capacity.get(name, 0.0), quantity) <= 0
            for name, quantity in remaining.items()
        )
        if viable:
            out.append(it)
    return out
