"""In-construction and in-flight node models for the greedy solver.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/{node.go:34-159,
existingnode.go:28-130}.  A SchedulingNode accumulates pods against a shrinking
set of viable instance types; an ExistingNode packs pods into the fixed capacity
of a real (possibly still-launching) node.
"""

from __future__ import annotations

import itertools
from dataclasses import replace
from typing import List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import OP_IN, Pod
from karpenter_core_tpu.cloudprovider import InstanceType
from karpenter_core_tpu.scheduling import (
    HostPortUsage,
    Requirement,
    Requirements,
    Taints,
)
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.topology import Topology
from karpenter_core_tpu.utils import resources as resources_util

_hostname_ids = itertools.count(1)


def compatible(instance_type: InstanceType, requirements: Requirements) -> bool:
    return instance_type.requirements.intersects(requirements) is None


def fits(instance_type: InstanceType, requests: resources_util.ResourceList) -> bool:
    return resources_util.fits(requests, instance_type.allocatable())


def has_offering(instance_type: InstanceType, requirements: Requirements) -> bool:
    for offering in instance_type.offerings.available():
        if (
            not requirements.has(labels_api.LABEL_TOPOLOGY_ZONE)
            or requirements.get(labels_api.LABEL_TOPOLOGY_ZONE).has(offering.zone)
        ) and (
            not requirements.has(labels_api.LABEL_CAPACITY_TYPE)
            or requirements.get(labels_api.LABEL_CAPACITY_TYPE).has(offering.capacity_type)
        ):
            return True
    return False


def filter_instance_types(
    instance_types: List[InstanceType],
    requirements: Requirements,
    requests: resources_util.ResourceList,
) -> List[InstanceType]:
    """compat ∧ fits ∧ offering in one pass (node.go:137-159).  The tensorized
    version is ops.masks.filter_instance_types — a single masked reduction."""
    return [
        it
        for it in instance_types
        if compatible(it, requirements) and fits(it, requests) and has_offering(it, requirements)
    ]


class SchedulingNode:
    """A node we intend to create (node.go:34-107)."""

    def __init__(
        self,
        machine_template: MachineTemplate,
        topology: Topology,
        daemon_resources: resources_util.ResourceList,
        instance_types: List[InstanceType],
    ) -> None:
        hostname = f"hostname-placeholder-{next(_hostname_ids):04d}"
        topology.register(labels_api.LABEL_HOSTNAME, hostname)
        self.template = replace(
            machine_template,
            requirements=Requirements(*machine_template.requirements.values()),
        )
        self.template.requirements.add(
            Requirement(labels_api.LABEL_HOSTNAME, OP_IN, [hostname])
        )
        self.hostname = hostname
        self.pods: List[Pod] = []
        self.topology = topology
        self.host_port_usage = HostPortUsage()
        self.instance_type_options = list(instance_types)
        self.requests = dict(daemon_resources)

    @property
    def provisioner_name(self) -> str:
        return self.template.provisioner_name

    @property
    def requirements(self) -> Requirements:
        return self.template.requirements

    @property
    def taints(self) -> Taints:
        return self.template.taints

    def add(self, pod: Pod) -> Optional[str]:
        """Try to place the pod; returns an error string (node unchanged) or
        None on success (state committed) — node.go:62-107."""
        err = self.taints.tolerates(pod)
        if err is not None:
            return err
        err = self.host_port_usage.validate(pod)
        if err is not None:
            return err

        node_requirements = Requirements(*self.requirements.values())
        pod_requirements = Requirements.from_pod(pod)

        err = node_requirements.compatible(pod_requirements)
        if err is not None:
            return f"incompatible requirements, {err}"
        node_requirements.add(*pod_requirements.values())

        topology_requirements, err = self.topology.add_requirements(
            pod_requirements, node_requirements, pod
        )
        if err is not None:
            return err
        err = node_requirements.compatible(topology_requirements)
        if err is not None:
            return err
        node_requirements.add(*topology_requirements.values())

        requests = resources_util.merge(self.requests, resources_util.requests_for_pods(pod))
        instance_types = filter_instance_types(
            self.instance_type_options, node_requirements, requests
        )
        if not instance_types:
            return (
                f"no instance type satisfied resources {requests} "
                f"and requirements {node_requirements!r}"
            )

        # commit
        self.pods.append(pod)
        self.instance_type_options = instance_types
        self.requests = requests
        self.template.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self.host_port_usage.add(pod)
        return None

    def finalize_scheduling(self) -> None:
        """Drop the placeholder hostname before launch (node.go:111-115)."""
        self.template.requirements.delete(labels_api.LABEL_HOSTNAME)

    def __repr__(self) -> str:
        names = ", ".join(it.name for it in self.instance_type_options[:5])
        if len(self.instance_type_options) > 5:
            names += f" and {len(self.instance_type_options) - 5} other(s)"
        return f"node with {len(self.pods)} pods requesting {self.requests} from types {names}"


class ExistingNode:
    """A real or in-flight node with fixed capacity (existingnode.go:28-130).

    ``state_node`` is a state.Node snapshot (deep copy — we mutate trackers).
    """

    def __init__(self, state_node, topology: Topology, daemon_resources) -> None:
        self.state_node = state_node
        self.node = state_node.node
        # remaining daemon resources = template overhead minus what already runs
        remaining = resources_util.subtract(daemon_resources, state_node.daemon_set_requests())
        remaining = {k: max(v, 0.0) for k, v in remaining.items()}
        self.pods: List[Pod] = []
        self.requests = remaining
        self.topology = topology
        self.requirements = Requirements.from_labels(self.node.metadata.labels)
        self.available = state_node.available()
        self.taints = Taints.of(state_node.taints())
        self.host_port_usage = state_node.host_port_usage().deep_copy()
        self.volume_usage = state_node.volume_usage().deep_copy()
        self.volume_limits = state_node.volume_limits()

        hostname = self.node.metadata.labels.get(labels_api.LABEL_HOSTNAME) or self.node.name
        self.requirements.add(Requirement(labels_api.LABEL_HOSTNAME, OP_IN, [hostname]))
        topology.register(labels_api.LABEL_HOSTNAME, hostname)

    @property
    def name(self) -> str:
        return self.node.name

    def add(self, pod: Pod) -> Optional[str]:
        err = self.taints.tolerates(pod)
        if err is not None:
            return err
        err = self.host_port_usage.validate(pod)
        if err is not None:
            return err

        mounted, err = self.volume_usage.validate(pod)
        if err is not None:
            return err
        if mounted.exceeds(self.volume_limits):
            return "would exceed node volume limits"

        # resource check first: the most likely failure on a fixed-size node
        requests = resources_util.merge(self.requests, resources_util.requests_for_pods(pod))
        if not resources_util.fits(requests, self.available):
            return "exceeds node resources"

        node_requirements = Requirements(*self.requirements.values())
        pod_requirements = Requirements.from_pod(pod)
        err = node_requirements.compatible(pod_requirements)
        if err is not None:
            return err
        node_requirements.add(*pod_requirements.values())

        topology_requirements, err = self.topology.add_requirements(
            pod_requirements, node_requirements, pod
        )
        if err is not None:
            return err
        err = node_requirements.compatible(topology_requirements)
        if err is not None:
            return err
        node_requirements.add(*topology_requirements.values())

        # commit
        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, node_requirements)
        self.host_port_usage.add(pod)
        self.volume_usage.add(pod)
        return None
