"""Ordered preference-relaxation ladder for unschedulable pods.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/preferences.go:38-46:
when a pod fails to schedule, soft constraints are removed one at a time, in
order: required node-affinity OR-terms (all but the last), preferred pod
affinity, preferred pod anti-affinity, preferred node affinity, ScheduleAnyway
topology spreads, and finally (when a provisioner carries a PreferNoSchedule
taint) a toleration for PreferNoSchedule taints.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from karpenter_core_tpu.apis.objects import (
    TAINT_EFFECT_PREFER_NO_SCHEDULE,
    SCHEDULE_ANYWAY,
    Pod,
    Toleration,
)

log = logging.getLogger(__name__)


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False) -> None:
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        relaxations: List[Callable[[Pod], Optional[str]]] = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_topology_spread_schedule_anyway,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for relax in relaxations:
            reason = relax(pod)
            if reason is not None:
                log.debug(
                    "relaxing soft constraints for pod %s/%s since it previously "
                    "failed to schedule, %s",
                    pod.namespace,
                    pod.name,
                    reason,
                )
                return True
        return False

    def _remove_required_node_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if (
            affinity is None
            or affinity.node_affinity is None
            or affinity.node_affinity.required is None
            or not affinity.node_affinity.required.node_selector_terms
        ):
            return None
        terms = affinity.node_affinity.required.node_selector_terms
        # terms are OR'd; we can drop all but the last
        if len(terms) > 1:
            removed = terms[0]
            affinity.node_affinity.required.node_selector_terms = terms[1:]
            return f"removing: requiredDuringScheduling nodeAffinity term {removed}"
        return None

    def _remove_preferred_node_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None or not affinity.node_affinity.preferred:
            return None
        terms = sorted(affinity.node_affinity.preferred, key=lambda t: -t.weight)
        removed = terms[0]
        affinity.node_affinity.preferred = terms[1:]
        return f"removing: preferred nodeAffinity term weight={removed.weight}"

    def _remove_preferred_pod_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if affinity is None or affinity.pod_affinity is None or not affinity.pod_affinity.preferred:
            return None
        terms = sorted(affinity.pod_affinity.preferred, key=lambda t: -t.weight)
        removed = terms[0]
        affinity.pod_affinity.preferred = terms[1:]
        return f"removing: preferred podAffinity term weight={removed.weight}"

    def _remove_preferred_pod_anti_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.affinity
        if (
            affinity is None
            or affinity.pod_anti_affinity is None
            or not affinity.pod_anti_affinity.preferred
        ):
            return None
        terms = sorted(affinity.pod_anti_affinity.preferred, key=lambda t: -t.weight)
        removed = terms[0]
        affinity.pod_anti_affinity.preferred = terms[1:]
        return f"removing: preferred podAntiAffinity term weight={removed.weight}"

    def _remove_topology_spread_schedule_anyway(self, pod: Pod) -> Optional[str]:
        for i, tsc in enumerate(pod.spec.topology_spread_constraints):
            if tsc.when_unsatisfiable == SCHEDULE_ANYWAY:
                constraints = pod.spec.topology_spread_constraints
                constraints[i] = constraints[-1]
                pod.spec.topology_spread_constraints = constraints[:-1]
                return f"removing: topologySpreadConstraint {tsc.topology_key}"
        return None

    @staticmethod
    def tolerates_prefer_no_schedule(pod: Pod) -> bool:
        return any(
            t.operator == "Exists"
            and t.effect == TAINT_EFFECT_PREFER_NO_SCHEDULE
            and not t.key
            and not t.value
            for t in pod.spec.tolerations
        )

    def _tolerate_prefer_no_schedule_taints(self, pod: Pod) -> Optional[str]:
        if self.tolerates_prefer_no_schedule(pod):
            return None
        pod.spec.tolerations = pod.spec.tolerations + [
            Toleration(operator="Exists", effect=TAINT_EFFECT_PREFER_NO_SCHEDULE)
        ]
        return "adding: toleration for PreferNoSchedule taints"
