"""Scheduler construction: templates, catalogs, topology domains.

Mirror of the wiring in /root/reference/pkg/controllers/provisioning/provisioner.go:237-296
(NewScheduler): order provisioners by weight, collect instance-type catalogs,
derive the topology-domain universe from instance-type requirements plus
provisioner In-requirements, then assemble the Scheduler.  Used by both the
provisioning controller and deprovisioning simulations.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from karpenter_core_tpu.apis.objects import OP_IN, Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner, order_by_weight
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.scheduler import Scheduler, SchedulerOptions
from karpenter_core_tpu.solver.topology import Topology


class NoProvisionersError(Exception):
    pass


def build_scheduler(
    kube_client,
    cloud_provider: CloudProvider,
    cluster,
    pods: List[Pod],
    state_nodes: list,
    daemonset_pods: Optional[List[Pod]] = None,
    recorder=None,
    opts: SchedulerOptions = SchedulerOptions(),
    provisioners: Optional[List[Provisioner]] = None,
) -> Scheduler:
    if provisioners is None:
        provisioners = kube_client.list_provisioners()
    provisioners = [
        p for p in provisioners if p.metadata.deletion_timestamp is None
    ]
    provisioners = order_by_weight(provisioners)
    if not provisioners:
        raise NoProvisionersError("no provisioners found")

    machines: List[MachineTemplate] = []
    instance_types: Dict[str, List[InstanceType]] = {}
    domains: Dict[str, Set[str]] = {}
    for provisioner in provisioners:
        machines.append(MachineTemplate.from_provisioner(provisioner))
        options = cloud_provider.get_instance_types(provisioner)
        instance_types.setdefault(provisioner.name, []).extend(options)
        # topology-domain universe
        for it in options:
            for key in it.requirements.keys():
                domains.setdefault(key, set()).update(it.requirements.get(key).values_list())
        provisioner_reqs = Requirements.from_node_selector_requirements(
            *provisioner.spec.requirements
        )
        for key in provisioner_reqs.keys():
            requirement = provisioner_reqs.get(key)
            if requirement.operator() == OP_IN:
                domains.setdefault(key, set()).update(requirement.values_list())

    topology = Topology(kube_client, cluster, domains, pods)
    return Scheduler(
        kube_client,
        machines,
        provisioners,
        cluster,
        state_nodes,
        topology,
        instance_types,
        daemonset_pods or [],
        recorder=recorder,
        opts=opts,
    )
