"""Solver-mode routing: which solver family runs a cold solve.

Two families share the encoded planes (docs/RELAX.md):

  scan    the exact greedy-by-priority class scan (ops/solve.py) — the
          default, and the only family that handles every constraint
  relax   the convex-relaxation family (karpenter_core_tpu/relax/):
          projected-gradient placement over the policy objective planes,
          deterministically rounded, exactly audited, scan-repaired

``KC_SOLVER_MODE=scan|relax|auto`` selects; a ``PolicyConfig.solver_mode``
(provisioner spec ``solverMode``) OVERRIDES the env — spec wins over env so a
per-tenant config can pin a family while the fleet default rides the flag.
``auto`` picks relax only at scale (>= ``KC_RELAX_MIN_PODS`` pods in the
batch): below that the scan is both exact and faster, above it the
relaxation's fixed iteration count beats the pod-proportional scan.

The dispatcher itself lives in ``TPUSolver.run_prepared`` (cold solves only:
warm-carry repairs always run the scan — the carry IS scan state), which
reports the outcome as the ``solve.mode`` span attr and
``karpenter_solve_mode_total{mode="relax"|"relax-fallback"}``
(solver.incremental.SOLVE_MODE).  A relax run that cannot stand (host gate,
non-convergence, audit wipeout) raises ``relax.solve.RelaxFallback`` and the
scan runs as if relax never existed — the mode is approximate in cost, never
wrong in placement.
"""

from __future__ import annotations

import os

MODE_SCAN = "scan"
MODE_RELAX = "relax"
MODE_AUTO = "auto"
_VALID = (MODE_SCAN, MODE_RELAX, MODE_AUTO)


def resolve_mode(policy=None) -> str:
    """The configured solver mode: provisioner/policy spec > KC_SOLVER_MODE
    env > scan.  Unknown values degrade to scan (the kill-switch semantics:
    a typo'd mode must not strand a tenant on an unintended family)."""
    spec = ""
    if policy is not None:
        spec = str(getattr(policy, "solver_mode", "") or "")
    mode = spec or os.environ.get("KC_SOLVER_MODE", "") or MODE_SCAN
    return mode if mode in _VALID else MODE_SCAN


def relax_min_pods() -> int:
    """KC_RELAX_MIN_PODS: the ``auto`` mode's pod-count threshold (default
    4096) — below it the exact scan wins on both latency and cost."""
    try:
        return int(os.environ.get("KC_RELAX_MIN_PODS", "4096"))
    except ValueError:
        return 4096


def relax_selected(mode: str, n_pods: int) -> bool:
    """Does this cold solve dispatch through the relax family?"""
    if mode == MODE_RELAX:
        return True
    if mode == MODE_AUTO:
        return int(n_pods) >= relax_min_pods()
    return False


def relax_max_iters() -> int:
    """KC_RELAX_MAX_ITERS: projected-gradient iteration cap (default 64).
    The iteration contracts geometrically (relax/kernel.py), so the default
    converges with a wide margin; a too-small cap is the convergence-fallback
    test's lever, not a production knob."""
    try:
        return int(os.environ.get("KC_RELAX_MAX_ITERS", "64"))
    except ValueError:
        return 64
