"""Topology engine: spread / affinity / anti-affinity domain tracking.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/{topology.go:37-406,
topologygroup.go:32-253, topologynodefilter.go:28-70}.  Domain counts are kept as
plain dicts here; the tensorized equivalent (shared hash-deduped groups with
forward/inverse count planes driving the water-fill and per-node caps) lives
in ``karpenter_core_tpu.ops.solve`` (TopoCounts and the _class_step phases).
"""

from __future__ import annotations

from enum import IntEnum
from typing import Dict, List, Optional, Set

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    LabelSelector,
    Node,
    Pod,
    PodAffinityTerm,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.utils import pod as pod_util

MAX_SKEW_UNBOUNDED = 1 << 31  # math.MaxInt32 stand-in for affinity groups


class TopologyType(IntEnum):
    SPREAD = 0
    POD_AFFINITY = 1
    POD_ANTI_AFFINITY = 2

    def __str__(self) -> str:
        return ("topology spread", "pod affinity", "pod anti-affinity")[int(self)]


class TopologyNodeFilter(List[Requirements]):
    """OR of requirement sets; empty filter matches everything
    (topologynodefilter.go:28-70)."""

    @classmethod
    def for_pod(cls, pod: Pod) -> "TopologyNodeFilter":
        node_selector = Requirements.from_labels(pod.spec.node_selector)
        affinity = pod.spec.affinity
        if (
            affinity is None
            or affinity.node_affinity is None
            or affinity.node_affinity.required is None
        ):
            return cls([node_selector])
        filter_ = cls()
        for term in affinity.node_affinity.required.node_selector_terms:
            requirements = Requirements()
            requirements.add(*node_selector.values())
            requirements.add(
                *Requirements.from_node_selector_requirements(*term.match_expressions).values()
            )
            filter_.append(requirements)
        return filter_

    def matches_node(self, node: Node) -> bool:
        return self.matches_requirements(Requirements.from_labels(node.metadata.labels))

    def matches_requirements(self, requirements: Requirements) -> bool:
        if not self:
            return True
        return any(requirements.compatible(req) is None for req in self)

    def hash_key(self):
        return tuple(
            tuple(sorted((r.key, r.complement, r.values, r.greater_than, r.less_than) for r in reqs.values()))
            for reqs in self
        )


def _selector_key(selector: Optional[LabelSelector]):
    if selector is None:
        return None
    return (
        tuple(sorted(selector.match_labels.items())),
        tuple(
            sorted(
                (e.key, e.operator, tuple(sorted(e.values)))
                for e in selector.match_expressions
            )
        ),
    )


class TopologyGroup:
    """Tracks pod counts per topology domain for one constraint
    (topologygroup.go:53-253)."""

    def __init__(
        self,
        topology_type: TopologyType,
        key: str,
        pod: Optional[Pod],
        namespaces: Set[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        domains: Set[str],
    ) -> None:
        self.type = topology_type
        self.key = key
        self.namespaces = set(namespaces)
        self.selector = selector
        self.max_skew = max_skew
        # nil filter (always-match) for affinity types; spread filters on the pod's
        # node selectors (topologygroup.go:71-75)
        self.node_filter = (
            TopologyNodeFilter.for_pod(pod)
            if topology_type == TopologyType.SPREAD and pod is not None
            else TopologyNodeFilter()
        )
        # sorted for deterministic tie-breaks: the reference iterates a Go map
        # (random order, so count-tied domain picks flap run to run,
        # topologygroup.go:163-176); fixing a total order is a deterministic
        # refinement of the same semantics and keeps the oracle reproducible
        self.domains: Dict[str, int] = {domain: 0 for domain in sorted(domains)}
        self.owners: Set[str] = set()  # pod UIDs that have this topology as a rule

    # -- counting -------------------------------------------------------------

    def record(self, *domains: str) -> None:
        for domain in domains:
            self.domains[domain] = self.domains.get(domain, 0) + 1

    def register(self, *domains: str) -> None:
        for domain in domains:
            self.domains.setdefault(domain, 0)

    def counts(self, pod: Pod, requirements: Requirements) -> bool:
        """Whether the pod, scheduled to a node with these requirements, counts
        toward this topology (topologygroup.go:109-111)."""
        return self.selects(pod) and self.node_filter.matches_requirements(requirements)

    def selects(self, pod: Pod) -> bool:
        # a nil selector matches nothing; an empty selector matches everything
        # (metav1.LabelSelectorAsSelector semantics)
        if self.selector is None:
            return False
        return pod.namespace in self.namespaces and self.selector.matches(pod.metadata.labels)

    # -- ownership ------------------------------------------------------------

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    def hash_key(self):
        """Identity for deduplication across pods sharing one constraint
        (topologygroup.go:137-153)."""
        return (
            self.key,
            int(self.type),
            frozenset(self.namespaces),
            _selector_key(self.selector),
            self.max_skew,
            self.node_filter.hash_key(),
        )

    # -- domain selection -----------------------------------------------------

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type == TopologyType.SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type == TopologyType.POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains)

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        """kube-scheduler skew formula: count + self - min <= maxSkew
        (topologygroup.go:155-182)."""
        min_count = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        min_domain = None
        min_domain_count = 1 << 31
        for domain, count in self.domains.items():
            if node_domains.has(domain):
                if self_selecting:
                    count = count + 1
                if count - min_count <= self.max_skew and count < min_domain_count:
                    min_domain = domain
                    min_domain_count = count
        if min_domain is None:
            return Requirement(pod_domains.key, OP_DOES_NOT_EXIST)
        return Requirement(pod_domains.key, OP_IN, [min_domain])

    def _domain_min_count(self, domains: Requirement) -> int:
        # hostname topologies always have min zero: we can always create a new node
        if self.key == labels_api.LABEL_HOSTNAME:
            return 0
        min_count = 1 << 31
        for domain, count in self.domains.items():
            if domains.has(domain) and count < min_count:
                min_count = count
        return min_count

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        options = Requirement(pod_domains.key, OP_DOES_NOT_EXIST)
        for domain, count in self.domains.items():
            if pod_domains.has(domain) and count > 0:
                options.insert(domain)
        # Bootstrap self-affinity: no matching pod scheduled anywhere yet
        # (topologygroup.go:210-231)
        if options.len() == 0 and self.selects(pod):
            intersected = pod_domains.intersection(node_domains)
            for domain in self.domains:
                if intersected.has(domain):
                    options.insert(domain)
                    break
            for domain in self.domains:
                if pod_domains.has(domain):
                    options.insert(domain)
                    break
        return options

    def _next_domain_anti_affinity(self, domains: Requirement) -> Requirement:
        options = Requirement(domains.key, OP_DOES_NOT_EXIST)
        for domain, count in self.domains.items():
            if domains.has(domain) and count == 0:
                options.insert(domain)
        return options


def ignored_for_topology(p: Pod) -> bool:
    return not pod_util.is_scheduled(p) or pod_util.is_terminal(p) or pod_util.is_terminating(p)


class Topology:
    """Hash-deduped topology groups plus inverse anti-affinity groups
    (topology.go:37-54).

    ``kube_client`` needs list_pods(namespace=, selector=) / get_node(name) /
    list_namespaces(selector=); ``cluster`` needs for_pods_with_anti_affinity().
    """

    def __init__(
        self,
        kube_client,
        cluster,
        domains: Dict[str, Set[str]],
        pods: List[Pod],
    ) -> None:
        self.kube_client = kube_client
        self.cluster = cluster
        self.domains = domains
        self.topologies: Dict[object, TopologyGroup] = {}
        self.inverse_topologies: Dict[object, TopologyGroup] = {}
        # pods being scheduled are excluded from counting to avoid double counts
        self.excluded_pods: Set[str] = {p.uid for p in pods}
        errs: List[str] = []
        err = self._update_inverse_affinities()
        if err:
            errs.append(err)
        for p in pods:
            err = self.update(p)
            if err:
                errs.append(err)
        if errs:
            raise ValueError("; ".join(errs))

    # -- registration ---------------------------------------------------------

    def update(self, p: Pod) -> Optional[str]:
        """(Re-)register the pod as owner of its topologies; called after
        relaxation to drop ownership of removed constraints (topology.go:86-117)."""
        for tg in self.topologies.values():
            tg.remove_owner(p.uid)

        if pod_util.has_pod_anti_affinity(p):
            err = self._update_inverse_anti_affinity(p, None)
            if err:
                return f"updating inverse anti-affinities, {err}"

        groups = self._new_for_topologies(p) + self._new_for_affinities(p)
        for tg in groups:
            hash_key = tg.hash_key()
            existing = self.topologies.get(hash_key)
            if existing is None:
                err = self._count_domains(tg)
                if err:
                    return err
                self.topologies[hash_key] = tg
            else:
                tg = existing
            tg.add_owner(p.uid)
        return None

    def record(self, p: Pod, requirements: Requirements) -> None:
        """Commit the pod's placement into every topology that counts it
        (topology.go:120-143)."""
        for tc in self.topologies.values():
            if tc.counts(p, requirements):
                domains = requirements.get(tc.key)
                if tc.type == TopologyType.POD_ANTI_AFFINITY:
                    # block every domain the pod could land in
                    tc.record(*domains.values_list())
                elif domains.len() == 1:
                    tc.record(domains.values_list()[0])
        for tc in self.inverse_topologies.values():
            if tc.is_owned_by(p.uid):
                tc.record(*requirements.get(tc.key).values_list())

    def add_requirements(
        self, pod_requirements: Requirements, node_requirements: Requirements, p: Pod
    ) -> "tuple[Optional[Requirements], Optional[str]]":
        """Tighten node requirements with each matching topology's next-domain
        selection (topology.go:149-167)."""
        requirements = Requirements(*node_requirements.values())
        # deliberate refinement over topology.go:149-167: each group reads the
        # ACCUMULATED requirements (not the original nodeRequirements), and
        # exclusion groups (anti / inverse) apply before min-picking spreads.
        # The reference hands every group the original domains and iterates a
        # Go map, so a spread sharing a key with an anti exclusion picks its
        # min domain blind — whether the pod schedules depends on random map
        # order (a spread min-pick inside the excluded zone intersects to
        # empty).  Threading the narrowing makes the coin toss deterministic
        # in the direction that schedules; every group's constraint is still
        # applied exactly.
        matching = self._matching_topologies(p, node_requirements)
        matching.sort(key=lambda tc: 0 if tc.type == TopologyType.POD_ANTI_AFFINITY else 1)
        for topology in matching:
            pod_domains = (
                pod_requirements.get(topology.key)
                if pod_requirements.has(topology.key)
                else Requirement(topology.key, OP_EXISTS)
            )
            node_domains = (
                requirements.get(topology.key)
                if requirements.has(topology.key)
                else Requirement(topology.key, OP_EXISTS)
            )
            domains = topology.get(p, pod_domains, node_domains)
            if domains.len() == 0:
                return None, f"unsatisfiable topology constraint for {topology.type}, key={topology.key}"
            requirements.add(domains)
        return requirements, None

    def register(self, topology_key: str, domain: str) -> None:
        """Make a new domain (e.g. a new hostname) visible to all groups."""
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.register(domain)

    # -- internals ------------------------------------------------------------

    def _update_inverse_affinities(self) -> Optional[str]:
        errs: List[str] = []

        def visit(pod: Pod, node: Node) -> bool:
            if pod.uid in self.excluded_pods:
                return True
            err = self._update_inverse_anti_affinity(pod, node.metadata.labels)
            if err:
                errs.append(f"tracking existing pod anti-affinity, {err}")
            return True

        if self.cluster is not None:
            self.cluster.for_pods_with_anti_affinity(visit)
        return "; ".join(errs) if errs else None

    def _update_inverse_anti_affinity(
        self, pod: Pod, domains: Optional[Dict[str, str]]
    ) -> Optional[str]:
        """Track pods with anti-affinity terms so future pods they repel are
        blocked (topology.go:202-227)."""
        for term in pod.spec.affinity.pod_anti_affinity.required:
            namespaces = self._build_namespace_list(
                pod.namespace, term.namespaces, term.namespace_selector
            )
            tg = TopologyGroup(
                TopologyType.POD_ANTI_AFFINITY,
                term.topology_key,
                pod,
                namespaces,
                term.label_selector,
                MAX_SKEW_UNBOUNDED,
                self.domains.get(term.topology_key, set()),
            )
            hash_key = tg.hash_key()
            existing = self.inverse_topologies.get(hash_key)
            if existing is None:
                self.inverse_topologies[hash_key] = tg
            else:
                tg = existing
            if domains and tg.key in domains:
                tg.record(domains[tg.key])
            tg.add_owner(pod.uid)
        return None

    def _count_domains(self, tg: TopologyGroup) -> Optional[str]:
        """Count existing matching pods per domain (topology.go:231-276)."""
        pods: List[Pod] = []
        for ns in tg.namespaces:
            pods.extend(self.kube_client.list_pods(namespace=ns, selector=tg.selector))
        for p in pods:
            if ignored_for_topology(p):
                continue
            if p.uid in self.excluded_pods:
                continue
            node = self.kube_client.get_node(p.spec.node_name)
            if node is None:
                return f"getting node {p.spec.node_name}"
            domain = node.metadata.labels.get(tg.key)
            # fall back to node name for not-yet-labeled hostname domains
            if domain is None and tg.key == labels_api.LABEL_HOSTNAME:
                domain = node.name
            if domain is None:
                continue
            if not tg.node_filter.matches_node(node):
                continue
            tg.record(domain)
        return None

    def _new_for_topologies(self, p: Pod) -> List[TopologyGroup]:
        groups = []
        for cs in p.spec.topology_spread_constraints:
            groups.append(
                TopologyGroup(
                    TopologyType.SPREAD,
                    cs.topology_key,
                    p,
                    {p.namespace},
                    cs.label_selector,
                    cs.max_skew,
                    self.domains.get(cs.topology_key, set()),
                )
            )
        return groups

    def _new_for_affinities(self, p: Pod) -> List[TopologyGroup]:
        groups = []
        if p.spec.affinity is None:
            return groups
        terms: Dict[TopologyType, List[PodAffinityTerm]] = {}
        if p.spec.affinity.pod_affinity is not None:
            terms.setdefault(TopologyType.POD_AFFINITY, []).extend(
                p.spec.affinity.pod_affinity.required
            )
            for weighted in p.spec.affinity.pod_affinity.preferred:
                terms.setdefault(TopologyType.POD_AFFINITY, []).append(
                    weighted.pod_affinity_term
                )
        if p.spec.affinity.pod_anti_affinity is not None:
            terms.setdefault(TopologyType.POD_ANTI_AFFINITY, []).extend(
                p.spec.affinity.pod_anti_affinity.required
            )
            for weighted in p.spec.affinity.pod_anti_affinity.preferred:
                terms.setdefault(TopologyType.POD_ANTI_AFFINITY, []).append(
                    weighted.pod_affinity_term
                )
        for topology_type, term_list in terms.items():
            for term in term_list:
                namespaces = self._build_namespace_list(
                    p.namespace, term.namespaces, term.namespace_selector
                )
                groups.append(
                    TopologyGroup(
                        topology_type,
                        term.topology_key,
                        p,
                        namespaces,
                        term.label_selector,
                        MAX_SKEW_UNBOUNDED,
                        self.domains.get(term.topology_key, set()),
                    )
                )
        return groups

    def _build_namespace_list(
        self, namespace: str, namespaces: List[str], selector: Optional[LabelSelector]
    ) -> Set[str]:
        if not namespaces and selector is None:
            return {namespace}
        if selector is None:
            return set(namespaces)
        selected = {
            ns.metadata.name for ns in self.kube_client.list_namespaces(selector=selector)
        }
        selected.update(namespaces)
        return selected

    def _matching_topologies(self, p: Pod, requirements: Requirements) -> List[TopologyGroup]:
        matching = [tc for tc in self.topologies.values() if tc.is_owned_by(p.uid)]
        matching.extend(
            tc for tc in self.inverse_topologies.values() if tc.counts(p, requirements)
        )
        return matching
