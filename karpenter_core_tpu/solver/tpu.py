"""TPU solver facade: encode → kernel → decode.

Stands behind the same Solve() contract as the host Scheduler
(solver.scheduler) for the batch shapes the kernel models (see
models.snapshot.classify_pods); callers use ``supports()``/KernelUnsupported to
route between the tensor path and the host path.  This is the Solver the
BASELINE.json north star describes: cluster snapshots in, node decisions out,
with the bin-pack running as a batch tensor program on the TPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner, order_by_weight
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.models.snapshot import (
    EncodedSnapshot,
    KernelUnsupported,
    encode_snapshot,
)
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.scheduler import _daemon_overhead
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class TPUNodeDecision:
    """One node the kernel decided to create."""

    provisioner_name: str
    instance_type_names: List[str]
    zones: List[str]
    pods: List[Pod] = field(default_factory=list)
    requests: resources_util.ResourceList = field(default_factory=dict)


@dataclass
class TPUSolveResults:
    new_nodes: List[TPUNodeDecision] = field(default_factory=list)
    failed_pods: List[Pod] = field(default_factory=list)
    n_slots_used: int = 0


class TPUSolver:
    def __init__(
        self,
        cloud_provider: CloudProvider,
        provisioners: List[Provisioner],
        daemonset_pods: Optional[List[Pod]] = None,
    ) -> None:
        self.provisioners = order_by_weight(
            [p for p in provisioners if p.metadata.deletion_timestamp is None]
        )
        self.templates = [MachineTemplate.from_provisioner(p) for p in self.provisioners]
        self.instance_types: Dict[str, List[InstanceType]] = {
            p.name: cloud_provider.get_instance_types(p) for p in self.provisioners
        }
        overhead = _daemon_overhead(self.templates, daemonset_pods or [])
        for template in self.templates:
            template.requests = overhead[id(template)]

    def encode(self, pods: List[Pod]) -> EncodedSnapshot:
        """Raises models.snapshot.KernelUnsupported when the batch needs the
        host path."""
        return encode_snapshot(pods, self.provisioners, self.templates, self.instance_types)

    def solve(self, pods: List[Pod], n_slots: int = 0) -> TPUSolveResults:
        snapshot = self.encode(pods)
        outputs = solve_ops.solve(snapshot, n_slots=n_slots)
        # slot exhaustion: retry once with double capacity
        n_used = int(outputs.state.n_next)
        slots = outputs.assign.shape[1]
        if int(np.sum(np.asarray(outputs.failed))) > 0 and n_used >= slots:
            outputs = solve_ops.solve(snapshot, n_slots=slots * 2)
            n_used = int(outputs.state.n_next)
        return self.decode(snapshot, outputs)

    def decode(self, snapshot: EncodedSnapshot, outputs: solve_ops.SolveOutputs) -> TPUSolveResults:
        assign = np.asarray(outputs.assign)  # [C, N]
        failed = np.asarray(outputs.failed)  # [C]
        state = outputs.state
        pod_count = np.asarray(state.pod_count)
        tmpl_id = np.asarray(state.tmpl_id)
        viable = np.asarray(state.viable)
        zone = np.asarray(state.zone)
        used = np.asarray(state.used)
        open_ = np.asarray(state.open_)

        results = TPUSolveResults(n_slots_used=int(state.n_next))
        nodes: Dict[int, TPUNodeDecision] = {}
        for n in np.nonzero(open_ & (pod_count > 0))[0]:
            nodes[int(n)] = TPUNodeDecision(
                provisioner_name=self.templates[int(tmpl_id[n])].provisioner_name,
                instance_type_names=[
                    snapshot.it_names[i] for i in np.nonzero(viable[n])[0]
                ],
                zones=[snapshot.zones[z] for z in np.nonzero(zone[n])[0]],
                requests={
                    name: float(used[n, r])
                    for r, name in enumerate(snapshot.resources)
                    if used[n, r] > 0
                },
            )

        for c, cls in enumerate(snapshot.classes):
            cursor = 0
            for n in np.nonzero(assign[c] > 0)[0]:
                take = int(assign[c, n])
                for pod in cls.pods[cursor : cursor + take]:
                    nodes[int(n)].pods.append(pod)
                cursor += take
            results.failed_pods.extend(cls.pods[cursor:])
        results.new_nodes = [nodes[n] for n in sorted(nodes)]
        return results


__all__ = ["TPUSolver", "TPUSolveResults", "TPUNodeDecision", "KernelUnsupported"]
