"""TPU solver facade: encode → kernel → decode.

Stands behind the same Solve() contract as the host Scheduler
(solver.scheduler) for the batch shapes the kernel models (see
models.snapshot.classify_pods); callers use ``supports()``/KernelUnsupported to
route between the tensor path and the host path.  This is the Solver the
BASELINE.json north star describes: cluster snapshots in, node decisions out,
with the bin-pack running as a batch tensor program on the TPU.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional

import jax
import numpy as np

from karpenter_core_tpu import tracing
from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner, order_by_weight
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.models.snapshot import (
    EncodedSnapshot,
    KernelUnsupported,
    encode_snapshot,
)
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.solver import modes as modes_mod
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.scheduler import _daemon_overhead
from karpenter_core_tpu.utils import resources as resources_util


class _LazyPlanes:
    """Per-solve node planes (viable/zone/used), fetched device→host once on
    first access.  Construction starts async copies so the transfer overlaps
    the host-side pod-assignment decode; the big bool planes ship bit-packed
    (the device link is a tunnel — bandwidth, not latency, is the cost)."""

    __slots__ = ("_viable_p", "_zone_p", "_ct_p", "_used_d", "_n_it",
                 "_n_zones", "_n_ct", "_viable", "_zone", "_ct", "_used")

    def __init__(self, state) -> None:
        from karpenter_core_tpu.utils import pipeline as pipeline_mod

        self._n_it = state.viable.shape[-1]
        self._n_zones = state.zone.shape[-1]
        self._n_ct = state.ct.shape[-1]
        self._viable_p = solve_ops.pack_bool(state.viable)
        self._zone_p = solve_ops.pack_bool(state.zone)
        self._ct_p = solve_ops.pack_bool(state.ct)
        used = state.used
        if pipeline_mod.donation_enabled():
            # the pipelined loop donates the carry these planes alias on the
            # NEXT dispatch; node decisions consume `used` lazily (launch
            # path) possibly after that, so take an owned device copy now.
            # The packed planes above are already fresh arrays.
            import jax.numpy as jnp

            used = jnp.copy(used) if hasattr(used, "is_deleted") else used
        self._used_d = used
        self._viable = self._zone = self._ct = self._used = None

    def prefetch(self) -> None:
        """Start async device→host copies.  Called *after* the solve's eager
        fetch so the big planes don't queue ahead of it on the relay."""
        for arr in (self._viable_p, self._zone_p, self._ct_p, self._used_d):
            try:
                arr.copy_to_host_async()
            except AttributeError:  # non-jax (already host) arrays
                pass

    def _fetch(self) -> None:
        if self._viable is None:
            from karpenter_core_tpu.utils import watchdog

            with tracing.span("materialize"):
                # deadline-bounded: the big-plane copy crosses the same relay
                # tunnel the solve fetch does, and can hang the same way
                viable_p, zone_p, ct_p, used = watchdog.run(
                    "pipeline.fetch", jax.device_get,
                    (self._viable_p, self._zone_p, self._ct_p, self._used_d),
                    key="planes",
                )
                self._viable = solve_ops.unpack_bool(viable_p, self._n_it)
                self._zone = solve_ops.unpack_bool(zone_p, self._n_zones)
                self._ct = solve_ops.unpack_bool(ct_p, self._n_ct)
                self._used = used
                # release the device buffers — node decisions can outlive the
                # solve (launch path), and holding both copies doubles memory
                self._viable_p = self._zone_p = self._ct_p = self._used_d = None

    @property
    def viable(self) -> np.ndarray:
        self._fetch()
        return self._viable

    @property
    def zone(self) -> np.ndarray:
        self._fetch()
        return self._zone

    @property
    def ct(self) -> np.ndarray:
        self._fetch()
        return self._ct

    @property
    def used(self) -> np.ndarray:
        self._fetch()
        return self._used


class TPUNodeDecision:
    """One node the kernel decided to create.  Instance-type/zone name lists
    and the request vector materialize lazily — at 50k-pod scale eager
    materialization of ~7k nodes × ~1k type names dominates decode time, and
    the underlying planes only cross the device link when first consumed
    (launch path), off the solve critical path.

    ``selected`` carries the policy objective's argmin offering for this node
    (ops.objective, stamped by TPUSolver decode when the policy stage is
    enabled): the launch then lands on exactly that (instance type, zone,
    capacity type) cell — zone/ct pinned, the selected type ordered first —
    instead of whichever offering the provider's first-compatible walk
    happens to hit.  None (the default) keeps today's behavior exactly."""

    __slots__ = ("provisioner_name", "pods", "selected", "_snapshot",
                 "_planes", "_slot")

    def __init__(self, provisioner_name, snapshot, planes, slot):
        self.provisioner_name = provisioner_name
        self.pods: List[Pod] = []
        self.selected: Optional[dict] = None
        self._snapshot = snapshot
        self._planes = planes
        self._slot = slot

    @property
    def instance_type_names(self) -> List[str]:
        row = self._planes.viable[self._slot]
        names = [self._snapshot.it_names[i] for i in np.nonzero(row)[0]]
        if self.selected is not None:
            chosen = self.selected["instance_type"]
            if chosen in names:
                names = [chosen] + [n for n in names if n != chosen]
        return names

    @property
    def zones(self) -> List[str]:
        if self.selected is not None:
            return [self.selected["zone"]]
        row = self._planes.zone[self._slot]
        return [self._snapshot.zones[z] for z in np.nonzero(row)[0]]

    @property
    def capacity_types(self) -> List[str]:
        if self.selected is not None:
            return [self.selected["capacity_type"]]
        row = self._planes.ct[self._slot]
        return [self._snapshot.capacity_types[c] for c in np.nonzero(row)[0]]

    @property
    def requests(self) -> resources_util.ResourceList:
        row = self._planes.used[self._slot]
        return {
            name: float(row[r])
            for r, name in enumerate(self._snapshot.resources)
            if row[r] > 0
        }


def _attach_pol(snapshot, statics_arrays):
    """The snapshot's policy objective planes (policy.planes.planes_of),
    catalog-padded to the prep's instance-type extent.  The pol planes share
    the snapshot's I axis (attach_planes stamps them at encode time, after any
    mesh alignment), so the pad is a no-op in production — it guards planes
    prepared outside that path (pad value +inf price = never-selected, the
    same sentinel the encode uses for absent offerings)."""
    from karpenter_core_tpu.policy import planes as planes_mod

    pol = planes_mod.planes_of(snapshot)
    if pol is None:
        return None
    n_it = int(np.asarray(statics_arrays.it_alloc).shape[0])
    if int(np.asarray(pol.price).shape[0]) != n_it:
        pol = pol._replace(
            price=solve_ops._pad_axis(
                np.asarray(pol.price, dtype=np.float32), 0, n_it, np.inf
            ),
            risk=solve_ops._pad_axis(
                np.asarray(pol.risk, dtype=np.float32), 0, n_it, 0.0
            ),
            throughput=solve_ops._pad_axis(
                np.asarray(pol.throughput, dtype=np.float32), 0, n_it, 0.0
            ),
        )
    return pol


class SolvePrep(NamedTuple):
    """One snapshot's kernel inputs, prepared (and bucket-padded) once.

    The seam the incremental session (solver.incremental) needs: a delta
    reconcile reuses a previous reconcile's SolvePrep verbatim — same padded
    tensors, same executable shape — and only swaps the class-count vector,
    so the jit cache stays warm across the whole churn regime."""

    cls: object  # ops.solve.ClassTensors (padded host/device pytree)
    statics_arrays: object  # ops.solve.StaticArrays
    key_has_bounds: tuple
    ex_state: object  # Optional[ops.solve.ExistingState]
    ex_static: object  # Optional[ops.solve.ExistingStatic]
    n_slots: int
    n_passes: int
    features: object  # ops.solve.SnapshotFeatures
    # mesh topology the prep was built for (parallel.mesh.solve_mesh_axes at
    # prepare time; None = unsharded).  Captured HERE so a lineage of repairs
    # keeps dispatching onto the topology its carry is sharded over — the
    # incremental session escalates to a full solve when the live topology
    # moves (solver.incremental "mesh-changed")
    mesh_axes: object = None
    # policy objective planes (policy.planes.ObjectivePlanes) for the relax
    # solver family's linear cost — attached FRESH on every prepare (prices
    # move while the shape anchors stay identical, so the warm-prep fast path
    # must never serve a cached sheet); None when the snapshot predates the
    # policy encode.  The scan variants never read it.
    pol: object = None


@dataclass
class TPUSolveResults:
    new_nodes: List[TPUNodeDecision] = field(default_factory=list)
    # existing-node placements: node name -> pods nominated onto it
    existing_assignments: Dict[str, List[Pod]] = field(default_factory=dict)
    failed_pods: List[Pod] = field(default_factory=list)
    # pods the kernel could not place but flagged spread_suspect: the
    # zone-spread water-fill could not prove host-oracle parity for their
    # class, so the host might still place them — callers must either route
    # them through the host path (ProvisioningController._schedule_tpu does)
    # or treat them as failed; they are never silently dropped (VERDICT r2 #2)
    spread_residual_pods: List[Pod] = field(default_factory=list)
    # zone the kernel committed each assignment-carrying existing node to
    # (singleton post-solve zone masks only) — the host re-route stamps these
    # onto zone-less nodes so both engines see one consistent commitment
    existing_committed_zones: Dict[str, str] = field(default_factory=dict)
    n_slots_used: int = 0
    # policy objective results (ops.objective, set when the policy stage ran):
    # the summed selected-offering price over this solve's open slots, raw and
    # risk-weighted.  None when policy is disabled — the planes never ran.
    fleet_cost: Optional[float] = None
    fleet_expected_cost: Optional[float] = None


@dataclass
class LaunchableNode:
    """Launch-path adapter (duck-typed like solver.node.SchedulingNode):
    template + instance types + requests + pods, consumable by
    ProvisioningController.launch."""

    template: object
    instance_type_options: List[InstanceType]
    requests: dict
    pods: List[Pod] = field(default_factory=list)

    @property
    def provisioner_name(self) -> str:
        return self.template.provisioner_name

    @property
    def requirements(self):
        return self.template.requirements


class TPUSolver:
    def __init__(
        self,
        cloud_provider: CloudProvider,
        provisioners: List[Provisioner],
        daemonset_pods: Optional[List[Pod]] = None,
        kube_client=None,
        policy=None,
    ) -> None:
        # kube_client resolves PVC -> CSI driver for volume attach-limit
        # planes (volumeusage.go:65-90); None matches the host oracle's
        # behavior of treating unresolvable volumes as unconstrained
        self.kube_client = kube_client
        # the policy-objective config (policy.PolicyConfig): None/disabled =
        # feasibility-only decode, exactly the pre-policy pipeline.  The
        # provider handle stays on the solver so the risk planes can read its
        # live capacity-error state at encode time (policy.planes).
        self.policy = policy
        # the last cold solve's solver-family outcome ("scan" | "relax" |
        # "relax-fallback:<reason>") — observability convenience mirroring the
        # solve.mode span / karpenter_solve_mode_total counter
        self.last_solve_mode = "scan"
        self.cloud_provider = cloud_provider
        self.provisioners = order_by_weight(
            [p for p in provisioners if p.metadata.deletion_timestamp is None]
        )
        self.templates = [MachineTemplate.from_provisioner(p) for p in self.provisioners]
        self.instance_types: Dict[str, List[InstanceType]] = {
            p.name: cloud_provider.get_instance_types(p) for p in self.provisioners
        }
        overhead = _daemon_overhead(self.templates, daemonset_pods or [])
        for template in self.templates:
            template.requests = overhead[id(template)]
        self._it_by_name = {
            it.name: it for its in self.instance_types.values() for it in its
        }

    def encode(
        self,
        pods,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
    ) -> EncodedSnapshot:
        """Raises models.snapshot.KernelUnsupported when the batch needs the
        host path.  Existing-node label values widen the vocabulary so NotIn
        checks against them stay exact; bound pods' anti-affinity terms
        register as groups so their inverse blocking reaches the kernel.

        ``pods`` is a pod list or a models.columnar.PodIngest; with an ingest
        the per-pod classification cost was already paid at watch-event time
        and encode runs in O(distinct classes)."""
        from karpenter_core_tpu.models.columnar import PodIngest

        classes = None
        if isinstance(pods, PodIngest):
            classes = pods.classes()
            # class representatives cover every distinct label set, which is
            # all the anti-affinity relevance check below needs
            pods = [cls.pods[0] for cls in classes]
        return self._encode_with_classes(pods, classes, state_nodes, bound_pods)

    def encode_classes(
        self,
        classes: list,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
    ) -> EncodedSnapshot:
        """Encode from prebuilt PodClass objects (the class-columnar wire path:
        the channel ships one representative pod + count per distinct shape).
        Orders/validates the classes in place (models.snapshot.finalize_classes)."""
        from karpenter_core_tpu.models.snapshot import finalize_classes

        classes = finalize_classes(list(classes))
        reps = [cls.pods[0] for cls in classes]
        return self._encode_with_classes(reps, classes, state_nodes, bound_pods)

    def _encode_with_classes(
        self,
        pods: List[Pod],
        classes: Optional[list],
        state_nodes: Optional[list],
        bound_pods: Optional[List[Pod]],
    ) -> EncodedSnapshot:
        with tracing.span(
            "encode",
            classes=len(classes) if classes is not None else None,
            state_nodes=len(state_nodes or ()),
        ) as sp:
            snapshot = self._encode_with_classes_impl(
                pods, classes, state_nodes, bound_pods
            )
            # delta-consuming encode provenance: True when the class planes
            # were shared by reference from the previous same-shape encode
            sp.set(**{"encode.reused": snapshot.encode_reused})
            return snapshot

    def _encode_with_classes_impl(
        self,
        pods: List[Pod],
        classes: Optional[list],
        state_nodes: Optional[list],
        bound_pods: Optional[List[Pod]],
    ) -> EncodedSnapshot:
        from karpenter_core_tpu.models.snapshot import (
            GRP_ANTI,
            UNLIMITED,
            KernelUnsupported,
            _group_spec,
        )

        extra = [
            Requirements.from_labels(n.node.metadata.labels) for n in (state_nodes or [])
        ]
        from karpenter_core_tpu.models.snapshot import term_namespaces

        extra_anti = []
        for pod in bound_pods or []:
            affinity = pod.spec.affinity
            if affinity is None or affinity.pod_anti_affinity is None:
                continue
            for term in affinity.pod_anti_affinity.required:
                try:
                    spec = _group_spec(
                        GRP_ANTI, term.topology_key, term.label_selector, UNLIMITED,
                        term_namespaces(pod, term),
                    )
                except KernelUnsupported:
                    # an unrepresentable anti key/scope only matters if it can
                    # gate a scheduling pod: selector match within the term's
                    # static scope (or any pod when the scope is dynamic)
                    if term.namespace_selector is not None:
                        scoped = list(pods)
                    else:
                        scope_ns = term_namespaces(pod, term)
                        scoped = [p for p in pods if (p.namespace or "") in scope_ns]
                    if term.label_selector is not None and any(
                        term.label_selector.matches(p.metadata.labels) for p in scoped
                    ):
                        raise
                    continue
                extra_anti.append((spec, term.label_selector))
        from karpenter_core_tpu.models.snapshot import pod_port_keys

        extra_ports = [key for pod in bound_pods or [] for key in pod_port_keys(pod)]
        # shard-aligned catalog extent: when the sharded solve path is on
        # (parallel.mesh, KC_SOLVER_MESH), the encode pads the instance-type
        # axis to the mesh's catalog-axis multiple so the shard_map dispatch
        # splits it evenly — one consistent padded extent everywhere
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        snapshot = encode_snapshot(
            pods, self.provisioners, self.templates, self.instance_types,
            extra_requirement_sets=extra,
            extra_anti_groups=extra_anti,
            cache_host=self,
            extra_host_ports=extra_ports,
            classes=classes,
            catalog_pad_multiple=mesh_mod.catalog_pad_multiple(),
        )
        snapshot.class_volumes = self._resolve_class_volumes(
            snapshot.classes, state_nodes
        )
        # objective planes ride every encode (price sheet / risk priors /
        # throughput weights) so the ``policy`` digest group versions the
        # economics even while the objective stage itself is disabled
        from karpenter_core_tpu.policy import planes as policy_planes

        policy_planes.attach_planes(
            snapshot, self._it_by_name, config=self.policy,
            provider=self.cloud_provider,
        )
        return snapshot

    def _resolve_class_volumes(self, classes, state_nodes) -> list:
        """Per-class volume profile for the kernel's attach-limit planes
        (volumeusage.go:65-90 resolution).  Each entry:

          {"shared": {driver: {pvc ids}}, "per_pod": {driver: count}}

        Only drivers with a finite limit on some state node can ever bind
        (new nodes have no CSINode), so claims on unlimited drivers are
        dropped up front — sharing through them is harmless.  For the rest a
        class must be either SHARED (every member mounts the same claim set —
        the per-node contribution is count-independent) or PERPOD (members
        mount pairwise-disjoint sets with equal per-driver counts, nothing
        overlapping other classes or already-mounted sets — the contribution
        is count-dependent).  Anything else routes to the host path, as do
        unresolvable references (the host path surfaces the per-pod error)."""
        from karpenter_core_tpu.scheduling import VolumeUsage

        empty = [{"shared": {}, "per_pod": {}} for _ in classes]
        if self.kube_client is None:
            return empty
        limited = {
            driver
            for state_node in state_nodes or []
            for driver in state_node.volume_limits()
        }
        has_claims = any(
            v.persistent_volume_claim is not None
            for cls in classes
            for v in cls.pods[0].spec.volumes
        )
        if not limited or not has_claims:
            return empty

        mounted_ids = {
            pvc_id
            for state_node in state_nodes or []
            for driver, ids in state_node.volume_usage().volumes.items()
            if driver in limited
            for pvc_id in ids
        }
        usage = VolumeUsage(self.kube_client)
        resolve_cache: Dict[tuple, dict] = {}  # claim names -> limited-driver sets

        def resolve(pod) -> dict:
            key = (
                pod.namespace or "",
                tuple(
                    sorted(
                        v.persistent_volume_claim.claim_name
                        for v in pod.spec.volumes
                        if v.persistent_volume_claim is not None
                    )
                ),
            )
            hit = resolve_cache.get(key)
            if hit is None:
                volumes, err = usage._validate(pod)
                if err is not None:
                    raise KernelUnsupported(f"volume resolution: {err}")
                hit = {d: ids for d, ids in volumes.items() if d in limited}
                resolve_cache[key] = hit
            return hit

        class_volumes = []
        seen: Dict[str, int] = {}  # pvc id -> class index
        for c, cls in enumerate(classes):
            if cls.is_ladder_variant:
                # ladder variants schedule the ROOT's pods, so they carry the
                # root's volume profile — resolving their lone representative
                # would misread the shared claims as cross-class sharing
                class_volumes.append(None)
                continue
            member_sets = [resolve(pod) for pod in cls.pods]
            first = member_sets[0]
            for ids in first.values():
                for pvc_id in ids:
                    if seen.setdefault(pvc_id, c) != c:
                        raise KernelUnsupported(
                            f"pvc {pvc_id} shared across pod classes not kernel-supported"
                        )
            if all(m == first for m in member_sets):
                class_volumes.append({"shared": first, "per_pod": {}})
                continue
            # PERPOD: pairwise-disjoint member sets, uniform count vector,
            # nothing shared with other classes or already mounted
            counts = {d: len(ids) for d, ids in first.items()}
            all_ids: set = set()
            for m in member_sets:
                if {d: len(ids) for d, ids in m.items()} != counts:
                    raise KernelUnsupported(
                        "mixed volume shapes within a pod class not kernel-supported"
                    )
                for ids in m.values():
                    for pvc_id in ids:
                        if pvc_id in all_ids or pvc_id in mounted_ids:
                            raise KernelUnsupported(
                                f"pvc {pvc_id} shared across pods not kernel-supported"
                            )
                        if seen.setdefault(pvc_id, c) != c:
                            raise KernelUnsupported(
                                f"pvc {pvc_id} shared across pod classes not kernel-supported"
                            )
                        all_ids.add(pvc_id)
            class_volumes.append({"shared": {}, "per_pod": counts})
        # backfill variants with their root's profile (chain order: the root
        # always precedes its variants in the finalized class list)
        index_of = {id(cls): c for c, cls in enumerate(classes)}
        for c, cls in enumerate(classes):
            if cls.relax_to is not None:
                class_volumes[index_of[id(cls.relax_to)]] = class_volumes[c]
        return class_volumes

    def encode_existing(
        self,
        snapshot: EncodedSnapshot,
        state_nodes: list,
        bound_pods: Optional[List[Pod]] = None,
    ):
        """(ExistingState, ExistingStatic) numpy planes for the kernel; the
        per-group member/owner node counts seed the kernel's topology counts.

        Mirrors ExistingNode construction (existingnode.go:43-75): available
        capacity, remaining daemonset overhead, label requirements, ephemeral-
        taint-filtered toleration checks; and topology countDomains
        (topology.go:231-276) for pre-existing matching pods.
        """
        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.scheduling import Taints

        vocab = snapshot.vocab
        E = max(len(state_nodes), 1)
        C = len(snapshot.classes)
        R = len(snapshot.resources)
        Z = len(snapshot.zones)
        CT = len(snapshot.capacity_types)
        K, W = vocab.n_keys, vocab.width

        G1 = len(snapshot.groups) + 1
        used = np.zeros((E, R), dtype=np.float32)
        alloc = np.zeros((E, R), dtype=np.float32)
        kmask = np.ones((E, K, W), dtype=bool)
        kdef = np.zeros((E, K), dtype=bool)
        kneg = np.zeros((E, K), dtype=bool)
        kgt = np.full((E, K), -np.inf, dtype=np.float32)
        klt = np.full((E, K), np.inf, dtype=np.float32)
        zone = np.zeros((E, Z), dtype=bool)
        ct = np.zeros((E, CT), dtype=bool)
        pod_count = np.zeros(E, dtype=np.int32)
        open_ = np.zeros(E, dtype=bool)
        init = np.zeros(E, dtype=bool)
        tol = np.zeros((C, E), dtype=bool)
        P = len(snapshot.ports)
        ports = np.zeros((E, P), dtype=bool)
        grp_node_member = np.zeros((G1, E), dtype=np.int32)
        grp_node_owner = np.zeros((G1, E), dtype=np.int32)
        node_capacity = np.zeros((E, R), dtype=np.float32)
        node_tmpl = np.zeros(E, dtype=np.int32)
        node_owned = np.zeros(E, dtype=bool)
        port_idx = {key: i for i, key in enumerate(snapshot.ports)}
        tmpl_index = {t.provisioner_name: i for i, t in enumerate(self.templates)}

        tmpl_by_name = {t.provisioner_name: t for t in self.templates}
        zone_idx = {z: i for i, z in enumerate(snapshot.zones)}
        ct_idx = {c: i for i, c in enumerate(snapshot.capacity_types)}

        for e, state_node in enumerate(state_nodes):
            node = state_node.node
            available = state_node.available()
            for r, name in enumerate(snapshot.resources):
                alloc[e, r] = available.get(name, 0.0)
            template = tmpl_by_name.get(
                node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, "")
            )
            if template is not None and template.requests:
                remaining = resources_util.subtract(
                    template.requests, state_node.daemon_set_requests()
                )
                for r, name in enumerate(snapshot.resources):
                    used[e, r] = max(remaining.get(name, 0.0), 0.0)
            reqs = Requirements.from_labels(node.metadata.labels)
            kmask[e], kdef[e], kneg[e], kgt[e], klt[e] = vocab.encode_requirements(reqs)
            z = node.metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE)
            if z is None:
                zone[e, :] = True  # unknown zone: any
            elif z in zone_idx:
                zone[e, zone_idx[z]] = True
            c_label = node.metadata.labels.get(labels_api.LABEL_CAPACITY_TYPE)
            if c_label is None:
                ct[e, :] = True
            elif c_label in ct_idx:
                ct[e, ct_idx[c_label]] = True
            open_[e] = True
            init[e] = state_node.initialized()
            capacity = state_node.capacity()
            for r, name in enumerate(snapshot.resources):
                node_capacity[e, r] = capacity.get(name, 0.0)
            t_idx = tmpl_index.get(
                node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, "")
            )
            if t_idx is not None:
                node_tmpl[e] = t_idx
                node_owned[e] = True
            taints = Taints.of(state_node.taints())
            for c, cls in enumerate(snapshot.classes):
                tol[c, e] = taints.tolerates(cls.pods[0]) is None

        # pre-existing pod counts per topology group (countDomains semantics,
        # topology.go:231-276): members (forward) and anti-term owners
        # (inverse); pods being scheduled this solve are excluded
        from karpenter_core_tpu.models.snapshot import (
            GRP_ANTI,
            UNLIMITED,
            _group_spec,
            term_namespaces,
        )

        node_index = {n.node.name: e for e, n in enumerate(state_nodes)}
        group_of = {spec: g for g, spec in enumerate(snapshot.groups)}
        scheduling_uids = {p.uid for cls in snapshot.classes for p in cls.pods}
        for pod in bound_pods or []:
            e = node_index.get(pod.spec.node_name)
            if e is None or pod.uid in scheduling_uids:
                continue
            from karpenter_core_tpu.models.snapshot import pod_port_keys as _ppk

            for key in _ppk(pod):
                i = port_idx.get(key)
                if i is not None:
                    ports[e, i] = True
            for g, scope in enumerate(snapshot.group_selectors):
                if scope is not None and scope.matches_pod(pod):
                    grp_node_member[g, e] += 1
            affinity = pod.spec.affinity
            if affinity is not None and affinity.pod_anti_affinity is not None:
                for term in affinity.pod_anti_affinity.required:
                    try:
                        spec = _group_spec(
                            GRP_ANTI, term.topology_key, term.label_selector,
                            UNLIMITED, term_namespaces(pod, term),
                        )
                    except Exception:  # noqa: BLE001 - unsupported keys don't track
                        continue
                    g = group_of.get(spec)
                    if g is not None:
                        grp_node_owner[g, e] += 1

        # -- volume attach-limit planes (volumeusage.go:33-236 as per-driver
        # counters; existingnode.go:77-130 enforcement).  Only existing nodes
        # carry limits (CSINode); the axis covers drivers mounted by a
        # scheduling class plus drivers already over their limit (which block
        # every add, volume-less pods included — VolumeCount.exceeds).
        from karpenter_core_tpu.models.snapshot import UNLIMITED

        class_volumes = snapshot.class_volumes or [
            {"shared": {}, "per_pod": {}} for _ in snapshot.classes
        ]
        drivers = sorted(
            {d for vols in class_volumes for d in vols["shared"]}
            | {d for vols in class_volumes for d in vols["per_pod"]}
        )
        for state_node in state_nodes:
            limits = state_node.volume_limits()
            mounted = state_node.volume_usage().volumes
            for d, lim in limits.items():
                if d not in drivers and len(mounted.get(d, ())) > lim:
                    drivers.append(d)
        D = max(len(drivers), 1)
        vol_used = np.zeros((E, D), dtype=np.int32)
        vol_limit = np.full((E, D), UNLIMITED, dtype=np.int32)
        cls_vol_add = np.zeros((C, E, D), dtype=np.int32)
        cls_vol_per_pod = np.zeros((C, D), dtype=np.int32)
        for i, d in enumerate(drivers):
            for c, vols in enumerate(class_volumes):
                cls_vol_per_pod[c, i] = vols["per_pod"].get(d, 0)
        for e, state_node in enumerate(state_nodes):
            mounted = state_node.volume_usage().volumes
            limits = state_node.volume_limits()
            for i, d in enumerate(drivers):
                have = mounted.get(d, set())
                vol_used[e, i] = len(have)
                if d in limits:
                    vol_limit[e, i] = limits[d]
                for c, vols in enumerate(class_volumes):
                    new = vols["shared"].get(d)
                    if new:
                        cls_vol_add[c, e, i] = len(new - have)

        # planes stay numpy: utils.compilecache bucket-pads them before the
        # device upload (ops/solve.pad_planes), so converting here would cost
        # an extra round trip over the relay
        ex_state = solve_ops.ExistingState(
            used=np.asarray(used),
            kmask=np.asarray(kmask),
            kdef=np.asarray(kdef),
            kneg=np.asarray(kneg),
            kgt=np.asarray(kgt),
            klt=np.asarray(klt),
            zone=np.asarray(zone),
            ct=np.asarray(ct),
            ports=np.asarray(ports),
            vol_used=np.asarray(vol_used),
            pod_count=np.asarray(pod_count),
            open_=np.asarray(open_),
        )
        ex_static = solve_ops.ExistingStatic(
            alloc=np.asarray(alloc),
            init=np.asarray(init),
            tol=np.asarray(tol),
            grp_node_member=np.asarray(grp_node_member),
            grp_node_owner=np.asarray(grp_node_owner),
            node_capacity=np.asarray(node_capacity),
            node_tmpl=np.asarray(node_tmpl),
            node_owned=np.asarray(node_owned),
            vol_limit=np.asarray(vol_limit),
            cls_vol_add=np.asarray(cls_vol_add),
            cls_vol_per_pod=np.asarray(cls_vol_per_pod),
        )
        return ex_state, ex_static

    def solve(
        self,
        pods,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
        n_slots: int = 0,
    ) -> TPUSolveResults:
        with tracing.span("tpu.solve"):
            snapshot = self.encode(pods, state_nodes, bound_pods)
            return self.solve_encoded(snapshot, state_nodes, bound_pods, n_slots)

    def warmup(
        self,
        n_pods: int = 4096,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
    ) -> bool:
        """Speculatively build the solve executable for the standard shape
        buckets before the first real batch needs it (the compile hides under
        the batcher's 10 s max window, settings.go:39-40 parity).

        The synthetic mix covers the common class shapes — several request
        sizes, a zonal spread, a hostname spread — against the REAL catalog
        and templates, so the padded buckets (ops/solve.pad_planes) this
        compiles are the ones steady-state batches land in.  Runs end to end
        (encode → compile → tiny device solve).  Purely an optimization: any
        failure returns False and the first real solve compiles as before.
        """
        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.apis.objects import (
            Affinity,
            Container,
            LabelSelector,
            ObjectMeta,
            PodAffinity,
            PodAffinityTerm,
            PodSpec,
            ResourceRequirements,
            TopologySpreadConstraint,
        )

        def pod(requests, labels=None, spread_key=None, affinity_key=None):
            spec = PodSpec(
                containers=[Container(resources=ResourceRequirements(requests=dict(requests)))]
            )
            if spread_key is not None:
                spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=spread_key,
                        label_selector=LabelSelector(match_labels=dict(labels)),
                    )
                ]
            if affinity_key is not None:
                spec.affinity = Affinity(
                    pod_affinity=PodAffinity(
                        required=[
                            PodAffinityTerm(
                                topology_key=affinity_key,
                                label_selector=LabelSelector(match_labels=dict(labels)),
                            )
                        ]
                    )
                )
            return Pod(
                metadata=ObjectMeta(name="warmup", labels=dict(labels or {})),
                spec=spec,
            )

        # the mix spans the common SnapshotFeatures tier (zone/host spread +
        # zone self-affinity), so the feature-keyed executable this compiles
        # is the one steady-state batches request (or a superset
        # compilecache.snap_features widens them to)
        protos = [
            pod({"cpu": 0.5, "memory": 512 * 2**20}),
            pod({"cpu": 1.0, "memory": 2 * 2**30}),
            pod({"cpu": 0.25, "memory": 256 * 2**20}, {"app": "warm-zspread"},
                labels_api.LABEL_TOPOLOGY_ZONE),
            pod({"cpu": 0.25, "memory": 256 * 2**20}, {"app": "warm-hspread"},
                labels_api.LABEL_HOSTNAME),
            pod({"cpu": 0.25, "memory": 256 * 2**20}, {"app": "warm-zaff"},
                affinity_key=labels_api.LABEL_TOPOLOGY_ZONE),
        ]
        per = max(n_pods // len(protos), 1)
        pods: List[Pod] = []
        for proto in protos:
            pods.extend([proto] * per)  # shared objects: shapes, not identity
        try:
            self.solve(pods, state_nodes, bound_pods)
            return True
        except Exception as e:  # noqa: BLE001 - warmup must never surface
            import logging

            logging.getLogger(__name__).debug("kernel warmup failed: %s", e)
            return False

    # snapshot fields whose identity anchors the warm-prep reuse: everything
    # prepare_host reads EXCEPT cls_count (the per-tick delta).  The
    # delta-native encode shares these by reference across same-shape ticks,
    # so a repeat prepare ships only the fresh count vector.
    _PREP_ANCHOR_FIELDS = (
        "cls_mask", "cls_defined", "cls_negative", "cls_gt", "cls_lt",
        "cls_zone", "cls_ct", "cls_it", "cls_requests", "cls_tol", "cls_ports",
        "cls_groups", "cls_relax_next", "cls_anti_soft", "cls_root",
        "it_mask", "it_defined", "it_negative", "it_gt", "it_lt",
        "it_alloc", "it_avail", "it_capacity",
        "tmpl_mask", "tmpl_defined", "tmpl_negative", "tmpl_gt", "tmpl_lt",
        "tmpl_zone", "tmpl_ct", "tmpl_it", "tmpl_daemon", "tmpl_limits",
        "valid", "is_custom", "vocab_ints",
        "grp_skew", "grp_is_zone", "grp_is_anti", "grp_member",
    )

    def prepare_encoded(
        self,
        snapshot: EncodedSnapshot,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
        n_slots: int = 0,
    ) -> SolvePrep:
        """Kernel inputs for one encoded snapshot, existing-node planes
        included, bucket-padded (unless KC_TPU_SHAPE_BUCKETS=0) and ready for
        ``run_prepared``.  ``KC_BUCKET_QUANTIZE`` selects the coarser
        powers-of-two padding ladder (ops.solve.bucket_quantize_enabled):
        mixed-size tenants quantize into fewer distinct shape buckets, so
        more of them fuse onto one coalesced executable (docs/SERVICE.md
        "Solve fusion").  Splitting prepare from run is what lets the
        incremental session hold a prep across reconciles and re-run it with
        a delta count vector + warm carry (docs/INCREMENTAL.md).

        Two delta-native fast paths (docs/KERNEL_PERF.md "Layer 6"): when the
        snapshot's shape planes are IDENTICAL (by reference — the delta
        encode's contract) to the last prepared ones and no existing-node
        planes are needed, the previous prep is reused with only a fresh
        padded count vector — the compact delta is all that moves.  And with
        KC_ENCODE_DEVICE_FINISH=1 the class-plane bucket padding is assembled
        on device under a small jit instead of host np.pad."""
        from karpenter_core_tpu.parallel import mesh as mesh_mod
        from karpenter_core_tpu.utils import compilecache

        ex_state = ex_static = None
        if state_nodes:
            with tracing.span("encode.existing", state_nodes=len(state_nodes)):
                ex_state, ex_static = self.encode_existing(
                    snapshot, state_nodes, bound_pods
                )
        if n_slots <= 0:
            n_slots = solve_ops.estimate_slots(snapshot)  # snap_slots applied inside
        features = solve_ops.features_with_existing(snapshot, ex_static)
        pad = os.environ.get("KC_TPU_SHAPE_BUCKETS", "1") != "0"
        anchors = None
        if ex_state is None and pad:
            # the quantize flag rides the anchor tuple: a mid-process flip
            # (bench A/B legs, tests) must not serve a prep padded under the
            # other grid
            anchors = tuple(
                getattr(snapshot, f, None) for f in self._PREP_ANCHOR_FIELDS
            ) + (solve_ops.bucket_quantize_enabled(),)
            cached = getattr(self, "_prep_cache", None)
            if cached is not None and all(
                a is b for a, b in zip(cached["anchors"], anchors)
            ):
                prev: SolvePrep = cached["prep"]
                c_pad = np.asarray(prev.cls.count).shape[0]
                count = solve_ops._pad_axis(
                    np.asarray(snapshot.cls_count, dtype=np.int32), 0, c_pad, 0
                )
                return SolvePrep(
                    cls=prev.cls._replace(count=count),
                    statics_arrays=prev.statics_arrays,
                    key_has_bounds=prev.key_has_bounds,
                    ex_state=None, ex_static=None,
                    n_slots=n_slots, n_passes=snapshot.scan_passes,
                    features=features,
                    mesh_axes=compilecache.resolve_mesh_axes(
                        mesh_mod.solve_mesh_axes(),
                        solve_ops.StaticArrays(*prev.statics_arrays),
                    ),
                    pol=_attach_pol(
                        snapshot, solve_ops.StaticArrays(*prev.statics_arrays)
                    ),
                )
        cls, statics_arrays, key_has_bounds = solve_ops.prepare_host(snapshot)
        if pad:
            cls, statics_arrays, key_has_bounds, ex_state, ex_static = (
                solve_ops.pad_planes(
                    cls, statics_arrays, key_has_bounds, ex_state, ex_static,
                    device_finish=solve_ops.encode_device_finish_enabled(),
                )
            )
        prep = SolvePrep(
            cls=cls, statics_arrays=statics_arrays, key_has_bounds=key_has_bounds,
            ex_state=ex_state, ex_static=ex_static, n_slots=n_slots,
            n_passes=snapshot.scan_passes, features=features,
            mesh_axes=compilecache.resolve_mesh_axes(
                mesh_mod.solve_mesh_axes(), solve_ops.StaticArrays(*statics_arrays)
            ),
            pol=_attach_pol(
                snapshot, solve_ops.StaticArrays(*statics_arrays)
            ),
        )
        if anchors is not None:
            self._prep_cache = {"anchors": anchors, "prep": prep}
        return prep

    def run_prepared(
        self,
        prep: SolvePrep,
        count=None,
        warm_carry=None,
        repair_plan=None,
        n_slots: int = 0,
        donate_carry=None,
    ) -> solve_ops.SolveOutputs:
        """Run the kernel on a SolvePrep.  ``count`` overrides the class-count
        vector (the repair solve passes only the delta pods; shape must match
        the padded class axis); ``warm_carry`` resumes from a previous solve's
        final carry (ops.solve.WarmCarry); ``repair_plan`` carries the freed-
        hole planes the repair's fills refill first plus the out-of-window
        topology bases of a bounded repair (ops.solve.RepairPlan).
        Returns raw SolveOutputs — device-resident futures (dispatch is
        asynchronous); decode is the caller's step, and ``begin_fetch``
        splits its device→host copy from the completion barrier so a
        pipelined caller overlaps the next dispatch with this one's fetch.

        Warm dispatches DONATE the carry's device buffers when the pipeline
        is armed (utils.pipeline, KC_PIPELINE=0 disarms): the caller must
        not read ``warm_carry`` after this call (the ``donated-read``
        kcanalyze rule).  An enabled policy objective keeps donation off —
        its decode stage re-reads the final state planes on device after
        the dispatch (ops.objective.select_for_state), and those planes
        alias the donated memory one tick later.  ``donate_carry`` overrides
        the auto decision (the incremental session passes False for
        dispatches routed through the service coalescer, whose batched
        executable stacks member carries and cannot donate them); an enabled
        policy still forces donation off."""
        from karpenter_core_tpu.utils import compilecache

        cls = prep.cls
        if count is not None:
            cls = cls._replace(count=np.asarray(count, dtype=np.int32))
        # -- solver-mode dispatch (solver/modes.py, docs/RELAX.md) ------------
        # Cold solves only: a warm-carry repair resumes SCAN state and a
        # repair_plan means this call IS the relax family's own cleanup pass
        # (relax.solve.run_relax re-enters run_prepared with both set, which
        # is also what makes this hook non-recursive).
        if warm_carry is None and repair_plan is None:
            mode = modes_mod.resolve_mode(self.policy)
            if mode != modes_mod.MODE_SCAN:
                n_pods = int(np.asarray(cls.count, dtype=np.int64).sum())
                if modes_mod.relax_selected(mode, n_pods):
                    from karpenter_core_tpu.relax import solve as relax_solve
                    from karpenter_core_tpu.solver.incremental import SOLVE_MODE

                    with tracing.span("solve.mode", mode=mode,
                                      pods=n_pods) as sp:
                        try:
                            out = relax_solve.run_relax(
                                self, prep, cls=cls, n_slots=n_slots
                            )
                        except relax_solve.RelaxFallback as fb:
                            # the scan below runs as if relax never existed;
                            # only the structured reason is left behind
                            sp.set(selected="relax-fallback", reason=fb.reason)
                            SOLVE_MODE.labels("relax-fallback").inc()
                            self.last_solve_mode = f"relax-fallback:{fb.reason}"
                        else:
                            sp.set(selected="relax")
                            SOLVE_MODE.labels("relax").inc()
                            self.last_solve_mode = "relax"
                            return out
                else:
                    self.last_solve_mode = "scan"
            else:
                self.last_solve_mode = "scan"
        ex_static = prep.ex_static
        if warm_carry is not None and ex_static is None:
            # the warm variant always takes the ex-static planes (its tol/vol
            # rows are per-class); synthesize the empty ones the full solve
            # built internally so the repair sees identical semantics
            # (shape reads only — the prep's planes may be device-resident)
            n_res = prep.cls.requests.shape[-1]
            n_classes = cls.count.shape[0]
            g1 = prep.statics_arrays.grp_skew.shape[0]
            ex_static = solve_ops.empty_existing_static(n_res, n_classes, g1)
        donate = "auto" if donate_carry is None else bool(donate_carry)
        if self.policy is not None and getattr(self.policy, "enabled", False):
            donate = False
        from karpenter_core_tpu.utils import pipeline as pipeline_mod
        from karpenter_core_tpu.utils import watchdog

        # deadline-bounded dispatch (utils/watchdog.py): keyed on the same
        # static identity the compile cache keys its executable on (shape
        # bucket via n_slots/passes/features + mesh topology), so warm
        # latencies of different programs budget separately and a hung
        # relay surfaces as a structured SolveTimeout, not a wedged worker
        return watchdog.run(
            "solve.dispatch",
            compilecache.run_solve,
            cls, prep.statics_arrays, n_slots or prep.n_slots, prep.key_has_bounds,
            None if warm_carry is not None else prep.ex_state,
            ex_static,
            key=(
                int(n_slots or prep.n_slots), int(prep.n_passes),
                # SNAPPED features, matching the executable run_solve will
                # actually pick: raw variants that widen to one covering
                # executable must share one deadline budget
                tuple(compilecache.snap_features(prep.features))
                if prep.features is not None else None,
                getattr(prep, "mesh_axes", None),
                warm_carry is not None,
                # executable-variant axes that recompile without moving the
                # shape identity: a flip (KC_PIPELINE, policy toggling
                # donation, kernel triage flags) must budget as a fresh
                # cold key, not spike a warm EWMA into a spurious timeout
                donate, pipeline_mod.donation_enabled(),
                compilecache.kernel_flags(),
            ),
            n_passes=prep.n_passes,
            features=prep.features,
            warm_carry=warm_carry,
            repair_plan=repair_plan,
            pre_padded=True,
            # the prep's captured topology, NOT "auto": a warm carry's plane
            # layout must keep matching the executable it resumes into even
            # if the live mesh config moves mid-lineage
            mesh_axes=getattr(prep, "mesh_axes", None),
            donate_carry=donate,
        )

    # ``begin_fetch``'s small-plane tuple layout.  The settle/exhaustion
    # checks here and in solver.incremental consume the fetched tuple by
    # these indices — extend the tuple ONLY by appending, and keep this
    # block in lockstep with the tuple construction below.
    FETCH_ASSIGN = 0
    FETCH_ASSIGN_EX = 1
    FETCH_FAILED = 2
    FETCH_SUSPECT = 3
    FETCH_EX_ZONE = 4
    FETCH_POD_COUNT = 5
    FETCH_TMPL_ID = 6
    FETCH_OPEN = 7
    FETCH_N_NEXT = 8

    @classmethod
    def fetch_exhausted(cls, fetched, slots) -> bool:
        """Slot-exhaustion verdict over a fetched begin_fetch tuple: pods
        failed AND the scan consumed every slot it was given.  The ONE
        definition every escalation path shares — solve_encoded's retry,
        the deferred anchor's settle, and the deferred repair's
        window-overflow check (solver.incremental)."""
        return (
            int(np.sum(fetched[cls.FETCH_FAILED])) > 0
            and int(fetched[cls.FETCH_N_NEXT]) >= int(slots)
        )

    def upload_prep(self, prep: SolvePrep) -> SolvePrep:
        """Upload a SolvePrep's padded planes to the device ONCE (with the
        prep's captured mesh shardings) and return the device-resident prep.
        The incremental session adopts this after every full solve: steady
        churn repairs then re-dispatch over the SAME device buffers tick
        after tick — ``device_put`` is a no-op for device-resident leaves,
        so only the fresh per-tick count vector ever crosses the host→device
        boundary again (docs/KERNEL_PERF.md "Layer 7"; the host→device twin
        of the warm carry's donation)."""
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        trees = (prep.cls, prep.statics_arrays, prep.ex_state, prep.ex_static)
        mesh_axes = getattr(prep, "mesh_axes", None)
        if mesh_axes is None:
            up = jax.device_put(trees)
        else:
            up = jax.device_put(
                trees,
                mesh_mod.mesh_shardings(trees, mesh_mod.mesh_for(mesh_axes)),
            )
        return prep._replace(
            cls=up[0], statics_arrays=up[1], ex_state=up[2], ex_static=up[3]
        )

    def begin_fetch(self, outputs: solve_ops.SolveOutputs, ring=None):
        """Split decode's fetch from its dispatch: start non-blocking
        device→host copies of every array decode consumes (the small planes
        first, the big lazy planes behind them) and return the
        utils.pipeline.FetchTicket whose ``wait()`` is the completion
        barrier.  ``decode(..., fetched=ticket)`` then materializes without
        re-touching the device — the seam that lets solve[k+1]'s dispatch
        overlap decode[k]'s copy and host expansion (docs/KERNEL_PERF.md
        "Layer 7").  ``ring`` stages the fetched arrays into reusable host
        buffers (the pipelined session's double-buffer)."""
        from karpenter_core_tpu.utils import pipeline as pipeline_mod

        state = outputs.state
        small = (
            outputs.assign,
            outputs.assign_existing,
            outputs.failed,
            outputs.spread_suspect,
            outputs.ex_state.zone,
            state.pod_count,
            state.tmpl_id,
            state.open_,
            state.n_next,
        )
        ticket = pipeline_mod.FetchTicket(small, ring=ring, label="decode")
        planes = _LazyPlanes(state)
        planes.prefetch()  # big planes ride the link behind the small fetch
        ticket.planes = planes
        return ticket

    def solve_encoded(
        self,
        snapshot: EncodedSnapshot,
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
        n_slots: int = 0,
    ) -> TPUSolveResults:
        from karpenter_core_tpu.solver.backendprobe import SOLVER_DISPATCH

        fault = SOLVER_DISPATCH.hit(
            kinds=("error", "timeout"), op="solve", classes=len(snapshot.classes)
        )
        if fault is not None and fault.kind in ("error", "timeout"):
            # surface exactly like a dead relay: a RuntimeError from the
            # first device op, which the provisioning breaker counts
            raise RuntimeError(fault.describe())

        prep = self.prepare_encoded(snapshot, state_nodes, bound_pods, n_slots)
        outputs = self.run_prepared(prep)
        # slot exhaustion: retry once with double capacity.  ONE ticket
        # serves both the exhaustion check and decode (the relay costs
        # ~67 ms per round trip — the old path fetched n_next/failed twice).
        ticket = self.begin_fetch(outputs)
        fetched = ticket.wait()
        slots = outputs.assign.shape[1]
        if self.fetch_exhausted(fetched, slots):
            outputs = self.run_prepared(prep, n_slots=slots * 2)
            ticket = self.begin_fetch(outputs)
        return self.decode(snapshot, outputs, state_nodes or [], fetched=ticket)

    def decode(
        self,
        snapshot: EncodedSnapshot,
        outputs: solve_ops.SolveOutputs,
        state_nodes: Optional[list] = None,
        fetched=None,
    ) -> TPUSolveResults:
        with tracing.span("decode") as sp:
            results = self._decode_impl(snapshot, outputs, state_nodes, fetched)
            self._apply_policy_selection(snapshot, outputs, results)
            sp.set(
                new_nodes=len(results.new_nodes),
                failed=len(results.failed_pods),
                residual=len(results.spread_residual_pods),
            )
            return results

    def _apply_policy_selection(self, snapshot, outputs, results) -> None:
        """The policy-objective stage folded into decode: one batched argmin
        over every open slot's feasible (instance type, zone, capacity type)
        cells (ops.objective), stamped onto the node decisions so the launch
        lands on the selected offering.  A no-op (zero device work) unless
        the solver's PolicyConfig enables the objective."""
        config = self.policy
        if config is None or not getattr(config, "enabled", False):
            return
        from karpenter_core_tpu.policy import planes as policy_planes

        planes = policy_planes.planes_of(snapshot)
        if planes is None:
            return
        from karpenter_core_tpu.ops import objective as objective_ops

        with tracing.span("decode.objective", nodes=len(results.new_nodes)):
            selection = objective_ops.select_for_state(
                outputs.state, planes, config, snapshot.capacity_types
            )
        for decision in results.new_nodes:
            n = decision._slot
            if not bool(selection.active[n]):
                continue
            decision.selected = {
                "instance_type": snapshot.it_names[int(selection.sel_it[n])],
                "zone": snapshot.zones[int(selection.sel_zone[n])],
                "capacity_type": snapshot.capacity_types[int(selection.sel_ct[n])],
                "price": float(selection.price[n]),
                "expected": float(selection.expected[n]),
            }
        results.fleet_cost = float(selection.fleet_cost)
        results.fleet_expected_cost = float(selection.fleet_expected)
        from karpenter_core_tpu.metrics.registry import POLICY_FLEET_COST

        POLICY_FLEET_COST.labels("price").set(results.fleet_cost)
        POLICY_FLEET_COST.labels("expected").set(results.fleet_expected_cost)

    def _decode_impl(
        self,
        snapshot: EncodedSnapshot,
        outputs: solve_ops.SolveOutputs,
        state_nodes: Optional[list] = None,
        fetched=None,
    ) -> TPUSolveResults:
        # NOTE: solver.incremental._locate_pods mirrors this walk's pod
        # consumption order (root-shared cursors, existing before new, index
        # order within each) to label pod -> slot for the repair path; a
        # change to the order here must be mirrored there (the tier-1 parity
        # fuzz in tests/test_incremental.py catches drift loudly).
        #
        # Every device→host copy was started at begin_fetch time (at the
        # dispatch site when the caller pipelines; here otherwise) so the
        # transfers overlap whatever host work ran since; everything eager
        # lands in ONE batched device_get — the relay is a high-latency
        # tunnel (~67 ms per round trip), and the n_next scalar as a bare
        # int() would cost a full round trip of its own.  Big planes stay
        # lazy until consumed (launch path).
        ticket = fetched if fetched is not None else self.begin_fetch(outputs)
        planes = ticket.planes
        # the fetch is its own child span so the decode stage splits into
        # device→host transfer vs host expansion — the boundary the decode
        # pipelining work needs independently visible (docs/KERNEL_PERF.md).
        # ``prefetched`` marks a completion barrier that already ran at the
        # pipelined settle (exposed wait ≈ 0 here); without an upstream sync
        # (ops/solve.sync_outputs) a cold barrier also absorbs any
        # still-running device compute.
        with tracing.span("decode.fetch", arrays=9, batched=True,
                          prefetched=ticket.done(), staged=ticket.staged):
            (assign, assign_ex, failed, suspect, ex_zone, pod_count, tmpl_id,
             open_, n_next) = ticket.wait()

        results = TPUSolveResults(n_slots_used=int(n_next))
        nodes: Dict[int, TPUNodeDecision] = {}
        provisioner_names = [t.provisioner_name for t in self.templates]
        for n in np.nonzero(open_ & (pod_count > 0))[0]:
            n = int(n)
            nodes[n] = TPUNodeDecision(
                provisioner_names[int(tmpl_id[n])], snapshot, planes, n
            )

        state_nodes = state_nodes or []
        # preference-ladder variants schedule pods from their ROOT's list: all
        # rows of one ladder share a cursor into the root's (identical) pods
        n_classes = len(snapshot.classes)
        if snapshot.cls_root is not None:
            root_of = [int(r) for r in snapshot.cls_root]
        else:
            root_of = list(range(n_classes))
        cursors = [0] * n_classes  # keyed by root index
        assigned_ex_idx: set = set()
        for c, cls in enumerate(snapshot.classes):
            r = root_of[c]
            pods, cursor = snapshot.classes[r].pods, cursors[r]
            # existing-node placements first (they were tried first in-kernel)
            ex_idx = np.nonzero(assign_ex[c] > 0)[0]
            for e, take in zip(ex_idx.tolist(), assign_ex[c][ex_idx].tolist()):
                if e < len(state_nodes):
                    name = state_nodes[e].node.name
                    results.existing_assignments.setdefault(name, []).extend(
                        pods[cursor : cursor + take]
                    )
                    assigned_ex_idx.add(e)
                cursor += take
            node_idx = np.nonzero(assign[c] > 0)[0]
            counts = assign[c][node_idx]
            for n, take in zip(node_idx.tolist(), counts.tolist()):
                nodes[n].pods.extend(pods[cursor : cursor + take])
                cursor += take
            cursors[r] = cursor
        # leftovers: spread_suspect classes (any ladder row) hand their pods to
        # the host re-route instead of failing them outright — the kernel could
        # not prove the water-fill matched the host oracle for those shapes.
        # (Required zonal anti never reaches the kernel: the iterative host
        # retroactively narrows anti nodes' zones as other pods co-locate,
        # which the forward scan cannot replay — classify routes it,
        # models/snapshot.py.)
        suspect_root = [False] * n_classes
        if suspect is not None:
            for c in range(n_classes):
                if bool(suspect[c]):
                    suspect_root[root_of[c]] = True
        for c, cls in enumerate(snapshot.classes):
            if root_of[c] != c:
                continue
            leftover = cls.pods[cursors[c] :]
            if not leftover:
                continue
            scope = cls.selectors.get(cls.zone_spread) if cls.zone_spread else None
            is_member = scope is not None and scope.matches_pod(cls.pods[0])
            if suspect_root[c] and is_member:
                results.spread_residual_pods.extend(leftover)
            else:
                results.failed_pods.extend(leftover)
                if tracing.enabled():
                    # the kernel reports failure per class, not per predicate:
                    # identical pods fail identically, so one audit entry
                    # covers the class (decode cannot see which gate zeroed
                    # the capacity — the host oracle's audit can)
                    tracing.record_unschedulable(
                        leftover[0],
                        engine="kernel",
                        count=len(leftover),
                        error="no viable placement for pod class (kernel solve)",
                    )
        # kernel zone commitments on existing nodes (singleton post-solve
        # masks): the host re-route stamps these onto zone-less nodes
        ex_zone_h = np.asarray(ex_zone, dtype=bool)
        for e in sorted(assigned_ex_idx):
            mask = ex_zone_h[e]
            if int(mask.sum()) == 1:
                z = int(np.argmax(mask))
                if z < len(snapshot.zones):
                    results.existing_committed_zones[state_nodes[e].node.name] = (
                        snapshot.zones[z]
                    )
        results.new_nodes = [nodes[n] for n in sorted(nodes)]
        return results

    def to_launchable(self, decision: TPUNodeDecision) -> LaunchableNode:
        """Convert a kernel node decision into a launch-path object: the
        provisioner's template with zone/capacity-type pinned to the decision's
        surviving domains and the viable instance-type list attached."""
        return self._build_launchable(
            decision.provisioner_name, decision.zones,
            decision.instance_type_names, decision.requests, decision.pods,
            # the pods' merged capacity-type requirement must ride the launch
            # exactly like zones (node.go:62-117 merge): without it the
            # provider's cheapest-offering pick can land an on-demand-required
            # pod on spot (found by testing/validator.py over fuzz seeds)
            capacity_types=decision.capacity_types,
        )

    def launchable_from_wire(self, entry: dict, pods: List[Pod]) -> LaunchableNode:
        """to_launchable for a remote solve: the snapshot channel's newNodes
        entry ({provisioner, instanceTypes, zones, capacityTypes?, requests})
        instead of an in-process decision.  No encode ran locally, so instance
        types resolve against this solver's catalog by name (wire order
        preserved — it is the decision's viability order from the serving
        side)."""
        return self._build_launchable(
            entry["provisioner"], list(entry.get("zones") or ()),
            list(entry.get("instanceTypes") or ()),
            {k: float(v) for k, v in (entry.get("requests") or {}).items()},
            pods,
            capacity_types=list(entry.get("capacityTypes") or ()),
        )

    def _build_launchable(self, provisioner_name, zones, instance_type_names,
                          requests, pods, capacity_types=()) -> LaunchableNode:
        from dataclasses import replace as dc_replace

        from karpenter_core_tpu.apis.objects import OP_IN

        template = next(
            t for t in self.templates if t.provisioner_name == provisioner_name
        )
        requirements = Requirements(*template.requirements.values())
        if zones:
            requirements.add(
                Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, list(zones))
            )
        if capacity_types:
            # consolidation's price rules may have pinned spot-only
            requirements.add(
                Requirement(labels_api.LABEL_CAPACITY_TYPE, OP_IN, list(capacity_types))
            )
        options = [
            self._it_by_name[name]
            for name in instance_type_names
            if name in self._it_by_name
        ]
        return LaunchableNode(
            template=dc_replace(template, requirements=requirements),
            instance_type_options=options,
            requests=dict(requests),
            pods=list(pods),
        )


__all__ = ["TPUSolver", "TPUSolveResults", "TPUNodeDecision", "KernelUnsupported"]
