"""TPU solver facade: encode → kernel → decode.

Stands behind the same Solve() contract as the host Scheduler
(solver.scheduler) for the batch shapes the kernel models (see
models.snapshot.classify_pods); callers use ``supports()``/KernelUnsupported to
route between the tensor path and the host path.  This is the Solver the
BASELINE.json north star describes: cluster snapshots in, node decisions out,
with the bin-pack running as a batch tensor program on the TPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner, order_by_weight
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.models.snapshot import (
    EncodedSnapshot,
    KernelUnsupported,
    encode_snapshot,
)
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.scheduler import _daemon_overhead
from karpenter_core_tpu.utils import resources as resources_util


class TPUNodeDecision:
    """One node the kernel decided to create.  Instance-type/zone name lists
    and the request vector materialize lazily — at 50k-pod scale eager
    materialization of ~7k nodes × ~1k type names dominates decode time."""

    __slots__ = ("provisioner_name", "pods", "_snapshot", "_viable", "_zone", "_used")

    def __init__(self, provisioner_name, snapshot, viable_row, zone_row, used_row):
        self.provisioner_name = provisioner_name
        self.pods: List[Pod] = []
        self._snapshot = snapshot
        self._viable = viable_row
        self._zone = zone_row
        self._used = used_row

    @property
    def instance_type_names(self) -> List[str]:
        return [self._snapshot.it_names[i] for i in np.nonzero(self._viable)[0]]

    @property
    def zones(self) -> List[str]:
        return [self._snapshot.zones[z] for z in np.nonzero(self._zone)[0]]

    @property
    def requests(self) -> resources_util.ResourceList:
        return {
            name: float(self._used[r])
            for r, name in enumerate(self._snapshot.resources)
            if self._used[r] > 0
        }


@dataclass
class TPUSolveResults:
    new_nodes: List[TPUNodeDecision] = field(default_factory=list)
    failed_pods: List[Pod] = field(default_factory=list)
    n_slots_used: int = 0


class TPUSolver:
    def __init__(
        self,
        cloud_provider: CloudProvider,
        provisioners: List[Provisioner],
        daemonset_pods: Optional[List[Pod]] = None,
    ) -> None:
        self.provisioners = order_by_weight(
            [p for p in provisioners if p.metadata.deletion_timestamp is None]
        )
        self.templates = [MachineTemplate.from_provisioner(p) for p in self.provisioners]
        self.instance_types: Dict[str, List[InstanceType]] = {
            p.name: cloud_provider.get_instance_types(p) for p in self.provisioners
        }
        overhead = _daemon_overhead(self.templates, daemonset_pods or [])
        for template in self.templates:
            template.requests = overhead[id(template)]

    def encode(self, pods: List[Pod]) -> EncodedSnapshot:
        """Raises models.snapshot.KernelUnsupported when the batch needs the
        host path."""
        return encode_snapshot(pods, self.provisioners, self.templates, self.instance_types)

    def solve(self, pods: List[Pod], n_slots: int = 0) -> TPUSolveResults:
        snapshot = self.encode(pods)
        outputs = solve_ops.solve(snapshot, n_slots=n_slots)
        # slot exhaustion: retry once with double capacity
        n_used = int(outputs.state.n_next)
        slots = outputs.assign.shape[1]
        if int(np.sum(np.asarray(outputs.failed))) > 0 and n_used >= slots:
            outputs = solve_ops.solve(snapshot, n_slots=slots * 2)
            n_used = int(outputs.state.n_next)
        return self.decode(snapshot, outputs)

    def decode(self, snapshot: EncodedSnapshot, outputs: solve_ops.SolveOutputs) -> TPUSolveResults:
        assign = np.asarray(outputs.assign)  # [C, N]
        failed = np.asarray(outputs.failed)  # [C]
        state = outputs.state
        n_it = state.viable.shape[-1]
        n_zones = state.zone.shape[-1]
        # big bool planes ship bit-packed (the device link is a tunnel)
        viable_p, zone_p, pod_count, tmpl_id, used, open_ = jax.device_get(
            (
                solve_ops.pack_bool(state.viable),
                solve_ops.pack_bool(state.zone),
                state.pod_count,
                state.tmpl_id,
                state.used,
                state.open_,
            )
        )
        viable = solve_ops.unpack_bool(viable_p, n_it)
        zone = solve_ops.unpack_bool(zone_p, n_zones)

        results = TPUSolveResults(n_slots_used=int(state.n_next))
        nodes: Dict[int, TPUNodeDecision] = {}
        provisioner_names = [t.provisioner_name for t in self.templates]
        for n in np.nonzero(open_ & (pod_count > 0))[0]:
            n = int(n)
            nodes[n] = TPUNodeDecision(
                provisioner_names[int(tmpl_id[n])], snapshot, viable[n], zone[n], used[n]
            )

        for c, cls in enumerate(snapshot.classes):
            node_idx = np.nonzero(assign[c] > 0)[0]
            counts = assign[c][node_idx]
            cursor = 0
            for n, take in zip(node_idx.tolist(), counts.tolist()):
                nodes[n].pods.extend(cls.pods[cursor : cursor + take])
                cursor += take
            results.failed_pods.extend(cls.pods[cursor:])
        results.new_nodes = [nodes[n] for n in sorted(nodes)]
        return results


__all__ = ["TPUSolver", "TPUSolveResults", "TPUNodeDecision", "KernelUnsupported"]
