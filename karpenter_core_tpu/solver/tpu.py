"""TPU solver facade: encode → kernel → decode.

Stands behind the same Solve() contract as the host Scheduler
(solver.scheduler) for the batch shapes the kernel models (see
models.snapshot.classify_pods); callers use ``supports()``/KernelUnsupported to
route between the tensor path and the host path.  This is the Solver the
BASELINE.json north star describes: cluster snapshots in, node decisions out,
with the bin-pack running as a batch tensor program on the TPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import numpy as np

from karpenter_core_tpu.apis.objects import Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner, order_by_weight
from karpenter_core_tpu.cloudprovider import CloudProvider, InstanceType
from karpenter_core_tpu.models.snapshot import (
    EncodedSnapshot,
    KernelUnsupported,
    encode_snapshot,
)
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.solver.machinetemplate import MachineTemplate
from karpenter_core_tpu.solver.scheduler import _daemon_overhead
from karpenter_core_tpu.utils import resources as resources_util


class TPUNodeDecision:
    """One node the kernel decided to create.  Instance-type/zone name lists
    and the request vector materialize lazily — at 50k-pod scale eager
    materialization of ~7k nodes × ~1k type names dominates decode time."""

    __slots__ = ("provisioner_name", "pods", "_snapshot", "_viable", "_zone", "_used")

    def __init__(self, provisioner_name, snapshot, viable_row, zone_row, used_row):
        self.provisioner_name = provisioner_name
        self.pods: List[Pod] = []
        self._snapshot = snapshot
        self._viable = viable_row
        self._zone = zone_row
        self._used = used_row

    @property
    def instance_type_names(self) -> List[str]:
        return [self._snapshot.it_names[i] for i in np.nonzero(self._viable)[0]]

    @property
    def zones(self) -> List[str]:
        return [self._snapshot.zones[z] for z in np.nonzero(self._zone)[0]]

    @property
    def requests(self) -> resources_util.ResourceList:
        return {
            name: float(self._used[r])
            for r, name in enumerate(self._snapshot.resources)
            if self._used[r] > 0
        }


@dataclass
class TPUSolveResults:
    new_nodes: List[TPUNodeDecision] = field(default_factory=list)
    # existing-node placements: node name -> pods nominated onto it
    existing_assignments: Dict[str, List[Pod]] = field(default_factory=dict)
    failed_pods: List[Pod] = field(default_factory=list)
    n_slots_used: int = 0


@dataclass
class LaunchableNode:
    """Launch-path adapter (duck-typed like solver.node.SchedulingNode):
    template + instance types + requests + pods, consumable by
    ProvisioningController.launch."""

    template: object
    instance_type_options: List[InstanceType]
    requests: dict
    pods: List[Pod] = field(default_factory=list)

    @property
    def provisioner_name(self) -> str:
        return self.template.provisioner_name

    @property
    def requirements(self):
        return self.template.requirements


class TPUSolver:
    def __init__(
        self,
        cloud_provider: CloudProvider,
        provisioners: List[Provisioner],
        daemonset_pods: Optional[List[Pod]] = None,
    ) -> None:
        self.provisioners = order_by_weight(
            [p for p in provisioners if p.metadata.deletion_timestamp is None]
        )
        self.templates = [MachineTemplate.from_provisioner(p) for p in self.provisioners]
        self.instance_types: Dict[str, List[InstanceType]] = {
            p.name: cloud_provider.get_instance_types(p) for p in self.provisioners
        }
        overhead = _daemon_overhead(self.templates, daemonset_pods or [])
        for template in self.templates:
            template.requests = overhead[id(template)]
        self._it_by_name = {
            it.name: it for its in self.instance_types.values() for it in its
        }

    def encode(
        self,
        pods: List[Pod],
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
    ) -> EncodedSnapshot:
        """Raises models.snapshot.KernelUnsupported when the batch needs the
        host path.  Existing-node label values widen the vocabulary so NotIn
        checks against them stay exact; bound pods' anti-affinity terms
        register as groups so their inverse blocking reaches the kernel."""
        from karpenter_core_tpu.models.snapshot import (
            GRP_ANTI,
            UNLIMITED,
            KernelUnsupported,
            _group_spec,
        )

        extra = [
            Requirements.from_labels(n.node.metadata.labels) for n in (state_nodes or [])
        ]
        extra_anti = []
        for pod in bound_pods or []:
            affinity = pod.spec.affinity
            if affinity is None or affinity.pod_anti_affinity is None:
                continue
            for term in affinity.pod_anti_affinity.required:
                try:
                    spec = _group_spec(GRP_ANTI, term.topology_key, term.label_selector, UNLIMITED)
                except KernelUnsupported:
                    # an unrepresentable anti key only matters if it can gate
                    # a scheduling pod
                    if term.label_selector is not None and any(
                        term.label_selector.matches(p.metadata.labels) for p in pods
                    ):
                        raise
                    continue
                extra_anti.append((spec, term.label_selector))
        from karpenter_core_tpu.models.snapshot import pod_port_keys

        extra_ports = [key for pod in bound_pods or [] for key in pod_port_keys(pod)]
        return encode_snapshot(
            pods, self.provisioners, self.templates, self.instance_types,
            extra_requirement_sets=extra,
            extra_anti_groups=extra_anti,
            cache_host=self,
            extra_host_ports=extra_ports,
        )

    def encode_existing(
        self,
        snapshot: EncodedSnapshot,
        state_nodes: list,
        bound_pods: Optional[List[Pod]] = None,
    ):
        """(ExistingState, ExistingStatic) numpy planes for the kernel; the
        per-group member/owner node counts seed the kernel's topology counts.

        Mirrors ExistingNode construction (existingnode.go:43-75): available
        capacity, remaining daemonset overhead, label requirements, ephemeral-
        taint-filtered toleration checks; and topology countDomains
        (topology.go:231-276) for pre-existing matching pods.
        """
        import jax.numpy as jnp

        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.scheduling import Taints

        vocab = snapshot.vocab
        E = max(len(state_nodes), 1)
        C = len(snapshot.classes)
        R = len(snapshot.resources)
        Z = len(snapshot.zones)
        CT = len(snapshot.capacity_types)
        K, W = vocab.n_keys, vocab.width

        G1 = len(snapshot.groups) + 1
        used = np.zeros((E, R), dtype=np.float32)
        alloc = np.zeros((E, R), dtype=np.float32)
        kmask = np.ones((E, K, W), dtype=bool)
        kdef = np.zeros((E, K), dtype=bool)
        kneg = np.zeros((E, K), dtype=bool)
        kgt = np.full((E, K), -np.inf, dtype=np.float32)
        klt = np.full((E, K), np.inf, dtype=np.float32)
        zone = np.zeros((E, Z), dtype=bool)
        ct = np.zeros((E, CT), dtype=bool)
        pod_count = np.zeros(E, dtype=np.int32)
        open_ = np.zeros(E, dtype=bool)
        init = np.zeros(E, dtype=bool)
        tol = np.zeros((C, E), dtype=bool)
        P = len(snapshot.ports)
        ports = np.zeros((E, P), dtype=bool)
        grp_node_member = np.zeros((G1, E), dtype=np.int32)
        grp_node_owner = np.zeros((G1, E), dtype=np.int32)
        node_capacity = np.zeros((E, R), dtype=np.float32)
        node_tmpl = np.zeros(E, dtype=np.int32)
        node_owned = np.zeros(E, dtype=bool)
        port_idx = {key: i for i, key in enumerate(snapshot.ports)}
        tmpl_index = {t.provisioner_name: i for i, t in enumerate(self.templates)}

        tmpl_by_name = {t.provisioner_name: t for t in self.templates}
        zone_idx = {z: i for i, z in enumerate(snapshot.zones)}
        ct_idx = {c: i for i, c in enumerate(snapshot.capacity_types)}

        for e, state_node in enumerate(state_nodes):
            node = state_node.node
            available = state_node.available()
            for r, name in enumerate(snapshot.resources):
                alloc[e, r] = available.get(name, 0.0)
            template = tmpl_by_name.get(
                node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, "")
            )
            if template is not None and template.requests:
                remaining = resources_util.subtract(
                    template.requests, state_node.daemon_set_requests()
                )
                for r, name in enumerate(snapshot.resources):
                    used[e, r] = max(remaining.get(name, 0.0), 0.0)
            reqs = Requirements.from_labels(node.metadata.labels)
            kmask[e], kdef[e], kneg[e], kgt[e], klt[e] = vocab.encode_requirements(reqs)
            z = node.metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE)
            if z is None:
                zone[e, :] = True  # unknown zone: any
            elif z in zone_idx:
                zone[e, zone_idx[z]] = True
            c_label = node.metadata.labels.get(labels_api.LABEL_CAPACITY_TYPE)
            if c_label is None:
                ct[e, :] = True
            elif c_label in ct_idx:
                ct[e, ct_idx[c_label]] = True
            open_[e] = True
            init[e] = state_node.initialized()
            capacity = state_node.capacity()
            for r, name in enumerate(snapshot.resources):
                node_capacity[e, r] = capacity.get(name, 0.0)
            t_idx = tmpl_index.get(
                node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY, "")
            )
            if t_idx is not None:
                node_tmpl[e] = t_idx
                node_owned[e] = True
            taints = Taints.of(state_node.taints())
            for c, cls in enumerate(snapshot.classes):
                tol[c, e] = taints.tolerates(cls.pods[0]) is None

        # pre-existing pod counts per topology group (countDomains semantics,
        # topology.go:231-276): members (forward) and anti-term owners
        # (inverse); pods being scheduled this solve are excluded
        from karpenter_core_tpu.models.snapshot import GRP_ANTI, UNLIMITED, _group_spec

        node_index = {n.node.name: e for e, n in enumerate(state_nodes)}
        group_of = {spec: g for g, spec in enumerate(snapshot.groups)}
        scheduling_uids = {p.uid for cls in snapshot.classes for p in cls.pods}
        for pod in bound_pods or []:
            e = node_index.get(pod.spec.node_name)
            if e is None or pod.uid in scheduling_uids:
                continue
            labels = pod.metadata.labels
            from karpenter_core_tpu.models.snapshot import pod_port_keys as _ppk

            for key in _ppk(pod):
                i = port_idx.get(key)
                if i is not None:
                    ports[e, i] = True
            for g, selector in enumerate(snapshot.group_selectors):
                if selector is not None and selector.matches(labels):
                    grp_node_member[g, e] += 1
            affinity = pod.spec.affinity
            if affinity is not None and affinity.pod_anti_affinity is not None:
                for term in affinity.pod_anti_affinity.required:
                    try:
                        spec = _group_spec(
                            GRP_ANTI, term.topology_key, term.label_selector, UNLIMITED
                        )
                    except Exception:  # noqa: BLE001 - unsupported keys don't track
                        continue
                    g = group_of.get(spec)
                    if g is not None:
                        grp_node_owner[g, e] += 1

        ex_state = solve_ops.ExistingState(
            used=jnp.asarray(used),
            kmask=jnp.asarray(kmask),
            kdef=jnp.asarray(kdef),
            kneg=jnp.asarray(kneg),
            kgt=jnp.asarray(kgt),
            klt=jnp.asarray(klt),
            zone=jnp.asarray(zone),
            ct=jnp.asarray(ct),
            ports=jnp.asarray(ports),
            pod_count=jnp.asarray(pod_count),
            open_=jnp.asarray(open_),
        )
        ex_static = solve_ops.ExistingStatic(
            alloc=jnp.asarray(alloc),
            init=jnp.asarray(init),
            tol=jnp.asarray(tol),
            grp_node_member=jnp.asarray(grp_node_member),
            grp_node_owner=jnp.asarray(grp_node_owner),
            node_capacity=jnp.asarray(node_capacity),
            node_tmpl=jnp.asarray(node_tmpl),
            node_owned=jnp.asarray(node_owned),
        )
        return ex_state, ex_static

    def solve(
        self,
        pods: List[Pod],
        state_nodes: Optional[list] = None,
        bound_pods: Optional[List[Pod]] = None,
        n_slots: int = 0,
    ) -> TPUSolveResults:
        snapshot = self.encode(pods, state_nodes, bound_pods)
        ex_state = ex_static = None
        if state_nodes:
            ex_state, ex_static = self.encode_existing(snapshot, state_nodes, bound_pods)
        if n_slots <= 0:
            n_slots = solve_ops.estimate_slots(snapshot)
        cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
        outputs = solve_ops._solve_jit(
            cls, statics_arrays, n_slots, key_has_bounds, ex_state, ex_static
        )
        # slot exhaustion: retry once with double capacity
        n_used = int(outputs.state.n_next)
        slots = outputs.assign.shape[1]
        if int(np.sum(np.asarray(outputs.failed))) > 0 and n_used >= slots:
            outputs = solve_ops._solve_jit(
                cls, statics_arrays, slots * 2, key_has_bounds, ex_state, ex_static
            )
        return self.decode(snapshot, outputs, state_nodes or [])

    def decode(
        self,
        snapshot: EncodedSnapshot,
        outputs: solve_ops.SolveOutputs,
        state_nodes: Optional[list] = None,
    ) -> TPUSolveResults:
        assign = np.asarray(outputs.assign)  # [C, N]
        assign_ex = np.asarray(outputs.assign_existing)  # [C, E]
        failed = np.asarray(outputs.failed)  # [C]
        state = outputs.state
        n_it = state.viable.shape[-1]
        n_zones = state.zone.shape[-1]
        # big bool planes ship bit-packed (the device link is a tunnel)
        viable_p, zone_p, pod_count, tmpl_id, used, open_ = jax.device_get(
            (
                solve_ops.pack_bool(state.viable),
                solve_ops.pack_bool(state.zone),
                state.pod_count,
                state.tmpl_id,
                state.used,
                state.open_,
            )
        )
        viable = solve_ops.unpack_bool(viable_p, n_it)
        zone = solve_ops.unpack_bool(zone_p, n_zones)

        results = TPUSolveResults(n_slots_used=int(state.n_next))
        nodes: Dict[int, TPUNodeDecision] = {}
        provisioner_names = [t.provisioner_name for t in self.templates]
        for n in np.nonzero(open_ & (pod_count > 0))[0]:
            n = int(n)
            nodes[n] = TPUNodeDecision(
                provisioner_names[int(tmpl_id[n])], snapshot, viable[n], zone[n], used[n]
            )

        state_nodes = state_nodes or []
        for c, cls in enumerate(snapshot.classes):
            cursor = 0
            # existing-node placements first (they were tried first in-kernel)
            ex_idx = np.nonzero(assign_ex[c] > 0)[0]
            for e, take in zip(ex_idx.tolist(), assign_ex[c][ex_idx].tolist()):
                if e < len(state_nodes):
                    name = state_nodes[e].node.name
                    results.existing_assignments.setdefault(name, []).extend(
                        cls.pods[cursor : cursor + take]
                    )
                cursor += take
            node_idx = np.nonzero(assign[c] > 0)[0]
            counts = assign[c][node_idx]
            for n, take in zip(node_idx.tolist(), counts.tolist()):
                nodes[n].pods.extend(cls.pods[cursor : cursor + take])
                cursor += take
            results.failed_pods.extend(cls.pods[cursor:])
        results.new_nodes = [nodes[n] for n in sorted(nodes)]
        return results

    def to_launchable(self, decision: TPUNodeDecision) -> LaunchableNode:
        """Convert a kernel node decision into a launch-path object: the
        provisioner's template with zone/capacity-type pinned to the decision's
        surviving domains and the viable instance-type list attached."""
        from dataclasses import replace as dc_replace

        from karpenter_core_tpu.apis.objects import OP_IN

        template = next(
            t for t in self.templates if t.provisioner_name == decision.provisioner_name
        )
        requirements = Requirements(*template.requirements.values())
        zones = decision.zones
        if zones:
            requirements.add(
                Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, zones)
            )
        options = [
            self._it_by_name[name]
            for name in decision.instance_type_names
            if name in self._it_by_name
        ]
        return LaunchableNode(
            template=dc_replace(template, requirements=requirements),
            instance_type_options=options,
            requests=dict(decision.requests),
            pods=list(decision.pods),
        )


__all__ = ["TPUSolver", "TPUSolveResults", "TPUNodeDecision", "KernelUnsupported"]
