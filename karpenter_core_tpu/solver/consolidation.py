"""TPU-accelerated consolidation search.

Couples the kernel subset sweep (ops.consolidate) with the reference's
validity rules (consolidation.go:190-290): every prefix of the disruption-
sorted candidate list is simulated in parallel on device; the host then
applies price filtering, the spot→spot prohibition, and the same-type price
sanity filter to each lane's decoded replacement, and picks the largest valid
prefix — the result the binary search converges to, computed in one pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import OP_IN, Pod
from karpenter_core_tpu.cloudprovider import InstanceType
from karpenter_core_tpu.controllers.deprovisioning import (
    Action,
    CandidateNode,
    Command,
    filter_by_price,
    MultiNodeConsolidation,
)
from karpenter_core_tpu.models.snapshot import KernelUnsupported
from karpenter_core_tpu.ops import consolidate as consolidate_ops
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.solver.tpu import TPUSolver

MAX_LANES = 64


def search_largest_prefix(n, evaluate, refine: bool = True):
    """Largest valid consolidation prefix via batched lane sweeps.

    ``evaluate(sizes) -> (best_command_or_None, best_k)`` runs one device
    sweep over the given prefix sizes and reports the largest valid one.  Up
    to MAX_LANES sizes cover [1, n] per pass; when the coarse grid leaves a
    gap between the best lane and the next, further passes re-grid the
    bracket, shrinking it ~MAX_LANES× each time — the boundary pins exactly
    in ceil(log64(n)) passes (2 up to 4096 candidates, 3 to 256k) vs the
    reference's ~log2(n) sequential full simulations
    (multinodeconsolidation.go:86-113).

    ``refine=False`` stops after the coarse pass — cost-delta scoring
    (policy objective) picks its optimum WITHIN a pass, and the bracket
    refinement's larger-k-wins assumption would let a worse-saving larger
    prefix displace it."""
    if n <= MAX_LANES:
        sizes = np.arange(1, n + 1, dtype=np.int32)
    else:
        sizes = np.unique(np.round(np.linspace(1, n, MAX_LANES)).astype(np.int32))
    best, best_k = evaluate(sizes)
    if n <= MAX_LANES or best is None or not refine:
        return best

    lo = best_k
    hi = int(sizes[np.searchsorted(sizes, best_k) + 1]) if best_k < int(sizes[-1]) else None
    while hi is not None and hi - lo > 1:
        span = np.arange(lo + 1, hi, dtype=np.int32)
        if len(span) > MAX_LANES:
            span = np.unique(
                np.round(np.linspace(lo + 1, hi - 1, MAX_LANES)).astype(np.int32)
            )
        refined, refined_k = evaluate(span)
        if refined is not None and refined_k > lo:
            best, best_k = refined, refined_k
            lo = refined_k
            if refined_k < int(span[-1]):
                hi = int(span[np.searchsorted(span, refined_k) + 1])
            # else: the bracket (refined_k, hi) is already one grid interval
        else:
            hi = int(span[0])
    return best


@dataclass
class TPUReplacement:
    """Launchable replacement description compatible with
    ProvisioningController.launch (duck-typed like solver.node.SchedulingNode)."""

    template: object
    instance_type_options: List[InstanceType]
    requests: dict
    pods: List[Pod] = field(default_factory=list)

    @property
    def provisioner_name(self) -> str:
        return self.template.provisioner_name

    @property
    def requirements(self) -> Requirements:
        return self.template.requirements


class TPUConsolidationSearch:
    def __init__(self, cloud_provider, provisioners, policy=None) -> None:
        # policy (policy.PolicyConfig): with the objective enabled, lanes are
        # scored by FLEET COST DELTA (old subset price minus replacement
        # cost) instead of node count — the cheapest fleet wins even when a
        # smaller prefix removes fewer nodes (docs/POLICY.md).  None/disabled
        # keeps the reference behavior: the largest valid prefix wins.
        self.policy = policy
        self.solver = TPUSolver(cloud_provider, provisioners, policy=policy)
        self.it_by_name = {
            it.name: it
            for p in self.solver.provisioners
            for it in self.solver.instance_types.get(p.name, [])
        }

    def compute_command(
        self,
        candidates: List[CandidateNode],
        pending_pods: List[Pod],
        state_nodes: list,
        bound_pods: Optional[List[Pod]] = None,
    ) -> Command:
        """candidates must be disruption-cost sorted.  Raises KernelUnsupported
        when the pod shapes need the host path."""
        if not candidates:
            return Command(Action.DO_NOTHING)

        candidate_pods = [p for c in candidates for p in c.pods]
        all_pods = list(pending_pods) + candidate_pods
        if not all_pods:
            # no pods anywhere: every candidate is empty, deleting all is
            # trivially valid (the simulation would open zero new nodes)
            return Command(Action.DELETE, [c.node for c in candidates])
        snapshot = self.solver.encode(all_pods, state_nodes, bound_pods)
        ex_state, ex_static = self.solver.encode_existing(
            snapshot, state_nodes, bound_pods
        )
        # encode_existing returns host numpy (so the provisioning path can
        # bucket-pad before upload); the sweep runs up to twice (coarse +
        # refine) on the same planes, so pin them device-resident once here
        import jax

        ex_state, ex_static = jax.device_put((ex_state, ex_static))

        # split class counts: pending (base) vs on-candidate (per-node)
        node_index = {n.node.name: e for e, n in enumerate(state_nodes)}
        candidate_names = {c.node.name for c in candidates}
        E = max(len(state_nodes), 1)
        C = len(snapshot.classes)
        ex_cls_count = np.zeros((C, E), dtype=np.int32)
        base_counts = np.zeros(C, dtype=np.int32)
        for c, cls in enumerate(snapshot.classes):
            if cls.is_ladder_variant:
                continue  # variants hold one representative copy, not real pods
            for pod in cls.pods:
                if pod.spec.node_name and pod.spec.node_name in candidate_names:
                    ex_cls_count[c, node_index[pod.spec.node_name]] += 1
                else:
                    base_counts[c] += 1
        snapshot.cls_count = base_counts

        rank = np.full(E, 1 << 30, dtype=np.int32)
        for i, candidate in enumerate(candidates):
            rank[node_index[candidate.node.name]] = i

        best = search_largest_prefix(
            len(candidates),
            lambda sizes: self._evaluate_sweep(
                snapshot, ex_state, ex_static, rank, ex_cls_count, sizes, candidates
            ),
            refine=not (
                self.policy is not None and getattr(self.policy, "enabled", False)
            ),
        )
        return best if best is not None else Command(Action.DO_NOTHING)

    def _candidate_price_cumsum(self, candidates) -> np.ndarray:
        """Cumulative current-offering price of the first-k candidates
        (nan-poisoned past any candidate whose offering is unknown, which
        drops those lanes out of cost scoring without failing the sweep)."""
        prices = np.full(len(candidates), np.nan, dtype=np.float64)
        for i, c in enumerate(candidates):
            offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
            if offering is not None:
                prices[i] = offering.price
        return np.cumsum(prices)

    def _evaluate_sweep(
        self, snapshot, ex_state, ex_static, rank, ex_cls_count, sizes, candidates
    ):
        """(best command, its prefix size) across the given lane sizes.

        Default scoring is the reference's: the LARGEST valid prefix wins
        (most nodes removed).  With the policy objective enabled, lanes are
        scored by fleet-cost saving — old subset price minus the lane's
        replacement cost (the kernel's ``new_cost``) — and the largest
        saving wins, node count breaking ties; fewest-nodes and
        cheapest-fleet genuinely disagree when a large prefix forces a
        pricey replacement while a smaller one deletes outright
        (tests/test_policy.py pins both directions)."""
        # the sweep auto-routes onto the 2D (catalog × lane) mesh when
        # KC_SOLVER_MESH enables it (parallel.mesh.lane_mesh_axes): prefix
        # lanes split across the lane axis, the catalog shards within each
        # lane group.  Assignments/viability/zone planes are bit-identical
        # to the unsharded sweep (mesh parity suite); the f32 per-lane
        # new_cost SUMS agree only to reduction-order ulp (XLA reassociates
        # across programs), so a razor-thin cost-delta tie can in principle
        # resolve differently with the mesh on vs off — same caveat as any
        # recompile (docs/KERNEL_PERF.md "Layer 5")
        from karpenter_core_tpu import tracing
        from karpenter_core_tpu.parallel import mesh as mesh_mod
        from karpenter_core_tpu.utils import pipeline as pipeline_mod

        mesh_axes = mesh_mod.lane_mesh_axes()
        with tracing.span(
            "consolidate.sweep", lanes=len(sizes),
            mesh=repr(mesh_axes) if mesh_axes else None,
        ):
            out = consolidate_ops.run_sweep(
                snapshot, ex_state, ex_static, rank, ex_cls_count, sizes,
                mesh_axes=mesh_axes,
            )
            # ONE batched device→host fetch of every sweep plane (async
            # copies started up front) instead of eight serial np.asarray
            # transfers — the coarse sweep's fetch no longer serializes
            # array-by-array ahead of the refine sweep's dispatch
            # structure-preserving; the sweep's barrier budgets under its
            # own watchdog site (a hung lane sweep must not wedge the
            # deprovisioner — it surfaces as a SolveTimeout the breaker
            # counts)
            out = pipeline_mod.fetch_tree(out, site="consolidate.sweep")
        n_new = np.asarray(out.n_new)
        failed = np.asarray(out.failed)
        uninit = np.asarray(out.used_uninitialized)
        viable = np.asarray(out.new_viable)
        zone = np.asarray(out.new_zone)
        ct = np.asarray(out.new_ct)
        used = np.asarray(out.new_used)
        tmpl_id = np.asarray(out.new_tmpl)
        new_cost = np.asarray(out.new_cost)
        cost_scoring = self.policy is not None and getattr(
            self.policy, "enabled", False
        )
        old_cum = self._candidate_price_cumsum(candidates) if cost_scoring else None

        best: Optional[Command] = None
        best_k = 0
        best_saving = -np.inf
        for lane, k in enumerate(sizes.tolist()):
            if failed[lane] > 0 or uninit[lane]:
                continue
            subset = candidates[:k]
            if int(n_new[lane]) == 0:
                cmd = Command(Action.DELETE, [c.node for c in subset])
                lane_cost = 0.0
            elif int(n_new[lane]) == 1:
                replacement = self._decode_replacement(
                    snapshot, viable[lane, 0], zone[lane, 0], ct[lane, 0],
                    used[lane, 0], int(tmpl_id[lane, 0]), subset,
                )
                if replacement is None:
                    continue
                cmd = Command(
                    Action.REPLACE, [c.node for c in subset], [replacement]
                )
                lane_cost = float(new_cost[lane])
            else:
                continue
            if cost_scoring:
                saving = float(old_cum[k - 1]) - lane_cost if k >= 1 else 0.0
                if np.isnan(saving):
                    saving = -np.inf  # unpriceable subset: never preferred
                if saving > best_saving or (
                    saving == best_saving and k > best_k
                ):
                    best, best_k, best_saving = cmd, k, saving
            else:
                best, best_k = cmd, k
        return best, best_k

    def _decode_replacement(
        self, snapshot, viable_row, zone_row, ct_row, used_row, tmpl_idx, subset
    ) -> Optional[TPUReplacement]:
        options = [
            self.it_by_name[snapshot.it_names[i]]
            for i in np.nonzero(viable_row)[0]
            if snapshot.it_names[i] in self.it_by_name
        ]
        zones = [snapshot.zones[z] for z in np.nonzero(zone_row)[0]]
        cts = [snapshot.capacity_types[c] for c in np.nonzero(ct_row)[0]]
        template = self.solver.templates[tmpl_idx]

        requirements = Requirements(*template.requirements.values())
        if zones:
            requirements.add(Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, zones))
        if cts:
            requirements.add(Requirement(labels_api.LABEL_CAPACITY_TYPE, OP_IN, cts))

        # price rules (consolidation.go:227-267)
        old_price = 0.0
        for c in subset:
            offering = c.instance_type.offerings.get(c.capacity_type, c.zone)
            if offering is None:
                return None
            old_price += offering.price
        options = filter_by_price(options, requirements, old_price)
        if not options:
            return None
        all_spot = all(
            c.capacity_type == labels_api.CAPACITY_TYPE_SPOT for c in subset
        )
        ct_req = requirements.get(labels_api.LABEL_CAPACITY_TYPE)
        if all_spot and ct_req.has(labels_api.CAPACITY_TYPE_SPOT):
            return None
        if ct_req.has(labels_api.CAPACITY_TYPE_SPOT) and ct_req.has(
            labels_api.CAPACITY_TYPE_ON_DEMAND
        ):
            requirements.add(
                Requirement(
                    labels_api.LABEL_CAPACITY_TYPE, OP_IN, [labels_api.CAPACITY_TYPE_SPOT]
                )
            )
        # same-type price sanity for multi-node (multinodeconsolidation.go:132-165)
        from dataclasses import replace as dc_replace

        out_template = dc_replace(template, requirements=requirements)
        requests = {
            name: float(used_row[r])
            for r, name in enumerate(snapshot.resources)
            if used_row[r] > 0
        }
        replacement = TPUReplacement(
            template=out_template,
            instance_type_options=options,
            requests=requests,
            pods=[p for c in subset for p in c.pods],
        )
        if len(subset) >= 2:
            replacement.instance_type_options = MultiNodeConsolidation.filter_out_same_type(
                replacement, subset
            )
            if not replacement.instance_type_options:
                return None
        return replacement
