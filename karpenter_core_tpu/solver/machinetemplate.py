"""MachineTemplate: the launchable shape derived from a Provisioner.

Mirror of /root/reference/pkg/controllers/provisioning/scheduling/machinetemplate.go:46-100.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_IN,
    Node,
    NodeSpec,
    ObjectMeta,
    OwnerReference,
)
from karpenter_core_tpu.apis.v1alpha5 import (
    KubeletConfiguration,
    Machine,
    MachineSpec,
    Provisioner,
    ProviderRef,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements, Taints
from karpenter_core_tpu.utils import resources as resources_util


@dataclass
class MachineTemplate:
    provisioner_name: str = ""
    instance_type_options: list = field(default_factory=list)  # List[InstanceType]
    provider: Optional[Dict[str, Any]] = None
    provider_ref: Optional[ProviderRef] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: Taints = field(default_factory=Taints)
    startup_taints: Taints = field(default_factory=Taints)
    requirements: Requirements = field(default_factory=Requirements)
    requests: resources_util.ResourceList = field(default_factory=dict)
    kubelet: Optional[KubeletConfiguration] = None

    @classmethod
    def from_provisioner(cls, provisioner: Provisioner) -> "MachineTemplate":
        labels = dict(provisioner.spec.labels)
        labels[labels_api.PROVISIONER_NAME_LABEL_KEY] = provisioner.name
        requirements = Requirements()
        requirements.add(
            *Requirements.from_node_selector_requirements(
                *provisioner.spec.requirements
            ).values()
        )
        requirements.add(*Requirements.from_labels(labels).values())
        return cls(
            provisioner_name=provisioner.name,
            provider=provisioner.spec.provider,
            provider_ref=provisioner.spec.provider_ref,
            kubelet=provisioner.spec.kubelet_configuration,
            annotations=dict(provisioner.spec.annotations),
            labels=labels,
            taints=Taints.of(provisioner.spec.taints),
            startup_taints=Taints.of(provisioner.spec.startup_taints),
            requirements=requirements,
        )

    def to_node(self) -> Node:
        labels = dict(self.labels)
        labels.update(self.requirements.labels())
        return Node(
            metadata=ObjectMeta(
                labels=labels,
                annotations=dict(self.annotations),
                finalizers=[labels_api.TERMINATION_FINALIZER],
            ),
            spec=NodeSpec(taints=list(self.taints) + list(self.startup_taints)),
        )

    def to_machine(self, owner: Provisioner) -> Machine:
        self.requirements.add(
            Requirement(
                labels_api.LABEL_INSTANCE_TYPE_STABLE,
                OP_IN,
                [it.name for it in self.instance_type_options],
            )
        )
        from karpenter_core_tpu.apis.objects import new_uid

        return Machine(
            metadata=ObjectMeta(
                name=f"{self.provisioner_name}-{new_uid()[:8]}",
                annotations=dict(self.annotations),
                labels=dict(self.labels),
                owner_references=[
                    OwnerReference(
                        api_version="karpenter.sh/v1alpha5",
                        kind="Provisioner",
                        name=owner.name,
                        uid=owner.metadata.uid,
                    )
                ],
            ),
            spec=MachineSpec(
                taints=list(self.taints),
                startup_taints=list(self.startup_taints),
                requirements=self.requirements.node_selector_requirements(),
                kubelet=self.kubelet,
                resources_requests=dict(self.requests),
                machine_template_ref=self.provider_ref,
            ),
        )
