"""Set-or-complement representation of a single node-selector requirement.

Semantics mirror the reference's Requirement exactly
(/root/reference/pkg/scheduling/requirement.go:36-243): a requirement is either
a concrete value set (``complement=False``: In / DoesNotExist) or the complement
of one (``complement=True``: NotIn / Exists), with optional integer Gt/Lt bounds
that only survive on complement sets.  This is also the *specification* for the
tensorized mask encoding in ``karpenter_core_tpu.ops.masks`` — the "other"
mask slot there is this class's complement bit.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)

# Stand-in for Go's math.MaxInt64 in Len() arithmetic: a complement set is
# "infinite minus the excluded values".
INFINITE = 1 << 63


class Requirement:
    __slots__ = ("key", "complement", "values", "greater_than", "less_than")

    def __init__(
        self,
        key: str,
        operator: str,
        values: Iterable[str] = (),
    ) -> None:
        key = labels_api.NORMALIZED_LABELS.get(key, key)
        self.key = key
        self.complement = operator not in (OP_IN, OP_DOES_NOT_EXIST)
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        vals: FrozenSet[str] = frozenset()
        values = list(values)
        if operator in (OP_IN, OP_NOT_IN):
            vals = frozenset(values)
        elif operator == OP_GT:
            self.greater_than = int(values[0])  # prevalidated upstream
        elif operator == OP_LT:
            self.less_than = int(values[0])
        self.values = vals

    # -- construction helpers -------------------------------------------------

    @classmethod
    def _raw(
        cls,
        key: str,
        complement: bool,
        values: FrozenSet[str],
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
    ) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = values
        r.greater_than = greater_than
        r.less_than = less_than
        return r

    # -- algebra --------------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Exact intersection over all four complement combinations
        (requirement.go:117-150)."""
        complement = self.complement and other.complement

        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, OP_DOES_NOT_EXIST)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = frozenset(v for v in values if _within(v, greater_than, less_than))
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, complement, values, greater_than, less_than)

    def has(self, value: str) -> bool:
        """True if the requirement allows the value (requirement.go:171-176)."""
        if self.complement:
            return value not in self.values and _within(value, self.greater_than, self.less_than)
        return value in self.values and _within(value, self.greater_than, self.less_than)

    def any(self) -> str:
        """An arbitrary allowed value, for label rendering (requirement.go:152-168)."""
        op = self.operator()
        if op == OP_IN:
            return next(iter(self.values))
        if op in (OP_NOT_IN, OP_EXISTS):
            # the smallest in-range value the complement set allows — the
            # reference draws randomly here, but an unseeded draw makes label
            # rendering unreplayable (chaos determinism gate) and can even
            # land on an excluded value; deterministic-and-allowed is
            # strictly better for both callers and tests
            lo = 0 if self.greater_than is None else self.greater_than + 1
            hi = (1 << 63) - 1 if self.less_than is None else self.less_than
            if lo >= hi:
                # empty integer domain (e.g. Gt 4 + Lt 5): surface the
                # contradiction loudly, as the reference's randrange(lo, hi)
                # did, instead of rendering a label the requirement excludes
                raise ValueError(
                    f"requirement {self.key} has no allowed value in [{lo}, {hi})"
                )
            # valid values are [lo, hi): stop at hi-1 so a fully-excluded
            # range returns an in-range (if excluded) value, as the
            # reference's randrange(lo, hi) did, never one past less_than
            candidate = lo
            while str(candidate) in self.values and candidate + 1 < hi:
                candidate += 1
            return str(candidate)
        return ""

    def insert(self, *items: str) -> None:
        self.values = self.values | frozenset(items)

    def operator(self) -> str:
        if self.complement:
            return OP_NOT_IN if self.len() < INFINITE else OP_EXISTS
        return OP_IN if self.len() > 0 else OP_DOES_NOT_EXIST

    def len(self) -> int:
        if self.complement:
            return INFINITE - len(self.values)
        return len(self.values)

    def values_list(self) -> list:
        return sorted(self.values)

    # -- conversion -----------------------------------------------------------

    def node_selector_requirement(self):
        from karpenter_core_tpu.apis.objects import NodeSelectorRequirement

        if self.greater_than is not None:
            return NodeSelectorRequirement(self.key, OP_GT, [str(self.greater_than)])
        if self.less_than is not None:
            return NodeSelectorRequirement(self.key, OP_LT, [str(self.less_than)])
        if self.complement:
            if self.values:
                return NodeSelectorRequirement(self.key, OP_NOT_IN, self.values_list())
            return NodeSelectorRequirement(self.key, OP_EXISTS)
        if self.values:
            return NodeSelectorRequirement(self.key, OP_IN, self.values_list())
        return NodeSelectorRequirement(self.key, OP_DOES_NOT_EXIST)

    def __repr__(self) -> str:
        op = self.operator()
        if op in (OP_EXISTS, OP_DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            values = self.values_list()
            if len(values) > 5:
                values = values[:5] + [f"and {len(values) - 5} others"]
            s = f"{self.key} {op} {values}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Requirement)
            and self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
        )

    def __hash__(self) -> int:
        return hash((self.key, self.complement, self.values, self.greater_than, self.less_than))


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """Bounds check; non-integer values fail when bounds are set
    (requirement.go:227-243)."""
    if greater_than is None and less_than is None:
        return True
    try:
        v = int(value)
    except ValueError:
        return False
    if greater_than is not None and greater_than >= v:
        return False
    if less_than is not None and less_than <= v:
        return False
    return True


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)
