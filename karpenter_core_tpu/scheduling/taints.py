"""Taint toleration checks (mirror of /root/reference/pkg/scheduling/taints.go:25-47)."""

from __future__ import annotations

from typing import Iterable, List, Optional

from karpenter_core_tpu.apis.objects import Pod, Taint


class Taints(List[Taint]):
    """Decorated list of taints."""

    def tolerates(self, pod: Pod) -> Optional[str]:
        """None if the pod tolerates all taints, else an error string."""
        errs = []
        for taint in self:
            if not any(t.tolerates_taint(taint) for t in pod.spec.tolerations):
                errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
        return "; ".join(errs) if errs else None

    @classmethod
    def of(cls, taints: Iterable[Taint]) -> "Taints":
        return cls(taints)
