"""Keyed requirement sets with intersection-on-add and compatibility checks.

Mirrors /root/reference/pkg/scheduling/requirements.go:32-223.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    NodeSelectorRequirement,
    Pod,
)
from karpenter_core_tpu.scheduling.requirement import Requirement


class IncompatibleError(Exception):
    """Raised (or returned) when two requirement sets cannot be satisfied together."""


class Requirements:
    """Map of key -> Requirement; Add() intersects with any existing entry
    (requirements.go:87-94)."""

    __slots__ = ("_items",)

    def __init__(self, *requirements: Requirement) -> None:
        self._items: Dict[str, Requirement] = {}
        self.add(*requirements)

    # -- constructors ---------------------------------------------------------

    @classmethod
    def from_node_selector_requirements(
        cls, *reqs: NodeSelectorRequirement
    ) -> "Requirements":
        return cls(*(Requirement(r.key, r.operator, r.values) for r in reqs))

    @classmethod
    def from_labels(cls, labels: Dict[str, str]) -> "Requirements":
        return cls(*(Requirement(k, OP_IN, [v]) for k, v in labels.items()))

    @classmethod
    def from_pod(cls, pod: Pod) -> "Requirements":
        """Node-selector + heaviest preferred term + first required term
        (requirements.go:61-78)."""
        requirements = cls.from_labels(pod.spec.node_selector)
        affinity = pod.spec.affinity
        if affinity is None or affinity.node_affinity is None:
            return requirements
        node_affinity = affinity.node_affinity
        if node_affinity.preferred:
            heaviest = max(node_affinity.preferred, key=lambda term: term.weight)
            requirements.add(
                *cls.from_node_selector_requirements(
                    *heaviest.preference.match_expressions
                ).values()
            )
        if node_affinity.required is not None and node_affinity.required.node_selector_terms:
            first = node_affinity.required.node_selector_terms[0]
            requirements.add(
                *cls.from_node_selector_requirements(*first.match_expressions).values()
            )
        return requirements

    # -- collection protocol --------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        for requirement in requirements:
            existing = self._items.get(requirement.key)
            if existing is not None:
                requirement = requirement.intersection(existing)
            self._items[requirement.key] = requirement

    def keys(self) -> set:
        return set(self._items)

    def values(self) -> List[Requirement]:
        return list(self._items.values())

    def has(self, key: str) -> bool:
        return key in self._items

    def get(self, key: str) -> Requirement:
        """Undefined keys behave as Exists (requirements.go:114-120)."""
        if key not in self._items:
            return Requirement(key, OP_EXISTS)
        return self._items[key]

    def delete(self, key: str) -> None:
        self._items.pop(key, None)

    def copy(self) -> "Requirements":
        return Requirements(*self.values())

    def __iter__(self) -> Iterator[str]:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._items

    # -- compatibility --------------------------------------------------------

    def compatible(self, requirements: "Requirements") -> Optional[str]:
        """None if the provided requirements can be met, else an error string
        (requirements.go:123-133).  Custom labels must intersect but are denied
        when undefined on the receiver; well-known labels are allowed when
        undefined.
        """
        errs: List[str] = []
        for key in requirements.keys() - labels_api.WELL_KNOWN_LABELS:
            operator = requirements.get(key).operator()
            if self.has(key) or operator in (OP_NOT_IN, OP_DOES_NOT_EXIST):
                continue
            errs.append(f"label {key!r} does not have known values{_label_hint(self, key)}")
        intersect_err = self.intersects(requirements)
        if intersect_err:
            errs.append(intersect_err)
        return "; ".join(errs) if errs else None

    def intersects(self, requirements: "Requirements") -> Optional[str]:
        """Error string when overlapping keys have empty intersections,
        except when both operators are negative (requirements.go:189-206)."""
        errs: List[str] = []
        for key in self.keys() & requirements.keys():
            existing = self.get(key)
            incoming = requirements.get(key)
            if existing.intersection(incoming).len() == 0:
                if incoming.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST) and existing.operator() in (
                    OP_NOT_IN,
                    OP_DOES_NOT_EXIST,
                ):
                    continue
                errs.append(f"key {key}, {incoming!r} not in {existing!r}")
        return "; ".join(errs) if errs else None

    def labels(self) -> Dict[str, str]:
        """Concrete labels renderable from the requirements (requirements.go:208-218)."""
        out: Dict[str, str] = {}
        for key, requirement in self._items.items():
            if not labels_api.is_restricted_node_label(key):
                value = requirement.any()
                if value:
                    out[key] = value
        return out

    def node_selector_requirements(self) -> List[NodeSelectorRequirement]:
        return [r.node_selector_requirement() for r in self._items.values()]

    def __repr__(self) -> str:
        shown = [
            repr(r)
            for r in self._items.values()
            if r.key not in labels_api.RESTRICTED_LABELS
        ]
        return ", ".join(shown)


def _edit_distance(s: str, t: str) -> int:
    m, n = len(s), len(t)
    if m == 0:
        return n
    if n == 0:
        return m
    prev = list(range(n))
    cur = [0] * n
    for i in range(1, m):
        for j in range(1, n):
            diff = 0 if s[i] == t[j] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + diff)
        prev, cur = cur, prev
    return prev[n - 1]


def _label_hint(r: Requirements, key: str) -> str:
    """Typo suggestions against well-known and defined labels
    (requirements.go:174-186)."""
    for well_known in labels_api.WELL_KNOWN_LABELS:
        if key in well_known or _edit_distance(key, well_known) < len(well_known) // 5:
            return f" (typo of {well_known!r}?)"
    for existing in r.keys():
        if key in existing or _edit_distance(key, existing) < len(existing) // 5:
            return f" (typo of {existing!r}?)"
    return ""
