from karpenter_core_tpu.scheduling.requirement import Requirement
from karpenter_core_tpu.scheduling.requirements import Requirements
from karpenter_core_tpu.scheduling.taints import Taints
from karpenter_core_tpu.scheduling.hostportusage import HostPortUsage
from karpenter_core_tpu.scheduling.volumeusage import VolumeUsage, VolumeCount

__all__ = ["Requirement", "Requirements", "Taints", "HostPortUsage", "VolumeUsage", "VolumeCount"]
