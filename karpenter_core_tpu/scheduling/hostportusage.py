"""Per-node <hostIP, hostPort, protocol> conflict tracking.

Mirror of /root/reference/pkg/scheduling/hostportusage.go:31-144.  Each
<hostIP, port, protocol> triple used by pods bound to a node must be unique;
an unspecified IP (0.0.0.0 / ::) conflicts with every IP on the same
port/protocol.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.apis.objects import Pod

_UNSPECIFIED = {"0.0.0.0", "::", ""}


@dataclass(frozen=True)
class _Entry:
    ip: str
    port: int
    protocol: str

    def matches(self, rhs: "_Entry") -> bool:
        if self.protocol != rhs.protocol or self.port != rhs.port:
            return False
        if self.ip != rhs.ip and self.ip not in _UNSPECIFIED and rhs.ip not in _UNSPECIFIED:
            return False
        return True

    def __str__(self) -> str:
        return f"IP={self.ip} Port={self.port} Proto={self.protocol}"


def _host_ports(pod: Pod) -> List[_Entry]:
    usage = []
    for container in pod.spec.containers:
        for port in container.ports:
            if port.host_port == 0:
                continue
            # K8s defaults hostIP to 0.0.0.0 and protocol to TCP.
            usage.append(_Entry(port.host_ip or "0.0.0.0", port.host_port, port.protocol or "TCP"))
    return usage


class HostPortUsage:
    def __init__(self) -> None:
        self.reserved: Dict[Tuple[str, str], List[_Entry]] = {}

    def validate(self, pod: Pod) -> Optional[str]:
        """Error string on host-port conflict, else None."""
        _, err = self._validate(pod)
        return err

    def add(self, pod: Pod) -> None:
        new_usage, _ = self._validate(pod)
        self.reserved[(pod.namespace, pod.name)] = new_usage

    def delete_pod(self, key: Tuple[str, str]) -> None:
        self.reserved.pop(key, None)

    def _validate(self, pod: Pod) -> Tuple[List[_Entry], Optional[str]]:
        new_usage = _host_ports(pod)
        pod_key = (pod.namespace, pod.name)
        for new_entry in new_usage:
            for key, entries in self.reserved.items():
                if key == pod_key:
                    continue
                for existing in entries:
                    if new_entry.matches(existing):
                        return [], (
                            f"{new_entry} conflicts with existing HostPort configuration {existing}"
                        )
        return new_usage, None

    def deep_copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out.reserved = copy.deepcopy(self.reserved)
        return out
