"""CSI-driver-keyed volume attach-limit counting.

Mirror of /root/reference/pkg/scheduling/volumeusage.go:33-236: tracks, per
node, the set of PVC ids mounted per CSI driver; ``VolumeCount.exceeds``
compares against per-driver attach limits from CSINode (absent driver limits
are unlimited).
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Set, Tuple

from karpenter_core_tpu.apis.objects import Pod


class VolumeCount(Dict[str, int]):
    def exceeds(self, limits: "VolumeCount") -> bool:
        for driver, count in self.items():
            if driver in limits and count > limits[driver]:
                return True
        return False

    def fits(self, rhs: "VolumeCount") -> bool:
        for driver, count in rhs.items():
            if driver in self and count > self[driver]:
                return False
        return True


_Volumes = Dict[str, Set[str]]  # driver -> pvc ids


def _union(a: _Volumes, b: _Volumes) -> _Volumes:
    out: _Volumes = {k: set(v) for k, v in a.items()}
    for k, v in b.items():
        out.setdefault(k, set()).update(v)
    return out


class VolumeUsage:
    """The kube_client is any object with get_persistent_volume_claim /
    get_persistent_volume / get_storage_class lookups (see
    karpenter_core_tpu.operator.kubeclient)."""

    def __init__(self, kube_client=None) -> None:
        self.kube_client = kube_client
        self.volumes: _Volumes = {}
        self.pod_volumes: Dict[Tuple[str, str], _Volumes] = {}

    def add(self, pod: Pod) -> None:
        pod_volumes, _ = self._validate(pod)
        self.pod_volumes[(pod.namespace, pod.name)] = pod_volumes
        self.volumes = _union(self.volumes, pod_volumes)

    def validate(self, pod: Pod) -> Tuple[Optional[VolumeCount], Optional[str]]:
        pod_volumes, err = self._validate(pod)
        if err is not None:
            return None, err
        result = VolumeCount()
        for driver, ids in _union(self.volumes, pod_volumes).items():
            result[driver] = result.get(driver, 0) + len(ids)
        return result, None

    def _validate(self, pod: Pod) -> Tuple[_Volumes, Optional[str]]:
        pod_pvcs: _Volumes = {}
        if self.kube_client is None:
            return pod_pvcs, None
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is None:
                continue
            claim_name = volume.persistent_volume_claim.claim_name
            pvc = self.kube_client.get_persistent_volume_claim(pod.namespace, claim_name)
            if pvc is None:
                return {}, f"pvc {pod.namespace}/{claim_name} not found"
            pvc_id = f"{pod.namespace}/{claim_name}"
            driver_name = ""
            if pvc.spec.volume_name:
                pv = self.kube_client.get_persistent_volume(pvc.spec.volume_name)
                if pv is None:
                    return {}, f"pv {pvc.spec.volume_name} not found"
                driver_name = pv.spec.csi_driver
            elif pvc.spec.storage_class_name:
                sc = self.kube_client.get_storage_class(pvc.spec.storage_class_name)
                if sc is None:
                    return {}, f"storage class {pvc.spec.storage_class_name} not found"
                driver_name = sc.provisioner
            if driver_name:
                pod_pvcs.setdefault(driver_name, set()).add(pvc_id)
        return pod_pvcs, None

    def delete_pod(self, key: Tuple[str, str]) -> None:
        self.pod_volumes.pop(key, None)
        self.volumes = {}
        for vols in self.pod_volumes.values():
            self.volumes = _union(self.volumes, vols)

    def deep_copy(self) -> "VolumeUsage":
        out = VolumeUsage(self.kube_client)
        out.volumes = copy.deepcopy(self.volumes)
        out.pod_volumes = copy.deepcopy(self.pod_volumes)
        return out
