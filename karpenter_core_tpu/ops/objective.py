"""Batched objective kernel: score and select among feasible offerings.

Runs AFTER ``ops.solve.solve_core`` feasibility: the solve's per-node planes
(viable instance types, surviving zone / capacity-type masks) define each new
node's feasible offering cells, and this kernel scores every cell with the
policy objective and argmin-selects one offering per node in a single
vectorized pass — the batched form of the host helpers that today answer the
same question one node at a time (``Offerings.cheapest``,
``worst_launch_price``).

Objective of one (instance type i, zone z, capacity type ct) cell:

    expected[i,z,ct] = price[i,z,ct] * (1 + risk_aversion * risk[i,z,ct])
    score[i,z,ct]    = cost_weight * expected[i,z,ct]
                       - throughput_weight * throughput[i]

Selection semantics (parity-pinned in tests/test_policy.py):

  - default weights (cost 1, risk 0, throughput 0) reduce the score to the
    offering price, so the selected price equals ``Offerings.cheapest()``
    over the node's feasible offering set — the host oracle, exactly;
  - exact score ties prefer spot when ``spot_preference`` is set (the host
    convention: ``worst_launch_price`` consults spot before on-demand and
    consolidation pins spot when both survive), then break deterministically
    by (instance-type index, zone index, capacity-type index) — the same
    stable order the catalog encode fixed.

Everything here is trace-safe device code; the host-facing entry
(``select_for_state``) builds the weight scalars from a PolicyConfig and
returns numpy-backed selections for decode to stamp onto node decisions.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class ObjectiveWeights(NamedTuple):
    """Traced scalar knobs — traced (not static) so weight changes reuse the
    compiled executable; shapes alone key the jit cache."""

    cost_weight: jnp.ndarray  # f32[]
    throughput_weight: jnp.ndarray  # f32[]
    risk_aversion: jnp.ndarray  # f32[]
    spot_preference: jnp.ndarray  # bool[]


class ObjectiveSelection(NamedTuple):
    """Per-new-node-slot argmin selection (leading dim N)."""

    sel_it: jnp.ndarray  # i32[N] selected instance-type index
    sel_zone: jnp.ndarray  # i32[N]
    sel_ct: jnp.ndarray  # i32[N]
    price: jnp.ndarray  # f32[N] raw offering price at the selection
    expected: jnp.ndarray  # f32[N] risk-weighted expected cost
    active: jnp.ndarray  # bool[N] open, pod-carrying, selectable slots
    fleet_cost: jnp.ndarray  # f32[] sum of selected prices over active slots
    fleet_expected: jnp.ndarray  # f32[] risk-weighted fleet cost


def weights_of(config) -> ObjectiveWeights:
    return ObjectiveWeights(
        cost_weight=jnp.float32(config.cost_weight),
        throughput_weight=jnp.float32(config.throughput_weight),
        risk_aversion=jnp.float32(config.risk_aversion),
        spot_preference=jnp.asarray(bool(config.spot_preference)),
    )


def cell_scores(price, risk, throughput, weights: ObjectiveWeights):
    """(expected f32[I,Z,CT], score f32[I,Z,CT]) of every offering cell —
    shared by selection here and by the risk-weighted replica studies in
    parallel.mesh."""
    expected = price * (1.0 + weights.risk_aversion * risk)
    score = (
        weights.cost_weight * expected
        - weights.throughput_weight * throughput[:, None, None]
    )
    return expected, score


@jax.jit
def select_offerings(
    viable: jnp.ndarray,  # bool[N, I]
    zone: jnp.ndarray,  # bool[N, Z]
    ct: jnp.ndarray,  # bool[N, CT]
    open_: jnp.ndarray,  # bool[N]
    pod_count: jnp.ndarray,  # i32[N]
    price: jnp.ndarray,  # f32[I, Z, CT] (+inf no offering)
    risk: jnp.ndarray,  # f32[I, Z, CT]
    throughput: jnp.ndarray,  # f32[I]
    is_spot: jnp.ndarray,  # bool[CT]
    weights: ObjectiveWeights,
) -> ObjectiveSelection:
    n = viable.shape[0]
    n_zct = zone.shape[1] * ct.shape[1]
    n_ct = ct.shape[1]
    expected, score = cell_scores(price, risk, throughput, weights)
    allowed = (
        viable[:, :, None, None]
        & zone[:, None, :, None]
        & ct[:, None, None, :]
        & jnp.isfinite(price)[None, :, :, :]
    )
    scored = jnp.where(allowed, score[None], jnp.inf).reshape(n, -1)
    best = jnp.min(scored, axis=1)
    has_any = jnp.isfinite(best)
    # exact-tie set, then the spot-preference filter: among tied cells keep
    # the spot ones when any exist (and the knob is on); argmax then takes
    # the FIRST tied cell in (it, zone, ct) row-major order — deterministic,
    # and matching the catalog's stable index order on full ties
    is_best = scored == best[:, None]
    spot_flat = jnp.broadcast_to(
        is_spot[None, None, :], price.shape
    ).reshape(-1)
    spot_ties = is_best & spot_flat[None, :]
    use_spot = weights.spot_preference & jnp.any(spot_ties, axis=1)
    candidates = jnp.where(use_spot[:, None], spot_ties, is_best)
    sel = jnp.argmax(candidates, axis=1).astype(jnp.int32)
    sel_it = sel // n_zct
    sel_zone = (sel % n_zct) // n_ct
    sel_ct = sel % n_ct
    sel_price = price.reshape(-1)[sel]
    sel_expected = expected.reshape(-1)[sel]
    active = open_ & (pod_count > 0) & has_any
    zero = jnp.float32(0.0)
    fleet_cost = jnp.sum(jnp.where(active, sel_price, zero))
    fleet_expected = jnp.sum(jnp.where(active, sel_expected, zero))
    return ObjectiveSelection(
        sel_it=sel_it,
        sel_zone=sel_zone,
        sel_ct=sel_ct,
        price=sel_price,
        expected=sel_expected,
        active=active,
        fleet_cost=fleet_cost,
        fleet_expected=fleet_expected,
    )


def select_for_state(state, planes, config, capacity_types) -> ObjectiveSelection:
    """Host entry: run the selection kernel over a solve's final NodeState
    with the snapshot's objective planes, returning host-fetched arrays.
    ``capacity_types`` is the snapshot's CT axis (names), spot-detected by
    the well-known label value."""
    from karpenter_core_tpu.apis import labels as labels_api

    is_spot = np.array(
        [name == labels_api.CAPACITY_TYPE_SPOT for name in capacity_types],
        dtype=bool,
    )
    selection = select_offerings(
        state.viable, state.zone, state.ct, state.open_, state.pod_count,
        jnp.asarray(planes.price), jnp.asarray(planes.risk),
        jnp.asarray(planes.throughput), jnp.asarray(is_spot),
        weights_of(config),
    )
    from karpenter_core_tpu.utils import watchdog

    # the objective stage's device→host fetch blocks like every barrier:
    # watchdog-bounded so a quiet device fails the decode, not the process
    return ObjectiveSelection(
        *watchdog.run(
            "pipeline.fetch", jax.device_get, tuple(selection),
            key="objective",
        )
    )
