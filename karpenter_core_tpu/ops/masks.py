"""Requirement-set algebra as boolean-mask kernels.

The tensorized form of karpenter_core_tpu.scheduling.{requirement,requirements}
(which mirror /root/reference/pkg/scheduling/requirement.go:117-150 and
requirements.go:123-206).  At snapshot-encode time every label key's value
universe is finite, so a Requirement over key k becomes a boolean mask over
``V_k + 1`` slots — the final slot means "values outside the vocabulary" and
carries the complement bit: an In set has other=0, a NotIn/Exists complement
has other=1.  Gt/Lt bounds ride as separate ±inf float planes; overlap through
*unseen* values is then computed exactly: two complements overlap outside the
vocabulary iff their combined integer range (or the unbounded string universe)
contains at least one value not in the vocabulary.

With that encoding:
  - Intersection            = elementwise AND + bound max/min
  - "intersection nonempty" = any(AND) | unseen-range overlap
  - Compatible / Intersects = masked all-reductions over keys (below)

All functions broadcast over leading batch axes and are jit/vmap-safe.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

NEG_INF = -jnp.inf
POS_INF = jnp.inf


class ReqTensor(NamedTuple):
    """A batch of requirement sets in mask form.

    mask:     bool[..., K, V+1]  allowed vocabulary values per key (undefined
                                 keys = all ones); slot V = "unseen values"
    defined:  bool[..., K]       key explicitly present
    negative: bool[..., K]       operator is NotIn or DoesNotExist
    gt:       f32[..., K]        exclusive lower bound (-inf when absent)
    lt:       f32[..., K]        exclusive upper bound (+inf when absent)
    """

    mask: jnp.ndarray
    defined: jnp.ndarray
    negative: jnp.ndarray
    gt: jnp.ndarray
    lt: jnp.ndarray


def _unseen_overlap(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """bool[..., K]: both sides admit some value OUTSIDE the vocabulary.

    Requires both other-slots set.  With no bounds the unseen string universe
    is infinite.  With bounds, only integers strictly inside (gt, lt) qualify
    (requirement.go:227-243 withinIntPtrs rejects non-ints under bounds);
    the count of such integers minus those already in the vocabulary must be
    positive.  ``vocab_ints`` is f32[K, V] — each key's vocabulary values as
    numbers, +inf where non-numeric (never inside a finite range).
    """
    both_other = a.mask[..., -1] & b.mask[..., -1]
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    # number of integers strictly between the bounds (inf when unbounded)
    n_range = jnp.maximum(jnp.ceil(lt) - jnp.floor(gt) - 1.0, 0.0)
    if vocab_ints is None:
        n_vocab_in_range = jnp.zeros_like(gt)
    else:
        inside = (vocab_ints > gt[..., None]) & (vocab_ints < lt[..., None])
        n_vocab_in_range = jnp.sum(inside.astype(jnp.float32), axis=-1)
    return both_other & (n_range - n_vocab_in_range >= 1.0)


def nonempty_intersection(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """bool[..., K]: per-key Intersection(a, b).Len() > 0."""
    vocab_overlap = jnp.any(a.mask[..., :-1] & b.mask[..., :-1], axis=-1)
    return vocab_overlap | _unseen_overlap(a, b, vocab_ints)


def derive_negative(
    mask: jnp.ndarray,
    gt: jnp.ndarray,
    lt: jnp.ndarray,
    valid: jnp.ndarray,
    vocab_ints: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """bool[..., K]: operator ∈ {NotIn, DoesNotExist} for a mask-form set.

    Mirrors requirement.go:186-197 Operator(): a complement is NotIn iff its
    exclusion list is non-empty — and bounds drop out-of-range values from the
    exclusion list (requirement.go:139-143), so only *within-bounds* vocabulary
    values count as exclusions.  A concrete empty set is DoesNotExist.
    """
    bounds_set = jnp.isfinite(gt) | jnp.isfinite(lt)
    if vocab_ints is None:
        within = jnp.ones(valid.shape[:-1] + (valid.shape[-1] - 1,), dtype=bool)
    else:
        in_range = (vocab_ints > gt[..., None]) & (vocab_ints < lt[..., None])
        within = jnp.where(bounds_set[..., None], in_range, True)
    exclusions = jnp.any(valid[..., :-1] & ~mask[..., :-1] & within, axis=-1)
    empty = ~jnp.any(mask, axis=-1)
    return (mask[..., -1] & exclusions) | empty


def intersection(
    a: ReqTensor,
    b: ReqTensor,
    valid: Optional[jnp.ndarray] = None,
    vocab_ints: Optional[jnp.ndarray] = None,
) -> ReqTensor:
    """Key-wise intersection (requirement.go:117-150 under the mask encoding).

    Bound filtering of vocabulary values is already baked into each side's
    mask; combined bounds propagate by max/min.  Operator negativity is
    re-derived from the result (see derive_negative) when ``valid`` is given;
    the fallback (both-negative | empty) is exact except for complements whose
    exclusion lists change NotIn↔Exists across the intersection.
    """
    mask = a.mask & b.mask
    defined = a.defined | b.defined
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    if valid is not None:
        negative = derive_negative(mask, gt, lt, valid, vocab_ints)
    else:
        empty = ~jnp.any(mask, axis=-1)
        negative = (a.negative & b.negative) | empty
    return ReqTensor(mask, defined, negative, gt, lt)


def intersects(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """bool[...]: requirements.go:189-206 Intersects == nil.

    Only keys defined on BOTH sides are checked; an empty intersection is
    forgiven when both operators are negative (NotIn/DoesNotExist).
    """
    checked = a.defined & b.defined
    nonempty = nonempty_intersection(a, b, vocab_ints)
    both_negative = a.negative & b.negative
    key_ok = ~checked | nonempty | both_negative
    return jnp.all(key_ok, axis=-1)


def compatible(
    a: ReqTensor,
    b: ReqTensor,
    is_custom: jnp.ndarray,
    vocab_ints: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """bool[...]: requirements.go:123-133 Compatible == nil, a=node side,
    b=incoming (pod) side.

    Adds the custom-label rule to Intersects: a custom (non-well-known) key
    required positively (In/Exists/Gt/Lt) by ``b`` must be defined on ``a``.
    ``is_custom`` is bool[K] from the vocabulary.
    """
    denied = is_custom & b.defined & ~b.negative & ~a.defined
    return intersects(a, b, vocab_ints) & ~jnp.any(denied, axis=-1)


def add(
    a: ReqTensor,
    b: ReqTensor,
    valid: Optional[jnp.ndarray] = None,
    vocab_ints: Optional[jnp.ndarray] = None,
) -> ReqTensor:
    """Requirements.Add: a tightened by b (intersect-on-add per key,
    requirements.go:87-94)."""
    return intersection(a, b, valid, vocab_ints)


def count_allowed(a: ReqTensor, valid: jnp.ndarray) -> jnp.ndarray:
    """int32[..., K]: number of in-vocabulary values allowed per key.  The
    "other" slot is excluded — callers needing Len()-infinite semantics should
    test mask[..., -1] directly."""
    return jnp.sum((a.mask & valid).astype(jnp.int32)[..., :-1], axis=-1)


def single_value(a: ReqTensor) -> jnp.ndarray:
    """bool[..., K]: the key collapsed to exactly one in-vocab value and
    excludes unseen values — the condition under which topology Record counts
    a domain (topology.go:129-131)."""
    in_vocab = jnp.sum(a.mask[..., :-1].astype(jnp.int32), axis=-1)
    return (in_vocab == 1) & ~a.mask[..., -1]
