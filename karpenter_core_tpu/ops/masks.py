"""Requirement-set algebra as boolean-mask kernels.

The tensorized form of karpenter_core_tpu.scheduling.{requirement,requirements}
(which mirror /root/reference/pkg/scheduling/requirement.go:117-150 and
requirements.go:123-206).  At snapshot-encode time every label key's value
universe is finite, so a Requirement over key k becomes a boolean mask over
``V_k + 1`` slots — the final slot means "values outside the vocabulary" and
carries the complement bit: an In set has other=0, a NotIn/Exists complement
has other=1.  Gt/Lt bounds ride as separate ±inf float planes; overlap through
*unseen* values is then computed exactly: two complements overlap outside the
vocabulary iff their combined integer range (or the unbounded string universe)
contains at least one value not in the vocabulary.

With that encoding:
  - Intersection            = elementwise AND + bound max/min
  - "intersection nonempty" = any(AND) | unseen-range overlap
  - Compatible / Intersects = masked all-reductions over keys (below)

Two storage layouts share one API.  The classic layout keeps the slots as a
bool plane ``[..., K, V+1]``; the *bit-packed* layout stores the same slots as
uint32 words ``[..., K, ceil((V+1)/32)]`` (``pack_mask``), which shrinks the
solve kernel's scan carry up to 32× and turns every slot reduction into a
word-wide AND + nonzero test (~100× faster than the bf16 einsum path on CPU
at bench shapes; the layout the future Pallas kernel will consume directly).
A ReqTensor is packed iff ``mask.dtype == uint32``; packed callers must pass
``v`` — the semantic slot count V+1 — because the word plane cannot recover
it.  The einsum/bool path stays fully supported (parity-fuzzed in
tests/test_kernel_fusion_parity.py) behind the kernel's ``packed_masks``
flag.

All functions broadcast over leading batch axes and are jit/vmap-safe.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

NEG_INF = -jnp.inf
POS_INF = jnp.inf

WORD = 32  # bits per packed mask word


def words_for(v: int) -> int:
    """Packed words needed for ``v`` slots."""
    return -(-int(v) // WORD)


def pack_mask(mask: jnp.ndarray) -> jnp.ndarray:
    """uint32[..., W] bit-packing of bool[..., M]: bit j of word w is slot
    ``w*32+j``.  Pad bits beyond M are zero (reductions never see phantom
    slots).  jit/vmap-safe; also accepts numpy input."""
    m = mask.shape[-1]
    pad = (-m) % WORD
    if pad:
        mask = jnp.pad(mask, [(0, 0)] * (mask.ndim - 1) + [(0, pad)])
    grouped = mask.reshape(mask.shape[:-1] + (-1, WORD)).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    return jnp.sum(grouped * weights, axis=-1, dtype=jnp.uint32)


def unpack_mask(words: jnp.ndarray, m: int) -> jnp.ndarray:
    """bool[..., m] inverse of pack_mask."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(bits.shape[:-2] + (-1,))
    return flat[..., :m] != 0


@functools.lru_cache(maxsize=64)
def full_words(v: int) -> np.ndarray:
    """uint32[W] constant with bits 0..v-1 set (all semantic slots)."""
    bits = np.ones(v, dtype=bool)
    pad = (-v) % WORD
    bits = np.pad(bits, (0, pad))
    weights = (np.uint32(1) << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)
    return (bits.reshape(-1, WORD) * weights).sum(axis=-1).astype(np.uint32)


@functools.lru_cache(maxsize=64)
def vocab_words(v: int) -> np.ndarray:
    """uint32[W] constant selecting the V in-vocabulary slots (drops the
    trailing "unseen" slot v-1)."""
    w = full_words(v).copy()
    w[(v - 1) // WORD] &= ~np.uint32(1 << ((v - 1) % WORD))
    return w


def other_bit(words: jnp.ndarray, v: int) -> jnp.ndarray:
    """bool[...]: the trailing "unseen values" slot of a packed mask."""
    return (words[..., (v - 1) // WORD] & jnp.uint32(1 << ((v - 1) % WORD))) != 0


def not_words(words: jnp.ndarray, v: int) -> jnp.ndarray:
    """Slot-complement of a packed mask (pad bits stay zero)."""
    return ~words & jnp.asarray(full_words(v))


def is_packed(t: "ReqTensor") -> bool:
    return t.mask.dtype == jnp.uint32


class ReqTensor(NamedTuple):
    """A batch of requirement sets in mask form.

    mask:     bool[..., K, V+1]  allowed vocabulary values per key (undefined
                                 keys = all ones); slot V = "unseen values".
                                 Bit-packed layout: uint32[..., K, W] words
                                 over the same slots (see pack_mask; callers
                                 pass ``v`` = V+1 to the ops below)
    defined:  bool[..., K]       key explicitly present
    negative: bool[..., K]       operator is NotIn or DoesNotExist
    gt:       f32[..., K]        exclusive lower bound (-inf when absent)
    lt:       f32[..., K]        exclusive upper bound (+inf when absent)
    """

    mask: jnp.ndarray
    defined: jnp.ndarray
    negative: jnp.ndarray
    gt: jnp.ndarray
    lt: jnp.ndarray


def pack_req(t: ReqTensor) -> ReqTensor:
    """Bit-pack a bool-layout ReqTensor's mask plane (no-op when packed)."""
    if is_packed(t):
        return t
    return t._replace(mask=pack_mask(t.mask))


def _other_slot(mask: jnp.ndarray, v: Optional[int]) -> jnp.ndarray:
    if mask.dtype == jnp.uint32:
        return other_bit(mask, v)
    return mask[..., -1]


def _unseen_range_overlap(
    gt: jnp.ndarray, lt: jnp.ndarray, vocab_ints: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """bool[..., K]: the combined (gt, lt) range admits some value OUTSIDE the
    vocabulary.  With no bounds the unseen string universe is infinite.  With
    bounds, only integers strictly inside (gt, lt) qualify
    (requirement.go:227-243 withinIntPtrs rejects non-ints under bounds); the
    count of such integers minus those already in the vocabulary must be
    positive.  ``vocab_ints`` is f32[K, V] — each key's vocabulary values as
    numbers, +inf where non-numeric (never inside a finite range)."""
    # number of integers strictly between the bounds (inf when unbounded)
    n_range = jnp.maximum(jnp.ceil(lt) - jnp.floor(gt) - 1.0, 0.0)
    if vocab_ints is None:
        n_vocab_in_range = jnp.zeros_like(gt)
    else:
        inside = (vocab_ints > gt[..., None]) & (vocab_ints < lt[..., None])
        n_vocab_in_range = jnp.sum(inside.astype(jnp.float32), axis=-1)
    return n_range - n_vocab_in_range >= 1.0


def _unseen_overlap(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray],
    v: Optional[int] = None,
) -> jnp.ndarray:
    """bool[..., K]: both sides admit some value OUTSIDE the vocabulary."""
    both_other = _other_slot(a.mask, v) & _other_slot(b.mask, v)
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    return both_other & _unseen_range_overlap(gt, lt, vocab_ints)


def nonempty_intersection(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray] = None,
    v: Optional[int] = None,
) -> jnp.ndarray:
    """bool[..., K]: per-key Intersection(a, b).Len() > 0."""
    if is_packed(a):
        vw = jnp.asarray(vocab_words(v))
        vocab_overlap = jnp.any((a.mask & b.mask & vw) != 0, axis=-1)
    else:
        vocab_overlap = jnp.any(a.mask[..., :-1] & b.mask[..., :-1], axis=-1)
    return vocab_overlap | _unseen_overlap(a, b, vocab_ints, v)


def derive_negative(
    mask: jnp.ndarray,
    gt: jnp.ndarray,
    lt: jnp.ndarray,
    valid: jnp.ndarray,
    vocab_ints: Optional[jnp.ndarray],
    v: Optional[int] = None,
    key_has_bounds=None,
) -> jnp.ndarray:
    """bool[..., K]: operator ∈ {NotIn, DoesNotExist} for a mask-form set.

    Mirrors requirement.go:186-197 Operator(): a complement is NotIn iff its
    exclusion list is non-empty — and bounds drop out-of-range values from the
    exclusion list (requirement.go:139-143), so only *within-bounds* vocabulary
    values count as exclusions.  A concrete empty set is DoesNotExist.

    Packed layout (``mask``/``valid`` uint32 words): the bounds correction
    needs per-slot range tests, so it unpacks the (rare) exclusion words —
    skipped entirely when ``key_has_bounds`` (static per-key tuple) says no
    key carries Gt/Lt anywhere in the problem, the common case.
    """
    if mask.dtype == jnp.uint32:
        vw = jnp.asarray(vocab_words(v))
        excl_words = valid & ~mask & vw
        exclusions = jnp.any(excl_words != 0, axis=-1)
        needs_bounds = vocab_ints is not None and (
            key_has_bounds is None or any(key_has_bounds)
        )
        if needs_bounds:
            bounds_set = jnp.isfinite(gt) | jnp.isfinite(lt)
            in_range = (vocab_ints > gt[..., None]) & (vocab_ints < lt[..., None])
            excl_bits = unpack_mask(excl_words, v)[..., : v - 1]
            excl_bounded = jnp.any(excl_bits & in_range, axis=-1)
            exclusions = jnp.where(bounds_set, excl_bounded, exclusions)
        empty = ~jnp.any(mask != 0, axis=-1)
        return (other_bit(mask, v) & exclusions) | empty
    bounds_set = jnp.isfinite(gt) | jnp.isfinite(lt)
    if vocab_ints is None:
        within = jnp.ones(valid.shape[:-1] + (valid.shape[-1] - 1,), dtype=bool)
    else:
        in_range = (vocab_ints > gt[..., None]) & (vocab_ints < lt[..., None])
        within = jnp.where(bounds_set[..., None], in_range, True)
    exclusions = jnp.any(valid[..., :-1] & ~mask[..., :-1] & within, axis=-1)
    empty = ~jnp.any(mask, axis=-1)
    return (mask[..., -1] & exclusions) | empty


def intersection(
    a: ReqTensor,
    b: ReqTensor,
    valid: Optional[jnp.ndarray] = None,
    vocab_ints: Optional[jnp.ndarray] = None,
    v: Optional[int] = None,
    key_has_bounds=None,
) -> ReqTensor:
    """Key-wise intersection (requirement.go:117-150 under the mask encoding).

    Bound filtering of vocabulary values is already baked into each side's
    mask; combined bounds propagate by max/min.  Operator negativity is
    re-derived from the result (see derive_negative) when ``valid`` is given;
    the fallback (both-negative | empty) is exact except for complements whose
    exclusion lists change NotIn↔Exists across the intersection.
    """
    mask = a.mask & b.mask
    defined = a.defined | b.defined
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    if valid is not None:
        negative = derive_negative(mask, gt, lt, valid, vocab_ints, v, key_has_bounds)
    else:
        if mask.dtype == jnp.uint32:
            empty = ~jnp.any(mask != 0, axis=-1)
        else:
            empty = ~jnp.any(mask, axis=-1)
        negative = (a.negative & b.negative) | empty
    return ReqTensor(mask, defined, negative, gt, lt)


def intersects(
    a: ReqTensor, b: ReqTensor, vocab_ints: Optional[jnp.ndarray] = None,
    v: Optional[int] = None,
) -> jnp.ndarray:
    """bool[...]: requirements.go:189-206 Intersects == nil.

    Only keys defined on BOTH sides are checked; an empty intersection is
    forgiven when both operators are negative (NotIn/DoesNotExist).
    """
    checked = a.defined & b.defined
    nonempty = nonempty_intersection(a, b, vocab_ints, v)
    both_negative = a.negative & b.negative
    key_ok = ~checked | nonempty | both_negative
    return jnp.all(key_ok, axis=-1)


def compatible(
    a: ReqTensor,
    b: ReqTensor,
    is_custom: jnp.ndarray,
    vocab_ints: Optional[jnp.ndarray] = None,
    v: Optional[int] = None,
) -> jnp.ndarray:
    """bool[...]: requirements.go:123-133 Compatible == nil, a=node side,
    b=incoming (pod) side.

    Adds the custom-label rule to Intersects: a custom (non-well-known) key
    required positively (In/Exists/Gt/Lt) by ``b`` must be defined on ``a``.
    ``is_custom`` is bool[K] from the vocabulary.
    """
    denied = is_custom & b.defined & ~b.negative & ~a.defined
    return intersects(a, b, vocab_ints, v) & ~jnp.any(denied, axis=-1)


def add(
    a: ReqTensor,
    b: ReqTensor,
    valid: Optional[jnp.ndarray] = None,
    vocab_ints: Optional[jnp.ndarray] = None,
    v: Optional[int] = None,
    key_has_bounds=None,
) -> ReqTensor:
    """Requirements.Add: a tightened by b (intersect-on-add per key,
    requirements.go:87-94)."""
    return intersection(a, b, valid, vocab_ints, v, key_has_bounds)


def count_allowed(
    a: ReqTensor, valid: jnp.ndarray, v: Optional[int] = None
) -> jnp.ndarray:
    """int32[..., K]: number of in-vocabulary values allowed per key.  The
    "other" slot is excluded — callers needing Len()-infinite semantics should
    test the other slot directly."""
    if is_packed(a):
        import jax

        vw = jnp.asarray(vocab_words(v))
        return jnp.sum(
            jax.lax.population_count(a.mask & valid & vw), axis=-1
        ).astype(jnp.int32)
    return jnp.sum((a.mask & valid).astype(jnp.int32)[..., :-1], axis=-1)


def single_value(a: ReqTensor, v: Optional[int] = None) -> jnp.ndarray:
    """bool[..., K]: the key collapsed to exactly one in-vocab value and
    excludes unseen values — the condition under which topology Record counts
    a domain (topology.go:129-131)."""
    if is_packed(a):
        import jax

        vw = jnp.asarray(vocab_words(v))
        in_vocab = jnp.sum(
            jax.lax.population_count(a.mask & vw), axis=-1
        ).astype(jnp.int32)
        return (in_vocab == 1) & ~other_bit(a.mask, v)
    in_vocab = jnp.sum(a.mask[..., :-1].astype(jnp.int32), axis=-1)
    return (in_vocab == 1) & ~a.mask[..., -1]
