"""Consolidation search as a parallel subset sweep on TPU.

The reference's multi-node consolidation binary-searches the first-N prefix of
disruption-sorted candidates, one full scheduling simulation per probe
(multinodeconsolidation.go:74-114).  Here every prefix size is evaluated
simultaneously: the simulation (a solve with the subset's nodes closed and
their pods re-injected) is vmapped over the prefix axis, so one device pass
answers "what is the largest set of nodes we can delete/replace" — and, unlike
binary search, it does not assume monotonic feasibility.  This is the
pmap-over-candidate-subsets search of BASELINE.json config 3.

The host wrapper (solver.consolidation) applies the price/spot validity rules
to each lane's decoded replacement and picks the largest valid prefix.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.utils import compilecache


class SweepOutputs(NamedTuple):
    """Per-lane (prefix size) results; leading dim S."""

    n_new: jnp.ndarray  # i32[S] new nodes the simulation opened
    failed: jnp.ndarray  # i32[S] pods that failed to schedule
    used_uninitialized: jnp.ndarray  # bool[S] relied on an uninitialized node
    new_viable: jnp.ndarray  # bool[S, M, I] replacement instance viability
    new_zone: jnp.ndarray  # bool[S, M, Z]
    new_ct: jnp.ndarray  # bool[S, M, CT]
    new_used: jnp.ndarray  # f32[S, M, R]
    new_tmpl: jnp.ndarray  # i32[S, M]
    # fleet cost of the lane's replacement nodes: sum over opened slots of
    # the cheapest surviving offering price (ops.solve.node_prices) — the
    # in-kernel half of policy-aware cost-delta consolidation (docs/POLICY.md)
    new_cost: jnp.ndarray  # f32[S]


def sweep(
    class_tensors,
    statics_arrays,
    key_has_bounds,
    ex_state: solve_ops.ExistingState,
    ex_static: solve_ops.ExistingStatic,
    candidate_rank: jnp.ndarray,  # i32[E]: position in disruption order, big=not candidate
    ex_cls_count: jnp.ndarray,  # i32[C, E]: candidate pods per class per node
    prefix_sizes: jnp.ndarray,  # i32[S]
    it_price: jnp.ndarray,  # f32[I, Z, CT] offering price sheet
    n_slots: int = 16,
    n_passes: int = 1,
    features=None,
) -> SweepOutputs:
    """Simulate closing the first-k candidates for every k in prefix_sizes."""


    def one_prefix(k):
        subset = candidate_rank < k  # bool[E]
        # close the subset's nodes; the topology count seeds derive from
        # grp_node_member/owner masked by open_, so pre-existing pods on
        # removed nodes stop counting automatically (excludedPods semantics)
        ex = ex_state._replace(open_=ex_state.open_ & ~subset)
        # displaced pods join their classes
        displaced = jnp.sum(
            ex_cls_count * subset[None, :].astype(jnp.int32), axis=-1
        )  # [C]
        cls = class_tensors._replace(count=class_tensors.count + displaced)
        out = solve_ops.solve_core(
            cls, statics_arrays, n_slots, key_has_bounds, ex, ex_static,
            n_passes=n_passes, features=features,
        )
        n_new = out.state.n_next
        failed = jnp.sum(out.failed)
        uninit = jnp.any(
            (out.assign_existing > 0) & ~ex_static.init[None, :]
        )
        prices = solve_ops.node_prices(out.state, it_price)
        cost = jnp.sum(jnp.where(jnp.isfinite(prices), prices, 0.0))
        return (
            n_new,
            failed,
            uninit,
            out.state.viable,
            out.state.zone,
            out.state.ct,
            out.state.used,
            out.state.tmpl_id,
            cost,
        )

    results = jax.vmap(one_prefix)(prefix_sizes)
    return SweepOutputs(*results)


_sweep_jit = functools.partial(
    jax.jit, static_argnames=("key_has_bounds", "n_slots", "n_passes", "features")
)(sweep)


@functools.lru_cache(maxsize=16)
def _sharded_sweep_fn(mesh, key_has_bounds, n_slots: int, n_passes: int = 1,
                      features=None):
    """Cached jitted sweep with the lane axis sharded over the mesh — a fresh
    closure per call would defeat JAX's compile cache (keyed on callable
    identity) and recompile every sweep."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lane_sharded = NamedSharding(mesh, P("replica"))

    def core(sizes_arg, cls_arg, statics_arg, ex_state_arg, ex_static_arg,
             rank_arg, counts_arg, price_arg):
        return sweep(
            cls_arg, statics_arg, key_has_bounds, ex_state_arg, ex_static_arg,
            rank_arg, counts_arg, sizes_arg, price_arg, n_slots=n_slots,
            n_passes=n_passes, features=features,
        )

    return jax.jit(
        core, in_shardings=(lane_sharded,) + (None,) * 7
    )


def run_sweep(
    snapshot,
    ex_state,
    ex_static,
    candidate_rank: np.ndarray,
    ex_cls_count: np.ndarray,
    prefix_sizes: np.ndarray,
    n_slots: int = 16,
    mesh=None,
) -> SweepOutputs:
    """With ``mesh``, the lane (prefix) axis shards across devices — each chip
    simulates its share of the subsets; lanes are independent so the only
    cross-device traffic is the gather of per-lane results."""
    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    sizes = jnp.asarray(prefix_sizes)
    it_price = jnp.asarray(snapshot.it_price)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        n_dev = mesh.devices.size
        pad = (-len(prefix_sizes)) % n_dev
        if pad:
            sizes = jnp.concatenate([sizes, jnp.repeat(sizes[-1:], pad)])
        fn = _sharded_sweep_fn(
            mesh, key_has_bounds, n_slots, snapshot.scan_passes,
            compilecache.snap_features(
                solve_ops.features_with_existing(snapshot, ex_static)
            ),
        )
        with mesh:
            out = fn(
                sizes, cls, statics_arrays, ex_state, ex_static,
                jnp.asarray(candidate_rank), jnp.asarray(ex_cls_count),
                it_price,
            )
        if pad:
            out = SweepOutputs(*(np.asarray(plane)[: len(prefix_sizes)] for plane in out))
        return out
    return _sweep_jit(
        cls,
        statics_arrays,
        key_has_bounds,
        ex_state,
        ex_static,
        jnp.asarray(candidate_rank),
        jnp.asarray(ex_cls_count),
        sizes,
        it_price,
        n_slots=n_slots,
        n_passes=snapshot.scan_passes,
        features=compilecache.snap_features(
            solve_ops.features_with_existing(snapshot, ex_static)
        ),
    )
