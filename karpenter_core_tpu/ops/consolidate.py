"""Consolidation search as a parallel subset sweep on TPU.

The reference's multi-node consolidation binary-searches the first-N prefix of
disruption-sorted candidates, one full scheduling simulation per probe
(multinodeconsolidation.go:74-114).  Here every prefix size is evaluated
simultaneously: the simulation (a solve with the subset's nodes closed and
their pods re-injected) is vmapped over the prefix axis, so one device pass
answers "what is the largest set of nodes we can delete/replace" — and, unlike
binary search, it does not assume monotonic feasibility.  This is the
pmap-over-candidate-subsets search of BASELINE.json config 3.

The host wrapper (solver.consolidation) applies the price/spot validity rules
to each lane's decoded replacement and picks the largest valid prefix.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.utils import compilecache


class SweepOutputs(NamedTuple):
    """Per-lane (prefix size) results; leading dim S."""

    n_new: jnp.ndarray  # i32[S] new nodes the simulation opened
    failed: jnp.ndarray  # i32[S] pods that failed to schedule
    used_uninitialized: jnp.ndarray  # bool[S] relied on an uninitialized node
    new_viable: jnp.ndarray  # bool[S, M, I] replacement instance viability
    new_zone: jnp.ndarray  # bool[S, M, Z]
    new_ct: jnp.ndarray  # bool[S, M, CT]
    new_used: jnp.ndarray  # f32[S, M, R]
    new_tmpl: jnp.ndarray  # i32[S, M]
    # fleet cost of the lane's replacement nodes: sum over opened slots of
    # the cheapest surviving offering price (ops.solve.node_prices) — the
    # in-kernel half of policy-aware cost-delta consolidation (docs/POLICY.md)
    new_cost: jnp.ndarray  # f32[S]


def sweep(
    class_tensors,
    statics_arrays,
    key_has_bounds,
    ex_state: solve_ops.ExistingState,
    ex_static: solve_ops.ExistingStatic,
    candidate_rank: jnp.ndarray,  # i32[E]: position in disruption order, big=not candidate
    ex_cls_count: jnp.ndarray,  # i32[C, E]: candidate pods per class per node
    prefix_sizes: jnp.ndarray,  # i32[S]
    it_price: jnp.ndarray,  # f32[I, Z, CT] offering price sheet
    n_slots: int = 16,
    n_passes: int = 1,
    features=None,
    catalog_axis=None,
) -> SweepOutputs:
    """Simulate closing the first-k candidates for every k in prefix_sizes.

    ``catalog_axis`` (static): inside the mesh dispatcher's shard_map body
    the catalog planes are local I-shards — the per-simulation solve and the
    price reduction finish their I-axis reductions with exact collectives
    over that axis (parallel.mesh; bit-identical to unsharded)."""

    def one_prefix(k):
        subset = candidate_rank < k  # bool[E]
        # close the subset's nodes; the topology count seeds derive from
        # grp_node_member/owner masked by open_, so pre-existing pods on
        # removed nodes stop counting automatically (excludedPods semantics)
        ex = ex_state._replace(open_=ex_state.open_ & ~subset)
        # displaced pods join their classes
        displaced = jnp.sum(
            ex_cls_count * subset[None, :].astype(jnp.int32), axis=-1
        )  # [C]
        cls = class_tensors._replace(count=class_tensors.count + displaced)
        out = solve_ops.solve_core(
            cls, statics_arrays, n_slots, key_has_bounds, ex, ex_static,
            n_passes=n_passes, features=features, catalog_axis=catalog_axis,
        )
        n_new = out.state.n_next
        failed = jnp.sum(out.failed)
        uninit = jnp.any(
            (out.assign_existing > 0) & ~ex_static.init[None, :]
        )
        prices = solve_ops.node_prices(out.state, it_price, catalog_axis)
        cost = jnp.sum(jnp.where(jnp.isfinite(prices), prices, 0.0))
        return (
            n_new,
            failed,
            uninit,
            out.state.viable,
            out.state.zone,
            out.state.ct,
            out.state.used,
            out.state.tmpl_id,
            cost,
        )

    results = jax.vmap(one_prefix)(prefix_sizes)
    return SweepOutputs(*results)


_sweep_jit = functools.partial(
    jax.jit, static_argnames=("key_has_bounds", "n_slots", "n_passes", "features")
)(sweep)


@functools.lru_cache(maxsize=16)
def _lane_sweep_fn(mesh_axes, key_has_bounds, n_slots: int, n_passes: int,
                   features, cls_specs, statics_specs):
    """Cached jit(shard_map(...)) sweep over the 2D (catalog × lane) mesh:
    the prefix-lane axis splits across ``lane`` while each lane group shards
    the catalog planes over ``catalog`` — the production topology
    (parallel.mesh.lane_mesh_axes).  A fresh wrapper per call would defeat
    JAX's compile cache (keyed on callable identity), so the builder is
    memoized on the topology + static config (the spec pytrees are hashable
    and shape-identifying)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from karpenter_core_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.mesh_for(mesh_axes)
    lane, cat = mesh_mod.LANE_AXIS, mesh_mod.CATALOG_AXIS

    def body(sizes_arg, cls_arg, statics_arg, ex_state_arg, ex_static_arg,
             rank_arg, counts_arg, price_arg):
        return sweep(
            cls_arg, statics_arg, key_has_bounds, ex_state_arg, ex_static_arg,
            rank_arg, counts_arg, sizes_arg, price_arg, n_slots=n_slots,
            n_passes=n_passes, features=features, catalog_axis=cat,
        )

    in_specs = (
        P(lane), cls_specs, statics_specs, P(), P(), P(), P(), P(cat),
    )
    out_specs = SweepOutputs(
        n_new=P(lane), failed=P(lane), used_uninitialized=P(lane),
        new_viable=P(lane, None, cat), new_zone=P(lane), new_ct=P(lane),
        new_used=P(lane), new_tmpl=P(lane), new_cost=P(lane),
    )
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        # lane outputs are genuinely sharded and the catalog collectives
        # inside the body are exact — the mesh parity suite pins every
        # lane-sweep plane bit-identical to unsharded except new_cost, a
        # f32 sum whose reduction order XLA reassociates per program
        # (last-ulp only; the summands themselves are pinned exact)
        check_rep=False,
    ))


def run_sweep(
    snapshot,
    ex_state,
    ex_static,
    candidate_rank: np.ndarray,
    ex_cls_count: np.ndarray,
    prefix_sizes: np.ndarray,
    n_slots: int = 16,
    mesh=None,
    mesh_axes="auto",
) -> SweepOutputs:
    """The production sweep entry.  On the mesh path (``mesh_axes``: a
    topology descriptor, ``"auto"`` = KC_SOLVER_MESH env via
    parallel.mesh.lane_mesh_axes, None = off) the prefix lanes shard across
    the mesh's ``lane`` axis AND each lane group shards the catalog — each
    device simulates its share of the subsets over its catalog shard, with
    one result gather plus the kernel's tiny exact collectives as the only
    cross-device traffic.  ``mesh`` (a legacy Mesh object) is honored as a
    lanes-only topology for the dryrun entry points."""
    from karpenter_core_tpu.parallel import mesh as mesh_mod

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    sizes = jnp.asarray(prefix_sizes)
    it_price = jnp.asarray(snapshot.it_price)
    features = compilecache.snap_features(
        solve_ops.features_with_existing(snapshot, ex_static)
    )
    if mesh is not None:
        # legacy dryrun callers pass a Mesh: shard lanes over all its devices.
        # An EXPLICIT mesh wins over the env auto-config — the dryrun must
        # test the topology it asked for, not whatever the env resolves to
        mesh_axes = ((mesh_mod.CATALOG_AXIS, 1),
                     (mesh_mod.LANE_AXIS, int(mesh.devices.size)))
    elif mesh_axes == "auto":
        mesh_axes = mesh_mod.lane_mesh_axes()
    if mesh_axes is not None:
        # the catalog split must divide I (encode pads production snapshots
        # shard-aligned; anything else falls back to lanes-only — LOUDLY,
        # because a sweep quietly idling most of the mesh is a perf bug)
        n_it = int(np.asarray(snapshot.it_alloc).shape[0])
        cat_size = int(dict(mesh_axes)[mesh_mod.CATALOG_AXIS])
        if n_it % max(cat_size, 1) != 0:
            import logging

            logging.getLogger(__name__).warning(
                "lane sweep: catalog extent %d not divisible by mesh axis "
                "%r; degrading to lanes-only (catalog unsharded)",
                n_it, mesh_axes,
            )
            mesh_axes = ((mesh_mod.CATALOG_AXIS, 1),
                         (mesh_mod.LANE_AXIS, dict(mesh_axes)[mesh_mod.LANE_AXIS]))
    if mesh_axes is not None:
        lanes = int(dict(mesh_axes)[mesh_mod.LANE_AXIS])
        pad = (-len(prefix_sizes)) % max(lanes, 1)
        if pad:
            sizes = jnp.concatenate([sizes, jnp.repeat(sizes[-1:], pad)])
        fn = _lane_sweep_fn(
            tuple(mesh_axes), key_has_bounds, n_slots, snapshot.scan_passes,
            features,
            mesh_mod.partition_specs(cls),
            mesh_mod.partition_specs(statics_arrays),
        )
        out = fn(
            sizes, cls, statics_arrays, ex_state, ex_static,
            jnp.asarray(candidate_rank), jnp.asarray(ex_cls_count),
            it_price,
        )
        if pad:
            out = SweepOutputs(*(np.asarray(plane)[: len(prefix_sizes)] for plane in out))
        return out
    return _sweep_jit(
        cls,
        statics_arrays,
        key_has_bounds,
        ex_state,
        ex_static,
        jnp.asarray(candidate_rank),
        jnp.asarray(ex_cls_count),
        sizes,
        it_price,
        n_slots=n_slots,
        n_passes=snapshot.scan_passes,
        features=features,
    )
