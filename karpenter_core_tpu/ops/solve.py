"""The TPU bin-packing solve kernel.

Re-centers the reference's greedy first-fit-decreasing loop
(/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:96-219,
node.go:62-159) as a batch tensor program:

  - pods are pre-grouped into equivalence classes (models.snapshot) and the
    kernel scans over *classes* — identical pods commit identically, so the
    sequential dependency that matters is between distinct shapes, not pods
  - each scan step is dense vectorized work over [N] node slots × [I] instance
    types: requirement-mask compatibility rides the MXU as [N,V]x[V,I] matmuls
    per key, capacity checks are [N,I] elementwise min-reductions, offering
    checks flatten zone×capacity-type and matmul too
  - zonal topology spread becomes a closed-form water-fill over per-zone
    counts (the per-pod argmin of topologygroup.go:155-182 telescopes into
    fill-the-lowest-level), then per-zone placement phases
  - hostname spread / anti-affinity become per-node caps on pods-per-class
  - node selection order (existing first, then emptiest new node,
    scheduler.go:174-190) becomes an argsort + prefix-sum fill

Static shapes: N node slots, I instance types, C classes, Z zones, CT capacity
types, K general keys, V+1 mask width, R resources.  Everything under jit; no
data-dependent Python control flow.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.models.snapshot import EncodedSnapshot, UNLIMITED
from karpenter_core_tpu.ops import masks as mask_ops

BIG = jnp.float32(1e30)


class NodeState(NamedTuple):
    """Per-new-node-slot solver state (all leading dim N)."""

    used: jnp.ndarray  # f32[N, R] accumulated requests incl. daemon overhead
    kmask: jnp.ndarray  # bool[N, K, V+1]
    kdef: jnp.ndarray  # bool[N, K]
    kneg: jnp.ndarray  # bool[N, K]
    kgt: jnp.ndarray  # f32[N, K]
    klt: jnp.ndarray  # f32[N, K]
    zone: jnp.ndarray  # bool[N, Z]
    ct: jnp.ndarray  # bool[N, CT]
    viable: jnp.ndarray  # bool[N, I]
    pod_count: jnp.ndarray  # i32[N]
    tmpl_id: jnp.ndarray  # i32[N]
    open_: jnp.ndarray  # bool[N]
    n_next: jnp.ndarray  # i32[] next free slot


class ExistingState(NamedTuple):
    """Per-existing-node solver state (leading dim E).

    Existing (in-flight/real) nodes have fixed capacity and no instance-type
    viability plane — that keeps consolidation sweeps over thousands of nodes
    memory-light (ExistingNode.Add semantics, existingnode.go:77-130).
    """

    used: jnp.ndarray  # f32[E, R] accumulated (starts at remaining daemon overhead)
    kmask: jnp.ndarray  # bool[E, K, V+1]
    kdef: jnp.ndarray  # bool[E, K]
    kneg: jnp.ndarray  # bool[E, K]
    kgt: jnp.ndarray  # f32[E, K]
    klt: jnp.ndarray  # f32[E, K]
    zone: jnp.ndarray  # bool[E, Z]
    ct: jnp.ndarray  # bool[E, CT]
    pod_count: jnp.ndarray  # i32[E] pods added THIS solve
    open_: jnp.ndarray  # bool[E]


class ExistingStatic(NamedTuple):
    """Trace-time constants for existing nodes."""

    alloc: jnp.ndarray  # f32[E, R] available() at snapshot time
    init: jnp.ndarray  # bool[E] karpenter.sh/initialized
    tol: jnp.ndarray  # bool[C, E] class tolerates node taints
    host_count0: jnp.ndarray  # i32[C, E] selector-matching pods already on node


class SolveOutputs(NamedTuple):
    assign: jnp.ndarray  # i32[C, N] pods of class c on NEW node n
    assign_existing: jnp.ndarray  # i32[C, E] pods of class c on existing node e
    failed: jnp.ndarray  # i32[C]
    state: NodeState
    ex_state: ExistingState


def _water_fill(count0: jnp.ndarray, allowed: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """i32[Z] quotas: distribute m pods over allowed zones, always filling the
    lowest-count zone first — the telescoped form of the reference's per-pod
    min-domain selection (topologygroup.go:155-182; maxSkew ≥ 1 guarantees the
    min-count zone is always admissible so skew never blocks the min choice).
    """
    z = count0.shape[0]
    c = jnp.where(allowed, count0.astype(jnp.float32), BIG)
    order = jnp.argsort(c)
    s = c[order]
    # cost[k] = pods needed to raise the k lowest zones to level s[k]
    idx = jnp.arange(z, dtype=jnp.float32)
    prefix = jnp.cumsum(s) - s
    cost = idx * s - prefix  # cost to reach level s[k] for first k zones
    cost = jnp.where(jnp.isfinite(cost), cost, BIG)
    mf = m.astype(jnp.float32)
    # k* = number of zones that participate in the fill
    k_star = jnp.sum((cost <= mf).astype(jnp.int32)) - 1
    k_star = jnp.clip(k_star, 0, z - 1)
    base_level = s[k_star]
    spent = cost[k_star]
    rem = mf - spent
    k_count = (k_star + 1).astype(jnp.float32)
    level = base_level + jnp.floor(rem / k_count)
    leftover = rem - jnp.floor(rem / k_count) * k_count
    # zones among the k* lowest get filled to `level`, the first `leftover`
    # (in sorted order) get one extra
    in_fill = jnp.arange(z) <= k_star
    extra = (jnp.arange(z) < leftover).astype(jnp.float32)
    final_sorted = jnp.where(in_fill, jnp.maximum(s, level + extra), s)
    final = jnp.zeros_like(c).at[order].set(final_sorted)
    quota = jnp.where(allowed, final - c, 0.0)
    return jnp.maximum(quota, 0.0).astype(jnp.int32)


def _key_compat_node_class(state: NodeState, cls, statics) -> jnp.ndarray:
    """bool[N]: Requirements.Compatible(node, class) vectorized over nodes."""
    node_t = mask_ops.ReqTensor(state.kmask, state.kdef, state.kneg, state.kgt, state.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    return mask_ops.compatible(node_t, cls_t, statics.is_custom, statics.vocab_ints)


def _merge_node_class(state: NodeState, cls, statics) -> mask_ops.ReqTensor:
    node_t = mask_ops.ReqTensor(state.kmask, state.kdef, state.kneg, state.kgt, state.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    return mask_ops.add(node_t, cls_t, statics.valid, statics.vocab_ints)


def _it_intersects(merged: mask_ops.ReqTensor, statics) -> jnp.ndarray:
    """bool[N, I]: InstanceType.Requirements.Intersects(nodeReqs) for every
    (node, instance type) pair (node.go:143-145), with the mask-AND reduction
    expressed as per-key [N,V]x[V,I] matmuls so it lands on the MXU."""
    it = statics.it  # ReqTensor [I, K, V+1]
    n_keys = it.mask.shape[-2]
    ok_all = None
    for k in range(n_keys):  # K is small and static: unrolled
        a_mask = merged.mask[:, k, :]  # [N, V+1]
        b_mask = it.mask[:, k, :]  # [I, V+1]
        vocab_overlap = (
            jnp.einsum(
                "nv,iv->ni",
                a_mask[:, :-1].astype(jnp.bfloat16),
                b_mask[:, :-1].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0.5
        )
        both_other = a_mask[:, -1:] & b_mask[None, :, -1]
        if statics.key_has_bounds[k]:
            gt = jnp.maximum(merged.gt[:, k, None], it.gt[None, :, k])
            lt = jnp.minimum(merged.lt[:, k, None], it.lt[None, :, k])
            n_range = jnp.maximum(jnp.ceil(lt) - jnp.floor(gt) - 1.0, 0.0)
            ints_k = statics.vocab_ints[k]  # [V]
            inside = (ints_k[None, None, :] > gt[..., None]) & (
                ints_k[None, None, :] < lt[..., None]
            )
            n_in = jnp.sum(inside.astype(jnp.float32), axis=-1)
            unseen = both_other & (n_range - n_in >= 1.0)
        else:
            unseen = both_other
        nonempty = vocab_overlap | unseen
        checked = merged.defined[:, k, None] & it.defined[None, :, k]
        both_neg = merged.negative[:, k, None] & it.negative[None, :, k]
        ok = ~checked | nonempty | both_neg
        ok_all = ok if ok_all is None else (ok_all & ok)
    return ok_all


def _capacity(used: jnp.ndarray, size: jnp.ndarray, statics) -> jnp.ndarray:
    """i32[N, I]: how many more pods of the class fit on node n as instance
    type i — min over resources of floor((alloc - used) / size)
    (resources Fits telescoped over identical pods)."""
    n_res = statics.it_alloc.shape[-1]
    count = None
    for r in range(n_res):  # R static: unrolled
        free = statics.it_alloc[None, :, r] - used[:, r, None]  # [N, I]
        per = jnp.where(
            size[r] > 0, jnp.floor((free + 1e-4) / jnp.maximum(size[r], 1e-9)), BIG
        )
        per = jnp.maximum(per, 0.0)
        count = per if count is None else jnp.minimum(count, per)
    return jnp.minimum(count, BIG).astype(jnp.int32)


def _offering_ok(zone_ok: jnp.ndarray, ct_ok: jnp.ndarray, statics) -> jnp.ndarray:
    """bool[N, I]: some available offering lies in the node's allowed
    zone × capacity-type rectangle (node.go:151-159 hasOffering)."""
    n = zone_ok.shape[0]
    zc = (zone_ok[:, :, None] & ct_ok[:, None, :]).reshape(n, -1)  # [N, Z*CT]
    avail2 = statics.it_avail.reshape(statics.it_avail.shape[0], -1)  # [I, Z*CT]
    return (
        jnp.einsum(
            "nz,iz->ni",
            zc.astype(jnp.bfloat16),
            avail2.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )


def _fill_by_priority(
    quota: jnp.ndarray, cap: jnp.ndarray, priority: jnp.ndarray
) -> jnp.ndarray:
    """i32[N]: assign up to quota pods to nodes in priority order (ascending),
    each node taking at most cap[n] — the vectorized form of 'sort nodes by
    pod count, first node that accepts wins' (scheduler.go:183-190)."""
    order = jnp.argsort(priority)
    cap_sorted = cap[order]
    before = jnp.cumsum(cap_sorted) - cap_sorted
    assigned_sorted = jnp.clip(quota - before, 0, cap_sorted)
    return jnp.zeros_like(cap).at[order].set(assigned_sorted)


class Statics(NamedTuple):
    """Trace-time constants bundled for the kernel."""

    it: mask_ops.ReqTensor
    it_alloc: jnp.ndarray
    it_avail: jnp.ndarray
    tmpl: mask_ops.ReqTensor
    tmpl_zone: jnp.ndarray
    tmpl_ct: jnp.ndarray
    tmpl_it: jnp.ndarray
    tmpl_daemon: jnp.ndarray
    valid: jnp.ndarray
    is_custom: jnp.ndarray
    vocab_ints: jnp.ndarray
    key_has_bounds: Tuple[bool, ...]  # python tuple -> static per-key branching


class ClassTensors(NamedTuple):
    mask: jnp.ndarray
    defined: jnp.ndarray
    negative: jnp.ndarray
    gt: jnp.ndarray
    lt: jnp.ndarray
    zone: jnp.ndarray
    ct: jnp.ndarray
    it: jnp.ndarray
    requests: jnp.ndarray
    count: jnp.ndarray
    tol: jnp.ndarray
    zone_cap: jnp.ndarray
    zone_skew: jnp.ndarray
    host_cap: jnp.ndarray
    zone_count0: jnp.ndarray
    zone_aff: jnp.ndarray
    host_aff: jnp.ndarray


def _phase_existing(
    ex: ExistingState,
    ex_static: ExistingStatic,
    cls: ClassTensors,
    statics: Statics,
    quota: jnp.ndarray,
    zone_restrict: jnp.ndarray,
    collapse_zone: bool,
    host_count0_row: jnp.ndarray,
    tol_row: jnp.ndarray,
    extra_elig: Optional[jnp.ndarray] = None,
    single_node: bool = False,
) -> Tuple[ExistingState, jnp.ndarray, jnp.ndarray]:
    """Place up to ``quota`` pods of the class onto existing nodes, in index
    order (the reference iterates existing nodes first, in order, and takes the
    first that accepts — scheduler.go:176-180).  ``extra_elig`` restricts to a
    node subset (affinity targets); ``single_node`` pins the whole quota to the
    first eligible node (hostname self-affinity bootstrap)."""
    n_ex = ex.used.shape[0]

    node_t = mask_ops.ReqTensor(ex.kmask, ex.kdef, ex.kneg, ex.kgt, ex.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    key_ok = mask_ops.compatible(node_t, cls_t, statics.is_custom, statics.vocab_ints)
    merged = mask_ops.add(node_t, cls_t, statics.valid, statics.vocab_ints)
    zone_ok = ex.zone & zone_restrict[None, :] & cls.zone[None, :]
    ct_ok = ex.ct & cls.ct[None, :]

    # fixed-capacity fit: min over resources of floor((available - used)/size)
    n_res = ex_static.alloc.shape[-1]
    cap = None
    for r in range(n_res):
        free = ex_static.alloc[:, r] - ex.used[:, r]
        per = jnp.where(
            cls.requests[r] > 0,
            jnp.floor((free + 1e-4) / jnp.maximum(cls.requests[r], 1e-9)),
            BIG,
        )
        per = jnp.maximum(per, 0.0)
        cap = per if cap is None else jnp.minimum(cap, per)
    cap = jnp.minimum(cap, BIG).astype(jnp.int32)

    elig = ex.open_ & key_ok & tol_row & jnp.any(zone_ok, axis=-1) & jnp.any(ct_ok, axis=-1)
    if extra_elig is not None:
        elig = elig & extra_elig
    host_cap = jnp.maximum(cls.host_cap - host_count0_row, 0)
    cap = jnp.where(elig, jnp.minimum(cap, host_cap), 0)
    if single_node:
        first = jnp.argmax(cap > 0)
        cap = jnp.where(jnp.arange(n_ex) == first, cap, 0)

    priority = jnp.where(cap > 0, jnp.arange(n_ex, dtype=jnp.int32), jnp.iinfo(jnp.int32).max)
    assigned = _fill_by_priority(quota, cap, priority)
    placed = jnp.sum(assigned)

    took = assigned > 0
    sel = took[:, None]
    new_ex = ExistingState(
        used=ex.used + assigned[:, None].astype(jnp.float32) * cls.requests[None, :],
        kmask=jnp.where(sel[..., None], merged.mask, ex.kmask),
        kdef=jnp.where(sel, merged.defined, ex.kdef),
        kneg=jnp.where(sel, merged.negative, ex.kneg),
        kgt=jnp.where(sel, merged.gt, ex.kgt),
        klt=jnp.where(sel, merged.lt, ex.klt),
        zone=jnp.where(sel, zone_ok, ex.zone) if collapse_zone else jnp.where(
            sel, ex.zone & cls.zone[None, :], ex.zone
        ),
        ct=jnp.where(sel, ct_ok, ex.ct),
        pod_count=ex.pod_count + assigned,
        open_=ex.open_,
    )
    return new_ex, assigned, placed


def _phase(
    state: NodeState,
    cls: ClassTensors,
    statics: Statics,
    quota: jnp.ndarray,
    zone_restrict: jnp.ndarray,
    collapse_zone: bool,
    max_new_nodes: Optional[int] = None,
) -> Tuple[NodeState, jnp.ndarray, jnp.ndarray]:
    """Place up to ``quota`` pods of the class on nodes whose zone mask meets
    ``zone_restrict`` — first onto open nodes, then fresh nodes from the first
    viable template.  Returns (state, assigned[N], placed).  ``max_new_nodes``
    caps node openings (hostname self-affinity bootstraps exactly one)."""
    n_slots = state.used.shape[0]
    n_tmpl = statics.tmpl_it.shape[0]

    merged = _merge_node_class(state, cls, statics)
    key_ok = _key_compat_node_class(state, cls, statics)  # [N]
    zone_ok = state.zone & zone_restrict[None, :] & cls.zone[None, :]  # [N, Z]
    ct_ok = state.ct & cls.ct[None, :]  # [N, CT]
    tol_ok = cls.tol[state.tmpl_id]  # [N]

    it_ok = (
        state.viable
        & cls.it[None, :]
        & _it_intersects(merged, statics)
        & _offering_ok(zone_ok, ct_ok, statics)
    )  # [N, I]
    cap_ni = _capacity(state.used, cls.requests, statics)
    cap_ni = jnp.where(it_ok, cap_ni, 0)
    cap_n = jnp.max(cap_ni, axis=-1)  # [N]

    elig = (
        state.open_
        & key_ok
        & tol_ok
        & jnp.any(zone_ok, axis=-1)
        & jnp.any(ct_ok, axis=-1)
    )
    cap_n = jnp.where(elig, jnp.minimum(cap_n, cls.host_cap), 0)
    if max_new_nodes is not None:
        # hostname self-affinity bootstrap: at most one node hosts the class
        first = jnp.argmax(cap_n > 0)
        cap_n = jnp.where(jnp.arange(n_slots) == first, cap_n, 0)

    # node order: emptiest first (pod count, then slot index); pod_count and
    # slot count both stay far below 2^15 so the packed key fits int32
    priority = state.pod_count * n_slots + jnp.arange(n_slots, dtype=jnp.int32)
    priority = jnp.where(cap_n > 0, priority, jnp.iinfo(jnp.int32).max)
    assigned = _fill_by_priority(quota, cap_n, priority)
    placed_existing = jnp.sum(assigned)

    # -- commit to existing nodes --------------------------------------------
    took = assigned > 0
    add_req = assigned[:, None].astype(jnp.float32) * cls.requests[None, :]
    used = state.used + add_req
    sel = took[:, None]
    kmask = jnp.where(sel[..., None], merged.mask, state.kmask)
    kdef = jnp.where(sel, merged.defined, state.kdef)
    kneg = jnp.where(sel, merged.negative, state.kneg)
    kgt = jnp.where(sel, merged.gt, state.kgt)
    klt = jnp.where(sel, merged.lt, state.klt)
    new_zone = jnp.where(sel, zone_ok, state.zone) if collapse_zone else jnp.where(
        sel, state.zone & cls.zone[None, :], state.zone
    )
    new_ct = jnp.where(sel, ct_ok, state.ct)
    viable = jnp.where(sel, it_ok & (cap_ni >= assigned[:, None]), state.viable)
    pod_count = state.pod_count + assigned

    # -- open fresh nodes ----------------------------------------------------
    rem = quota - placed_existing

    # template viability for this class+restriction (scheduler.go:192-217):
    # taints, requirement compat, and a non-empty filtered instance list
    tmpl_t = statics.tmpl
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    tmpl_key_ok = mask_ops.compatible(tmpl_t, cls_t, statics.is_custom, statics.vocab_ints)
    tmpl_merged = mask_ops.add(tmpl_t, cls_t, statics.valid, statics.vocab_ints)
    t_zone = statics.tmpl_zone & zone_restrict[None, :] & cls.zone[None, :]  # [T, Z]
    t_ct = statics.tmpl_ct & cls.ct[None, :]
    t_it_ok = (
        statics.tmpl_it
        & cls.it[None, :]
        & _it_intersects(tmpl_merged, statics)
        & _offering_ok(t_zone, t_ct, statics)
    )  # [T, I]
    t_cap_ti = _capacity(statics.tmpl_daemon, cls.requests, statics)
    t_cap_ti = jnp.where(t_it_ok, t_cap_ti, 0)
    t_cap = jnp.max(t_cap_ti, axis=-1)  # [T]
    t_viable = (
        cls.tol
        & tmpl_key_ok
        & jnp.any(t_zone, axis=-1)
        & jnp.any(t_ct, axis=-1)
        & (t_cap > 0)
    )
    t_star = jnp.argmax(t_viable)  # first True (argmax of bool picks first max)
    t_ok = t_viable[t_star]

    per_node = jnp.minimum(t_cap[t_star], cls.host_cap)
    per_node = jnp.maximum(per_node, 1)
    n_new = jnp.where(t_ok & (rem > 0), -(-rem // per_node), 0)
    free_slots = n_slots - state.n_next
    n_new = jnp.minimum(n_new, free_slots)
    if max_new_nodes is not None:
        # single-node semantics: once the class bootstrapped onto an open
        # slot, the remainder must join it — no fresh node for the overflow
        n_new = jnp.where(placed_existing > 0, 0, jnp.minimum(n_new, max_new_nodes))

    slot_idx = jnp.arange(n_slots)
    is_new = (slot_idx >= state.n_next) & (slot_idx < state.n_next + n_new)
    rank = slot_idx - state.n_next
    a_new = jnp.where(is_new, jnp.clip(rem - rank * per_node, 0, per_node), 0)
    placed_new = jnp.sum(a_new)

    seln = is_new[:, None]
    used = jnp.where(seln, statics.tmpl_daemon[t_star][None, :] + a_new[:, None].astype(jnp.float32) * cls.requests[None, :], used)
    kmask = jnp.where(seln[..., None], tmpl_merged.mask[t_star][None], kmask)
    kdef = jnp.where(seln, tmpl_merged.defined[t_star][None], kdef)
    kneg = jnp.where(seln, tmpl_merged.negative[t_star][None], kneg)
    kgt = jnp.where(seln, tmpl_merged.gt[t_star][None], kgt)
    klt = jnp.where(seln, tmpl_merged.lt[t_star][None], klt)
    new_zone = jnp.where(seln, t_zone[t_star][None, :], new_zone)
    new_ct = jnp.where(seln, t_ct[t_star][None, :], new_ct)
    fresh_viable = t_it_ok[t_star][None, :] & (t_cap_ti[t_star][None, :] >= a_new[:, None])
    viable = jnp.where(seln, fresh_viable, viable)
    pod_count = jnp.where(is_new, a_new, pod_count)
    tmpl_id = jnp.where(is_new, t_star, state.tmpl_id)
    open_ = state.open_ | is_new
    n_next = state.n_next + n_new

    new_state = NodeState(
        used, kmask, kdef, kneg, kgt, klt, new_zone, new_ct, viable,
        pod_count, tmpl_id, open_, n_next,
    )
    return new_state, assigned + a_new, placed_existing + placed_new


def _class_step(
    statics: Statics,
    ex_static: ExistingStatic,
    n_zones: int,
    carry,
    cls_with_index,
):
    """One scan step: schedule every pod of one class — existing nodes first,
    then new nodes, per phase."""
    state, ex = carry
    cls, cls_index = cls_with_index
    m = cls.count
    spread = cls.zone_skew < UNLIMITED
    anti = cls.zone_cap < UNLIMITED

    host_count0_row = ex_static.host_count0[cls_index]  # [E]
    tol_row = ex_static.tol[cls_index]  # [E]

    quotas = _water_fill(cls.zone_count0, cls.zone, m)
    assigned_total = jnp.zeros_like(state.pod_count)
    assigned_ex_total = jnp.zeros_like(ex.pod_count)
    placed_total = jnp.int32(0)

    def run_phase(state, ex, quota, restrict, collapse):
        """Wrapped in lax.cond so zero-quota phases (most of them: each class
        participates in 1-2 of the Z+4 phase kinds) cost nothing on device."""

        def do(operand):
            state_i, ex_i = operand
            ex_o, a_ex, placed_ex = _phase_existing(
                ex_i, ex_static, cls, statics, quota, restrict, collapse,
                host_count0_row, tol_row,
            )
            state_o, a_new, placed_new = _phase(
                state_i, cls, statics, quota - placed_ex, restrict, collapse_zone=collapse
            )
            return state_o, ex_o, a_new, a_ex, placed_ex + placed_new

        def skip(operand):
            state_i, ex_i = operand
            return (
                state_i,
                ex_i,
                jnp.zeros_like(state_i.pod_count),
                jnp.zeros_like(ex_i.pod_count),
                jnp.int32(0),
            )

        return jax.lax.cond(quota > 0, do, skip, (state, ex))

    # zone-constrained phases (spread classes commit one zone per phase)
    for z in range(n_zones):
        restrict = jnp.zeros(n_zones, dtype=bool).at[z].set(True)
        q = jnp.where(spread, quotas[z], 0)
        state, ex, assigned, assigned_ex, placed = run_phase(state, ex, q, restrict, True)
        assigned_total = assigned_total + assigned
        assigned_ex_total = assigned_ex_total + assigned_ex
        placed_total = placed_total + placed

    # anti-affinity phase: one pod, restricted to zero-count allowed zones
    zero_zones = cls.zone & (cls.zone_count0 == 0)
    anti_quota = jnp.where(anti & jnp.any(zero_zones), jnp.minimum(m, 1), 0)
    state, ex, assigned, assigned_ex, placed = run_phase(
        state, ex, anti_quota, zero_zones, True
    )
    assigned_total = assigned_total + assigned
    assigned_ex_total = assigned_ex_total + assigned_ex
    placed_total = placed_total + placed

    # zone self-affinity: nonzero-count zones when matching pods exist,
    # else bootstrap a single allowed zone (topologygroup.go:202-233)
    zone_aff = cls.zone_aff
    host_aff = cls.host_aff
    nonzero_zones = cls.zone & (cls.zone_count0 > 0)
    bootstrap_zone = (
        jnp.zeros(n_zones, dtype=bool).at[jnp.argmax(cls.zone)].set(jnp.any(cls.zone))
    )
    zone_aff_restrict = jnp.where(jnp.any(nonzero_zones), nonzero_zones, bootstrap_zone)
    zone_aff_quota = jnp.where(zone_aff & ~host_aff, m, 0)
    state, ex, assigned, assigned_ex, placed = run_phase(
        state, ex, zone_aff_quota, zone_aff_restrict, True
    )
    assigned_total = assigned_total + assigned
    assigned_ex_total = assigned_ex_total + assigned_ex
    placed_total = placed_total + placed

    # hostname self-affinity: fill target nodes (count>0) when they exist,
    # else bootstrap the whole class onto exactly one node
    all_zones = jnp.ones(n_zones, dtype=bool)
    host_restrict = jnp.where(zone_aff, zone_aff_restrict, all_zones)
    host_targets = host_count0_row > 0
    targets_exist = jnp.any(host_targets & ex.open_)
    host_quota = jnp.where(host_aff, m, 0)

    def do_host_aff(operand):
        state_i, ex_i = operand
        q_targets = jnp.where(targets_exist, host_quota, 0)
        ex_o, a_ex_t, placed_t = _phase_existing(
            ex_i, ex_static, cls, statics, q_targets, host_restrict, True,
            host_count0_row, tol_row, extra_elig=host_targets,
        )
        q_boot = jnp.where(targets_exist, 0, host_quota)
        ex_o, a_ex_b, placed_b = _phase_existing(
            ex_o, ex_static, cls, statics, q_boot, host_restrict, True,
            host_count0_row, tol_row, single_node=True,
        )
        q_new = jnp.where(placed_b > 0, 0, q_boot - placed_b)
        state_o, a_new_h, placed_h = _phase(
            state_i, cls, statics, q_new, host_restrict, collapse_zone=True, max_new_nodes=1
        )
        return state_o, ex_o, a_new_h, a_ex_t + a_ex_b, placed_t + placed_b + placed_h

    def skip_host_aff(operand):
        state_i, ex_i = operand
        return (
            state_i, ex_i,
            jnp.zeros_like(state_i.pod_count),
            jnp.zeros_like(ex_i.pod_count),
            jnp.int32(0),
        )

    state, ex, a_new_h, a_ex_h, placed_h = jax.lax.cond(
        host_quota > 0, do_host_aff, skip_host_aff, (state, ex)
    )
    assigned_total = assigned_total + a_new_h
    assigned_ex_total = assigned_ex_total + a_ex_h
    placed_total = placed_total + placed_h

    # unconstrained phase for plain classes
    any_quota = jnp.where(spread | anti | zone_aff | host_aff, 0, m)
    state, ex, assigned, assigned_ex, placed = run_phase(
        state, ex, any_quota, all_zones, False
    )
    assigned_total = assigned_total + assigned
    assigned_ex_total = assigned_ex_total + assigned_ex
    placed_total = placed_total + placed

    failed = m - placed_total
    return (state, ex), (assigned_total, assigned_ex_total, failed)


def solve_core(
    class_tensors,
    statics_arrays,
    n_slots: int,
    key_has_bounds,
    existing_state: "Optional[ExistingState]" = None,
    existing_static: "Optional[ExistingStatic]" = None,
):
    """Unjitted kernel core — jit/vmap/shard_map-composable (the parallel layer
    vmaps this over snapshot replicas and consolidation subsets;
    __graft_entry__ compile-checks it)."""
    statics = Statics(*statics_arrays, key_has_bounds=key_has_bounds)
    n_zones = statics.tmpl_zone.shape[-1]
    n_res = statics.it_alloc.shape[-1]
    n_keys = statics.valid.shape[0]
    width = statics.valid.shape[1]
    n_it = statics.it_alloc.shape[0]
    n_ct = statics.tmpl_ct.shape[-1]
    n_classes = class_tensors.count.shape[0]

    state = NodeState(
        used=jnp.zeros((n_slots, n_res), dtype=jnp.float32),
        kmask=jnp.ones((n_slots, n_keys, width), dtype=bool),
        kdef=jnp.zeros((n_slots, n_keys), dtype=bool),
        kneg=jnp.zeros((n_slots, n_keys), dtype=bool),
        kgt=jnp.full((n_slots, n_keys), -jnp.inf, dtype=jnp.float32),
        klt=jnp.full((n_slots, n_keys), jnp.inf, dtype=jnp.float32),
        zone=jnp.ones((n_slots, n_zones), dtype=bool),
        ct=jnp.ones((n_slots, n_ct), dtype=bool),
        viable=jnp.ones((n_slots, n_it), dtype=bool),
        pod_count=jnp.zeros(n_slots, dtype=jnp.int32),
        tmpl_id=jnp.zeros(n_slots, dtype=jnp.int32),
        open_=jnp.zeros(n_slots, dtype=bool),
        n_next=jnp.int32(0),
    )
    if existing_state is None:
        existing_state = empty_existing_state(n_res, n_keys, width, n_zones, n_ct)
        existing_static = empty_existing_static(n_res, n_classes)

    def step(carry, cls_with_index):
        return _class_step(statics, existing_static, n_zones, carry, cls_with_index)

    cls_indices = jnp.arange(n_classes, dtype=jnp.int32)
    (final_state, final_ex), (assign, assign_ex, failed) = jax.lax.scan(
        step, (state, existing_state), (class_tensors, cls_indices)
    )
    return SolveOutputs(
        assign=assign,
        assign_existing=assign_ex,
        failed=failed,
        state=final_state,
        ex_state=final_ex,
    )


def empty_existing_state(n_res, n_keys, width, n_zones, n_ct) -> ExistingState:
    """A single closed dummy slot (E=0 shapes upset some XLA reductions)."""
    return ExistingState(
        used=jnp.zeros((1, n_res), dtype=jnp.float32),
        kmask=jnp.ones((1, n_keys, width), dtype=bool),
        kdef=jnp.zeros((1, n_keys), dtype=bool),
        kneg=jnp.zeros((1, n_keys), dtype=bool),
        kgt=jnp.full((1, n_keys), -jnp.inf, dtype=jnp.float32),
        klt=jnp.full((1, n_keys), jnp.inf, dtype=jnp.float32),
        zone=jnp.ones((1, n_zones), dtype=bool),
        ct=jnp.ones((1, n_ct), dtype=bool),
        pod_count=jnp.zeros(1, dtype=jnp.int32),
        open_=jnp.zeros(1, dtype=bool),
    )


def empty_existing_static(n_res, n_classes) -> ExistingStatic:
    return ExistingStatic(
        alloc=jnp.zeros((1, n_res), dtype=jnp.float32),
        init=jnp.zeros(1, dtype=bool),
        tol=jnp.zeros((n_classes, 1), dtype=bool),
        host_count0=jnp.zeros((n_classes, 1), dtype=jnp.int32),
    )


_solve_jit = functools.partial(jax.jit, static_argnames=("n_slots", "key_has_bounds"))(
    solve_core
)


@jax.jit
def pack_bool(arr: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., ceil(M/8)] bit-packed bools — device→host transfers ride a
    network tunnel under axon, so the big [N, I] planes ship packed (8×
    smaller) and unpack host-side with np.unpackbits."""
    m = arr.shape[-1]
    pad = (-m) % 8
    if pad:
        arr = jnp.pad(arr, [(0, 0)] * (arr.ndim - 1) + [(0, pad)])
    grouped = arr.reshape(arr.shape[:-1] + (-1, 8)).astype(jnp.uint8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)
    return jnp.sum(grouped * weights, axis=-1, dtype=jnp.uint8)


def unpack_bool(packed: np.ndarray, m: int) -> np.ndarray:
    """Host-side inverse of pack_bool."""
    bits = np.unpackbits(packed, axis=-1)
    return bits[..., :m].astype(bool)


def node_prices(state: NodeState, it_price: jnp.ndarray) -> jnp.ndarray:
    """f32[N]: min over (viable instance type, allowed zone, allowed ct) of
    offering price; +inf when no offering, 0 for closed slots."""
    # price[i, z, ct] -> restrict to node's viable/zone/ct masks
    allowed = (
        state.viable[:, :, None, None]
        & state.zone[:, None, :, None]
        & state.ct[:, None, None, :]
    )
    priced = jnp.where(allowed, it_price[None, :, :, :], jnp.inf)
    best = jnp.min(priced, axis=(1, 2, 3))
    return jnp.where(state.open_ & (state.pod_count > 0), best, 0.0)


def solve(snapshot: EncodedSnapshot, n_slots: int = 0) -> SolveOutputs:
    """Run the kernel on an encoded snapshot.  ``n_slots`` defaults to a
    rounded estimate; if slots run out (failed>0 with n_next==n_slots) the
    caller should retry with more (solver.tpu handles this)."""
    if n_slots <= 0:
        n_slots = estimate_slots(snapshot)
    cls, statics_arrays, key_has_bounds = prepare(snapshot)
    return _solve_jit(cls, statics_arrays, n_slots, key_has_bounds)


def prepare(snapshot: EncodedSnapshot):
    """Device-ready kernel inputs: (class_tensors, statics_arrays,
    key_has_bounds)."""
    cls = ClassTensors(
        mask=jnp.asarray(snapshot.cls_mask),
        defined=jnp.asarray(snapshot.cls_defined),
        negative=jnp.asarray(snapshot.cls_negative),
        gt=jnp.asarray(snapshot.cls_gt),
        lt=jnp.asarray(snapshot.cls_lt),
        zone=jnp.asarray(snapshot.cls_zone),
        ct=jnp.asarray(snapshot.cls_ct),
        it=jnp.asarray(snapshot.cls_it),
        requests=jnp.asarray(snapshot.cls_requests),
        count=jnp.asarray(snapshot.cls_count),
        tol=jnp.asarray(snapshot.cls_tol),
        zone_cap=jnp.asarray(snapshot.cls_zone_cap),
        zone_skew=jnp.asarray(snapshot.cls_zone_skew),
        host_cap=jnp.asarray(snapshot.cls_host_cap),
        zone_count0=jnp.asarray(snapshot.cls_zone_count0),
        zone_aff=jnp.asarray(snapshot.cls_zone_aff),
        host_aff=jnp.asarray(snapshot.cls_host_aff),
    )
    it_t = mask_ops.ReqTensor(
        jnp.asarray(snapshot.it_mask),
        jnp.asarray(snapshot.it_defined),
        jnp.asarray(snapshot.it_negative),
        jnp.asarray(snapshot.it_gt),
        jnp.asarray(snapshot.it_lt),
    )
    tmpl_t = mask_ops.ReqTensor(
        jnp.asarray(snapshot.tmpl_mask),
        jnp.asarray(snapshot.tmpl_defined),
        jnp.asarray(snapshot.tmpl_negative),
        jnp.asarray(snapshot.tmpl_gt),
        jnp.asarray(snapshot.tmpl_lt),
    )
    statics_arrays = (
        it_t,
        jnp.asarray(snapshot.it_alloc),
        jnp.asarray(snapshot.it_avail),
        tmpl_t,
        jnp.asarray(snapshot.tmpl_zone),
        jnp.asarray(snapshot.tmpl_ct),
        jnp.asarray(snapshot.tmpl_it),
        jnp.asarray(snapshot.tmpl_daemon),
        jnp.asarray(snapshot.valid),
        jnp.asarray(snapshot.is_custom),
        jnp.asarray(snapshot.vocab_ints),
    )
    key_has_bounds = tuple(
        bool(np.isfinite(snapshot.cls_gt[:, k]).any() or np.isfinite(snapshot.cls_lt[:, k]).any()
             or np.isfinite(snapshot.it_gt[:, k]).any() or np.isfinite(snapshot.it_lt[:, k]).any()
             or np.isfinite(snapshot.tmpl_gt[:, k]).any() or np.isfinite(snapshot.tmpl_lt[:, k]).any())
        for k in range(snapshot.valid.shape[0])
    )
    return cls, statics_arrays, key_has_bounds


def estimate_slots(snapshot: EncodedSnapshot) -> int:
    """Optimistic node-count estimate: per class, best pods-per-node over the
    catalog, plus slack for zone phases; rounded up to a power of two for
    compile-cache friendliness."""
    total = 16
    alloc = snapshot.it_alloc  # [I, R]
    for c in range(len(snapshot.classes)):
        size = snapshot.cls_requests[c]  # [R]
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.floor(np.where(size > 0, alloc / np.maximum(size, 1e-9), np.inf))
        per_it = np.min(np.where(np.isfinite(per), per, np.inf), axis=-1)
        best = np.max(per_it) if per_it.size else 0
        best = max(1.0, min(best, float(snapshot.cls_host_cap[c])))
        total += int(np.ceil(float(snapshot.cls_count[c]) / best)) + snapshot.cls_zone.shape[1]
    return int(2 ** np.ceil(np.log2(max(total, 16))))
