"""The TPU bin-packing solve kernel.

Re-centers the reference's greedy first-fit-decreasing loop
(/root/reference/pkg/controllers/provisioning/scheduling/scheduler.go:96-219,
node.go:62-159) as a batch tensor program:

  - pods are pre-grouped into equivalence classes (models.snapshot) and the
    kernel scans over *classes* — identical pods commit identically, so the
    sequential dependency that matters is between distinct shapes, not pods
  - each scan step is dense vectorized work over [N] node slots × [I] instance
    types: requirement-mask compatibility rides the MXU as [N,V]x[V,I] matmuls
    per key, capacity checks are [N,I] elementwise min-reductions, offering
    checks flatten zone×capacity-type and matmul too
  - zonal topology spread becomes a closed-form water-fill over per-zone
    counts (the per-pod argmin of topologygroup.go:155-182 telescopes into
    fill-the-lowest-level), then per-zone placement phases
  - hostname spread / anti-affinity become per-node caps on pods-per-class
  - node selection order (existing first, then emptiest new node,
    scheduler.go:174-190) becomes an argsort + prefix-sum fill

Static shapes: N node slots, I instance types, C classes, Z zones, CT capacity
types, K general keys, V+1 mask width, R resources.  Everything under jit; no
data-dependent Python control flow.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.models.snapshot import EncodedSnapshot, UNLIMITED
from karpenter_core_tpu.ops import masks as mask_ops

# plain numpy scalar: a jnp literal here would initialize the device backend
# at import time (and hang any process whose preferred backend is unreachable
# before it can pin itself to CPU — __graft_entry__._ensure_live_backend)
BIG = np.float32(1e30)


class SnapshotFeatures(NamedTuple):
    """Static phase-plan flags: which constraint families the snapshot can
    exercise at all.  Computed host-side in models.snapshot.encode_snapshot
    from the CLASSES (plus bound-pod anti groups and, at solve time, the
    existing-node volume planes) and threaded through solve_core as a static
    jit argument — a False flag means the corresponding phase family is
    provably dead for every class in the snapshot, so the kernel never traces
    it: no compile time, no per-step lax.cond, no dead carry writes.

    Soundness is one-directional: a flag may be True with the feature absent
    from the data (the phases are then runtime no-ops, exactly the pre-flag
    behavior), but must never be False when some class needs the family.
    utils.compilecache.snap_features exploits that monotonicity to widen a
    requested set to an already-built superset executable instead of
    recompiling (and to bound the variant space).
    """

    zone_spread: bool = True  # some class owns a zonal topology-spread slot
    host_spread: bool = True  # ... a hostname spread slot
    zone_affinity: bool = True  # ... a zonal pod-affinity slot
    host_affinity: bool = True  # ... a hostname pod-affinity slot
    zone_anti: bool = True  # ... a zonal anti-affinity slot (soft or required)
    required_zone_anti: bool = True  # ... REQUIRED zonal anti (committal phases)
    host_anti: bool = True  # ... a hostname anti-affinity slot
    # inverse planes: anti GROUPS whose owners can register inverse counts —
    # required class-owned terms or bound-pod terms (extra_anti_groups)
    inv_zone_anti: bool = True
    inv_host_anti: bool = True
    host_ports: bool = True  # some class binds host ports
    volume_limits: bool = True  # existing nodes carry finite CSI attach limits

    def canonical(self) -> "SnapshotFeatures":
        """Normalize implications so equivalent requests share a cache key:
        required zonal anti implies the zonal-anti family and inverse plane."""
        f = self
        if f.required_zone_anti:
            f = f._replace(zone_anti=True, inv_zone_anti=True)
        return f

    def covers(self, other: "SnapshotFeatures") -> bool:
        """True when an executable traced with ``self`` is sound for a
        snapshot requesting ``other`` (self is a flag superset)."""
        return all(a or not b for a, b in zip(self, other))

    def union(self, other: "SnapshotFeatures") -> "SnapshotFeatures":
        return SnapshotFeatures(*(a or b for a, b in zip(self, other)))


ALL_FEATURES = SnapshotFeatures()


class NodeState(NamedTuple):
    """Per-new-node-slot solver state (all leading dim N)."""

    used: jnp.ndarray  # f32[N, R] accumulated requests incl. daemon overhead
    kmask: jnp.ndarray  # bool[N, K, V+1], or uint32[N, K, W] packed words
    kdef: jnp.ndarray  # bool[N, K]
    kneg: jnp.ndarray  # bool[N, K]
    kgt: jnp.ndarray  # f32[N, K]
    klt: jnp.ndarray  # f32[N, K]
    zone: jnp.ndarray  # bool[N, Z]
    ct: jnp.ndarray  # bool[N, CT]
    viable: jnp.ndarray  # bool[N, I]
    ports: jnp.ndarray  # bool[N, P] bound (port, proto) pairs
    pod_count: jnp.ndarray  # i32[N]
    tmpl_id: jnp.ndarray  # i32[N]
    open_: jnp.ndarray  # bool[N]
    n_next: jnp.ndarray  # i32[] next free slot


class ExistingState(NamedTuple):
    """Per-existing-node solver state (leading dim E).

    Existing (in-flight/real) nodes have fixed capacity and no instance-type
    viability plane — that keeps consolidation sweeps over thousands of nodes
    memory-light (ExistingNode.Add semantics, existingnode.go:77-130).
    """

    used: jnp.ndarray  # f32[E, R] accumulated (starts at remaining daemon overhead)
    kmask: jnp.ndarray  # bool[E, K, V+1]
    kdef: jnp.ndarray  # bool[E, K]
    kneg: jnp.ndarray  # bool[E, K]
    kgt: jnp.ndarray  # f32[E, K]
    klt: jnp.ndarray  # f32[E, K]
    zone: jnp.ndarray  # bool[E, Z]
    ct: jnp.ndarray  # bool[E, CT]
    ports: jnp.ndarray  # bool[E, P] bound (port, proto) pairs
    vol_used: jnp.ndarray  # i32[E, D] distinct PVCs mounted per CSI driver
    pod_count: jnp.ndarray  # i32[E] pods added THIS solve
    open_: jnp.ndarray  # bool[E]


class ExistingStatic(NamedTuple):
    """Trace-time constants for existing nodes."""

    alloc: jnp.ndarray  # f32[E, R] available() at snapshot time
    init: jnp.ndarray  # bool[E] karpenter.sh/initialized
    tol: jnp.ndarray  # bool[C, E] class tolerates node taints
    # bound pods per topology group per node: members (forward counts) and
    # anti-term owners (inverse counts) — count seeds derive from these with
    # the node open-mask applied, so consolidation subsets adjust for free
    grp_node_member: jnp.ndarray  # i32[G1, E]
    grp_node_owner: jnp.ndarray  # i32[G1, E]
    # provisioner-limit accounting (scheduler.go:244-246): open owned nodes
    # consume their template's budget; closed (consolidated) nodes release it
    node_capacity: jnp.ndarray  # f32[E, R]
    node_tmpl: jnp.ndarray  # i32[E] owning template (0 ok when not owned)
    node_owned: jnp.ndarray  # bool[E]
    # volume attach limits (volumeusage.go / existingnode.go:77-130): only
    # existing nodes carry limits — new nodes have no CSINode yet.  Within a
    # class all pods mount the same PVC set, so the per-node increment is
    # count-independent; cross-class PVC sharing routes to the host path
    vol_limit: jnp.ndarray  # i32[E, D] per-driver attach limit (UNLIMITED none)
    cls_vol_add: jnp.ndarray  # i32[C, E, D] distinct new PVCs class c adds to e
    cls_vol_per_pod: jnp.ndarray  # i32[C, D] per-pod claims (disjoint sets mode)


class TopoCounts(NamedTuple):
    """Shared topology-group counts, carried through the class scan.

    Forward counts track selector-matching (member) pods — they gate spread
    skew, affinity targets, and anti-affinity owners.  Inverse counts track
    anti-term *owners* — they gate the pods those owners repel
    (topology.go:44-47 inverse topologies).

    All four planes count pods PER NODE; per-zone counts are DERIVED at each
    class step from the nodes' *current* zone masks (``_derive_zone_counts``).
    This is the kernel analog of the host recounting domains from live node
    state every push: when a later pod narrows a node's zone set (node.go
    merge), every earlier resident's zone contribution narrows with it —
    in particular a multi-zone anti owner stops poisoning the zones it can no
    longer be in, which is what lets required zonal anti-affinity converge
    inside one batch exactly like the iterative host (r4 fuzzer finding (a);
    accumulating per-zone snapshots at record time could never replay that
    narrowing)."""

    fwd_ex: jnp.ndarray  # i32[G1, E] member pods per existing node
    inv_ex: jnp.ndarray  # i32[G1, E] anti-owner pods per existing node
    fwd_new: jnp.ndarray  # i32[G1, N] member pods per new slot
    inv_new: jnp.ndarray  # i32[G1, N] anti-owner pods per new slot


class SolveOutputs(NamedTuple):
    assign: jnp.ndarray  # i32[C, N] pods of class c on NEW node n
    assign_existing: jnp.ndarray  # i32[C, E] pods of class c on existing node e
    failed: jnp.ndarray  # i32[C]
    state: NodeState
    ex_state: ExistingState
    # bool[C]: the zone-spread water-fill could not prove host parity for this
    # class (round bound hit with headroom left, or quota unrealized in-phase);
    # failed pods of flagged classes re-route to the host oracle (VERDICT r2 #2)
    spread_suspect: jnp.ndarray = None
    # the rest of the final scan carry, returned so a later repair solve can
    # resume from it (WarmCarry): shared topology counts and the remaining
    # provisioner-limit budget.  Stays device-resident until consumed.
    topo: "TopoCounts" = None
    remaining: jnp.ndarray = None  # f32[T, R]


class WarmCarry(NamedTuple):
    """The previous solve's final scan carry, carried as the initial state of
    a warm-start repair solve (docs/INCREMENTAL.md).

    ``state``/``ex_state`` hold every placement the previous solve committed
    (used capacity, merged requirement masks, zone/ct commitments, ports,
    volume counters); ``topo`` the shared topology-group counts; ``remaining``
    the provisioner-limit budget.  A repair solve re-enters ``solve_core``
    with this carry and a class-count vector holding only the DELTA pods —
    every phase then fills leftover capacity exactly as the full solve's later
    classes would, so the constraint semantics are identical by construction.
    Evictions are applied to the carry first (``repair_free``): capacity and
    counts are returned, but merged requirement masks / zone commitments /
    port claims are NOT un-merged — that one-way pessimism is the optimality
    drift the fallback policy's periodic full-solve audit bounds."""

    state: NodeState
    ex_state: ExistingState
    topo: TopoCounts
    remaining: jnp.ndarray  # f32[T, R]


class RepairPlan(NamedTuple):
    """The dirty-region plan of a warm-start repair solve.

    ``pref_new`` / ``pref_ex`` are the per-class freed-hole planes: how many
    pods of class c were evicted from each new-node slot / existing node since
    the carry was taken.  Every placement fill prefers refilling these holes
    (capped at the freed count — ``_fill_with_pref``) before the normal
    emptiest-first / index order, which is what makes steady-state churn
    repairs land on EXACTLY the slots the departures vacated and keeps the
    lineage's assignments identical to a from-scratch solve.  All-zeros is a
    valid no-preference plan (pure additions).

    The ``base_*`` planes ([G1, Z] i32) carry the topology-count
    contributions of new-node slots OUTSIDE a bounded repair window
    (``gather_repair_window``): the zone derivations in ``_class_step`` add
    them as constants so a windowed repair sees the same zone counts a
    full-width solve would.  All-zeros when the repair runs unwindowed."""

    pref_new: jnp.ndarray  # i32[C, N]
    pref_ex: jnp.ndarray  # i32[C, E]
    base_fwd_sing: jnp.ndarray  # i32[G1, Z] committed-zone forward counts
    base_fwd_full: jnp.ndarray  # i32[G1, Z] pessimistic (anti) forward counts
    base_inv_full: jnp.ndarray  # i32[G1, Z] inverse-ownership counts


def _imax(x: jnp.ndarray, statics: "Statics") -> jnp.ndarray:
    """Finish a reduction over the catalog (instance-type) axis.

    The local ``jnp.max`` already ran; when the solve executes inside a
    ``shard_map`` with the catalog sharded (parallel.mesh dispatch), every
    device holds only its I-shard's partial maximum and this inserts the
    cross-shard ``lax.pmax``.  Unsharded solves pass ``catalog_axis=None``
    and this is the identity — the single-chip path is literally the same
    code (docs/KERNEL_PERF.md "Layer 5").  max over i32/f32 is exactly
    associative, so the sharded solve stays BIT-IDENTICAL to single-device.
    """
    if statics.catalog_axis is not None:
        x = jax.lax.pmax(x, statics.catalog_axis)
    return x


def _isum(x: jnp.ndarray, statics: "Statics") -> jnp.ndarray:
    """Cross-shard ``lax.psum`` over the catalog axis (see ``_imax``).  Only
    used for integer-valued f32 counts (einsum of 0/1 products), whose
    partial sums are exact in f32 — summation order cannot change the bits.
    """
    if statics.catalog_axis is not None:
        x = jax.lax.psum(x, statics.catalog_axis)
    return x


def _water_fill(count0: jnp.ndarray, allowed: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """i32[Z] quotas: distribute m pods over allowed zones, always filling the
    lowest-count zone first — the telescoped form of the reference's per-pod
    min-domain selection (topologygroup.go:155-182; maxSkew ≥ 1 guarantees the
    min-count zone is always admissible so skew never blocks the min choice).
    """
    z = count0.shape[0]
    c = jnp.where(allowed, count0.astype(jnp.float32), BIG)
    order = jnp.argsort(c)
    s = c[order]
    # cost[k] = pods needed to raise the k lowest zones to level s[k]
    idx = jnp.arange(z, dtype=jnp.float32)
    prefix = jnp.cumsum(s) - s
    cost = idx * s - prefix  # cost to reach level s[k] for first k zones
    cost = jnp.where(jnp.isfinite(cost), cost, BIG)
    mf = m.astype(jnp.float32)
    # k* = number of zones that participate in the fill
    k_star = jnp.sum((cost <= mf).astype(jnp.int32)) - 1
    k_star = jnp.clip(k_star, 0, z - 1)
    base_level = s[k_star]
    spent = cost[k_star]
    rem = mf - spent
    k_count = (k_star + 1).astype(jnp.float32)
    level = base_level + jnp.floor(rem / k_count)
    leftover = rem - jnp.floor(rem / k_count) * k_count
    # zones among the k* lowest get filled to `level`, the first `leftover`
    # (in sorted order) get one extra
    in_fill = jnp.arange(z) <= k_star
    extra = (jnp.arange(z) < leftover).astype(jnp.float32)
    final_sorted = jnp.where(in_fill, jnp.maximum(s, level + extra), s)
    final = jnp.zeros_like(c).at[order].set(final_sorted)
    quota = jnp.where(allowed, final - c, 0.0)
    return jnp.maximum(quota, 0.0).astype(jnp.int32)


def _key_compat_node_class(state: NodeState, cls, statics) -> jnp.ndarray:
    """bool[N]: Requirements.Compatible(node, class) vectorized over nodes."""
    node_t = mask_ops.ReqTensor(state.kmask, state.kdef, state.kneg, state.kgt, state.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    return mask_ops.compatible(
        node_t, cls_t, statics.is_custom, statics.vocab_ints, v=statics.mask_v
    )


def _merge_node_class(state: NodeState, cls, statics) -> mask_ops.ReqTensor:
    node_t = mask_ops.ReqTensor(state.kmask, state.kdef, state.kneg, state.kgt, state.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    return mask_ops.add(
        node_t, cls_t, statics.valid, statics.vocab_ints,
        v=statics.mask_v, key_has_bounds=statics.key_has_bounds,
    )


def _it_intersects(merged: mask_ops.ReqTensor, statics) -> jnp.ndarray:
    """bool[N, I]: InstanceType.Requirements.Intersects(nodeReqs) for every
    (node, instance type) pair (node.go:143-145).  Packed masks reduce by a
    word-wide AND + nonzero test per key (the hot path); the bool layout keeps
    the per-key [N,V]x[V,I] matmul form so it lands on the MXU."""
    it = statics.it  # ReqTensor [I, K, V+1] (or [I, K, W] packed words)
    n_keys = it.defined.shape[-1]
    packed = statics.packed
    if packed:
        vocab = jnp.asarray(mask_ops.vocab_words(statics.mask_v))
        a_other_all = mask_ops.other_bit(merged.mask, statics.mask_v)  # [N, K]
        b_other_all = mask_ops.other_bit(it.mask, statics.mask_v)  # [I, K]
    ok_all = None
    for k in range(n_keys):  # K is small and static: unrolled
        a_mask = merged.mask[:, k, :]  # [N, V+1] bools or [N, W] words
        b_mask = it.mask[:, k, :]  # [I, V+1] bools or [I, W] words
        if packed:
            vocab_overlap = jnp.any(
                (a_mask[:, None, :] & vocab & b_mask[None, :, :]) != 0, axis=-1
            )
            both_other = a_other_all[:, k, None] & b_other_all[None, :, k]
        else:
            vocab_overlap = (
                jnp.einsum(
                    "nv,iv->ni",
                    a_mask[:, :-1].astype(jnp.bfloat16),
                    b_mask[:, :-1].astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0.5
            )
            both_other = a_mask[:, -1:] & b_mask[None, :, -1]
        if statics.key_has_bounds[k]:
            gt = jnp.maximum(merged.gt[:, k, None], it.gt[None, :, k])
            lt = jnp.minimum(merged.lt[:, k, None], it.lt[None, :, k])
            n_range = jnp.maximum(jnp.ceil(lt) - jnp.floor(gt) - 1.0, 0.0)
            ints_k = statics.vocab_ints[k]  # [V]
            inside = (ints_k[None, None, :] > gt[..., None]) & (
                ints_k[None, None, :] < lt[..., None]
            )
            n_in = jnp.sum(inside.astype(jnp.float32), axis=-1)
            unseen = both_other & (n_range - n_in >= 1.0)
        else:
            unseen = both_other
        nonempty = vocab_overlap | unseen
        checked = merged.defined[:, k, None] & it.defined[None, :, k]
        both_neg = merged.negative[:, k, None] & it.negative[None, :, k]
        ok = ~checked | nonempty | both_neg
        ok_all = ok if ok_all is None else (ok_all & ok)
    return ok_all


def _capacity(used: jnp.ndarray, size: jnp.ndarray, statics) -> jnp.ndarray:
    """i32[N, I]: how many more pods of the class fit on node n as instance
    type i — min over resources of floor((alloc - used) / size)
    (resources Fits telescoped over identical pods)."""
    n_res = statics.it_alloc.shape[-1]
    count = None
    for r in range(n_res):  # R static: unrolled
        free = statics.it_alloc[None, :, r] - used[:, r, None]  # [N, I]
        per = jnp.where(
            size[r] > 0, jnp.floor((free + 1e-4) / jnp.maximum(size[r], 1e-9)), BIG
        )
        per = jnp.maximum(per, 0.0)
        count = per if count is None else jnp.minimum(count, per)
    return jnp.minimum(count, BIG).astype(jnp.int32)


def _offering_ok(zone_ok: jnp.ndarray, ct_ok: jnp.ndarray, statics) -> jnp.ndarray:
    """bool[N, I]: some available offering lies in the node's allowed
    zone × capacity-type rectangle (node.go:151-159 hasOffering)."""
    n = zone_ok.shape[0]
    zc = (zone_ok[:, :, None] & ct_ok[:, None, :]).reshape(n, -1)  # [N, Z*CT]
    avail2 = statics.it_avail.reshape(statics.it_avail.shape[0], -1)  # [I, Z*CT]
    return (
        jnp.einsum(
            "nz,iz->ni",
            zc.astype(jnp.bfloat16),
            avail2.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )


def _fill_by_priority(
    quota: jnp.ndarray, cap: jnp.ndarray, priority: jnp.ndarray
) -> jnp.ndarray:
    """i32[N]: assign up to quota pods to nodes in priority order (ascending),
    each node taking at most cap[n] — the vectorized form of 'sort nodes by
    pod count, first node that accepts wins' (scheduler.go:183-190)."""
    order = jnp.argsort(priority)
    cap_sorted = cap[order]
    before = jnp.cumsum(cap_sorted) - cap_sorted
    assigned_sorted = jnp.clip(quota - before, 0, cap_sorted)
    return jnp.zeros_like(cap).at[order].set(assigned_sorted)


def _fill_with_pref(quota, cap, priority, pref):
    """Warm-repair hole refill (docs/INCREMENTAL.md): slots a departed pod of
    THIS class freed since the carry was taken (``pref[n]`` > 0) absorb the
    quota first — each capped at its freed count, so a slot with slack beyond
    its holes cannot siphon a neighbor's refill — then the normal priority
    order sees the remainder.  With steady-state churn (replacements shaped
    like the departures) the holes absorb the whole quota and the repair's
    final placements are IDENTICAL to a from-scratch solve; without holes
    (``pref`` None or zero) this is exactly ``_fill_by_priority``."""
    if pref is None:
        return _fill_by_priority(quota, cap, priority)
    i32max = jnp.iinfo(jnp.int32).max
    idx = jnp.arange(cap.shape[0], dtype=jnp.int32)
    hole_cap = jnp.minimum(cap, pref)
    a0 = _fill_by_priority(quota, hole_cap, jnp.where(hole_cap > 0, idx, i32max))
    cap_rest = cap - a0
    a1 = _fill_by_priority(
        quota - jnp.sum(a0), cap_rest, jnp.where(cap_rest > 0, priority, i32max)
    )
    return a0 + a1


class Statics(NamedTuple):
    """Trace-time constants bundled for the kernel."""

    it: mask_ops.ReqTensor
    it_alloc: jnp.ndarray
    it_avail: jnp.ndarray
    tmpl: mask_ops.ReqTensor
    tmpl_zone: jnp.ndarray
    tmpl_ct: jnp.ndarray
    tmpl_it: jnp.ndarray
    tmpl_daemon: jnp.ndarray
    tmpl_limits0: jnp.ndarray  # f32[T, R] initial remaining (limits - usage)
    it_capacity: jnp.ndarray  # f32[I, R]
    valid: jnp.ndarray
    is_custom: jnp.ndarray
    vocab_ints: jnp.ndarray
    grp_skew: jnp.ndarray  # i32[G1]
    grp_is_zone: jnp.ndarray  # bool[G1]
    grp_is_anti: jnp.ndarray  # bool[G1]
    grp_member: jnp.ndarray  # bool[C, G1]
    key_has_bounds: Tuple[bool, ...]  # python tuple -> static per-key branching
    packed: bool = False  # mask planes are uint32 words (ops/masks.py pack_mask)
    mask_v: int = 0  # semantic slot count V+1 (only meaningful when packed)
    # mesh axis name the catalog (I) planes are sharded over inside a
    # shard_map body (parallel.mesh); None = unsharded, no collectives traced
    catalog_axis: "Optional[str]" = None


class StaticArrays(NamedTuple):
    """The array part of Statics (everything but the static key_has_bounds
    tuple) — the pytree prepare_host returns and pad_planes transforms.  Field
    order MUST match Statics so ``Statics(*static_arrays, key_has_bounds=...)``
    stays valid."""

    it: mask_ops.ReqTensor
    it_alloc: jnp.ndarray
    it_avail: jnp.ndarray
    tmpl: mask_ops.ReqTensor
    tmpl_zone: jnp.ndarray
    tmpl_ct: jnp.ndarray
    tmpl_it: jnp.ndarray
    tmpl_daemon: jnp.ndarray
    tmpl_limits0: jnp.ndarray
    it_capacity: jnp.ndarray
    valid: jnp.ndarray
    is_custom: jnp.ndarray
    vocab_ints: jnp.ndarray
    grp_skew: jnp.ndarray
    grp_is_zone: jnp.ndarray
    grp_is_anti: jnp.ndarray
    grp_member: jnp.ndarray


class ClassTensors(NamedTuple):
    mask: jnp.ndarray
    defined: jnp.ndarray
    negative: jnp.ndarray
    gt: jnp.ndarray
    lt: jnp.ndarray
    zone: jnp.ndarray
    ct: jnp.ndarray
    it: jnp.ndarray
    requests: jnp.ndarray
    count: jnp.ndarray
    tol: jnp.ndarray
    ports: jnp.ndarray  # bool[C, P] host ports each pod of the class binds
    groups: jnp.ndarray  # i32[C, 6]: owned group per kind (G = none):
    # [zone_spread, host_spread, zone_aff, host_aff, zone_anti, host_anti]
    relax_next: jnp.ndarray  # i32[C] preference-ladder successor (-1 none):
    # failed counts roll to the successor class between scan passes
    anti_soft: jnp.ndarray  # bool[C, 2] (zone, host) anti slot came from a
    # preferred term: owner seeks zero-count domains but registers no inverse
    # counts (topology.go:203-206 skips inverse tracking for preferences)
    root: jnp.ndarray  # i32[C] ladder root index (self when not a variant):
    # shared-volume adds are once-per-(LADDER, node), tracked at the root


class ExClassPrep(NamedTuple):
    """Per-(class, existing-node) quantities constant across one class step's
    phases: intake capacity, merged requirement tensors, zone/capacity-type
    masks, and the class's volume rows.  Computing them once per step is safe
    because a step's phases touch disjoint existing-node sets: committed-zone
    phases narrow a taken node's live ex.zone to their zone (so later zone
    phases exclude it — _phase_existing checks the LIVE mask), and the other
    phase families run at most one capacity-consuming phase per step."""

    cap: jnp.ndarray  # i32[E] intake for this class; 0 = ineligible node
    merged: mask_ops.ReqTensor  # node ∩ class requirements, per node
    zone_full: jnp.ndarray  # bool[E, Z] node zone ∩ class zone
    ct_ok: jnp.ndarray  # bool[E, C2] node capacity-type ∩ class
    vol_add: jnp.ndarray  # i32[E, D]
    vol_per_pod: jnp.ndarray  # i32[D]


def _prep_existing(
    ex: ExistingState,
    ex_static: ExistingStatic,
    cls: ClassTensors,
    statics: Statics,
    host_cap_vec: jnp.ndarray,
    tol_row: jnp.ndarray,
    vol_add_row: jnp.ndarray,
    vol_per_pod_row: jnp.ndarray,
    ft: SnapshotFeatures = ALL_FEATURES,
) -> ExClassPrep:
    """How many pods of the class each existing node can still take — min over
    resource fit, CSI attach limits, host-port exclusivity, and hostname-group
    caps; 0 for ineligible nodes (closed, key-incompatible, intolerable
    taints, port conflicts, volume-blocked).  The same intake the reference
    derives per pod in existingnode.go:77-130, hoisted to class granularity."""
    node_t = mask_ops.ReqTensor(ex.kmask, ex.kdef, ex.kneg, ex.kgt, ex.klt)
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    key_ok = mask_ops.compatible(
        node_t, cls_t, statics.is_custom, statics.vocab_ints, v=statics.mask_v
    )
    merged = mask_ops.add(
        node_t, cls_t, statics.valid, statics.vocab_ints,
        v=statics.mask_v, key_has_bounds=statics.key_has_bounds,
    )
    zone_full = ex.zone & cls.zone[None, :]
    ct_ok = ex.ct & cls.ct[None, :]

    # fixed-capacity fit: min over resources of floor((available - used)/size)
    n_res = ex_static.alloc.shape[-1]
    cap = None
    for r in range(n_res):
        free = ex_static.alloc[:, r] - ex.used[:, r]
        per = jnp.where(
            cls.requests[r] > 0,
            jnp.floor((free + 1e-4) / jnp.maximum(cls.requests[r], 1e-9)),
            BIG,
        )
        per = jnp.maximum(per, 0.0)
        cap = per if cap is None else jnp.minimum(cap, per)
    cap = jnp.minimum(cap, BIG).astype(jnp.int32)

    elig = ex.open_ & key_ok & tol_row & jnp.any(zone_full, axis=-1) & jnp.any(ct_ok, axis=-1)
    if ft.host_ports:
        # host ports: conflict blocks the node; identical pods conflict with
        # each other, so a port-bearing class caps at one pod per node
        # (hostportusage.go:31-56)
        has_ports = jnp.any(cls.ports)
        port_conflict = jnp.any(ex.ports & cls.ports[None, :], axis=-1)
        elig = elig & ~port_conflict
        cap = jnp.minimum(cap, jnp.where(has_ports, 1, UNLIMITED))
    if ft.volume_limits:
        # volume attach limits.  Shared-set classes add a fixed count on first
        # placement (count-independent); per-pod classes add per assigned pod
        # (disjoint claim sets), capping the node's intake like a resource
        vol_free = ex_static.vol_limit - ex.vol_used - vol_add_row  # [E, D]
        vol_ok = jnp.all(vol_free >= vol_per_pod_row[None, :], axis=-1)
        cap_vol = jnp.min(
            jnp.where(
                vol_per_pod_row[None, :] > 0,
                vol_free // jnp.maximum(vol_per_pod_row[None, :], 1),
                UNLIMITED,
            ),
            axis=-1,
        ).astype(jnp.int32)
        cap = jnp.minimum(cap, jnp.maximum(cap_vol, 0))
        elig = elig & vol_ok
    cap = jnp.where(elig, jnp.minimum(cap, host_cap_vec), 0)
    return ExClassPrep(
        cap=cap, merged=merged, zone_full=zone_full, ct_ok=ct_ok,
        vol_add=vol_add_row, vol_per_pod=vol_per_pod_row,
    )


def _phase_existing(
    ex: ExistingState,
    prep: ExClassPrep,
    cls: ClassTensors,
    quota: jnp.ndarray,
    zone_restrict: jnp.ndarray,
    extra_elig: Optional[jnp.ndarray] = None,
    single_node: bool = False,
    ft: SnapshotFeatures = ALL_FEATURES,
    pref: Optional[jnp.ndarray] = None,
) -> Tuple[ExistingState, jnp.ndarray, jnp.ndarray]:
    """Place up to ``quota`` pods of the class onto existing nodes, in index
    order (the reference iterates existing nodes first, in order, and takes the
    first that accepts — scheduler.go:176-180).  ``prep`` carries the step-wide
    intake/merge tensors; ``extra_elig`` restricts to a node subset (affinity
    targets / inverse anti-affinity blocks); ``single_node`` pins the whole
    quota to the first eligible node (hostname self-affinity bootstrap);
    ``pref`` (warm repair only) the class's freed-hole counts per node
    (``_fill_with_pref``)."""
    n_ex = ex.used.shape[0]
    merged = prep.merged
    # zone eligibility reads the LIVE state, not the prep snapshot: an
    # unknown-zone node (all-zones mask) that took pods in an earlier
    # committed-zone phase narrowed its ex.zone there, which is what excludes
    # it here — prep.cap would otherwise be stale for it (double-placement)
    zone_ok = ex.zone & cls.zone[None, :] & zone_restrict[None, :]
    cap = jnp.where(jnp.any(zone_ok, axis=-1), prep.cap, 0)
    if extra_elig is not None:
        cap = jnp.where(extra_elig, cap, 0)
    if single_node:
        first = jnp.argmax(cap > 0)
        cap = jnp.where(jnp.arange(n_ex) == first, cap, 0)

    priority = jnp.where(cap > 0, jnp.arange(n_ex, dtype=jnp.int32), jnp.iinfo(jnp.int32).max)
    assigned = _fill_with_pref(quota, cap, priority, pref)
    placed = jnp.sum(assigned)

    took = assigned > 0
    sel = took[:, None]
    new_ex = ExistingState(
        used=ex.used + assigned[:, None].astype(jnp.float32) * cls.requests[None, :],
        kmask=jnp.where(sel[..., None], merged.mask, ex.kmask),
        kdef=jnp.where(sel, merged.defined, ex.kdef),
        kneg=jnp.where(sel, merged.negative, ex.kneg),
        kgt=jnp.where(sel, merged.gt, ex.kgt),
        klt=jnp.where(sel, merged.lt, ex.klt),
        zone=jnp.where(sel, zone_ok, ex.zone),
        ct=jnp.where(sel, prep.ct_ok, ex.ct),
        ports=jnp.where(sel, ex.ports | cls.ports[None, :], ex.ports)
        if ft.host_ports else ex.ports,
        vol_used=jnp.where(
            sel,
            ex.vol_used + prep.vol_add + assigned[:, None] * prep.vol_per_pod[None, :],
            ex.vol_used,
        )
        if ft.volume_limits else ex.vol_used,
        pod_count=ex.pod_count + assigned,
        open_=ex.open_,
    )
    return new_ex, assigned, placed


def _phase(
    state: NodeState,
    cls: ClassTensors,
    statics: Statics,
    quota: jnp.ndarray,
    zone_restrict: jnp.ndarray,
    host_cap_vec: jnp.ndarray,
    fresh_host_cap: jnp.ndarray,
    remaining: jnp.ndarray,
    extra_elig: Optional[jnp.ndarray] = None,
    max_new_nodes: Optional[int] = None,
    ft: SnapshotFeatures = ALL_FEATURES,
    pref: Optional[jnp.ndarray] = None,
) -> Tuple[NodeState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Place up to ``quota`` pods of the class on nodes whose zone mask meets
    ``zone_restrict`` — first onto open nodes, then fresh nodes from the first
    viable template.  Returns (state, assigned[N], placed).  ``host_cap_vec``
    is the per-slot class cap from hostname groups, ``fresh_host_cap`` the cap
    for newly opened nodes; ``max_new_nodes`` caps node openings (hostname
    self-affinity bootstraps exactly one, target-fill phases open none);
    ``pref`` (warm repair only) the class's freed-hole counts per slot
    (``_fill_with_pref``)."""
    n_slots = state.used.shape[0]
    n_tmpl = statics.tmpl_it.shape[0]

    merged = _merge_node_class(state, cls, statics)
    key_ok = _key_compat_node_class(state, cls, statics)  # [N]
    zone_ok = state.zone & zone_restrict[None, :] & cls.zone[None, :]  # [N, Z]
    ct_ok = state.ct & cls.ct[None, :]  # [N, CT]
    tol_ok = cls.tol[state.tmpl_id]  # [N]

    it_ok = (
        state.viable
        & cls.it[None, :]
        & _it_intersects(merged, statics)
        & _offering_ok(zone_ok, ct_ok, statics)
    )  # [N, I]
    cap_ni = _capacity(state.used, cls.requests, statics)
    cap_ni = jnp.where(it_ok, cap_ni, 0)
    cap_n = _imax(jnp.max(cap_ni, axis=-1), statics)  # [N]

    elig = (
        state.open_
        & key_ok
        & tol_ok
        & jnp.any(zone_ok, axis=-1)
        & jnp.any(ct_ok, axis=-1)
    )
    if extra_elig is not None:
        elig = elig & extra_elig
    if ft.host_ports:
        has_ports = jnp.any(cls.ports)
        port_conflict = jnp.any(state.ports & cls.ports[None, :], axis=-1)
        elig = elig & ~port_conflict
        cap_n = jnp.minimum(cap_n, jnp.where(has_ports, 1, UNLIMITED))
    cap_n = jnp.where(elig, jnp.minimum(cap_n, host_cap_vec), 0)
    if max_new_nodes is not None and max_new_nodes == 1:
        # hostname self-affinity bootstrap: at most one node hosts the class
        first = jnp.argmax(cap_n > 0)
        cap_n = jnp.where(jnp.arange(n_slots) == first, cap_n, 0)

    # node order: emptiest first (pod count, then slot index); pod_count and
    # slot count both stay far below 2^15 so the packed key fits int32
    priority = state.pod_count * n_slots + jnp.arange(n_slots, dtype=jnp.int32)
    priority = jnp.where(cap_n > 0, priority, jnp.iinfo(jnp.int32).max)
    assigned = _fill_with_pref(quota, cap_n, priority, pref)
    placed_existing = jnp.sum(assigned)

    # -- commit to existing nodes --------------------------------------------
    took = assigned > 0
    add_req = assigned[:, None].astype(jnp.float32) * cls.requests[None, :]
    used = state.used + add_req
    sel = took[:, None]
    kmask = jnp.where(sel[..., None], merged.mask, state.kmask)
    kdef = jnp.where(sel, merged.defined, state.kdef)
    kneg = jnp.where(sel, merged.negative, state.kneg)
    kgt = jnp.where(sel, merged.gt, state.kgt)
    klt = jnp.where(sel, merged.lt, state.klt)
    # the node inherits the pod's zone requirements (incl. anti-affinity
    # exclusions and the phase restriction) exactly as the host merges pod
    # requirements into the node on add (node.go:62-117)
    new_zone = jnp.where(sel, zone_ok, state.zone)
    new_ct = jnp.where(sel, ct_ok, state.ct)
    viable = jnp.where(sel, it_ok & (cap_ni >= assigned[:, None]), state.viable)
    if ft.host_ports:
        ports_plane = jnp.where(sel, state.ports | cls.ports[None, :], state.ports)
    else:
        ports_plane = state.ports
    pod_count = state.pod_count + assigned

    # -- open fresh nodes ----------------------------------------------------
    rem = quota - placed_existing

    # template viability for this class+restriction (scheduler.go:192-217):
    # taints, requirement compat, and a non-empty filtered instance list
    tmpl_t = statics.tmpl
    cls_t = mask_ops.ReqTensor(
        cls.mask[None], cls.defined[None], cls.negative[None], cls.gt[None], cls.lt[None]
    )
    tmpl_key_ok = mask_ops.compatible(
        tmpl_t, cls_t, statics.is_custom, statics.vocab_ints, v=statics.mask_v
    )
    tmpl_merged = mask_ops.add(
        tmpl_t, cls_t, statics.valid, statics.vocab_ints,
        v=statics.mask_v, key_has_bounds=statics.key_has_bounds,
    )
    t_zone = statics.tmpl_zone & zone_restrict[None, :] & cls.zone[None, :]  # [T, Z]
    t_ct = statics.tmpl_ct & cls.ct[None, :]
    # provisioner limits: drop instance types whose launch would breach the
    # remaining budget (scheduler.go:292-309 filterByRemainingResources)
    within_limits = jnp.all(
        statics.it_capacity[None, :, :] <= remaining[:, None, :] + 1e-4, axis=-1
    )  # [T, I]
    t_it_ok = (
        statics.tmpl_it
        & cls.it[None, :]
        & _it_intersects(tmpl_merged, statics)
        & _offering_ok(t_zone, t_ct, statics)
        & within_limits
    )  # [T, I]
    t_cap_ti = _capacity(statics.tmpl_daemon, cls.requests, statics)
    t_cap_ti = jnp.where(t_it_ok, t_cap_ti, 0)
    t_cap = _imax(jnp.max(t_cap_ti, axis=-1), statics)  # [T]
    t_viable = (
        cls.tol
        & tmpl_key_ok
        & jnp.any(t_zone, axis=-1)
        & jnp.any(t_ct, axis=-1)
        & (t_cap > 0)
    )
    t_star = jnp.argmax(t_viable)  # first True (argmax of bool picks first max)
    t_ok = t_viable[t_star]

    per_node = jnp.minimum(t_cap[t_star], fresh_host_cap)
    if ft.host_ports:
        per_node = jnp.minimum(per_node, jnp.where(has_ports, 1, UNLIMITED))
    per_node = jnp.maximum(per_node, 1)
    n_new = jnp.where(t_ok & (rem > 0), -(-rem // per_node), 0)
    free_slots = n_slots - state.n_next
    n_new = jnp.minimum(n_new, free_slots)
    # provisioner-limit budget: opening a node pessimistically consumes the
    # largest surviving instance type (subtractMax), so the batch of openings
    # is capped by floor(remaining / max_capacity) per limited resource
    max_cap_star = _imax(jnp.max(
        jnp.where(t_it_ok[t_star][:, None], statics.it_capacity, 0.0), axis=0
    ), statics)  # [R]
    rem_star = remaining[t_star]  # [R]
    budget_per_r = jnp.where(
        jnp.isfinite(rem_star) & (max_cap_star > 0),
        jnp.floor((rem_star + 1e-4) / jnp.maximum(max_cap_star, 1e-9)),
        BIG,
    )
    budget_nodes = jnp.maximum(jnp.min(budget_per_r), 0.0).astype(jnp.int32)
    n_new = jnp.minimum(n_new, budget_nodes)
    if max_new_nodes is not None:
        # single-node semantics: once the class bootstrapped onto an open
        # slot, the remainder must join it — no fresh node for the overflow
        n_new = jnp.where(placed_existing > 0, 0, jnp.minimum(n_new, max_new_nodes))

    slot_idx = jnp.arange(n_slots)
    is_new = (slot_idx >= state.n_next) & (slot_idx < state.n_next + n_new)
    rank = slot_idx - state.n_next
    a_new = jnp.where(is_new, jnp.clip(rem - rank * per_node, 0, per_node), 0)
    placed_new = jnp.sum(a_new)

    seln = is_new[:, None]
    used = jnp.where(
        seln,
        statics.tmpl_daemon[t_star][None, :]
        + a_new[:, None].astype(jnp.float32) * cls.requests[None, :],
        used,
    )
    kmask = jnp.where(seln[..., None], tmpl_merged.mask[t_star][None], kmask)
    kdef = jnp.where(seln, tmpl_merged.defined[t_star][None], kdef)
    kneg = jnp.where(seln, tmpl_merged.negative[t_star][None], kneg)
    kgt = jnp.where(seln, tmpl_merged.gt[t_star][None], kgt)
    klt = jnp.where(seln, tmpl_merged.lt[t_star][None], klt)
    new_zone = jnp.where(seln, t_zone[t_star][None, :], new_zone)
    new_ct = jnp.where(seln, t_ct[t_star][None, :], new_ct)
    fresh_viable = t_it_ok[t_star][None, :] & (t_cap_ti[t_star][None, :] >= a_new[:, None])
    viable = jnp.where(seln, fresh_viable, viable)
    if ft.host_ports:
        ports_plane = jnp.where(
            seln, (a_new > 0)[:, None] & cls.ports[None, :], ports_plane
        )
    pod_count = jnp.where(is_new, a_new, pod_count)
    tmpl_id = jnp.where(is_new, t_star, state.tmpl_id)
    open_ = state.open_ | is_new
    n_next = state.n_next + n_new

    # pessimistic limit tracking: each opened node may become the largest
    # surviving instance type (scheduler.go:273-290 subtractMax)
    remaining = remaining.at[t_star].add(-n_new.astype(jnp.float32) * max_cap_star)

    new_state = NodeState(
        used, kmask, kdef, kneg, kgt, klt, new_zone, new_ct, viable,
        ports_plane, pod_count, tmpl_id, open_, n_next,
    )
    return new_state, assigned + a_new, placed_existing + placed_new, remaining


def _and_opt(a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]):
    """AND of two optional eligibility masks (None = unrestricted)."""
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _class_step(
    statics: Statics,
    ex_static: ExistingStatic,
    n_zones: int,
    carry,
    cls_with_index,
    features: SnapshotFeatures = ALL_FEATURES,
    fuse_zones: bool = True,
    pref: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    topo_base: Optional[Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = None,
):
    """One scan step: schedule every pod of one class — existing nodes first,
    then new nodes, per phase.  Topology lives in shared group counts (the
    reference's hash-deduped TopologyGroups): forward counts gate spread skew /
    affinity targets / anti owners; inverse counts gate the pods anti owners
    repel.

    ``pref`` (warm repair only) is the class's ``(freed_new[N], freed_ex[E])``
    hole counts: every fill prefers refilling the slots this class's departed
    pods vacated (``_fill_with_pref``) before the normal priority order.
    ``topo_base`` (windowed warm repair only) is the
    ``(fwd_sing, fwd_full, inv_full)`` [G1, Z] zone-count contribution of
    new-node slots outside the repair window (RepairPlan docstring), added as
    constants into the zone derivations below.

    ``features`` (static) prunes whole phase families the snapshot provably
    cannot exercise — they are never traced, not just runtime-skipped.
    ``fuse_zones`` (static) replaces the Z sequential zone-committal
    ``run_phase`` sweeps (zone spread, required zonal anti) with one batched
    multi-zone block (``committal_block``) that shares a single dense prep and
    resolves shared-node conflicts by zone order with cumulative caps; the
    sequential path is kept for parity fuzzing."""
    ft = features
    state, ex, topo, remaining = carry
    cls, cls_index = cls_with_index
    pref_new = pref[0] if pref is not None else None
    pref_ex = pref[1] if pref is not None else None
    m = cls.count
    n_ex = ex.pod_count.shape[0]
    n_new_slots = state.pod_count.shape[0]
    g1 = statics.grp_skew.shape[0]
    g_dummy = g1 - 1

    g_zs, g_hs, g_zaf, g_haf, g_zan, g_han = (cls.groups[i] for i in range(6))
    member_row = statics.grp_member[cls_index]  # [G1]
    tol_row = ex_static.tol[cls_index]  # [E]
    vol_add_row = ex_static.cls_vol_add[cls_index]  # [E, D]
    vol_per_pod_row = ex_static.cls_vol_per_pod[cls_index]  # [D]

    def own_onehot(g):
        return (jnp.arange(g1) == g) & (g < g_dummy)

    has_zs = g_zs < g_dummy
    has_zaf = g_zaf < g_dummy
    has_haf = g_haf < g_dummy
    has_zan = g_zan < g_dummy

    # -- derived per-zone counts (TopoCounts docstring): positive groups
    # count pods on zone-COMMITTED (singleton-mask) nodes, the committed-zone
    # rule of topology.go:231-276; anti groups count every zone a resident
    # node could still be in (pessimistic).  Reading the CURRENT masks — not
    # record-time snapshots — replays the host's retroactive narrowing.
    any_zone_groups = ft.zone_spread or ft.zone_affinity or ft.zone_anti
    if any_zone_groups or ft.inv_zone_anti:
        ex_zone_i = ex.zone.astype(jnp.int32) * ex.open_.astype(jnp.int32)[:, None]
        new_zone_i = state.zone.astype(jnp.int32) * state.open_.astype(jnp.int32)[:, None]
    zone_fwd = None
    if any_zone_groups:
        ex_sing_zone = jnp.where(
            jnp.sum(ex_zone_i, axis=-1, keepdims=True) == 1, ex_zone_i, 0
        )
        new_sing_zone = jnp.where(
            jnp.sum(new_zone_i, axis=-1, keepdims=True) == 1, new_zone_i, 0
        )
        zone_fwd_sing = jnp.einsum("ge,ez->gz", topo.fwd_ex, ex_sing_zone) + jnp.einsum(
            "gn,nz->gz", topo.fwd_new, new_sing_zone
        )  # [G1, Z]
        if topo_base is not None:
            zone_fwd_sing = zone_fwd_sing + topo_base[0]
        if ft.zone_anti:
            zone_fwd_full = jnp.einsum("ge,ez->gz", topo.fwd_ex, ex_zone_i) + jnp.einsum(
                "gn,nz->gz", topo.fwd_new, new_zone_i
            )
            if topo_base is not None:
                zone_fwd_full = zone_fwd_full + topo_base[1]
            zone_fwd = jnp.where(
                statics.grp_is_anti[:, None], zone_fwd_full, zone_fwd_sing
            )
        else:
            zone_fwd = zone_fwd_sing

    # -- inverse anti-affinity blocks (topology.go:44-47): members of anti
    # groups avoid every domain the group's owners could occupy
    if ft.inv_zone_anti:
        zone_inv_full = jnp.einsum("ge,ez->gz", topo.inv_ex, ex_zone_i) + jnp.einsum(
            "gn,nz->gz", topo.inv_new, new_zone_i
        )
        if topo_base is not None:
            zone_inv_full = zone_inv_full + topo_base[2]
        mem_anti_zone = member_row & statics.grp_is_anti & statics.grp_is_zone
        blocked_z = jnp.any(mem_anti_zone[:, None] & (zone_inv_full > 0), axis=0)  # [Z]
        allowed_zone = cls.zone & ~blocked_z
    else:
        allowed_zone = cls.zone
    if ft.inv_host_anti:
        mem_anti_host = member_row & statics.grp_is_anti & ~statics.grp_is_zone
        ok_ex = ~jnp.any(mem_anti_host[:, None] & (topo.inv_ex > 0), axis=0)  # [E]
        ok_new = ~jnp.any(mem_anti_host[:, None] & (topo.inv_new > 0), axis=0)  # [N]
    else:
        ok_ex = None
        ok_new = None

    # -- per-node caps from hostname groups -----------------------------------
    # spread (topologygroup.go:184-188: hostname min-count is 0, so cap=skew):
    # members consume cap; non-members only need count <= skew
    cap_parts_ex = []
    cap_parts_new = []
    fresh_parts = []
    if ft.host_spread:
        skew_hs = statics.grp_skew[g_hs]
        member_hs = member_row[g_hs]
        hs_fwd_ex = topo.fwd_ex[g_hs]
        hs_fwd_new = topo.fwd_new[g_hs]
        cap_parts_ex.append(jnp.where(
            member_hs,
            jnp.maximum(skew_hs - hs_fwd_ex, 0),
            jnp.where(hs_fwd_ex <= skew_hs, UNLIMITED, 0),
        ))
        cap_parts_new.append(jnp.where(
            member_hs,
            jnp.maximum(skew_hs - hs_fwd_new, 0),
            jnp.where(hs_fwd_new <= skew_hs, UNLIMITED, 0),
        ))
        fresh_parts.append(jnp.where(member_hs, skew_hs, UNLIMITED))
    if ft.host_anti:
        # owned hostname anti-affinity: only zero-count nodes; self-members cap 1
        han_fwd_ex = topo.fwd_ex[g_han]
        han_fwd_new = topo.fwd_new[g_han]
        member_han = member_row[g_han]
        cap_parts_ex.append(jnp.where(
            g_han < g_dummy,
            jnp.where(han_fwd_ex == 0, jnp.where(member_han, 1, UNLIMITED), 0),
            UNLIMITED,
        ))
        cap_parts_new.append(jnp.where(
            g_han < g_dummy,
            jnp.where(han_fwd_new == 0, jnp.where(member_han, 1, UNLIMITED), 0),
            UNLIMITED,
        ))
        fresh_parts.append(jnp.where((g_han < g_dummy) & member_han, 1, UNLIMITED))
    if cap_parts_ex:
        host_cap_ex = functools.reduce(jnp.minimum, cap_parts_ex).astype(jnp.int32)
        host_cap_new = functools.reduce(jnp.minimum, cap_parts_new).astype(jnp.int32)
        fresh_host_cap = functools.reduce(jnp.minimum, fresh_parts).astype(jnp.int32)
    else:
        host_cap_ex = jnp.full((n_ex,), UNLIMITED, dtype=jnp.int32)
        host_cap_new = jnp.full((n_new_slots,), UNLIMITED, dtype=jnp.int32)
        fresh_host_cap = jnp.int32(UNLIMITED)

    # step-wide existing-node intake/merge tensors (valid across this step's
    # phases — they touch disjoint node sets; see ExClassPrep)
    ex_prep = _prep_existing(
        ex, ex_static, cls, statics, host_cap_ex, tol_row,
        vol_add_row, vol_per_pod_row, ft,
    )

    assigned_total = jnp.zeros_like(state.pod_count)
    assigned_ex_total = jnp.zeros_like(ex.pod_count)
    placed_total = jnp.int32(0)

    def run_phase(state, ex, remaining, quota, restrict, targets_ex=None,
                  targets_new=None, single_node=False, max_new_nodes=None):
        """Wrapped in lax.cond so zero-quota phases (most of them: each class
        participates in 1-2 of the surviving phase kinds) cost nothing on
        device."""

        def do(operand):
            state_i, ex_i, rem_i = operand
            extra_ex = _and_opt(ok_ex, targets_ex)
            extra_new = _and_opt(ok_new, targets_new)
            ex_o, a_ex, placed_ex = _phase_existing(
                ex_i, ex_prep, cls, quota, restrict,
                extra_elig=extra_ex, single_node=single_node, ft=ft,
                pref=pref_ex,
            )
            q_new = quota - placed_ex
            if single_node:
                q_new = jnp.where(placed_ex > 0, 0, q_new)
            state_o, a_new, placed_new, rem_o = _phase(
                state_i, cls, statics, q_new, restrict,
                host_cap_new, fresh_host_cap, rem_i, extra_elig=extra_new,
                max_new_nodes=max_new_nodes, ft=ft, pref=pref_new,
            )
            return state_o, ex_o, a_new, a_ex, placed_ex + placed_new, rem_o

        def skip(operand):
            state_i, ex_i, rem_i = operand
            return (
                state_i,
                ex_i,
                jnp.zeros_like(state_i.pod_count),
                jnp.zeros_like(ex_i.pod_count),
                jnp.int32(0),
                rem_i,
            )

        return jax.lax.cond(quota > 0, do, skip, (state, ex, remaining))

    def committal_block(state, ex, remaining, quota_z, cap_total):
        """All Z zone-committal phases of one family (zone spread quotas /
        required zonal anti), fused into ONE dense sweep.

        The sequential form runs Z full ``run_phase`` passes, each re-deriving
        the merge/compat/intersect planes and re-writing the whole carry.
        Those planes are IDENTICAL across the block: a node that takes pods in
        zone z narrows its zone mask to {z} and thereby leaves every later
        zone phase, so per-node capacity is consumed at most once and the
        per-class mask merge is idempotent for everyone else.  The fusion
        computes the dense prep once, derives all-Z capacity planes in batch,
        and resolves shared-node conflicts by zone order with cumulative caps
        inside a cheap lax.scan over zones ([N]/[E]-wide fills only); the one
        state commit at the end writes each plane once instead of Z times.
        ``cap_total`` bounds cumulative placement across zones (the required-
        anti family places at most ``m`` pods, one per admissible zone).
        Parity with the sequential path is fuzzed in
        tests/test_kernel_fusion_parity.py."""

        def do(operand):
            state_i, ex_i, rem_i = operand
            i32max = jnp.iinfo(jnp.int32).max
            # ---- dense prep shared by every zone --------------------------
            merged = _merge_node_class(state_i, cls, statics)
            key_ok = _key_compat_node_class(state_i, cls, statics)
            ct_ok = state_i.ct & cls.ct[None, :]
            tol_ok = cls.tol[state_i.tmpl_id]
            it_base = state_i.viable & cls.it[None, :] & _it_intersects(merged, statics)
            cap_ni = _capacity(state_i.used, cls.requests, statics)
            elig = state_i.open_ & key_ok & tol_ok & jnp.any(ct_ok, axis=-1)
            if ok_new is not None:
                elig = elig & ok_new
            if ft.host_ports:
                has_ports = jnp.any(cls.ports)
                port_conflict = jnp.any(state_i.ports & cls.ports[None, :], axis=-1)
                elig = elig & ~port_conflict
            zone_has_new = state_i.zone & cls.zone[None, :]  # [N, Z]
            cap_z_list = []
            viable_z_list = []
            for z in range(n_zones):
                ov = (
                    jnp.einsum(
                        "nc,ic->ni",
                        ct_ok.astype(jnp.bfloat16),
                        statics.it_avail[:, z, :].astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
                ok_z = it_base & ov
                viable_z_list.append(ok_z)
                cap_z = jnp.max(jnp.where(ok_z, cap_ni, 0), axis=-1)
                if ft.host_ports:
                    cap_z = jnp.minimum(cap_z, jnp.where(has_ports, 1, UNLIMITED))
                cap_z = jnp.where(
                    elig & zone_has_new[:, z], jnp.minimum(cap_z, host_cap_new), 0
                )
                cap_z_list.append(cap_z)
            # one cross-shard max for the whole [Z, N] block: the clamps above
            # (host-port cap, eligibility mask, host_cap_new) all commute with
            # pmax — replicated operands, monotone ops over nonnegative caps
            cap_open_z = _imax(jnp.stack(cap_z_list), statics)  # [Z, N]
            viable_nzi = jnp.stack(viable_z_list, axis=1)  # [N, Z, I]
            priority = state_i.pod_count * n_new_slots + jnp.arange(
                n_new_slots, dtype=jnp.int32
            )
            # existing-node side: step prep reused, LIVE zone mask at entry
            ex_cap = ex_prep.cap if ok_ex is None else jnp.where(ok_ex, ex_prep.cap, 0)
            zone_has_ex = ex_i.zone & cls.zone[None, :]  # [E, Z]
            # template side: merge/compat/intersect are zone-independent
            cls_t = mask_ops.ReqTensor(
                cls.mask[None], cls.defined[None], cls.negative[None],
                cls.gt[None], cls.lt[None],
            )
            tmpl_key_ok = mask_ops.compatible(
                statics.tmpl, cls_t, statics.is_custom, statics.vocab_ints,
                v=statics.mask_v,
            )
            tmpl_merged = mask_ops.add(
                statics.tmpl, cls_t, statics.valid, statics.vocab_ints,
                v=statics.mask_v, key_has_bounds=statics.key_has_bounds,
            )
            t_ct = statics.tmpl_ct & cls.ct[None, :]
            t_ct_any = jnp.any(t_ct, axis=-1)
            t_base = statics.tmpl_it & cls.it[None, :] & _it_intersects(tmpl_merged, statics)
            t_cap_ti0 = _capacity(statics.tmpl_daemon, cls.requests, statics)
            ovt_z = jnp.stack([
                jnp.einsum(
                    "tc,ic->ti",
                    t_ct.astype(jnp.bfloat16),
                    statics.it_avail[:, z, :].astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0.5
                for z in range(n_zones)
            ])  # [Z, T, I]
            t_zone_cls = statics.tmpl_zone & cls.zone[None, :]  # [T, Z]

            def zone_body(zc, xs):
                (taken_ex, a_ex_acc, zex, taken_new, a_open_acc, zopen,
                 fresh_t, fresh_a, fresh_z, fresh_viable, n_next, rem, placed) = zc
                z, quota, cap_open, ovt, zh_ex, tz = xs
                q = jnp.clip(jnp.minimum(quota, cap_total - placed), 0, None)
                # existing nodes first, in index order (scheduler.go:176-180)
                cap_e = jnp.where(~taken_ex & zh_ex, ex_cap, 0)
                pri_e = jnp.where(cap_e > 0, jnp.arange(n_ex, dtype=jnp.int32), i32max)
                a_ex = _fill_with_pref(q, cap_e, pri_e, pref_ex)
                placed_ex = jnp.sum(a_ex)
                took_e = a_ex > 0
                taken_ex = taken_ex | took_e
                a_ex_acc = a_ex_acc + a_ex
                zex = jnp.where(took_e, z, zex)
                # then open slots, emptiest first
                q2 = q - placed_ex
                cap_n = jnp.where(~taken_new, cap_open, 0)
                pri_n = jnp.where(cap_n > 0, priority, i32max)
                a_op = _fill_with_pref(q2, cap_n, pri_n, pref_new)
                placed_op = jnp.sum(a_op)
                took_n = a_op > 0
                taken_new = taken_new | took_n
                a_open_acc = a_open_acc + a_op
                zopen = jnp.where(took_n, z, zopen)
                # then fresh nodes from the first viable template for the zone
                rem_pods = q2 - placed_op
                within = jnp.all(
                    statics.it_capacity[None, :, :] <= rem[:, None, :] + 1e-4, axis=-1
                )
                t_it_ok = t_base & ovt & within
                t_cap_ti = jnp.where(t_it_ok, t_cap_ti0, 0)
                t_cap = _imax(jnp.max(t_cap_ti, axis=-1), statics)
                t_viable = cls.tol & tmpl_key_ok & tz & t_ct_any & (t_cap > 0)
                t_star = jnp.argmax(t_viable)
                t_ok = t_viable[t_star]
                per_node = jnp.minimum(t_cap[t_star], fresh_host_cap)
                if ft.host_ports:
                    per_node = jnp.minimum(per_node, jnp.where(has_ports, 1, UNLIMITED))
                per_node = jnp.maximum(per_node, 1)
                n_new = jnp.where(t_ok & (rem_pods > 0), -(-rem_pods // per_node), 0)
                n_new = jnp.minimum(n_new, n_new_slots - n_next)
                max_cap_star = _imax(jnp.max(
                    jnp.where(t_it_ok[t_star][:, None], statics.it_capacity, 0.0), axis=0
                ), statics)
                rem_star = rem[t_star]
                budget_per_r = jnp.where(
                    jnp.isfinite(rem_star) & (max_cap_star > 0),
                    jnp.floor((rem_star + 1e-4) / jnp.maximum(max_cap_star, 1e-9)),
                    BIG,
                )
                budget_nodes = jnp.maximum(jnp.min(budget_per_r), 0.0).astype(jnp.int32)
                n_new = jnp.minimum(n_new, budget_nodes)
                slot_idx = jnp.arange(n_new_slots)
                is_new = (slot_idx >= n_next) & (slot_idx < n_next + n_new)
                a_fr = jnp.where(
                    is_new,
                    jnp.clip(rem_pods - (slot_idx - n_next) * per_node, 0, per_node),
                    0,
                )
                fresh_t = jnp.where(is_new, t_star, fresh_t)
                fresh_a = fresh_a + a_fr
                fresh_z = jnp.where(is_new, z, fresh_z)
                fv_row = t_it_ok[t_star][None, :] & (
                    t_cap_ti[t_star][None, :] >= a_fr[:, None]
                )
                fresh_viable = jnp.where(is_new[:, None], fv_row, fresh_viable)
                rem = rem.at[t_star].add(-n_new.astype(jnp.float32) * max_cap_star)
                n_next = n_next + n_new
                placed = placed + placed_ex + placed_op + jnp.sum(a_fr)
                return (taken_ex, a_ex_acc, zex, taken_new, a_open_acc, zopen,
                        fresh_t, fresh_a, fresh_z, fresh_viable, n_next, rem,
                        placed), None

            n_it = state_i.viable.shape[-1]
            zc0 = (
                jnp.zeros(n_ex, bool), jnp.zeros(n_ex, jnp.int32),
                jnp.zeros(n_ex, jnp.int32),
                jnp.zeros(n_new_slots, bool), jnp.zeros(n_new_slots, jnp.int32),
                jnp.zeros(n_new_slots, jnp.int32),
                jnp.full(n_new_slots, -1, jnp.int32), jnp.zeros(n_new_slots, jnp.int32),
                jnp.zeros(n_new_slots, jnp.int32),
                jnp.zeros((n_new_slots, n_it), bool),
                state_i.n_next, rem_i, jnp.int32(0),
            )
            xs = (
                jnp.arange(n_zones, dtype=jnp.int32), quota_z.astype(jnp.int32),
                cap_open_z, ovt_z, zone_has_ex.T, t_zone_cls.T,
            )
            (taken_ex, a_ex, zex, taken_new, a_open, zopen, fresh_t, fresh_a,
             fresh_z, fresh_viable, n_next, rem_o, placed), _ = jax.lax.scan(
                zone_body, zc0, xs
            )

            # ---- one-shot commit (each node took pods in at most one zone) --
            took_e = a_ex > 0
            sel_e = took_e[:, None]
            zhot_e = (jnp.arange(n_zones)[None, :] == zex[:, None]) & sel_e
            mex = ex_prep.merged
            ex_o = ExistingState(
                used=ex_i.used + a_ex[:, None].astype(jnp.float32) * cls.requests[None, :],
                kmask=jnp.where(sel_e[..., None], mex.mask, ex_i.kmask),
                kdef=jnp.where(sel_e, mex.defined, ex_i.kdef),
                kneg=jnp.where(sel_e, mex.negative, ex_i.kneg),
                kgt=jnp.where(sel_e, mex.gt, ex_i.kgt),
                klt=jnp.where(sel_e, mex.lt, ex_i.klt),
                zone=jnp.where(sel_e, zhot_e, ex_i.zone),
                ct=jnp.where(sel_e, ex_prep.ct_ok, ex_i.ct),
                ports=jnp.where(sel_e, ex_i.ports | cls.ports[None, :], ex_i.ports)
                if ft.host_ports else ex_i.ports,
                vol_used=jnp.where(
                    sel_e,
                    ex_i.vol_used + ex_prep.vol_add
                    + a_ex[:, None] * ex_prep.vol_per_pod[None, :],
                    ex_i.vol_used,
                )
                if ft.volume_limits else ex_i.vol_used,
                pod_count=ex_i.pod_count + a_ex,
                open_=ex_i.open_,
            )
            took_o = a_open > 0
            is_fresh = fresh_t >= 0
            tmpl_idx = jnp.maximum(fresh_t, 0)
            sel_o = took_o[:, None]
            sel_f = is_fresh[:, None]
            zhot_o = (jnp.arange(n_zones)[None, :] == zopen[:, None]) & sel_o
            zhot_f = (jnp.arange(n_zones)[None, :] == fresh_z[:, None]) & sel_f
            used = state_i.used + a_open[:, None].astype(jnp.float32) * cls.requests[None, :]
            used = jnp.where(
                sel_f,
                statics.tmpl_daemon[tmpl_idx]
                + fresh_a[:, None].astype(jnp.float32) * cls.requests[None, :],
                used,
            )
            kmask = jnp.where(sel_o[..., None], merged.mask, state_i.kmask)
            kmask = jnp.where(sel_f[..., None], tmpl_merged.mask[tmpl_idx], kmask)
            kdef = jnp.where(sel_o, merged.defined, state_i.kdef)
            kdef = jnp.where(sel_f, tmpl_merged.defined[tmpl_idx], kdef)
            kneg = jnp.where(sel_o, merged.negative, state_i.kneg)
            kneg = jnp.where(sel_f, tmpl_merged.negative[tmpl_idx], kneg)
            kgt = jnp.where(sel_o, merged.gt, state_i.kgt)
            kgt = jnp.where(sel_f, tmpl_merged.gt[tmpl_idx], kgt)
            klt = jnp.where(sel_o, merged.lt, state_i.klt)
            klt = jnp.where(sel_f, tmpl_merged.lt[tmpl_idx], klt)
            zone = jnp.where(sel_o, zhot_o, state_i.zone)
            zone = jnp.where(sel_f, zhot_f, zone)
            ct = jnp.where(sel_o, ct_ok, state_i.ct)
            ct = jnp.where(sel_f, t_ct[tmpl_idx], ct)
            v_open = jnp.take_along_axis(
                viable_nzi, jnp.maximum(zopen, 0)[:, None, None], axis=1
            )[:, 0, :]
            viable = jnp.where(
                sel_o, v_open & (cap_ni >= a_open[:, None]), state_i.viable
            )
            viable = jnp.where(sel_f, fresh_viable, viable)
            if ft.host_ports:
                ports_pl = jnp.where(
                    sel_o, state_i.ports | cls.ports[None, :], state_i.ports
                )
                ports_pl = jnp.where(
                    sel_f, (fresh_a > 0)[:, None] & cls.ports[None, :], ports_pl
                )
            else:
                ports_pl = state_i.ports
            pod_count = state_i.pod_count + a_open
            pod_count = jnp.where(is_fresh, fresh_a, pod_count)
            tmpl_id = jnp.where(is_fresh, tmpl_idx, state_i.tmpl_id)
            state_o = NodeState(
                used, kmask, kdef, kneg, kgt, klt, zone, ct, viable, ports_pl,
                pod_count, tmpl_id, state_i.open_ | is_fresh, n_next,
            )
            return state_o, ex_o, a_open + fresh_a, a_ex, placed, rem_o

        def skip(operand):
            state_i, ex_i, rem_i = operand
            return (
                state_i,
                ex_i,
                jnp.zeros_like(state_i.pod_count),
                jnp.zeros_like(ex_i.pod_count),
                jnp.int32(0),
                rem_i,
            )

        return jax.lax.cond(jnp.sum(quota_z) > 0, do, skip, (state, ex, remaining))

    def accumulate(results):
        nonlocal state, ex, remaining, assigned_total, assigned_ex_total, placed_total
        state, ex, assigned, assigned_ex, placed, remaining = results
        assigned_total = assigned_total + assigned
        assigned_ex_total = assigned_ex_total + assigned_ex
        placed_total = placed_total + placed

    # zones some template can actually serve for this class (or an eligible
    # existing node with intake left sits in) — used by spread quotas and the
    # affinity bootstrap below
    if ft.zone_spread or ft.zone_affinity:
        # the einsum's i-contraction is partial per catalog shard; psum of the
        # integer-valued f32 partials is exact, so the >0.5 test is unmoved
        tmpl_offers = _isum(jnp.einsum(
            "ti,izc,tz,tc->z",
            statics.tmpl_it.astype(jnp.bfloat16),
            (statics.it_avail & cls.it[:, None, None]).astype(jnp.bfloat16),
            statics.tmpl_zone.astype(jnp.bfloat16),
            (statics.tmpl_ct & cls.ct[None, :]).astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ), statics) > 0.5  # [Z]
        ex_cap_spread = ex_prep.cap if ok_ex is None else jnp.where(ok_ex, ex_prep.cap, 0)
        # per-zone intake for this class: existing nodes contribute their
        # remaining intake; template zones open new nodes on demand (unbounded).
        # A multi-zone (unknown-zone) node's intake deliberately counts into
        # EVERY zone of its mask: the estimate must be optimistic, because an
        # over-grant surfaces as a phase shortfall (the spread_suspect sentinel
        # below routes it to the host oracle), whereas pinning the intake to
        # one zone would under-estimate the others and under-place with no
        # detectable signal — the host can commit such a node to whichever
        # zone the fill needs.
        ex_cap_z = jnp.sum(
            jnp.minimum(ex_cap_spread, m)[:, None]
            * ex_prep.zone_full.astype(jnp.int32),
            axis=0,
        )  # i32[Z]
        fillable = tmpl_offers | (ex_cap_z > 0)

    # -- zone spread phases (one committed zone per phase) --------------------
    spread_suspect = jnp.array(False)
    if ft.zone_spread:
        counts_zs = zone_fwd[g_zs]  # [Z]
        member_zs = member_row[g_zs]
        cap_pods_z = jnp.where(tmpl_offers, UNLIMITED, jnp.minimum(ex_cap_z, UNLIMITED))

        # the reference's per-pod skew check measures against the min over ALL
        # the pod's domains, including zones that cannot take this class —
        # their counts stay frozen, capping every fillable zone at
        # frozen_min + maxSkew (topology_test.go:124-162 "existing pod" case).
        # A zone whose intake runs out MID-fill freezes the same way
        # (nextDomainTopologySpread keeps measuring it,
        # topologygroup.go:155-182), so the water-fill proceeds in rounds:
        # each round fills min-first up to the nearest saturation level, then
        # the saturated zone joins the frozen set and bounds the rest.
        unreachable = allowed_zone & ~fillable
        skew_zs = statics.grp_skew[g_zs]
        BIGI = jnp.int32(1 << 30)
        finite_cap = cap_pods_z < UNLIMITED
        quotas = jnp.zeros(n_zones, dtype=jnp.int32)
        sat = jnp.zeros(n_zones, dtype=bool)
        m_rem = m
        # worst case: one round per sequentially-saturating finite-cap zone,
        # plus a final redistribution round for the unbounded zones
        for _ in range(n_zones + 1):
            counts_now = counts_zs + quotas
            min_frozen = jnp.min(jnp.where(unreachable | sat, counts_now, BIGI))
            skew_cap = jnp.clip(min_frozen + skew_zs - counts_now, 0, UNLIMITED)
            active = allowed_zone & fillable & ~sat
            cap_rem = jnp.clip(cap_pods_z - quotas, 0, UNLIMITED)
            # level where the nearest capacity-bounded active zone saturates;
            # fills stop there so its frozen count bounds the next round
            lvl_sat = jnp.min(jnp.where(active & finite_cap, counts_now + cap_rem, BIGI))
            q = _water_fill(counts_now, active, m_rem)
            q = jnp.minimum(q, jnp.clip(lvl_sat - counts_now, 0, UNLIMITED))
            q = jnp.minimum(q, jnp.minimum(skew_cap, cap_rem))
            q = jnp.where(active, q, 0)
            quotas = quotas + q
            m_rem = m_rem - jnp.sum(q)
            sat = sat | (active & finite_cap & (quotas >= cap_pods_z))
        quotas = jnp.where(member_zs, quotas, 0)
        # under-placement sentinel (host-oracle parity,
        # topologygroup.go:155-182): the round bound can exhaust with quota
        # still unallocated while some active zone retains both skew and
        # capacity headroom — the shape ROADMAP gap 5 documented as silent.
        # Flag it; the shell re-routes the class's leftover pods through the
        # host path instead of quietly failing them.
        counts_end = counts_zs + quotas
        min_frozen_end = jnp.min(jnp.where(unreachable | sat, counts_end, BIGI))
        skew_headroom = (counts_end - min_frozen_end) < skew_zs
        cap_headroom = (cap_pods_z - quotas) > 0
        fill_residual = (m_rem > 0) & jnp.any(
            allowed_zone & fillable & ~sat & skew_headroom & cap_headroom
        )
        quotas_gated = jnp.where(has_zs, quotas, 0)
        if fuse_zones:
            results_zs = committal_block(
                state, ex, remaining, quotas_gated, jnp.int32(UNLIMITED)
            )
            placed_zs = results_zs[4]
            accumulate(results_zs)
        else:
            placed_zs = jnp.int32(0)
            for z in range(n_zones):
                restrict = jnp.zeros(n_zones, dtype=bool).at[z].set(True)
                results_z = run_phase(state, ex, remaining, quotas_gated[z], restrict)
                placed_zs = placed_zs + results_z[4]
                accumulate(results_z)
        # quota granted but not realized in-phase: the water-fill's per-zone
        # intake estimate (ex_cap_z) is optimistic — e.g. a multi-zone node's
        # capacity counts into every zone of its mask — so a phase can place
        # fewer pods than its quota with no later round to redistribute them
        quota_shortfall = placed_zs < jnp.sum(quotas)
        spread_suspect = has_zs & member_zs & (fill_residual | quota_shortfall)

        # non-self-selecting zone spread: the pod never increments its own
        # group's counts, so the skew formula (count + 0 - min <= maxSkew,
        # topologygroup.go:155-182) yields a STATIC admissible-zone mask — one
        # plain phase over it, no per-zone quotas or committal needed
        min_zs = jnp.min(jnp.where(cls.zone, counts_zs, jnp.int32(1 << 30)))
        admissible_zs = allowed_zone & (counts_zs - min_zs <= statics.grp_skew[g_zs])
        q_nm = jnp.where(has_zs & ~member_zs & jnp.any(admissible_zs), m, 0)
        accumulate(run_phase(state, ex, remaining, q_nm, admissible_zs))

    # -- owned zone anti-affinity: zero-forward-count zones only --------------
    # self-members place one pod per currently-unpoisoned zone, each phase
    # COMMITTING its node to that single zone (the restrict narrows the node
    # mask to a singleton on merge).  This reaches the host's converged
    # fixpoint — one member per admissible zone — in batch one: the host's
    # record-time domain snapshots only get there over batches/retries as
    # co-location luck narrows masks (topology_test.go:1879-1923), so the
    # fuzzer contract is kernel >= host batch-one, equal at the fixpoint.
    # Non-member owners don't repel each other: plain multi-zone phase.
    # soft (preferred) anti keeps the single pessimistic multi-zone phase:
    # the reference relaxes failing preference pods onto existing nodes and
    # never revisits them, so one-per-zone committal would permanently
    # diverge from its packing (topology_test.go:1478 — co-location allowed);
    # required anti commits because the reference CONVERGES to one-per-zone
    # over batches (pods stay pending until zones register)
    if ft.zone_anti:
        zero_zones = allowed_zone & (zone_fwd[g_zan] == 0)
        anti_member = member_row[g_zan]
        anti_required = has_zan & anti_member & ~cls.anti_soft[0]
        # the committal phases are only reachable for required-anti members;
        # when the snapshot statically has none (features.required_zone_anti
        # False, from encode_snapshot), they are never traced — formerly the
        # single largest per-class phase block, all compile + per-step cost
        if ft.required_zone_anti:
            anti_quota_z = (anti_required & zero_zones).astype(jnp.int32)
            if fuse_zones:
                accumulate(committal_block(state, ex, remaining, anti_quota_z, m))
            else:
                placed_anti = jnp.int32(0)
                for z in range(n_zones):
                    restrict = jnp.zeros(n_zones, dtype=bool).at[z].set(True)
                    q = jnp.where(
                        anti_required & zero_zones[z] & (placed_anti < m),
                        jnp.int32(1),
                        jnp.int32(0),
                    )
                    results_a = run_phase(state, ex, remaining, q, restrict)
                    placed_anti = placed_anti + results_a[4]
                    accumulate(results_a)
        anti_quota = jnp.where(
            has_zan & jnp.any(zero_zones),
            jnp.where(
                anti_member,
                jnp.where(cls.anti_soft[0], jnp.minimum(m, 1), 0),
                m,
            ),
            0,
        )
        accumulate(run_phase(state, ex, remaining, anti_quota, zero_zones))

    # -- zone affinity: nonzero-count zones (the selected pods' locations),
    # else self-members bootstrap one allowed zone (topologygroup.go:202-233).
    # The bootstrap must be capacity-aware (the host's per-node bootstrap only
    # lands where a node is viable): restrict to zones some template offers
    # for this class, or where an open existing node sits
    if ft.zone_affinity:
        bootstrap_allowed = allowed_zone & fillable
        nonzero_zones = allowed_zone & (zone_fwd[g_zaf] > 0)
        bootstrap_zone = (
            jnp.zeros(n_zones, dtype=bool)
            .at[jnp.argmax(bootstrap_allowed)]
            .set(jnp.any(bootstrap_allowed) & member_row[g_zaf])
        )
        zone_aff_restrict = jnp.where(
            jnp.any(nonzero_zones), nonzero_zones, bootstrap_zone
        )
        zone_aff_quota = jnp.where(has_zaf & ~has_haf & jnp.any(zone_aff_restrict), m, 0)
        accumulate(run_phase(state, ex, remaining, zone_aff_quota, zone_aff_restrict))

    # -- hostname affinity: fill target nodes (forward count > 0) on both
    # planes; else self-members bootstrap exactly one node
    all_zones = jnp.ones(n_zones, dtype=bool)
    if ft.host_affinity:
        if ft.zone_affinity:
            host_restrict = jnp.where(has_zaf, zone_aff_restrict, all_zones) & allowed_zone
        else:
            host_restrict = all_zones & allowed_zone
        targets_ex = (topo.fwd_ex[g_haf] > 0) & ex.open_
        targets_new = (topo.fwd_new[g_haf] > 0) & state.open_
        targets_exist = jnp.any(targets_ex) | jnp.any(targets_new)
        host_quota = jnp.where(has_haf, m, 0)
        q_targets = jnp.where(targets_exist, host_quota, 0)
        accumulate(
            run_phase(
                state, ex, remaining, q_targets, host_restrict,
                targets_ex=targets_ex, targets_new=targets_new, max_new_nodes=0,
            )
        )
        q_boot = jnp.where(targets_exist | ~member_row[g_haf], 0, host_quota)
        accumulate(
            run_phase(
                state, ex, remaining, q_boot, host_restrict,
                single_node=True, max_new_nodes=1,
            )
        )

    # -- unconstrained phase for plain classes --------------------------------
    any_quota = jnp.where(has_zs | has_zan | has_zaf | has_haf, 0, m)
    accumulate(run_phase(state, ex, remaining, any_quota, allowed_zone))

    # -- record (topology.go:120-143): update shared PER-NODE counts ----------
    # zone projections happen at read time from live masks (derivation above),
    # so recording is pure bookkeeping: each placed pod adds its class's
    # membership/ownership to its node's row in every relevant group.
    # No class can own or match a group when no feature family exists, so the
    # whole record step prunes away with them.
    if (ft.zone_spread or ft.host_spread or ft.zone_affinity or ft.host_affinity
            or ft.zone_anti or ft.host_anti or ft.inv_zone_anti or ft.inv_host_anti):
        a_ex_f = assigned_ex_total.astype(jnp.int32)
        a_new_f = assigned_total.astype(jnp.int32)
        member_i = member_row.astype(jnp.int32)
        # preferred-anti owners register no inverse counts (the reference skips
        # inverse tracking for preferences, topology.go:203-206)
        own_zan_inv = jnp.where(cls.anti_soft[0], 0, own_onehot(g_zan).astype(jnp.int32))
        own_han_inv = jnp.where(cls.anti_soft[1], 0, own_onehot(g_han).astype(jnp.int32))
        own_inv = own_zan_inv + own_han_inv
        topo = TopoCounts(
            fwd_ex=topo.fwd_ex + member_i[:, None] * a_ex_f[None, :],
            inv_ex=topo.inv_ex + own_inv[:, None] * a_ex_f[None, :],
            fwd_new=topo.fwd_new + member_i[:, None] * a_new_f[None, :],
            inv_new=topo.inv_new + own_inv[:, None] * a_new_f[None, :],
        )

    failed = m - placed_total
    return (
        (state, ex, topo, remaining),
        (assigned_total, assigned_ex_total, failed, spread_suspect),
    )


def solve_core(
    class_tensors,
    statics_arrays,
    n_slots: int,
    key_has_bounds,
    existing_state: "Optional[ExistingState]" = None,
    existing_static: "Optional[ExistingStatic]" = None,
    n_passes: int = 1,
    emit_zonal_anti: "Optional[bool]" = None,
    features: "Optional[SnapshotFeatures]" = None,
    fuse_zones: bool = True,
    packed_masks: bool = True,
    warm_carry: "Optional[WarmCarry]" = None,
    repair_plan: "Optional[RepairPlan]" = None,
    catalog_axis: "Optional[str]" = None,
):
    """Unjitted kernel core — jit/vmap/shard_map-composable (the parallel layer
    vmaps this over snapshot replicas and consolidation subsets;
    __graft_entry__ compile-checks it).

    ``catalog_axis`` (static) names the mesh axis the catalog (instance-type)
    planes are sharded over when this body runs inside a ``shard_map``
    (parallel.mesh dispatch): every I-axis reduction finishes with a
    ``pmax``/``psum`` collective over that axis (``_imax``/``_isum``), all of
    them exact, so the sharded solve is bit-identical to the single-device
    solve.  None (the default; the auto mesh config resolves to it on a
    single device) traces no collectives at all, while a FORCED 1-device
    mesh keeps them as singleton no-ops — the degenerate case is the same
    code either way.

    ``n_passes`` > 1 re-scans still-failed pods seeded by earlier passes'
    topology counts — the kernel's equivalent of the host queue re-pushing
    failed pods until no progress (scheduler.go:117-123), needed when a
    cross-group affinity follower scans before its target
    (models.snapshot.affinity_scan_passes).

    ``features`` (static) is the snapshot's SnapshotFeatures phase plan —
    pass EncodedSnapshot.features so constraint families no class can
    exercise are never traced (docs/KERNEL_PERF.md).  ``emit_zonal_anti`` is
    the legacy single-flag form (pre-features callers); it maps onto
    features.required_zone_anti.  ``fuse_zones`` (static) selects the batched
    multi-zone committal block over the sequential per-zone phases;
    ``packed_masks`` (static) stores requirement masks as uint32 words and
    runs the mask algebra as bitwise AND + popcount (ops/masks.py) instead of
    bf16 einsums.  Both default on; the alternates are kept for parity
    fuzzing.

    ``warm_carry`` (traced pytree, shapes fixed) switches the call into a
    warm-start REPAIR solve: the scan resumes from a previous solve's final
    carry instead of empty slots, and ``class_tensors.count`` holds only the
    delta pods to place (docs/INCREMENTAL.md).  The carry's plane shapes must
    match this call's buckets — solver.incremental guarantees that by reusing
    the previous padded tensors verbatim.  ``existing_static`` is still
    required when the carry has real existing nodes (its tol/vol rows are
    per-class); with a warm carry the topology/budget seeding is skipped —
    both already live in the carry.  ``repair_plan`` (warm path only) carries
    the per-class freed-hole planes every fill prefers to refill first plus
    the out-of-window topology bases of a bounded repair (RepairPlan
    docstring)."""
    if features is None:
        ft = ALL_FEATURES
        if emit_zonal_anti is not None:
            ft = ft._replace(required_zone_anti=bool(emit_zonal_anti))
    else:
        ft = SnapshotFeatures(*features)
    ft = ft.canonical()
    sa = StaticArrays(*statics_arrays)
    width = sa.valid.shape[-1]  # semantic slot count V+1, pre-packing
    if packed_masks:
        sa = sa._replace(
            it=mask_ops.pack_req(sa.it),
            tmpl=mask_ops.pack_req(sa.tmpl),
            valid=mask_ops.pack_mask(sa.valid),
        )
        class_tensors = class_tensors._replace(
            mask=mask_ops.pack_mask(class_tensors.mask)
        )
    statics = Statics(
        *sa, key_has_bounds=key_has_bounds, packed=packed_masks, mask_v=width,
        catalog_axis=catalog_axis,
    )
    n_zones = statics.tmpl_zone.shape[-1]
    n_res = statics.it_alloc.shape[-1]
    n_keys = sa.it.defined.shape[-1]
    n_it = statics.it_alloc.shape[0]
    n_ct = statics.tmpl_ct.shape[-1]
    n_classes = class_tensors.count.shape[0]

    g1 = statics.grp_skew.shape[0]
    n_ports = class_tensors.ports.shape[-1] if n_classes else 1
    if warm_carry is not None:
        # warm-start repair: resume from the previous solve's final carry.
        # The carry's planes already went through this function once — masks
        # are packed, topology counts and the limit budget are live — so all
        # of the seeding below is skipped (it would double-count).
        wc = WarmCarry(*warm_carry)
        state = NodeState(*wc.state)
        existing_state = ExistingState(*wc.ex_state)
        n_slots = state.pod_count.shape[0]
        if existing_static is None:
            existing_static = empty_existing_static(n_res, n_classes, g1)
        topo = TopoCounts(*wc.topo)
        remaining0 = wc.remaining
    else:
        if packed_masks:
            kmask0 = jnp.broadcast_to(
                jnp.asarray(mask_ops.full_words(width)),
                (n_slots, n_keys, mask_ops.words_for(width)),
            )
        else:
            kmask0 = jnp.ones((n_slots, n_keys, width), dtype=bool)
        state = NodeState(
            used=jnp.zeros((n_slots, n_res), dtype=jnp.float32),
            kmask=kmask0,
            kdef=jnp.zeros((n_slots, n_keys), dtype=bool),
            kneg=jnp.zeros((n_slots, n_keys), dtype=bool),
            kgt=jnp.full((n_slots, n_keys), -jnp.inf, dtype=jnp.float32),
            klt=jnp.full((n_slots, n_keys), jnp.inf, dtype=jnp.float32),
            zone=jnp.ones((n_slots, n_zones), dtype=bool),
            ct=jnp.ones((n_slots, n_ct), dtype=bool),
            viable=jnp.ones((n_slots, n_it), dtype=bool),
            ports=jnp.zeros((n_slots, n_ports), dtype=bool),
            pod_count=jnp.zeros(n_slots, dtype=jnp.int32),
            tmpl_id=jnp.zeros(n_slots, dtype=jnp.int32),
            open_=jnp.zeros(n_slots, dtype=bool),
            n_next=jnp.int32(0),
        )
        if existing_state is None:
            existing_state = empty_existing_state(n_res, n_keys, width, n_zones, n_ct, n_ports)
            existing_static = empty_existing_static(n_res, n_classes, g1)
        if packed_masks and existing_state.kmask.dtype != jnp.uint32:
            existing_state = existing_state._replace(
                kmask=mask_ops.pack_mask(existing_state.kmask)
            )

        # seed topology counts from pre-existing pods (topology.go:231-276
        # countDomains): forward from selector-matching pods, inverse from
        # anti-term owners — closed nodes (consolidation subsets) drop out at
        # derivation time (the zone projection multiplies by the open mask)
        open_i = existing_state.open_.astype(jnp.int32)
        member_open = existing_static.grp_node_member * open_i[None, :]
        owner_open = existing_static.grp_node_owner * open_i[None, :]
        topo = TopoCounts(
            fwd_ex=member_open,
            inv_ex=owner_open,
            fwd_new=jnp.zeros((g1, n_slots), dtype=jnp.int32),
            inv_new=jnp.zeros((g1, n_slots), dtype=jnp.int32),
        )

    def step(carry, cls_with_index):
        # the whole class step is masked behind count > 0: a zero-count class
        # contributes nothing (phases place 0, record adds 0), so skipping it
        # is a pure no-op that saves the step's dense prep on device.  This is
        # what makes the warm-start REPAIR scan cost proportional to the dirty
        # region: clean classes carry count 0 and fall through, while the
        # iteration shape (C steps) stays fixed so the executable is reused
        # across reconciles.  Full solves benefit too — padded bucket rows and
        # ladder-variant rows idle at 0 until a pass rolls counts into them.
        if repair_plan is not None:
            cls, cls_index, pref_new_row, pref_ex_row = cls_with_index
            pref = (pref_new_row, pref_ex_row)
            base = (
                repair_plan.base_fwd_sing,
                repair_plan.base_fwd_full,
                repair_plan.base_inv_full,
            )
        else:
            cls, cls_index = cls_with_index
            pref = None
            base = None

        def do(carry_in):
            return _class_step(
                statics, existing_static, n_zones, carry_in, (cls, cls_index),
                features=ft, fuse_zones=fuse_zones, pref=pref, topo_base=base,
            )

        def skip(carry_in):
            state_i, ex_i, _, _ = carry_in
            return carry_in, (
                jnp.zeros_like(state_i.pod_count),
                jnp.zeros_like(ex_i.pod_count),
                jnp.int32(0),
                jnp.array(False),
            )

        return jax.lax.cond(cls.count > 0, do, skip, carry)

    cls_indices = jnp.arange(n_classes, dtype=jnp.int32)
    if warm_carry is None:
        # charge open owned nodes' capacity against their provisioner's budget
        n_tmpl = statics.tmpl_zone.shape[0]
        tmpl_onehot = (
            existing_static.node_tmpl[:, None] == jnp.arange(n_tmpl)[None, :]
        ) & (existing_static.node_owned & existing_state.open_)[:, None]  # [E, T]
        used_budget = jnp.einsum(
            "et,er->tr", tmpl_onehot.astype(jnp.float32), existing_static.node_capacity
        )
        remaining0 = statics.tmpl_limits0 - used_budget
    carry = (state, existing_state, topo, remaining0)
    assign = jnp.zeros((n_classes, n_slots), dtype=jnp.int32)
    n_ex = existing_state.pod_count.shape[0]
    assign_ex = jnp.zeros((n_classes, n_ex), dtype=jnp.int32)
    count_left = class_tensors.count
    failed = count_left
    suspect = jnp.zeros(n_classes, dtype=bool)
    for p in range(max(n_passes, 1)):
        cls_pass = class_tensors._replace(count=count_left)
        xs = (cls_pass, cls_indices)
        if repair_plan is not None:
            xs = xs + (
                repair_plan.pref_new.astype(jnp.int32),
                repair_plan.pref_ex.astype(jnp.int32),
            )
        carry, (a, a_ex, failed, suspect_p) = jax.lax.scan(step, carry, xs)
        assign = assign + a
        assign_ex = assign_ex + a_ex
        suspect = suspect | suspect_p
        # roll failed counts one step down the preference ladder (the host
        # path's fail -> Preferences.Relax -> re-push round); classes with no
        # successor retry as themselves (late-affinity re-scan)
        roll_to = jnp.where(
            class_tensors.relax_next >= 0, class_tensors.relax_next, cls_indices
        )
        count_left = jnp.zeros_like(failed).at[roll_to].add(failed)
        if p + 1 < n_passes:
            # shared volume adds are once-per-(LADDER, node): ladder rows
            # share one claim profile, so a root placing in pass 1 and its
            # variant landing on the same node in pass 2 must count the claim
            # set once — collapse placements to the root row before the add
            state_c, ex_c, topo_c, rem_c = carry
            placed_any = (assign_ex > 0).astype(jnp.int32)  # [C, E]
            placed_root = (
                jnp.zeros_like(placed_any).at[class_tensors.root].max(placed_any)
            )
            is_root = (class_tensors.root == cls_indices)[:, None].astype(jnp.int32)
            shared = jnp.sum(
                (placed_root * is_root)[:, :, None] * existing_static.cls_vol_add,
                axis=0,
            )
            per_pod = jnp.sum(
                assign_ex[:, :, None] * existing_static.cls_vol_per_pod[:, None, :],
                axis=0,
            )
            ex_c = ex_c._replace(vol_used=existing_state.vol_used + shared + per_pod)
            carry = (state_c, ex_c, topo_c, rem_c)
    final_state, final_ex, final_topo, final_remaining = carry
    return SolveOutputs(
        assign=assign,
        assign_existing=assign_ex,
        failed=failed,
        state=final_state,
        ex_state=final_ex,
        spread_suspect=suspect,
        topo=final_topo,
        remaining=final_remaining,
    )


def empty_existing_state(
    n_res, n_keys, width, n_zones, n_ct, n_ports: int = 1, n_drivers: int = 1
) -> ExistingState:
    """A single closed dummy slot (E=0 shapes upset some XLA reductions)."""
    return ExistingState(
        used=jnp.zeros((1, n_res), dtype=jnp.float32),
        kmask=jnp.ones((1, n_keys, width), dtype=bool),
        kdef=jnp.zeros((1, n_keys), dtype=bool),
        kneg=jnp.zeros((1, n_keys), dtype=bool),
        kgt=jnp.full((1, n_keys), -jnp.inf, dtype=jnp.float32),
        klt=jnp.full((1, n_keys), jnp.inf, dtype=jnp.float32),
        zone=jnp.ones((1, n_zones), dtype=bool),
        ct=jnp.ones((1, n_ct), dtype=bool),
        ports=jnp.zeros((1, n_ports), dtype=bool),
        vol_used=jnp.zeros((1, n_drivers), dtype=jnp.int32),
        pod_count=jnp.zeros(1, dtype=jnp.int32),
        open_=jnp.zeros(1, dtype=bool),
    )


def empty_existing_static(
    n_res, n_classes, n_groups1: int = 1, n_drivers: int = 1
) -> ExistingStatic:
    return ExistingStatic(
        alloc=jnp.zeros((1, n_res), dtype=jnp.float32),
        init=jnp.zeros(1, dtype=bool),
        tol=jnp.zeros((n_classes, 1), dtype=bool),
        grp_node_member=jnp.zeros((n_groups1, 1), dtype=jnp.int32),
        grp_node_owner=jnp.zeros((n_groups1, 1), dtype=jnp.int32),
        node_capacity=jnp.zeros((1, n_res), dtype=jnp.float32),
        node_tmpl=jnp.zeros(1, dtype=jnp.int32),
        node_owned=jnp.zeros(1, dtype=bool),
        vol_limit=jnp.full((1, n_drivers), UNLIMITED, dtype=jnp.int32),
        cls_vol_add=jnp.zeros((n_classes, 1, n_drivers), dtype=jnp.int32),
        cls_vol_per_pod=jnp.zeros((n_classes, n_drivers), dtype=jnp.int32),
    )


_solve_jit = functools.partial(
    jax.jit,
    static_argnames=(
        "n_slots", "key_has_bounds", "n_passes", "emit_zonal_anti",
        "features", "fuse_zones", "packed_masks",
    ),
)(solve_core)


def warm_carry_of(outputs: SolveOutputs) -> Optional[WarmCarry]:
    """Package a solve's final carry for a later repair solve.  All leaves are
    (lazy) device arrays — holding a WarmCarry costs no transfer; None when
    the outputs predate the carry fields (hand-built in tests)."""
    if outputs.topo is None or outputs.remaining is None:
        return None
    return WarmCarry(
        state=outputs.state,
        ex_state=outputs.ex_state,
        topo=outputs.topo,
        remaining=outputs.remaining,
    )


def _repair_free_impl(
    warm_carry: WarmCarry,
    free_new: jnp.ndarray,
    free_ex: jnp.ndarray,
    cls_requests: jnp.ndarray,
    member: jnp.ndarray,
    own_inv: jnp.ndarray,
) -> WarmCarry:
    """Return evicted pods' capacity and topology counts to a warm carry.

    ``free_new`` i32[C, N] / ``free_ex`` i32[C, E] count the pods of class c
    evicted from each slot since the carry was produced; ``cls_requests``
    f32[C, R] is the per-pod request vector, ``member`` / ``own_inv``
    i32[C, G1] the class's topology-group membership and inverse-ownership
    rows (solver.incremental builds them host-side from the snapshot).

    Deliberately one-way: used capacity, pod counts, and group counts are
    returned, but merged requirement masks, zone/ct commitments, port claims,
    and volume counters are NOT reverted — a freed slot keeps every
    requirement its departed residents stamped on it.  That pessimism can
    only under-place (never corrupt), and it is exactly the accumulated
    optimality drift the fallback policy's periodic full-solve audit resets
    (docs/INCREMENTAL.md)."""
    wc = WarmCarry(*warm_carry)
    state = NodeState(*wc.state)
    ex = ExistingState(*wc.ex_state)
    topo = TopoCounts(*wc.topo)
    f_new = free_new.astype(jnp.float32)
    f_ex = free_ex.astype(jnp.float32)
    state = state._replace(
        used=state.used - jnp.einsum("cn,cr->nr", f_new, cls_requests),
        pod_count=jnp.maximum(state.pod_count - jnp.sum(free_new, axis=0), 0),
    )
    ex = ex._replace(
        used=ex.used - jnp.einsum("ce,cr->er", f_ex, cls_requests),
        pod_count=jnp.maximum(ex.pod_count - jnp.sum(free_ex, axis=0), 0),
    )
    topo = TopoCounts(
        fwd_ex=jnp.maximum(topo.fwd_ex - jnp.einsum("cg,ce->ge", member, free_ex), 0),
        inv_ex=jnp.maximum(topo.inv_ex - jnp.einsum("cg,ce->ge", own_inv, free_ex), 0),
        fwd_new=jnp.maximum(topo.fwd_new - jnp.einsum("cg,cn->gn", member, free_new), 0),
        inv_new=jnp.maximum(topo.inv_new - jnp.einsum("cg,cn->gn", own_inv, free_new), 0),
    )
    return WarmCarry(state=state, ex_state=ex, topo=topo, remaining=wc.remaining)


repair_free = jax.jit(_repair_free_impl)
# the pipelined loop's twin (utils.pipeline.donation_enabled): the input
# carry's device buffers are DONATED — steady-state churn frees evictions in
# place instead of reallocating the full-width planes every tick.  The caller
# contract matches the donated-read analysis rule (docs/ANALYSIS.md): the
# first positional argument must never be read after this call.
repair_free_donated = jax.jit(_repair_free_impl, donate_argnums=(0,))


@jax.jit
def gather_repair_window(warm_carry: WarmCarry, idx: jnp.ndarray, n_open_w):
    """Gather the repair's dirty slot window out of a full-width carry.

    ``idx`` i32[S] names the global new-node slots the bounded repair may
    touch — the freed-hole slots (in ascending order), any open filler, then
    the fresh tail starting at the carry's ``n_next`` — and ``n_open_w`` is
    how many of them are open.  Returns the windowed WarmCarry (per-slot
    NodeState planes and the new-side topology columns gathered; existing
    planes and the limit budget pass through whole) plus the
    ``(fwd_sing, fwd_full, inv_full)`` [G1, Z] zone-count contribution of
    every EXCLUDED open slot, which the windowed solve adds back as constants
    (RepairPlan).  The per-class-step cost of the repair then scales with the
    window, not the fleet (docs/INCREMENTAL.md)."""
    wc = WarmCarry(*warm_carry)
    state = NodeState(*wc.state)
    topo = TopoCounts(*wc.topo)
    n_slots = state.pod_count.shape[0]
    excl_open = jnp.ones(n_slots, dtype=bool).at[idx].set(False) & state.open_
    zone_i = state.zone.astype(jnp.int32) * excl_open.astype(jnp.int32)[:, None]
    sing = jnp.where(jnp.sum(zone_i, axis=-1, keepdims=True) == 1, zone_i, 0)
    base = (
        jnp.einsum("gn,nz->gz", topo.fwd_new, sing),
        jnp.einsum("gn,nz->gz", topo.fwd_new, zone_i),
        jnp.einsum("gn,nz->gz", topo.inv_new, zone_i),
    )
    w_state = NodeState(
        used=state.used[idx],
        kmask=state.kmask[idx],
        kdef=state.kdef[idx],
        kneg=state.kneg[idx],
        kgt=state.kgt[idx],
        klt=state.klt[idx],
        zone=state.zone[idx],
        ct=state.ct[idx],
        viable=state.viable[idx],
        ports=state.ports[idx],
        pod_count=state.pod_count[idx],
        tmpl_id=state.tmpl_id[idx],
        open_=state.open_[idx],
        n_next=jnp.asarray(n_open_w, dtype=jnp.int32),
    )
    w_topo = TopoCounts(
        fwd_ex=topo.fwd_ex,
        inv_ex=topo.inv_ex,
        fwd_new=topo.fwd_new[:, idx],
        inv_new=topo.inv_new[:, idx],
    )
    return (
        WarmCarry(state=w_state, ex_state=wc.ex_state, topo=w_topo,
                  remaining=wc.remaining),
        base,
    )


def _scatter_repair_window_impl(
    warm_carry: WarmCarry, window_carry: WarmCarry, idx: jnp.ndarray, n_open_w
) -> WarmCarry:
    """Write a windowed repair's final carry back over the full-width carry:
    per-slot planes scatter to their global slots, the existing-node state
    and limit budget are replaced whole (the repair is their only writer),
    and ``n_next`` advances by however many fresh slots the repair opened."""
    wc = WarmCarry(*warm_carry)
    ww = WarmCarry(*window_carry)
    gs = NodeState(*wc.state)
    ws = NodeState(*ww.state)
    gt = TopoCounts(*wc.topo)
    wt = TopoCounts(*ww.topo)
    state = NodeState(
        used=gs.used.at[idx].set(ws.used),
        kmask=gs.kmask.at[idx].set(ws.kmask),
        kdef=gs.kdef.at[idx].set(ws.kdef),
        kneg=gs.kneg.at[idx].set(ws.kneg),
        kgt=gs.kgt.at[idx].set(ws.kgt),
        klt=gs.klt.at[idx].set(ws.klt),
        zone=gs.zone.at[idx].set(ws.zone),
        ct=gs.ct.at[idx].set(ws.ct),
        viable=gs.viable.at[idx].set(ws.viable),
        ports=gs.ports.at[idx].set(ws.ports),
        pod_count=gs.pod_count.at[idx].set(ws.pod_count),
        tmpl_id=gs.tmpl_id.at[idx].set(ws.tmpl_id),
        open_=gs.open_.at[idx].set(ws.open_),
        n_next=gs.n_next + (ws.n_next - jnp.asarray(n_open_w, dtype=jnp.int32)),
    )
    topo = TopoCounts(
        fwd_ex=wt.fwd_ex,
        inv_ex=wt.inv_ex,
        fwd_new=gt.fwd_new.at[:, idx].set(wt.fwd_new),
        inv_new=gt.inv_new.at[:, idx].set(wt.inv_new),
    )
    return WarmCarry(state=state, ex_state=ww.ex_state, topo=topo,
                     remaining=ww.remaining)


scatter_repair_window = jax.jit(_scatter_repair_window_impl)
# donating twin (utils.pipeline): the FULL-WIDTH carry (first positional
# argument) is donated — the scatter writes the window back into the same
# device memory.  The window carry is NOT donated: its state planes are the
# repair outputs the (possibly still pending) decode reads.  Same caller
# contract as repair_free_donated: never read arg 0 after this call.
scatter_repair_window_donated = jax.jit(
    _scatter_repair_window_impl, donate_argnums=(0,)
)


@jax.jit
def pack_bool(arr: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., ceil(M/8)] bit-packed bools — device→host transfers ride a
    network tunnel under axon, so the big [N, I] planes ship packed (8×
    smaller) and unpack host-side with np.unpackbits."""
    m = arr.shape[-1]
    pad = (-m) % 8
    if pad:
        arr = jnp.pad(arr, [(0, 0)] * (arr.ndim - 1) + [(0, pad)])
    grouped = arr.reshape(arr.shape[:-1] + (-1, 8)).astype(jnp.uint8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)
    return jnp.sum(grouped * weights, axis=-1, dtype=jnp.uint8)


def unpack_bool(packed: np.ndarray, m: int) -> np.ndarray:
    """Host-side inverse of pack_bool."""
    bits = np.unpackbits(packed, axis=-1)
    return bits[..., :m].astype(bool)


def node_prices(state: NodeState, it_price: jnp.ndarray,
                catalog_axis: "Optional[str]" = None) -> jnp.ndarray:
    """f32[N]: min over (viable instance type, allowed zone, allowed ct) of
    offering price; +inf when no offering, 0 for closed slots.

    ``catalog_axis``: inside a shard_map body with the catalog sharded, the
    viable/price planes are local I-shards — the min finishes with an exact
    cross-shard ``pmin`` (parallel.mesh lane sweep)."""
    # price[i, z, ct] -> restrict to node's viable/zone/ct masks
    allowed = (
        state.viable[:, :, None, None]
        & state.zone[:, None, :, None]
        & state.ct[:, None, None, :]
    )
    priced = jnp.where(allowed, it_price[None, :, :, :], jnp.inf)
    best = jnp.min(priced, axis=(1, 2, 3))
    if catalog_axis is not None:
        best = jax.lax.pmin(best, catalog_axis)
    return jnp.where(state.open_ & (state.pod_count > 0), best, 0.0)


def snapshot_features(snapshot) -> SnapshotFeatures:
    """The snapshot's static phase plan, normalized.  Snapshots encoded before
    the features field existed (or built by hand in tests) degrade to the
    all-on plan, optionally narrowed by the legacy has_required_zonal_anti
    flag — widening is always sound (SnapshotFeatures docstring)."""
    f = getattr(snapshot, "features", None)
    if f is None:
        return ALL_FEATURES._replace(
            required_zone_anti=bool(getattr(snapshot, "has_required_zonal_anti", True))
        ).canonical()
    return SnapshotFeatures(*f).canonical()


def features_with_existing(snapshot, ex_static) -> SnapshotFeatures:
    """snapshot_features refined by the existing-node planes: the volume-limit
    family only binds when some node carries a finite CSI attach limit —
    encode_snapshot cannot see the node planes, so solve-time callers that
    have them (TPUSolver, the consolidation sweeps) refine the flag here."""
    f = snapshot_features(snapshot)
    if ex_static is not None and bool(
        np.any(np.asarray(ex_static.vol_limit) < UNLIMITED)
    ):
        f = f._replace(volume_limits=True)
    return f


def solve(snapshot: EncodedSnapshot, n_slots: int = 0,
          mesh_axes="auto") -> SolveOutputs:
    """Run the kernel on an encoded snapshot.  ``n_slots`` defaults to a
    rounded estimate; if slots run out (failed>0 with n_next==n_slots) the
    caller should retry with more (solver.tpu handles this).  ``mesh_axes``
    rides through to compilecache.run_solve: ``"auto"`` (default) follows
    KC_SOLVER_MESH onto the sharded dispatch path, ``None`` pins the
    single-device program (parity baselines)."""
    from karpenter_core_tpu import tracing
    from karpenter_core_tpu.utils import compilecache

    with tracing.span("prepare", classes=len(snapshot.classes)):
        if n_slots <= 0:
            n_slots = estimate_slots(snapshot)
        host_cls, host_statics, key_has_bounds = prepare_host(snapshot)
    return compilecache.run_solve(
        host_cls, host_statics, n_slots, key_has_bounds,
        n_passes=snapshot.scan_passes,
        features=snapshot_features(snapshot),
        mesh_axes=mesh_axes,
    )


def sync_outputs(outputs: SolveOutputs) -> SolveOutputs:
    """Block until the device solve behind ``outputs`` has finished.

    The solve/decode stage split: ``solve()`` returns lazily (device compute
    still in flight) and decode's batched fetch is normally the first sync
    point, so a naive ``t(solve) + t(decode)`` measurement fuses device
    compute into the decode number.  Callers that need the split — bench.py's
    ``solve_s``/``decode_s`` stage lines, and the upcoming decode pipelining
    work (overlap solve[k+1] with decode[k]) — call this between the two so
    device compute lands in the solve stage and decode measures only
    transfer + host expansion.  Production paths deliberately do NOT sync
    here: skipping it saves one relay round trip (~67 ms).  The barrier runs
    under the watchdog (utils/watchdog.py): a device that went quiet raises
    a bounded SolveTimeout instead of blocking forever."""
    from karpenter_core_tpu.utils import watchdog

    watchdog.run("solve.sync", jax.block_until_ready, outputs)
    return outputs


def prepare(snapshot: EncodedSnapshot):
    """Device-ready kernel inputs: (class_tensors, statics_arrays,
    key_has_bounds)."""
    cls, statics_arrays, key_has_bounds = prepare_host(snapshot)
    cls, statics_arrays = jax.device_put((cls, statics_arrays))
    return cls, statics_arrays, key_has_bounds


def prepare_host(snapshot: EncodedSnapshot):
    """Kernel input pytrees still on host (numpy) — same shapes/dtypes as
    prepare().  Callers that want to overlap the device upload with the
    (seconds-long, relay-bound) compile load pass these to
    compilecache.solve_callable and device_put on a separate thread."""
    cls = ClassTensors(
        mask=snapshot.cls_mask,
        defined=snapshot.cls_defined,
        negative=snapshot.cls_negative,
        gt=snapshot.cls_gt,
        lt=snapshot.cls_lt,
        zone=snapshot.cls_zone,
        ct=snapshot.cls_ct,
        it=snapshot.cls_it,
        requests=snapshot.cls_requests,
        count=snapshot.cls_count,
        tol=snapshot.cls_tol,
        ports=snapshot.cls_ports,
        groups=snapshot.cls_groups,
        relax_next=snapshot.cls_relax_next,
        anti_soft=snapshot.cls_anti_soft,
        root=snapshot.cls_root,
    )
    it_t = mask_ops.ReqTensor(
        snapshot.it_mask,
        snapshot.it_defined,
        snapshot.it_negative,
        snapshot.it_gt,
        snapshot.it_lt,
    )
    tmpl_t = mask_ops.ReqTensor(
        snapshot.tmpl_mask,
        snapshot.tmpl_defined,
        snapshot.tmpl_negative,
        snapshot.tmpl_gt,
        snapshot.tmpl_lt,
    )
    statics_arrays = StaticArrays(
        it=it_t,
        it_alloc=snapshot.it_alloc,
        it_avail=snapshot.it_avail,
        tmpl=tmpl_t,
        tmpl_zone=snapshot.tmpl_zone,
        tmpl_ct=snapshot.tmpl_ct,
        tmpl_it=snapshot.tmpl_it,
        tmpl_daemon=snapshot.tmpl_daemon,
        tmpl_limits0=snapshot.tmpl_limits,
        it_capacity=snapshot.it_capacity,
        valid=snapshot.valid,
        is_custom=snapshot.is_custom,
        vocab_ints=snapshot.vocab_ints,
        grp_skew=snapshot.grp_skew,
        grp_is_zone=snapshot.grp_is_zone,
        grp_is_anti=snapshot.grp_is_anti,
        grp_member=snapshot.grp_member,
    )
    key_has_bounds = tuple(
        bool(np.isfinite(snapshot.cls_gt[:, k]).any() or np.isfinite(snapshot.cls_lt[:, k]).any()
             or np.isfinite(snapshot.it_gt[:, k]).any() or np.isfinite(snapshot.it_lt[:, k]).any()
             or np.isfinite(snapshot.tmpl_gt[:, k]).any() or np.isfinite(snapshot.tmpl_lt[:, k]).any())
        for k in range(snapshot.valid.shape[0])
    )
    return cls, statics_arrays, key_has_bounds


def estimate_slots(snapshot: EncodedSnapshot) -> int:
    """Optimistic node-count estimate: per class, best pods-per-node over the
    catalog, plus slack for zone phases; rounded up to a power of two for
    compile-cache friendliness."""
    # zone-phase slack scales with the PADDED class count (the bucket the
    # executable is compiled for), not the actual one — otherwise a one-class
    # wobble in the pod mix moves the total across a power-of-two boundary
    # and recompiles an otherwise-identical program (VERDICT r2 #3)
    total = 16 + bucket(len(snapshot.classes)) * snapshot.cls_zone.shape[1]
    alloc = snapshot.it_alloc  # [I, R]
    for c, cls in enumerate(snapshot.classes):
        size = snapshot.cls_requests[c]  # [R]
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.floor(np.where(size > 0, alloc / np.maximum(size, 1e-9), np.inf))
        per_it = np.min(np.where(np.isfinite(per), per, np.inf), axis=-1)
        best = np.max(per_it) if per_it.size else 0
        host_cap = float(UNLIMITED)
        if cls.host_spread is not None:
            host_cap = float(cls.host_spread.skew)
        if cls.host_anti is not None:
            host_cap = 1.0
        best = max(1.0, min(best, host_cap))
        total += int(np.ceil(float(snapshot.cls_count[c]) / best))
    estimate = int(2 ** np.ceil(np.log2(max(total, 16))))
    # hysteresis at the shared derivation point so every caller (provisioning
    # solve, consolidation sweep, mesh studies) reuses covering executables
    from karpenter_core_tpu.utils import compilecache

    return compilecache.snap_slots(estimate)

# -- shape-bucket padding -----------------------------------------------------
#
# The compile cache keys on every input shape, so a one-class change in the
# pod mix (or one node joining the cluster) would recompile an identical
# program.  Steady-state reconciles instead pad the variable axes -- C classes,
# E existing nodes, G topology groups, P port pairs, K keys, V vocabulary
# values, D CSI drivers -- up to a bucket grid (powers of two and 1.5x powers
# of two, <=33% waste).  Padding is semantically invisible:
#
#   - padded classes have count=0: every phase is a lax.cond no-op and the
#     record step adds zero to all topology counts
#   - padded existing nodes are closed (open_=False): never eligible, never
#     seed counts
#   - padded groups clone the dummy "none" row (skew=UNLIMITED, no members);
#     the class sentinel index is remapped to the new last row
#   - padded keys are undefined on every side: Compatible/Intersects skip them
#   - padded value slots sit before the "unseen" slot with mask=False and
#     valid=False: no real value maps to them, no reduction counts them
#   - padded drivers have vol_limit=UNLIMITED and zero usage
#
# The reference has no analog (Go recompiles nothing); this is TPU operational
# parity, same motive as utils.compilecache.


def pad_catalog(cls, statics_arrays, multiple: int, it_price=None):
    """Pad the instance-type (I) axis of prepared host planes to a multiple of
    the mesh's catalog axis with INERT types: no availability, zero
    allocatable/capacity, excluded from every template and class mask, and
    (when a price sheet rides along) +inf price.  Padded columns can never be
    viable, so the padded solve is bit-identical to the unpadded one on the
    real columns — the shard_map dispatcher (parallel.mesh) requires the
    sharded axis to divide evenly.  Production snapshots are already encoded
    shard-aligned (models.snapshot.encode_snapshot ``catalog_pad_multiple``);
    this is the safety net for planes prepared outside that path.

    Returns (cls, statics_arrays[, it_price]) unchanged when the axis already
    divides."""
    sa = StaticArrays(*statics_arrays)
    i0 = np.asarray(sa.it_alloc).shape[0]
    i_new = -(-max(i0, 1) // max(multiple, 1)) * max(multiple, 1)
    if i_new == i0:
        return (cls, sa) if it_price is None else (cls, sa, it_price)
    it = sa.it
    it_p = mask_ops.ReqTensor(
        mask=_pad_axis(np.asarray(it.mask), 0, i_new, False),
        defined=_pad_axis(np.asarray(it.defined), 0, i_new, False),
        negative=_pad_axis(np.asarray(it.negative), 0, i_new, False),
        gt=_pad_axis(np.asarray(it.gt), 0, i_new, -np.inf),
        lt=_pad_axis(np.asarray(it.lt), 0, i_new, np.inf),
    )
    sa = sa._replace(
        it=it_p,
        it_alloc=_pad_axis(np.asarray(sa.it_alloc), 0, i_new, 0.0),
        it_avail=_pad_axis(np.asarray(sa.it_avail), 0, i_new, False),
        tmpl_it=_pad_axis(np.asarray(sa.tmpl_it), 1, i_new, False),
        it_capacity=_pad_axis(np.asarray(sa.it_capacity), 0, i_new, 0.0),
    )
    cls = cls._replace(it=_pad_axis(np.asarray(cls.it), 1, i_new, False))
    if it_price is None:
        return cls, sa
    return cls, sa, _pad_axis(np.asarray(it_price), 0, i_new, np.inf)


def bucket_quantize_enabled() -> bool:
    """KC_BUCKET_QUANTIZE: the opt-in coarser bucket ladder (docs/SERVICE.md
    "Solve fusion").  When set, :func:`bucket` skips the 1.5x rungs and pads
    straight up the powers of two — mixed-size tenants land in FEWER distinct
    shape buckets, so more of them share one coalesced executable and batch
    occupancy rises, at the cost of up to ~50% more padded rows per axis
    (the padded-FLOP vs executable-reuse trade ``bench.py fusion_line``
    measures).  Default off: unset (or "0") keeps the exact default grid,
    byte-identical planes and cache keys."""
    return os.environ.get("KC_BUCKET_QUANTIZE", "") not in ("", "0")


def bucket(n: int, floor: int = 8) -> int:
    """Smallest grid value >= max(n, floor); the grid is the powers of two
    and 1.5x powers of two starting at 2 (2, 3, 4, 6, 8, 12, ...).  Under
    ``KC_BUCKET_QUANTIZE`` (``bucket_quantize_enabled``) the 1.5x rungs drop
    out and the grid is the powers of two alone — a strict subset, so every
    quantized bucket is >= its default-grid value and the distinct-bucket
    count over any size mix can only shrink."""
    target = max(int(n), int(floor), 2)
    b = 2
    if bucket_quantize_enabled():
        while b < target:
            b <<= 1
        return b
    while b < target:
        b = b * 3 // 2 if (b & (b - 1)) == 0 else (b // 3) * 4
    return b


def _pad_axis(a: np.ndarray, axis: int, target: int, value) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= target:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - cur)
    return np.pad(a, widths, constant_values=value)


def _widen_mask(mask: np.ndarray, v_new: int) -> np.ndarray:
    """Insert always-False value slots before the trailing "unseen" slot."""
    v = mask.shape[-1] - 1
    if v >= v_new:
        return mask
    block = np.zeros(mask.shape[:-1] + (v_new - v,), dtype=mask.dtype)
    return np.concatenate([mask[..., :v], block, mask[..., v:]], axis=-1)


def _pad_req(t: mask_ops.ReqTensor, k_new: int, v_new: int) -> mask_ops.ReqTensor:
    """Pad a ReqTensor's K axis (undefined keys, mask=ones) and vocabulary
    width (False slots before "unseen")."""
    mask = _widen_mask(np.asarray(t.mask), v_new)
    mask = _pad_axis(mask, -2, k_new, True)
    return mask_ops.ReqTensor(
        mask=mask,
        defined=_pad_axis(np.asarray(t.defined), -1, k_new, False),
        negative=_pad_axis(np.asarray(t.negative), -1, k_new, False),
        gt=_pad_axis(np.asarray(t.gt), -1, k_new, -np.inf),
        lt=_pad_axis(np.asarray(t.lt), -1, k_new, np.inf),
    )


def pad_planes(cls, statics_arrays, key_has_bounds, ex_state=None, ex_static=None,
               device_finish=False):
    """Bucket-pad kernel inputs (host numpy pytrees from prepare_host /
    TPUSolver.encode_existing).  Returns (cls, statics_arrays, key_has_bounds,
    ex_state, ex_static) with stable shapes across nearby problem sizes.

    ``device_finish`` assembles the class-axis planes ON DEVICE under a small
    memoized jit (``finish_class_planes_device``): the host ships the compact
    class rows and the broadcast/scatter into the padded bucket happens
    device-side — bit-identical fills, smaller host→device transfer, no host
    np.pad over the class block (docs/KERNEL_PERF.md "Layer 6")."""
    sa = StaticArrays(*statics_arrays)

    c_old = cls.count.shape[0]
    k_old = sa.valid.shape[0]
    v_old = sa.valid.shape[1] - 1
    g1_old = sa.grp_skew.shape[0]
    p_old = cls.ports.shape[-1]

    c_new = bucket(c_old)
    k_new = bucket(k_old)
    v_new = bucket(v_old)
    g1_new = bucket(g1_old - 1, floor=4) + 1
    p_new = bucket(p_old, floor=4)

    if device_finish:
        cls = finish_class_planes_device(
            cls, c_new=c_new, k_new=k_new, v_new=v_new,
            g1_old=g1_old, g1_new=g1_new, p_new=p_new,
        )
    else:
        groups = np.asarray(cls.groups)
        groups = np.where(groups >= g1_old - 1, g1_new - 1, groups)
        cls_t = _pad_req(
            mask_ops.ReqTensor(cls.mask, cls.defined, cls.negative, cls.gt, cls.lt),
            k_new, v_new,
        )
        cls = ClassTensors(
            mask=_pad_axis(cls_t.mask, 0, c_new, True),
            defined=_pad_axis(cls_t.defined, 0, c_new, False),
            negative=_pad_axis(cls_t.negative, 0, c_new, False),
            gt=_pad_axis(cls_t.gt, 0, c_new, -np.inf),
            lt=_pad_axis(cls_t.lt, 0, c_new, np.inf),
            zone=_pad_axis(np.asarray(cls.zone), 0, c_new, True),
            ct=_pad_axis(np.asarray(cls.ct), 0, c_new, True),
            it=_pad_axis(np.asarray(cls.it), 0, c_new, True),
            requests=_pad_axis(np.asarray(cls.requests), 0, c_new, 0),
            count=_pad_axis(np.asarray(cls.count), 0, c_new, 0),
            tol=_pad_axis(np.asarray(cls.tol), 0, c_new, False),
            ports=_pad_axis(_pad_axis(np.asarray(cls.ports), -1, p_new, False), 0, c_new, False),
            groups=_pad_axis(groups, 0, c_new, g1_new - 1),
            relax_next=_pad_axis(np.asarray(cls.relax_next), 0, c_new, -1),
            anti_soft=_pad_axis(np.asarray(cls.anti_soft), 0, c_new, False),
            # padded rows never place (count 0), so any root value is inert
            root=_pad_axis(np.asarray(cls.root), 0, c_new, 0),
        )

    statics_arrays = sa._replace(
        it=_pad_req(sa.it, k_new, v_new),
        tmpl=_pad_req(sa.tmpl, k_new, v_new),
        valid=_pad_axis(_widen_mask(np.asarray(sa.valid), v_new), 0, k_new, False),
        is_custom=_pad_axis(np.asarray(sa.is_custom), 0, k_new, False),
        vocab_ints=_pad_axis(
            _pad_axis(np.asarray(sa.vocab_ints), -1, v_new, np.inf), 0, k_new, np.inf
        ),
        grp_skew=_pad_axis(np.asarray(sa.grp_skew), 0, g1_new, UNLIMITED),
        grp_is_zone=_pad_axis(np.asarray(sa.grp_is_zone), 0, g1_new, False),
        grp_is_anti=_pad_axis(np.asarray(sa.grp_is_anti), 0, g1_new, False),
        grp_member=_pad_axis(
            _pad_axis(np.asarray(sa.grp_member), -1, g1_new, False), 0, c_new, False
        ),
    )
    key_has_bounds = tuple(key_has_bounds) + (False,) * (k_new - k_old)

    if ex_state is not None:
        e_old = ex_state.pod_count.shape[0]
        d_old = ex_state.vol_used.shape[-1]
        # floor 8: node churn below eight existing nodes must not change the
        # plane shape (the bucket grid's 4->6->8 steps are too fine there)
        e_new = bucket(e_old, floor=8)
        d_new = bucket(d_old, floor=2)
        ex_req = _pad_req(
            mask_ops.ReqTensor(
                ex_state.kmask, ex_state.kdef, ex_state.kneg, ex_state.kgt, ex_state.klt
            ),
            k_new, v_new,
        )
        ex_state = ExistingState(
            used=_pad_axis(np.asarray(ex_state.used), 0, e_new, 0),
            kmask=_pad_axis(ex_req.mask, 0, e_new, True),
            kdef=_pad_axis(ex_req.defined, 0, e_new, False),
            kneg=_pad_axis(ex_req.negative, 0, e_new, False),
            kgt=_pad_axis(ex_req.gt, 0, e_new, -np.inf),
            klt=_pad_axis(ex_req.lt, 0, e_new, np.inf),
            zone=_pad_axis(np.asarray(ex_state.zone), 0, e_new, True),
            ct=_pad_axis(np.asarray(ex_state.ct), 0, e_new, True),
            ports=_pad_axis(_pad_axis(np.asarray(ex_state.ports), -1, p_new, False), 0, e_new, False),
            vol_used=_pad_axis(_pad_axis(np.asarray(ex_state.vol_used), -1, d_new, 0), 0, e_new, 0),
            pod_count=_pad_axis(np.asarray(ex_state.pod_count), 0, e_new, 0),
            open_=_pad_axis(np.asarray(ex_state.open_), 0, e_new, False),
        )
        ex_static = ExistingStatic(
            alloc=_pad_axis(np.asarray(ex_static.alloc), 0, e_new, 0),
            init=_pad_axis(np.asarray(ex_static.init), 0, e_new, False),
            tol=_pad_axis(_pad_axis(np.asarray(ex_static.tol), -1, e_new, False), 0, c_new, False),
            grp_node_member=_pad_axis(
                _pad_axis(np.asarray(ex_static.grp_node_member), -1, e_new, 0), 0, g1_new, 0
            ),
            grp_node_owner=_pad_axis(
                _pad_axis(np.asarray(ex_static.grp_node_owner), -1, e_new, 0), 0, g1_new, 0
            ),
            node_capacity=_pad_axis(np.asarray(ex_static.node_capacity), 0, e_new, 0),
            node_tmpl=_pad_axis(np.asarray(ex_static.node_tmpl), 0, e_new, 0),
            node_owned=_pad_axis(np.asarray(ex_static.node_owned), 0, e_new, False),
            vol_limit=_pad_axis(
                _pad_axis(np.asarray(ex_static.vol_limit), -1, d_new, UNLIMITED), 0, e_new, UNLIMITED
            ),
            cls_vol_add=_pad_axis(
                _pad_axis(
                    _pad_axis(np.asarray(ex_static.cls_vol_add), -1, d_new, 0), -2, e_new, 0
                ),
                0, c_new, 0,
            ),
            cls_vol_per_pod=_pad_axis(
                _pad_axis(np.asarray(ex_static.cls_vol_per_pod), -1, d_new, 0), 0, c_new, 0
            ),
        )
    return cls, statics_arrays, key_has_bounds, ex_state, ex_static


# -- device-side plane finishing (docs/KERNEL_PERF.md "Layer 6") --------------
#
# The encode's class planes are compact (C rows); the executable wants the
# bucket-padded layout.  With KC_ENCODE_DEVICE_FINISH=1 the pad/scatter runs
# ON DEVICE under a small memoized jit: the host→device transfer carries the
# exact class rows and the padded planes never exist host-side.  Fill values
# mirror pad_planes' host branch cell for cell, so the two finishing paths
# are bit-identical (tests/test_encode_delta.py pins it).


def encode_device_finish_enabled() -> bool:
    """KC_ENCODE_DEVICE_FINISH=1 opts the prepare path into device-side
    class-plane finishing (default off: on CPU backends the device IS the
    host, so the jit adds dispatch cost for no transfer win)."""
    return os.environ.get("KC_ENCODE_DEVICE_FINISH", "0") == "1"


def _jpad(a, axis, target, value):
    cur = a.shape[axis]
    if cur >= target:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - cur)
    return jnp.pad(a, widths, constant_values=value)


def _jwiden_mask(mask, v_new):
    v = mask.shape[-1] - 1
    if v >= v_new:
        return mask
    block = jnp.zeros(mask.shape[:-1] + (v_new - v,), dtype=mask.dtype)
    return jnp.concatenate([mask[..., :v], block, mask[..., v:]], axis=-1)


@functools.lru_cache(maxsize=64)
def _cls_finish_fn(c_new: int, k_new: int, v_new: int, g1_old: int,
                   g1_new: int, p_new: int):
    """One jitted finisher per (bucket-target, group-extent) combination —
    steady-state encodes reuse a single compiled program per shape bucket."""

    def finish(cls):
        mask = _jwiden_mask(cls.mask, v_new)
        mask = _jpad(mask, -2, k_new, True)
        groups = jnp.where(cls.groups >= g1_old - 1, g1_new - 1, cls.groups)
        return ClassTensors(
            mask=_jpad(mask, 0, c_new, True),
            defined=_jpad(_jpad(cls.defined, -1, k_new, False), 0, c_new, False),
            negative=_jpad(_jpad(cls.negative, -1, k_new, False), 0, c_new, False),
            gt=_jpad(_jpad(cls.gt, -1, k_new, -jnp.inf), 0, c_new, -jnp.inf),
            lt=_jpad(_jpad(cls.lt, -1, k_new, jnp.inf), 0, c_new, jnp.inf),
            zone=_jpad(cls.zone, 0, c_new, True),
            ct=_jpad(cls.ct, 0, c_new, True),
            it=_jpad(cls.it, 0, c_new, True),
            requests=_jpad(cls.requests, 0, c_new, 0),
            count=_jpad(cls.count, 0, c_new, 0),
            tol=_jpad(cls.tol, 0, c_new, False),
            ports=_jpad(_jpad(cls.ports, -1, p_new, False), 0, c_new, False),
            groups=_jpad(groups, 0, c_new, g1_new - 1),
            relax_next=_jpad(cls.relax_next, 0, c_new, -1),
            anti_soft=_jpad(cls.anti_soft, 0, c_new, False),
            # padded rows never place (count 0), so any root value is inert
            root=_jpad(cls.root, 0, c_new, 0),
        )

    return jax.jit(finish)


def finish_class_planes_device(cls, c_new: int, k_new: int, v_new: int,
                               g1_old: int, g1_new: int, p_new: int):
    """Padded ClassTensors assembled on device from the compact host rows —
    the device-finishing twin of pad_planes' host class branch."""
    return _cls_finish_fn(c_new, k_new, v_new, g1_old, g1_new, p_new)(cls)
