"""Operator entrypoint: ``python -m karpenter_core_tpu.cmd.operator``.

The runnable equivalent of the reference's controller binary
(/root/reference/cmd/controller — cloud providers compose the operator the
same way).  The cloud provider is a plug point: ``CLOUD_PROVIDER`` names a
``module:attr`` to import (a CloudProvider instance or zero-arg factory);
the default is the fake provider so the pair runs end-to-end out of the box.

Flags come from operator.options.Options (env-var equivalents included);
serving (metrics/probes/pprof) is always on for a deployed operator.
"""

from __future__ import annotations

import importlib
import logging
import os
import signal
import sys
import threading


def load_cloud_provider(spec: str):
    module_name, _, attr = spec.partition(":")
    obj = getattr(importlib.import_module(module_name), attr or "CloudProvider")
    return obj() if callable(obj) else obj


def main(argv=None) -> int:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from karpenter_core_tpu.operator.operator import Operator
    from karpenter_core_tpu.operator.options import Options

    options = Options.parse(argv)
    provider = load_cloud_provider(
        os.environ.get(
            "CLOUD_PROVIDER",
            "karpenter_core_tpu.cloudprovider.fake:FakeCloudProvider",
        )
    )
    operator = (
        Operator(
            cloud_provider=provider,
            options=options,
            serve_http=True,
            use_tpu_kernel=os.environ.get("KC_TPU_KERNEL", "1") == "1",
        )
        .with_controllers()
        .with_webhooks()
        .start()
    )
    logging.getLogger(__name__).info(
        "operator up: metrics :%d, probes :%d, leader-election %s, "
        "kube-backend %s%s",
        operator.http.metrics_port,
        operator.http.health_port,
        "on" if options.enable_leader_election else "off",
        options.kube_backend,
        f" ({options.kube_apiserver})" if options.kube_backend == "apiserver" else "",
    )

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    operator.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
