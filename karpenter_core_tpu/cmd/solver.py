"""TPU solver sidecar entrypoint: ``python -m karpenter_core_tpu.cmd.solver``.

Runs the gRPC snapshot channel (service.snapshot_channel) on the TPU host —
the second container of the deployed pair (BASELINE.json north-star split:
controller plane where it is, solves on the accelerator).  Persistent compile
caches make sidecar restarts cheap; the first request on a fresh machine pays
the one-time compile.

Env:
  KC_SOLVER_LISTEN    bind address (default 0.0.0.0:8980)
  CLOUD_PROVIDER      module:attr of the CloudProvider (default: fake)
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading

from karpenter_core_tpu.cmd.operator import load_cloud_provider


def main() -> int:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from karpenter_core_tpu.service.snapshot_channel import serve

    provider = load_cloud_provider(
        os.environ.get(
            "CLOUD_PROVIDER",
            "karpenter_core_tpu.cloudprovider.fake:FakeCloudProvider",
        )
    )
    address = os.environ.get("KC_SOLVER_LISTEN", "0.0.0.0:8980")
    server, port = serve(provider, address=address)
    logging.getLogger(__name__).info("tpu solver sidecar listening on :%d", port)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=5)
    return 0


if __name__ == "__main__":
    sys.exit(main())
