"""The relaxation kernel: one jit from encoded planes to rounded placements.

Formulation (docs/RELAX.md).  For every relax-eligible class c the decision
variable is a continuous mass vector x[c, i, z] >= 0 over (instance type,
zone) cells with sum_iz x[c, i, z] = count[c] — the class simplex scaled by
its pod count.  The support of each class's simplex is derived from the SAME
exact predicate planes the scan kernel commits with (ops/solve.py):
``mask_ops.compatible``/``add`` against every template, ``_it_intersects``
over the merged requirement tensor, ``_capacity`` for per-pod-per-node
intake, template zone/ct rectangles, and the ``it_avail`` offering sheet.
The linear cost of a cell is the policy objective score (ops/objective
vocabulary: ``cost_weight * price * (1 + risk_aversion * risk) -
throughput_weight * throughput``) of the cheapest allowed capacity type,
divided by the cell's per-node pod intake — i.e. the marginal per-pod price
of landing the class there.

The solve is projected gradient on ``min <cost, x> + mu/2 |x|^2`` with an
exact sort-based simplex projection (Held et al.; the Duchi et al. O(S log S)
form) per step.  The small strongly-convex term gives the iteration a 1/2
contraction factor at ``lr = 1/(2 mu)`` so convergence is geometric and the
iteration count small and data-independent.  After the loop a crossover step
snaps each class to the argmin-cost vertex of the unregularized linear
program (deterministic on plateaus via a rank epsilon) — the linear cost of
that vertex lower-bounds every feasible x, so crossover never loses fleet
cost, and it undoes the quadratic term's mass spreading before rounding.

Rounding is largest-fraction-first with a seeded tie permutation: floors are
kept, the per-class deficit is filled one pod per cell in (fraction desc,
seeded rank asc) order — fully deterministic given (x, seed), and identical
under any input sharding because sorts/cumsums are shape-, not
layout-, defined.  A vectorized audit then re-checks every rounded cell
against the exact predicate planes (independently re-gathered at the chosen
template) and zeroes violating cells — their pods join the leftover vector
the orchestrator (relax/solve.py) hands to the exact repair pass.

Everything below runs under ``_relax_jit`` (module-level, same idiom as
ops.solve._solve_jit); statics are ``n_slots``, ``key_has_bounds`` and
``packed_masks`` — exactly the compile-cache key fields they correspond to
in utils/compilecache.relax_callable.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu.ops import masks as mask_ops
from karpenter_core_tpu.ops import solve as solve_ops

# same plain-numpy BIG as ops/solve.py: a module-level jnp literal would
# initialize the backend at import time
BIG = np.float32(1e30)
_HALF_BIG = np.float32(5e29)
# quantization grid for the rounding pass's fraction ordering: fractions are
# compared as floor(frac * 2^20) so the order is exact-integer, not f32-ulp
_FRAC_Q = np.float32(2 ** 20)
# deterministic plateau-breaking epsilon, relative to the class cost scale
_RANK_EPS = np.float32(3e-3)
# curvature of the strongly-convex term, relative to cost scale / class mass.
# Two hard bounds pin this constant.  It cannot be tiny: each projection step
# computes ``x/2 - cost_eff/(2 mu)`` and the f32 cancellation noise of the
# threshold subtraction is ``eps_f32 / (2 _MU0)`` of the class mass — at 1e-6
# that is ~6% of m and the step delta never settles below any usable tol
# (observed as non-convergence at bench scale).  It also need not be small
# enough to concentrate plateau mass by itself: the crossover step below
# snaps each class to the argmin vertex of the UNregularized linear cost
# after the loop, so mu only has to keep the iteration contractive and the
# regularized optimum a faithful convergence witness.  1e-3 gives noise
# ~6e-5 * m per step, comfortably under the 1e-4 tol.
_MU0 = np.float32(1e-3)
# shave floors by one ppm before flooring so f32 simplex-projection error can
# never round a class ABOVE its count (sum(floor(x * (1-1e-6))) < count)
_FLOOR_SHAVE = np.float32(1.0 - 1e-6)


class RelaxResult(NamedTuple):
    """Device outputs of one ``relax_core`` run."""

    assign: jnp.ndarray  # i32[C, N] pods of class c materialized on slot n
    state: solve_ops.NodeState  # full-width slot planes (relax slots + cold tail)
    leftover: jnp.ndarray  # i32[C] pods the exact repair pass must place
    iters: jnp.ndarray  # i32[] projected-gradient iterations run
    converged: jnp.ndarray  # bool[] final step delta <= tol
    violations: jnp.ndarray  # i32[] rounded pods the exact audit rejected
    placed: jnp.ndarray  # i32[] pods materialized onto slots
    spilled: jnp.ndarray  # i32[] rounded pods that overflowed n_slots
    relaxed_cost: jnp.ndarray  # f32[] <cost, x> of the continuous optimum


def _simplex_project(y, support, m, jidx):
    """Euclidean projection of each row of ``y`` onto ``{x >= 0 on support,
    sum x = m}`` — sort-descending / cumulative-sum threshold form.  Rows with
    empty support (or m = 0) project to all-zeros."""
    yy = jnp.where(support, y, -BIG)
    ys = -jnp.sort(-yy, axis=1)  # descending
    css = jnp.cumsum(ys, axis=1)
    # ys_j - (css_j - m)/j > 0, multiplied through by j (> 0)
    cond = ys * jidx[None, :] > css - m[:, None]
    rho = jnp.clip(jnp.sum(cond.astype(jnp.int32), axis=1), 1, ys.shape[1])
    css_rho = jnp.take_along_axis(css, (rho - 1)[:, None], axis=1)[:, 0]
    theta = (css_rho - m) / rho.astype(jnp.float32)
    return jnp.where(support, jnp.maximum(y - theta[:, None], 0.0), 0.0)


def relax_core(
    class_tensors,
    statics_arrays,
    pol_price,
    pol_risk,
    pol_throughput,
    eligible,
    weights,
    max_iters,
    tol,
    seed,
    *,
    n_slots: int,
    key_has_bounds,
    packed_masks: bool = True,
) -> RelaxResult:
    """Relax, round, audit, and materialize one snapshot's eligible classes.

    Traced inputs: the padded ``ClassTensors`` / ``StaticArrays`` pytrees the
    scan kernel takes, the padded objective planes (f32[I, Z, CT] price/risk,
    f32[I] throughput), ``eligible`` bool[C] (host-gated: groupless,
    portless, ladderless classes — relax/solve.py), ``weights`` f32[3]
    (cost_weight, risk_aversion, throughput_weight), and the loop knobs
    (``max_iters`` i32, ``tol`` f32, ``seed`` u32 tie-order seed) — all
    runtime values so weight/knob changes never retrace."""
    sa = solve_ops.StaticArrays(*statics_arrays)
    width = sa.valid.shape[-1]  # semantic slot count V+1, pre-packing
    if packed_masks:
        sa = sa._replace(
            it=mask_ops.pack_req(sa.it),
            tmpl=mask_ops.pack_req(sa.tmpl),
            valid=mask_ops.pack_mask(sa.valid),
        )
        class_tensors = class_tensors._replace(
            mask=mask_ops.pack_mask(class_tensors.mask)
        )
    statics = solve_ops.Statics(
        *sa, key_has_bounds=key_has_bounds, packed=packed_masks, mask_v=width,
        catalog_axis=None,
    )
    cls = class_tensors
    n_classes = cls.count.shape[0]
    n_tmpl, n_zones = statics.tmpl_zone.shape
    n_it = statics.it_alloc.shape[0]
    n_ct = statics.tmpl_ct.shape[-1]
    n_keys = cls.defined.shape[-1]
    n_ports = cls.ports.shape[-1]
    n_cells = n_it * n_zones
    n_total = n_classes * n_cells

    counts = jnp.where(eligible, cls.count, 0).astype(jnp.int32)  # [C]

    # -- exact per-(class, template) predicate planes -------------------------
    def tmpl_planes(mask, defined, negative, gt, lt, requests, tol_row):
        cls_t = mask_ops.ReqTensor(
            mask[None], defined[None], negative[None], gt[None], lt[None]
        )
        key_ok = mask_ops.compatible(
            statics.tmpl, cls_t, statics.is_custom, statics.vocab_ints,
            v=statics.mask_v,
        )
        merged = mask_ops.add(
            statics.tmpl, cls_t, statics.valid, statics.vocab_ints,
            v=statics.mask_v, key_has_bounds=statics.key_has_bounds,
        )
        it_int = solve_ops._it_intersects(merged, statics)  # [T, I]
        per_pod = solve_ops._capacity(statics.tmpl_daemon, requests, statics)
        return key_ok & tol_row, merged, it_int, per_pod

    key_ok, merged, it_int, per_pod = jax.vmap(tmpl_planes)(
        cls.mask, cls.defined, cls.negative, cls.gt, cls.lt,
        cls.requests, cls.tol,
    )
    # key_ok bool[C,T]; merged ReqTensor[C,T,...]; it_int bool[C,T,I];
    # per_pod i32[C,T,I]

    t_zone = statics.tmpl_zone[None, :, :] & cls.zone[:, None, :]  # [C,T,Z]
    t_ct = statics.tmpl_ct[None, :, :] & cls.ct[:, None, :]  # [C,T,CT]
    base_ti = (
        statics.tmpl_it[None, :, :] & cls.it[:, None, :]
        & it_int & (per_pod >= 1) & key_ok[:, :, None]
    )  # [C,T,I]

    # -- objective: cheapest allowed capacity type per (c,t,i,z) --------------
    cw, ra, tw = weights[0], weights[1], weights[2]
    score = cw * pol_price * (1.0 + ra * pol_risk) - tw * pol_throughput[:, None, None]
    offer_priced = statics.it_avail & jnp.isfinite(pol_price)  # [I,Z,CT]
    score = jnp.where(offer_priced, score, BIG)
    best = jnp.full((n_classes, n_tmpl, n_it, n_zones), BIG, dtype=jnp.float32)
    for k in range(n_ct):  # CT is tiny and static: unrolled
        sc_k = jnp.where(t_ct[:, :, None, None, k], score[None, None, :, :, k], BIG)
        best = jnp.minimum(best, sc_k)
    feas = base_ti[:, :, :, None] & t_zone[:, :, None, :] & (best < _HALF_BIG)

    pp_f = jnp.clip(per_pod.astype(jnp.float32), 1.0, np.float32(1e6))
    unit = jnp.where(feas, best / pp_f[:, :, :, None], BIG)  # [C,T,I,Z]

    # reduce over templates: cheapest realization of each (c,i,z) cell.
    # argmin takes the FIRST minimum — deterministic template tie order.
    unit_ciz = jnp.min(unit, axis=1)  # [C,I,Z]
    tstar = jnp.argmin(unit, axis=1).astype(jnp.int32)  # [C,I,Z]
    feas_ciz = jnp.any(feas, axis=1)

    # -- projected gradient on the class simplices ----------------------------
    cost = unit_ciz.reshape(n_classes, n_cells)
    support = feas_ciz.reshape(n_classes, n_cells) & (counts > 0)[:, None]
    m = counts.astype(jnp.float32)
    # the class's cost magnitude — the epsilon/curvature yardstick.  NOT
    # ``+ 1``-floored: unit prices are tiny (price / pods-per-node), and an
    # epsilon scaled off an inflated yardstick would overwhelm genuine cost
    # gaps and pick cells by index instead of by price
    scale = jnp.maximum(
        jnp.max(jnp.where(support, jnp.abs(cost), 0.0), axis=1),
        np.float32(1e-20),
    )  # [C]
    cell_rank = jnp.arange(n_cells, dtype=jnp.float32) / np.float32(max(n_cells, 1))
    cost_eff = (
        jnp.where(support, cost, 0.0)
        + (_RANK_EPS * scale)[:, None] * cell_rank[None, :]
    )
    mu = (_MU0 * scale / jnp.maximum(m, 1.0))[:, None]  # [C,1]
    lr = 1.0 / (2.0 * mu)
    jidx = jnp.arange(1, n_cells + 1, dtype=jnp.float32)

    x0 = _simplex_project(
        jnp.zeros((n_classes, n_cells), dtype=jnp.float32), support, m, jidx
    )

    def cond_fn(carry):
        _, it, delta = carry
        return jnp.logical_and(it < max_iters, delta > tol)

    def body_fn(carry):
        x, it, _ = carry
        x1 = _simplex_project(x - lr * (cost_eff + mu * x), support, m, jidx)
        delta = jnp.max(jnp.abs(x1 - x) / jnp.maximum(m, 1.0)[:, None])
        return (x1, it + jnp.int32(1), delta)

    x, iters, delta = jax.lax.while_loop(
        cond_fn, body_fn,
        (x0, jnp.int32(0), jnp.asarray(np.inf, dtype=jnp.float32)),
    )
    converged = delta <= tol

    # -- crossover to a basic solution ----------------------------------------
    # The regularized optimum spreads each class over a ``mu * m``-wide cost
    # neighborhood of its best cell (that spread is what made the iteration
    # contractive).  The underlying LINEAR program is separable per class, so
    # its optimal vertex is the argmin-cost supported cell — move the whole
    # class there.  ``cost_eff`` keeps the argmin deterministic on plateaus
    # (rank epsilon), and the linear cost of the vertex is <= the linear cost
    # of ANY feasible x, so crossover never loses fleet cost; it only undoes
    # the quadratic term's spreading before rounding (spread mass rounds into
    # partially-filled nodes).  Standard LP-relaxation practice: solve the
    # smoothed program for a convergence certificate, cross over to a vertex.
    jstar = jnp.argmin(jnp.where(support, cost_eff, BIG), axis=1)  # i32[C]
    onehot = (
        jnp.arange(n_cells, dtype=jnp.int32)[None, :] == jstar[:, None]
    ).astype(jnp.float32)
    x = jnp.where(
        support.any(axis=1)[:, None], m[:, None] * onehot * support, x
    )
    relaxed_cost = jnp.sum(jnp.where(support, cost * x, 0.0))

    # -- deterministic rounding: floors + largest-fraction-first --------------
    x_r = x * _FLOOR_SHAVE
    n0f = jnp.floor(x_r)
    frac = x_r - n0f
    n0 = n0f.astype(jnp.int32)
    deficit = jnp.clip(counts - jnp.sum(n0, axis=1), 0, None)  # i32[C]
    fq = jnp.floor(frac * _FRAC_Q).astype(jnp.int32)
    fq = jnp.where(support, fq, jnp.int32(-1))  # off-support sorts last
    perm = jax.random.permutation(
        jax.random.PRNGKey(seed.astype(jnp.uint32)), n_cells
    ).astype(jnp.int32)
    # stable two-key sort: permute columns into the seeded tie order, then a
    # stable descending-fraction argsort — ties resolve in seeded-rank order
    fq_p = jnp.take(fq, perm, axis=1)  # [C,S]
    ordb = jnp.argsort(-fq_p, axis=1)  # stable
    cells_sorted = jnp.take(perm, ordb)  # [C,S] cell index at each take rank
    take_sorted = (
        jnp.arange(n_cells, dtype=jnp.int32)[None, :] < deficit[:, None]
    ).astype(jnp.int32)
    add = jnp.zeros_like(n0).at[
        jnp.arange(n_classes, dtype=jnp.int32)[:, None], cells_sorted
    ].add(take_sorted)
    n_round = (n0 + add) * support.astype(jnp.int32)  # i32[C,S]

    # -- exact feasibility audit at the chosen template -----------------------
    # independently recombine the EXACT predicate planes (offering existence
    # from it_avail, not the priced objective sheet) and re-gather at tstar:
    # a placement survives only if the scan kernel's own predicates admit it
    offer_exact = (
        jnp.einsum(
            "ctk,izk->ctiz",
            t_ct.astype(jnp.bfloat16),
            statics.it_avail.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0.5
    )  # [C,T,I,Z]
    audit_plane = base_ti[:, :, :, None] & t_zone[:, :, None, :] & offer_exact
    tsel = tstar.reshape(n_classes, n_cells)
    audit_at = jnp.take_along_axis(
        audit_plane.reshape(n_classes, n_tmpl, n_cells),
        tsel[:, None, :], axis=1,
    )[:, 0]  # [C,S]
    viol = (n_round > 0) & ~audit_at
    violations = jnp.sum(jnp.where(viol, n_round, 0))
    n_ok = jnp.where(viol, 0, n_round)

    # -- materialize: cells -> node slots -------------------------------------
    pp_cell = jnp.take_along_axis(
        jnp.broadcast_to(
            per_pod[:, :, :, None], (n_classes, n_tmpl, n_it, n_zones)
        ).reshape(n_classes, n_tmpl, n_cells),
        tsel[:, None, :], axis=1,
    )[:, 0]  # i32[C,S]
    ppg = jnp.clip(pp_cell.reshape(n_total), 1, np.int32(10 ** 6))
    # materialize only the pods that fill WHOLE nodes at their cell's
    # per-node intake.  The sub-node tail of each class joins ``leftover``
    # and rides the exact repair pass instead, where the scan kernel can
    # bin-pack the tails of DIFFERENT classes onto shared nodes — a
    # per-class materializer cannot co-locate, and a partially-filled node
    # per class is exactly the fleet-cost gap vs the greedy scan.
    ncell = (n_ok.reshape(n_total) // ppg) * ppg
    nodes_g = ncell // ppg
    cum = jnp.cumsum(nodes_g)
    offs = cum - nodes_g
    total_nodes = jnp.sum(nodes_g)
    used_slots = jnp.minimum(total_nodes, n_slots).astype(jnp.int32)
    avail_nodes = jnp.clip(n_slots - offs, 0, nodes_g)
    placed_g = jnp.minimum(ncell, avail_nodes * ppg)
    placed_c = jnp.sum(placed_g.reshape(n_classes, n_cells), axis=1)
    leftover = jnp.maximum(cls.count - placed_c, 0).astype(jnp.int32)
    spilled = jnp.sum(ncell) - jnp.sum(placed_g)

    slots = jnp.arange(n_slots, dtype=jnp.int32)
    gid = jnp.searchsorted(cum, slots, side="right").astype(jnp.int32)
    sel = slots < used_slots
    gidc = jnp.clip(gid, 0, n_total - 1)
    rank = slots - offs[gidc]
    a = jnp.where(
        sel, jnp.clip(ncell[gidc] - rank * ppg[gidc], 0, ppg[gidc]), 0
    ).astype(jnp.int32)
    c_s = gidc // n_cells
    s_s = gidc - c_s * n_cells
    i_s = s_s // n_zones
    z_s = s_s - i_s * n_zones
    t_s = tsel.reshape(n_total)[gidc]

    km = merged.mask[c_s, t_s]  # [N, K, W] (or [N, K, V+1] unpacked)
    kd = merged.defined[c_s, t_s]
    kn = merged.negative[c_s, t_s]
    kg = merged.gt[c_s, t_s]
    kl = merged.lt[c_s, t_s]
    zone_hot = jnp.arange(n_zones, dtype=jnp.int32)[None, :] == z_s[:, None]
    ct_row = t_ct[c_s, t_s]  # [N, CT]
    feas_row = feas[c_s, t_s]  # [N, I, Z]
    feas_z = jnp.take_along_axis(feas_row, z_s[:, None, None], axis=2)[:, :, 0]
    pp_row = per_pod[c_s, t_s]  # [N, I]
    viable_row = feas_z & (pp_row >= a[:, None])
    used_row = statics.tmpl_daemon[t_s] + a[:, None].astype(jnp.float32) * cls.requests[c_s]

    if packed_masks:
        kmask0 = jnp.broadcast_to(
            jnp.asarray(mask_ops.full_words(width)),
            (n_slots, n_keys, mask_ops.words_for(width)),
        )
    else:
        kmask0 = jnp.ones((n_slots, n_keys, width), dtype=bool)
    state = solve_ops.NodeState(
        used=jnp.where(sel[:, None], used_row, 0.0),
        kmask=jnp.where(sel[:, None, None], km, kmask0),
        kdef=jnp.where(sel[:, None], kd, False),
        kneg=jnp.where(sel[:, None], kn, False),
        kgt=jnp.where(sel[:, None], kg, -jnp.inf).astype(jnp.float32),
        klt=jnp.where(sel[:, None], kl, jnp.inf).astype(jnp.float32),
        zone=jnp.where(sel[:, None], zone_hot, True),
        ct=jnp.where(sel[:, None], ct_row, True),
        viable=jnp.where(sel[:, None], viable_row, True),
        ports=jnp.zeros((n_slots, n_ports), dtype=bool),
        pod_count=a,
        tmpl_id=jnp.where(sel, t_s, 0).astype(jnp.int32),
        open_=sel & (a > 0),
        n_next=used_slots,
    )
    assign = jnp.where(
        (jnp.arange(n_classes, dtype=jnp.int32)[:, None] == c_s[None, :])
        & sel[None, :],
        a[None, :],
        0,
    ).astype(jnp.int32)

    return RelaxResult(
        assign=assign,
        state=state,
        leftover=leftover,
        iters=iters,
        converged=converged,
        violations=violations.astype(jnp.int32),
        placed=jnp.sum(placed_g).astype(jnp.int32),
        spilled=spilled.astype(jnp.int32),
        relaxed_cost=relaxed_cost,
    )


_relax_jit = functools.partial(
    jax.jit,
    static_argnames=("n_slots", "key_has_bounds", "packed_masks"),
)(relax_core)
