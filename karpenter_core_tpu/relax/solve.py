"""The relax family's host orchestrator: gate, dispatch, audit-repair, merge.

``run_relax`` is the cold-solve twin of ``TPUSolver.run_prepared``'s scan
dispatch (which calls it when solver/modes.py routes a batch here).  The
contract with the caller is all-or-nothing per batch:

  1. HOST GATES — constraint families the relaxation does not model raise
     ``RelaxFallback`` immediately (the scan runs instead, and the reason
     rides the ``solve.mode`` span + relax-fallback counter): no objective
     planes on the prep, existing-node planes, finite provisioner limits, or
     no relax-eligible class at all.  Per-CLASS gates are softer: a class
     with topology groups, host ports, a preference ladder, or soft-anti
     terms is simply not eligible — its pods skip the relaxation and go to
     the exact repair pass with every constraint enforced.
  2. KERNEL — one ``relax_core`` jit (relax/kernel.py) served through
     ``utils.compilecache.relax_callable`` and deadline-bounded by
     ``utils.watchdog`` like every other solve variant; inputs upload with
     the prep's captured mesh shardings so the catalog axis stays sharded
     (parallel/mesh.py partition rules).
  3. VERDICT — non-convergence or a fully-audited-away result raises
     ``RelaxFallback`` (nothing was committed; the scan re-solves from
     scratch).
  4. EXACT REPAIR — leftover pods (ineligible classes, audited-out cells,
     slot spill) run through the existing warm-start repair machinery over
     the relax result's carry: a bounded window when it fits
     (``ops.solve.gather/scatter_repair_window``), the full width otherwise.
     The repair is the exact scan — so every pod the relaxation could not
     place correctly is placed by the kernel that can, or reported failed.

The merged ``SolveOutputs`` is full-width and scan-shaped: decode, the
policy objective stage, and the incremental session's ``warm_carry_of``
anchor all consume it unchanged.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_core_tpu import tracing
from karpenter_core_tpu.ops import masks as mask_ops
from karpenter_core_tpu.ops import solve as solve_ops

log = logging.getLogger(__name__)

# projected-gradient convergence tolerance (max per-class normalized step);
# the iteration halves the step every round, so the default iteration cap
# (solver.modes.relax_max_iters) clears this with a wide margin
RELAX_TOL = np.float32(1e-4)
# deterministic rounding tie-order seed: a constant, so the same snapshot
# rounds identically across processes, replicas, and mesh topologies
RELAX_SEED = 0


class RelaxFallback(Exception):
    """The relax family declines this batch; the scan must run it.

    ``reason`` is the structured label surfaced on the ``solve.mode`` span
    and carried by ``karpenter_solve_mode_total{mode="relax-fallback"}``:
    no-planes | existing-nodes | template-limits | no-eligible-classes |
    non-convergence | no-placements."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


def eligible_classes(prep, cls=None) -> np.ndarray:
    """bool[C]: classes the relaxation models EXACTLY (docs/RELAX.md).

    A class qualifies when its constraints are all cell-local — requirement
    masks, zone/ct/instance-type rectangles, per-pod resources — i.e. it owns
    no topology group, is a member of none, binds no host ports, sits on no
    preference ladder, and carries no soft-anti terms.  Everything else keeps
    full pod counts in ``leftover`` and routes to the exact repair."""
    if cls is None:
        cls = prep.cls
    sa = solve_ops.StaticArrays(*prep.statics_arrays)
    g1 = int(np.asarray(sa.grp_skew).shape[0])
    groups = np.asarray(cls.groups)
    member = np.asarray(sa.grp_member)
    idx = np.arange(groups.shape[0], dtype=np.int64)
    return (
        np.all(groups == g1 - 1, axis=1)
        & ~member[:, : max(g1 - 1, 0)].any(axis=1)
        & ~np.asarray(cls.ports).any(axis=1)
        & (np.asarray(cls.relax_next) < 0)
        & (np.asarray(cls.root) == idx)
        & ~np.asarray(cls.anti_soft).any(axis=1)
    )


def _policy_weights(policy) -> np.ndarray:
    """f32[3] (cost_weight, risk_aversion, throughput_weight).  With policy
    off the objective degrades to the raw price sheet — the planes exist on
    every encode (policy.planes.attach_planes), so relax can always price."""
    if policy is not None and getattr(policy, "enabled", False):
        return np.asarray(
            [
                float(getattr(policy, "cost_weight", 1.0)),
                float(getattr(policy, "risk_aversion", 0.0)),
                float(getattr(policy, "throughput_weight", 0.0)),
            ],
            dtype=np.float32,
        )
    return np.asarray([1.0, 0.0, 0.0], dtype=np.float32)


def _empty_carry_planes(prep, cls, n_slots: int, packed: bool):
    """(ex_state, topo, remaining) for a cold relax result — the same inert
    planes solve_core builds internally for a cold scan with no existing
    nodes, so the repair resumes over semantics identical by construction."""
    sa = solve_ops.StaticArrays(*prep.statics_arrays)
    n_res = int(np.asarray(sa.it_alloc).shape[-1])
    n_keys = int(np.asarray(sa.valid).shape[0])
    width = int(np.asarray(sa.valid).shape[-1])
    g1 = int(np.asarray(sa.grp_skew).shape[0])
    n_zones = int(np.asarray(cls.zone).shape[-1])
    n_ct = int(np.asarray(cls.ct).shape[-1])
    n_ports = int(np.asarray(cls.ports).shape[-1])
    ex_state = solve_ops.empty_existing_state(
        n_res, n_keys, width, n_zones, n_ct, n_ports
    )
    if packed:
        ex_state = ex_state._replace(kmask=mask_ops.pack_mask(ex_state.kmask))
    topo = solve_ops.TopoCounts(
        fwd_ex=jnp.zeros((g1, 1), dtype=jnp.int32),
        inv_ex=jnp.zeros((g1, 1), dtype=jnp.int32),
        fwd_new=jnp.zeros((g1, n_slots), dtype=jnp.int32),
        inv_new=jnp.zeros((g1, n_slots), dtype=jnp.int32),
    )
    remaining = jnp.asarray(np.asarray(sa.tmpl_limits0, dtype=np.float32))
    return ex_state, topo, remaining


def _zero_repair_plan(n_classes: int, n_slots_w: int, g1: int, n_zones: int,
                      base=None) -> solve_ops.RepairPlan:
    """A no-preference RepairPlan (pure additions); ``base`` carries the
    out-of-window topology planes from ``gather_repair_window`` when the
    repair is bounded."""
    if base is None:
        zeros_gz = jnp.zeros((g1, n_zones), dtype=jnp.int32)
        base = (zeros_gz, zeros_gz, zeros_gz)
    return solve_ops.RepairPlan(
        pref_new=jnp.zeros((n_classes, n_slots_w), dtype=jnp.int32),
        pref_ex=jnp.zeros((n_classes, 1), dtype=jnp.int32),
        base_fwd_sing=base[0],
        base_fwd_full=base[1],
        base_inv_full=base[2],
    )


def run_relax(solver, prep, cls=None, n_slots: int = 0) -> solve_ops.SolveOutputs:
    """Run one cold solve through the relax family (module docstring).

    ``solver`` is the TPUSolver (policy weights + the repair dispatch);
    ``prep`` a cold SolvePrep (no existing planes, no warm carry); ``cls``
    optionally overrides the prep's class tensors (run_prepared's ``count``
    merge).  Returns full-width scan-shaped SolveOutputs or raises
    ``RelaxFallback``."""
    from karpenter_core_tpu.solver import modes
    from karpenter_core_tpu.utils import compilecache, watchdog

    if cls is None:
        cls = prep.cls
    pol = getattr(prep, "pol", None)
    if pol is None:
        raise RelaxFallback("no-planes")
    if prep.ex_state is not None:
        raise RelaxFallback("existing-nodes")
    sa_host = solve_ops.StaticArrays(*prep.statics_arrays)
    if bool(np.isfinite(np.asarray(sa_host.tmpl_limits0)).any()):
        raise RelaxFallback("template-limits")
    counts = np.asarray(cls.count, dtype=np.int64)
    eligible = eligible_classes(prep, cls)
    if not bool(np.any(eligible & (counts > 0))):
        raise RelaxFallback("no-eligible-classes")

    n_slots = int(n_slots or prep.n_slots)
    n_classes = int(counts.shape[0])
    _, packed = compilecache.kernel_flags()
    mesh_axes = getattr(prep, "mesh_axes", None)
    max_iters = modes.relax_max_iters()

    fn = compilecache.relax_callable(
        cls, prep.statics_arrays, pol, n_slots, prep.key_has_bounds,
        packed_masks=packed, mesh_axes=mesh_axes,
    )
    trees = (cls, prep.statics_arrays, pol)
    if mesh_axes is not None:
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        trees = jax.device_put(
            trees, mesh_mod.mesh_shardings(trees, mesh_mod.mesh_for(mesh_axes))
        )
    else:
        trees = jax.device_put(trees)
    cls_d, sa_d, pol_d = trees

    with tracing.span(
        "relax.solve", n_slots=n_slots, classes=n_classes,
        mesh=repr(mesh_axes) if mesh_axes else None,
    ) as sp:
        res = watchdog.run(
            "solve.relax", fn,
            cls_d, sa_d, pol_d.price, pol_d.risk, pol_d.throughput,
            jnp.asarray(eligible), jnp.asarray(_policy_weights(solver.policy)),
            jnp.int32(max_iters), jnp.float32(RELAX_TOL),
            jnp.uint32(RELAX_SEED),
            key=(n_slots, packed, mesh_axes),
        )
        iters, converged, violations, leftover, placed, n_used = watchdog.run(
            "solve.sync", jax.device_get,
            (res.iters, res.converged, res.violations, res.leftover,
             res.placed, res.state.n_next),
            key="relax",
        )
        sp.set(
            iters=int(iters), converged=bool(converged),
            violations=int(violations), placed=int(placed),
            leftover=int(np.sum(leftover)),
        )
        # bench/test observability: the last relax dispatch's verdict, host
        # data only (mirrors the span attrs — bench.relax_line reports the
        # audited-violation count from here)
        solver.last_relax_stats = {
            "iters": int(iters),
            "converged": bool(converged),
            "rounded_violations": int(violations),
            "placed": int(placed),
            "leftover": int(np.sum(leftover)),
        }
    if not bool(converged):
        raise RelaxFallback("non-convergence")
    if int(placed) == 0 and int(np.sum(counts)) > 0:
        raise RelaxFallback("no-placements")

    leftover = np.asarray(leftover, dtype=np.int32)
    total_leftover = int(np.sum(leftover))
    ex_state, topo, remaining = _empty_carry_planes(prep, cls, n_slots, packed)
    g1 = int(topo.fwd_ex.shape[0])
    n_zones = int(np.asarray(cls.zone).shape[-1])

    if total_leftover == 0:
        return solve_ops.SolveOutputs(
            assign=res.assign,
            assign_existing=jnp.zeros((n_classes, 1), dtype=jnp.int32),
            failed=jnp.zeros((n_classes,), dtype=jnp.int32),
            state=res.state,
            ex_state=ex_state,
            spread_suspect=jnp.zeros((n_classes,), dtype=bool),
            topo=topo,
            remaining=remaining,
        )

    # -- exact repair over the relax carry ------------------------------------
    carry = solve_ops.WarmCarry(
        state=res.state, ex_state=ex_state, topo=topo, remaining=remaining
    )
    n_used = int(n_used)
    # bounded window when it fits: the relax-open slots (all open slots are
    # the contiguous prefix [0, n_used)) plus a fresh tail sized for the
    # leftover — contiguous, so idx is a plain prefix range
    window_w = solve_ops.bucket(min(n_used + max(total_leftover, 16), n_slots))
    repaired = None
    if window_w < n_slots:
        idx = jnp.arange(window_w, dtype=jnp.int32)
        win_carry, base = solve_ops.gather_repair_window(carry, idx, n_used)
        plan = _zero_repair_plan(n_classes, window_w, g1, n_zones, base=base)
        rep = solver.run_prepared(
            prep, count=leftover, warm_carry=win_carry, repair_plan=plan,
            n_slots=window_w, donate_carry=False,
        )
        ticket = solver.begin_fetch(rep)
        fetched = ticket.wait()
        if solver.fetch_exhausted(fetched, window_w):
            log.debug(
                "relax repair window %d exhausted; retrying full-width",
                window_w,
            )
        else:
            merged = solve_ops.scatter_repair_window(carry, solve_ops.warm_carry_of(rep), idx, n_used)
            assign = res.assign + jnp.zeros(
                (n_classes, n_slots), dtype=jnp.int32
            ).at[:, idx].set(rep.assign)
            repaired = (rep, merged, assign)
    if repaired is None:
        plan = _zero_repair_plan(n_classes, n_slots, g1, n_zones)
        rep = solver.run_prepared(
            prep, count=leftover, warm_carry=carry, repair_plan=plan,
            n_slots=n_slots, donate_carry=False,
        )
        merged = solve_ops.warm_carry_of(rep)
        repaired = (rep, merged, res.assign + rep.assign)
    rep, merged, assign = repaired
    return solve_ops.SolveOutputs(
        assign=assign,
        assign_existing=rep.assign_existing,
        failed=rep.failed,
        state=merged.state,
        ex_state=merged.ex_state,
        spread_suspect=rep.spread_suspect,
        topo=merged.topo,
        remaining=merged.remaining,
    )
