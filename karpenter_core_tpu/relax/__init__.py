"""The relaxation solver family (``KC_SOLVER_MODE=relax``, docs/RELAX.md).

A second solver family next to the exact greedy-by-priority scan kernel
(ops/solve.py): pod-class -> (instance type, zone, capacity type) placement
formulated as a continuous relaxation over the SAME encoded planes the scan
consumes — decision tensor x[C, I, Z] with class counts as simplex
constraints, the packed-mask / capacity / offering predicates as the
support, and the policy objective planes (policy/planes.py) as the linear
cost — solved by a projected-gradient loop inside one pure-jnp
``lax.while_loop`` jit (relax/kernel.py), rounded deterministically
(largest fraction first, seeded tie order), audited against the exact
predicate planes, and repaired by the existing warm-start scan machinery
(relax/solve.py).  Approximate in cost, never wrong in placement.
"""

from karpenter_core_tpu.relax.kernel import RelaxResult, relax_core
from karpenter_core_tpu.relax.solve import RelaxFallback, run_relax

__all__ = ["RelaxResult", "RelaxFallback", "relax_core", "run_relax"]
