"""Event recorder with dedupe + rate limiting.

Mirror of /root/reference/pkg/events/recorder.go:44-79: events identical in
(involved object, reason, message) are deduped within a 2-minute window, and
event types may carry their own token-bucket rate limiter.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

DEDUPE_TTL_SECONDS = 120.0


@dataclass
class Event:
    involved_object: object
    type: str  # Normal | Warning
    reason: str
    message: str
    dedupe_values: List[str] = field(default_factory=list)
    # events per second allowed for this reason; None = unlimited
    rate_limit_qps: Optional[float] = None

    def dedupe_key(self) -> tuple:
        if self.dedupe_values:
            return (self.reason, *self.dedupe_values)
        obj = self.involved_object
        meta = getattr(obj, "metadata", None)
        name = getattr(meta, "name", str(obj))
        namespace = getattr(meta, "namespace", "")
        return (self.type, self.reason, namespace, name, self.message)


class _TokenBucket:
    def __init__(self, qps: float, burst: int = 10, clock: Callable[[], float] = time.monotonic):
        self.qps = qps
        self.burst = burst
        self.tokens = float(burst)
        self.last = clock()
        self.clock = clock

    def allow(self) -> bool:
        now = self.clock()
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.qps)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class Recorder:
    """Sink is any callable taking an Event; the operator wires this to logging
    and the controllers' test harnesses capture it directly."""

    def __init__(self, sink: Optional[Callable[[Event], None]] = None, clock=time.monotonic):
        self.sink = sink
        self.clock = clock
        self._seen: Dict[tuple, float] = {}
        self._limiters: Dict[str, _TokenBucket] = {}
        self.events: List[Event] = []
        self._lock = threading.Lock()

    # retain at most this many events for test inspection; older are dropped
    MAX_RETAINED_EVENTS = 10_000

    def publish(self, event: Event) -> None:
        # publishers are concurrent (launch_machines fans out over a thread
        # pool): the dedupe map, limiter registry, and retained-event list
        # mutate under one lock — the 100k sharded soak's launch storms
        # crashed the unlocked sweep with "dictionary changed size during
        # iteration".  The sink call stays OUTSIDE the lock (it is arbitrary
        # user code and may publish re-entrantly).
        key = event.dedupe_key()
        now = self.clock()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and now - last < DEDUPE_TTL_SECONDS:
                return
            if event.rate_limit_qps is not None:
                limiter = self._limiters.setdefault(
                    event.reason,
                    _TokenBucket(event.rate_limit_qps, clock=self.clock),
                )
                if not limiter.allow():
                    return
            self._seen[key] = now
            self._expire(now)
            self.events.append(event)
            if len(self.events) > self.MAX_RETAINED_EVENTS:
                del self.events[: len(self.events) - self.MAX_RETAINED_EVENTS]
        if self.sink is not None:
            self.sink(event)

    def _expire(self, now: float) -> None:
        """Evict dedupe entries past the TTL (the reference uses a 120s TTL
        cache with a janitor; we sweep opportunistically on publish).
        Caller holds ``_lock``."""
        if len(self._seen) < 1024:
            return
        expired = [k for k, ts in self._seen.items() if now - ts >= DEDUPE_TTL_SECONDS]
        for k in expired:
            del self._seen[k]

    def reset(self) -> None:
        with self._lock:
            self.events.clear()
            self._seen.clear()
