"""Typed event constructors (mirror of /root/reference/pkg/events/events.go:27-75)."""

from __future__ import annotations

from karpenter_core_tpu.apis.objects import Node, Pod
from karpenter_core_tpu.events.recorder import Event


def nominate_pod(pod: Pod, node: Node) -> Event:
    return Event(
        involved_object=pod,
        type="Normal",
        reason="Nominated",
        message=(
            f"Pod should schedule on node {node.name}"
        ),
        dedupe_values=[pod.namespace, pod.name, node.name],
    )


def evict_pod(pod: Pod) -> Event:
    return Event(
        involved_object=pod,
        type="Normal",
        reason="Evicted",
        message="Evicted pod",
        dedupe_values=[pod.namespace, pod.name],
    )


def pod_failed_to_schedule(pod: Pod, err: str) -> Event:
    return Event(
        involved_object=pod,
        type="Warning",
        reason="FailedScheduling",
        message=f"Failed to schedule pod, {err}",
        dedupe_values=[pod.namespace, pod.name, err],
    )


def node_failed_to_drain(node: Node, err: str) -> Event:
    return Event(
        involved_object=node,
        type="Warning",
        reason="FailedDraining",
        message=f"Failed to drain node, {err}",
        dedupe_values=[node.name],
    )


def node_inflight_check(node: Node, message: str) -> Event:
    return Event(
        involved_object=node,
        type="Warning",
        reason="FailedInflightCheck",
        message=message,
        dedupe_values=[node.name, message],
    )


def terminating_node(node: Node, reason: str) -> Event:
    return Event(
        involved_object=node,
        type="Normal",
        reason="DeprovisioningTerminating",
        message=f"Deprovisioning node via {reason}",
        dedupe_values=[node.name, reason],
    )


def launching_node(node_repr: str, reason: str) -> Event:
    return Event(
        involved_object=node_repr,
        type="Normal",
        reason="DeprovisioningLaunching",
        message=f"Launching node for {reason}",
        dedupe_values=[node_repr, reason],
    )


def waiting_on_readiness(node_repr: str) -> Event:
    return Event(
        involved_object=node_repr,
        type="Normal",
        reason="DeprovisioningWaitingReadiness",
        message="Waiting on readiness to continue deprovisioning",
        dedupe_values=[str(node_repr)],
    )


def shape_hint(pod: Pod, message: str) -> Event:
    """Policy counter-proposal (docs/POLICY.md): the pod is unschedulable
    (or schedulable only expensively) as specified, but a bounded resize
    would fit a strictly cheaper fleet.  Advisory — the workload owner
    decides; nothing mutates the pod."""
    return Event(
        involved_object=pod,
        type="Normal",
        reason="ShapeHint",
        message=message,
        dedupe_values=[pod.namespace, pod.name, message],
    )


def unconsolidatable(node: Node, reason: str) -> Event:
    return Event(
        involved_object=node,
        type="Normal",
        reason="Unconsolidatable",
        message=reason,
        dedupe_values=[node.name, reason],
        rate_limit_qps=None,
    )
