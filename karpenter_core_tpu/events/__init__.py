from karpenter_core_tpu.events.recorder import Event, Recorder

__all__ = ["Event", "Recorder"]
