"""Dynamic settings store.

Mirror of /root/reference/pkg/operator/settingsstore/settingsstore.go:34-98 and
apis/config/settings (knative UntypedStore): watches the
``karpenter-global-settings`` ConfigMap-equivalent, blocks startup until it
exists (or seeds it), parses-or-raises on updates, and hands the live Settings
to every controller through a shared mutable holder.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from karpenter_core_tpu.apis.objects import ObjectMeta
from karpenter_core_tpu.operator.settings import Settings

log = logging.getLogger(__name__)

SETTINGS_NAME = "karpenter-global-settings"


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


class SettingsStore:
    """Live settings holder; controllers read attributes through it so updates
    apply without rewiring (the role of InjectSettings, injectsettings.go:30-52)."""

    def __init__(self, kube_client, defaults: Optional[Settings] = None) -> None:
        self.kube_client = kube_client
        self._settings = defaults or Settings()
        self._lock = threading.Lock()
        self._watchers: List[Callable[[Settings], None]] = []

    # controllers read settings fields through the store
    @property
    def batch_max_duration(self) -> float:
        return self.current.batch_max_duration

    @property
    def batch_idle_duration(self) -> float:
        return self.current.batch_idle_duration

    @property
    def drift_enabled(self) -> bool:
        return self.current.drift_enabled

    @property
    def current(self) -> Settings:
        with self._lock:
            return self._settings

    def on_change(self, callback: Callable[[Settings], None]) -> None:
        self._watchers.append(callback)

    def start(self) -> "SettingsStore":
        """Ensure the ConfigMap exists (the reference blocks startup until all
        registered ConfigMaps appear, settingsstore.go:71-92) and watch it.
        The seed serializes the store's defaults so a restart re-reading the
        seeded ConfigMap reproduces them instead of resetting to globals."""
        existing = self.kube_client.get(ConfigMap, SETTINGS_NAME, "karpenter")
        if existing is None:
            self.kube_client.create(
                ConfigMap(
                    metadata=ObjectMeta(name=SETTINGS_NAME, namespace="karpenter"),
                    data=self._serialize(self.current),
                )
            )
        else:
            self._apply(existing)
        self.kube_client.watch(ConfigMap, self._on_event, replay=False)
        return self

    @staticmethod
    def _serialize(settings: Settings) -> Dict[str, str]:
        return {
            "batchMaxDuration": f"{settings.batch_max_duration}s",
            "batchIdleDuration": f"{settings.batch_idle_duration}s",
            "featureGates.driftEnabled": "true" if settings.drift_enabled else "false",
        }

    def _on_event(self, event_type: str, cm: ConfigMap) -> None:
        if cm.metadata.name != SETTINGS_NAME or event_type == "DELETED":
            return
        self._apply(cm)

    def _apply(self, cm: ConfigMap) -> None:
        # parse-or-raise, mirroring the reference's panic-on-invalid contract
        # (settings.go:61-66) — but on *updates* we keep the last good config
        try:
            parsed = Settings.from_config_map(cm.data)
        except ValueError as e:
            log.error("invalid settings update rejected, %s", e)
            return
        with self._lock:
            self._settings = parsed
        for callback in self._watchers:
            callback(parsed)


LOGGING_CONFIG_NAME = "config-logging"


class LoggingConfigWatcher:
    """Dynamic log level from the ``config-logging`` ConfigMap — the
    reference reloads its zap level the same way
    (/root/reference/pkg/operator/logger.go:31 ChangeLevel watch).  Data key:
    ``loglevel.controller`` (debug|info|warning|error); invalid values keep
    the last good level."""

    def __init__(self, kube_client, logger_name: str = "karpenter_core_tpu") -> None:
        self.kube_client = kube_client
        self.logger_name = logger_name

    def start(self) -> "LoggingConfigWatcher":
        existing = self.kube_client.get(ConfigMap, LOGGING_CONFIG_NAME, "karpenter")
        if existing is not None:
            self._apply(existing)
        self.kube_client.watch(ConfigMap, self._on_event, replay=False)
        return self

    def _on_event(self, event_type: str, cm: ConfigMap) -> None:
        if cm.metadata.name != LOGGING_CONFIG_NAME or event_type == "DELETED":
            return
        self._apply(cm)

    def _apply(self, cm: ConfigMap) -> None:
        name = cm.data.get("loglevel.controller")
        if name is None:
            return  # key absent: keep the current level (incl. LOG_LEVEL env)
        level = logging.getLevelName(name.upper())
        if not isinstance(level, int):
            log.error("invalid log level %r in %s, keeping current", name, LOGGING_CONFIG_NAME)
            return
        logging.getLogger(self.logger_name).setLevel(level)
        log.info("log level set to %s (%s)", name, LOGGING_CONFIG_NAME)
